"""Purely functional operation generators.

Generators tell the framework what to do during a test. A generator
supports two functions:

  op(gen, test, ctx)  ->  None                 generator exhausted
                          (PENDING, gen')      can't tell yet
                          (op, gen')           next op + successor state

  update(gen, test, ctx, event) -> gen'        react to invoke/complete

Plain Python values are generators: dicts are one-shot ops, lists/tuples
run their elements in order, callables are invoked (with (test, ctx) or no
args) to produce generators repeatedly until they return None, and Python
iterators/generator-objects are consumed lazily.

Capability reference: jepsen/src/jepsen/generator.clj (protocol 408-416,
default impls 560-612, fill-in-op 500-537, combinators 644-1608). The
semantics here track the reference's docstring spec (generator.clj:1-200)
combinator-for-combinator; the implementation is new (Python value
dispatch + int-bitset contexts rather than protocol extension over JVM
types).
"""

from __future__ import annotations

import inspect
import logging
import random as _random
import weakref
from typing import Any, Callable, Iterable

from .. import util
from ..history import Op
from .context import (AllBut, Context, NEMESIS, all_but, make_thread_filter,
                      truthy)

logger = logging.getLogger(__name__)

# Public sentinel: "I might have an op later, but not yet."
PENDING = "pending"

# Module fallback RNG, used when the context carries no "rng". Tests
# that set test["seed"] get a per-test RNG installed by
# Context.for_test(test), so two concurrent seeded tests in one
# process can't perturb each other's schedules; seedless tests share
# this fallback, which set_seed controls (the simulator relies on it).
_rng = _random.Random()


def set_seed(seed) -> None:
    """Seeds the fallback generator-scheduling RNG (mix choice, stagger
    jitter, soonest-op tie-breaks). Setting test["seed"] instead scopes
    determinism to that one test's context."""
    _rng.seed(seed)


def _ctx_rng(ctx):
    """The context's per-test RNG, else the module fallback."""
    r = ctx.get("rng") if ctx is not None else None
    return r if r is not None else _rng


# ---------------------------------------------------------------------------
# Context helpers re-exported (generator.clj import-vars)
# ---------------------------------------------------------------------------

def context(test) -> Context:
    return Context.for_test(test)


def all_threads(ctx: Context):
    return ctx.all_thread_names()


def free_threads(ctx: Context):
    return ctx.free_thread_names()


def all_processes(ctx: Context):
    return ctx.all_processes()


def free_processes(ctx: Context):
    return ctx.free_processes()


def some_free_process(ctx: Context):
    return ctx.some_free_process()


def process_to_thread(ctx: Context, process):
    return ctx.process_to_thread_name(process)


def thread_to_process(ctx: Context, thread):
    return ctx.thread_to_process(thread)


# ---------------------------------------------------------------------------
# fill-in-op
# ---------------------------------------------------------------------------

def fill_in_op(m: dict, ctx: Context):
    """Fills in :type :process :time from context; returns PENDING when no
    process is free (generator.clj:500-537)."""
    p = ctx.some_free_process()
    if p is None:
        return PENDING
    time = m.get("time", ctx.time)
    type_ = m.get("type", "invoke")
    process = m.get("process", p)
    f = m.get("f")
    value = m.get("value")
    ext = {k: v for k, v in m.items()
           if k not in ("time", "type", "process", "f", "value")}
    return Op(index=-1, time=time, type=type_, process=process, f=f,
              value=value, ext=ext or None)


# ---------------------------------------------------------------------------
# Generator base + value dispatch
# ---------------------------------------------------------------------------

class Generator:
    """Base class for combinator generators."""

    def op(self, test, ctx):
        raise NotImplementedError

    def update(self, test, ctx, event):
        return self


class _LazyList:
    """Append-only cache over an iterator so lazy (even infinite) Python
    iterables behave as persistent sequences."""

    __slots__ = ("_it", "_cache", "_done")

    def __init__(self, iterable):
        self._it = iter(iterable)
        self._cache: list = []
        self._done = False

    def get(self, i: int):
        cache = self._cache
        while len(cache) <= i and not self._done:
            try:
                cache.append(next(self._it))
            except StopIteration:
                self._done = True
        if i < len(cache):
            return True, cache[i]
        return False, None


class Seq(Generator):
    """Sequence generator: runs each element to exhaustion in order
    (generator.clj Seqable impl, 583-612). `current` holds the evolved
    state of the element at position i (or _FRESH)."""

    _FRESH = object()

    __slots__ = ("items", "i", "current")

    def __init__(self, items, i=0, current=_FRESH):
        self.items = items  # list/tuple or _LazyList
        self.i = i
        self.current = current

    @classmethod
    def of(cls, items):
        if isinstance(items, (list, tuple)):
            return cls(items)
        return cls(_LazyList(items))

    def _get(self, i):
        items = self.items
        if isinstance(items, _LazyList):
            return items.get(i)
        if i < len(items):
            return True, items[i]
        return False, None

    def _head(self, i, current):
        if current is not Seq._FRESH:
            return True, current
        return self._get(i)

    def op(self, test, ctx):
        i, current = self.i, self.current
        while True:
            found, head = self._head(i, current)
            if not found:
                return None
            res = op(head, test, ctx)
            if res is None:
                i += 1
                current = Seq._FRESH
                continue
            o, g2 = res
            # Tail flattening: a Seq at its final element is equivalent
            # to that element's continuation. Returning it bare keeps
            # fn-generator chains (Seq([g, fn]) rebuilt per call) at
            # constant depth instead of nesting once per exhaustion,
            # which blew the recursion limit past ~400 consumed ops.
            if (not isinstance(self.items, _LazyList)
                    and i == len(self.items) - 1):
                return o, g2
            return o, Seq(self.items, i, g2)

    def update(self, test, ctx, event):
        found, head = self._head(self.i, self.current)
        if not found:
            return self
        return Seq(self.items, self.i, update(head, test, ctx, event))


class _FnGen(Generator):
    """Function generator: calls f to produce a generator, exhausts it,
    then calls f again (generator.clj Fn record, 539-556)."""

    __slots__ = ("f", "arity")

    def __init__(self, f, arity):
        self.f = f
        self.arity = arity

    def op(self, test, ctx):
        g = self.f(test, ctx) if self.arity == 2 else self.f()
        if g is None:
            return None
        return op(Seq([g, self]), test, ctx)

    def __repr__(self):
        return f"FnGen<{getattr(self.f, '__name__', self.f)!r}>"


def _fn_arity(f) -> int:
    try:
        sig = inspect.signature(f)
    except (TypeError, ValueError):
        return 0
    n = 0
    for p in sig.parameters.values():
        if p.kind in (p.POSITIONAL_ONLY, p.POSITIONAL_OR_KEYWORD):
            if p.default is p.empty:
                n += 1
        elif p.kind == p.VAR_POSITIONAL:
            return 2
    return 2 if n >= 2 else 0


class Delayed(Generator):
    """Not evaluated until it could produce an op; then replaced by the
    generator the thunk returns (generator.clj Delay impl, 579-582)."""

    __slots__ = ("thunk", "_forced", "_value")

    def __init__(self, thunk):
        self.thunk = thunk
        self._forced = False
        self._value = None

    def _force(self):
        if not self._forced:
            self._value = self.thunk()
            self._forced = True
        return self._value

    def op(self, test, ctx):
        return op(self._force(), test, ctx)

    def update(self, test, ctx, event):
        return self


class Promise(Generator):
    """PENDING until delivered, then behaves as the delivered generator
    (generator.clj init! promise extension, 622-643)."""

    __slots__ = ("_value", "_delivered")

    def __init__(self):
        self._value = None
        self._delivered = False

    def deliver(self, gen):
        self._value = gen
        self._delivered = True

    def op(self, test, ctx):
        if self._delivered:
            return op(self._value, test, ctx)
        return PENDING, self

    def update(self, test, ctx, event):
        return self


# Iterators are the one non-persistent generator input: consuming them in
# place would break combinators (like Repeat) that re-run the *same*
# generator value. Cache the persistent Seq wrapper per iterator object so
# every use sees the same append-only view.
_iter_cache: "weakref.WeakKeyDictionary" = weakref.WeakKeyDictionary()


def _coerce_iterator(gen) -> Seq:
    try:
        seq = _iter_cache.get(gen)
        if seq is None:
            seq = Seq.of(gen)
            _iter_cache[gen] = seq
        return seq
    except TypeError:  # not weak-referenceable; accept one-shot semantics
        return Seq.of(gen)


def op(gen, test, ctx):
    """Asks a generator for its next operation. Returns None, (PENDING, g),
    or (Op, g')."""
    if gen is None:
        return None
    if isinstance(gen, Generator):
        return gen.op(test, ctx)
    if isinstance(gen, dict):
        o = fill_in_op(gen, ctx)
        if o is PENDING:
            return PENDING, gen
        return o, None
    if isinstance(gen, (list, tuple)):
        return Seq(gen).op(test, ctx)
    if callable(gen):
        return _FnGen(gen, _fn_arity(gen)).op(test, ctx)
    if hasattr(gen, "__next__"):
        return _coerce_iterator(gen).op(test, ctx)
    if hasattr(gen, "__iter__"):
        return Seq.of(gen).op(test, ctx)
    raise TypeError(f"Not a generator: {gen!r}")


def update(gen, test, ctx, event):
    """Updates a generator with an invoke/complete event."""
    if gen is None:
        return None
    if isinstance(gen, Generator):
        return gen.update(test, ctx, event)
    if isinstance(gen, dict):
        return gen
    if isinstance(gen, (list, tuple)):
        return Seq(gen).update(test, ctx, event)
    if callable(gen):
        return gen
    if hasattr(gen, "__next__"):
        return _coerce_iterator(gen).update(test, ctx, event)
    if hasattr(gen, "__iter__"):
        return Seq.of(gen).update(test, ctx, event)
    raise TypeError(f"Not a generator: {gen!r}")


# ---------------------------------------------------------------------------
# Validation wrappers
# ---------------------------------------------------------------------------

class InvalidOp(Exception):
    def __init__(self, problems, res, gen):
        self.problems = problems
        self.res = res
        self.gen = gen
        super().__init__(
            "Generator produced an invalid [op, gen'] tuple: "
            f"{problems} (result {res!r})")


class Validate(Generator):
    """Asserts well-formedness of emitted ops (generator.clj:644-699)."""

    __slots__ = ("gen",)

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        if not (isinstance(res, tuple) and len(res) == 2):
            raise InvalidOp(["should return a pair of [op, gen']"], res,
                            self.gen)
        o, g2 = res
        if o is not PENDING:
            problems = []
            if not isinstance(o, Op):
                problems.append("should be PENDING or an Op")
            else:
                if o.type not in ("invoke", "info", "sleep", "log"):
                    problems.append(
                        "type should be invoke, info, sleep, or log")
                if not isinstance(o.time, (int, float)):
                    problems.append("time should be a number")
                if o.process is None:
                    problems.append("no process")
                else:
                    thread = ctx.process_to_thread_name(o.process)
                    if thread is None or not ctx.thread_free(thread):
                        problems.append(f"process {o.process} is not free")
            if problems:
                raise InvalidOp(problems, res, self.gen)
        return (res[0], Validate(g2))

    def update(self, test, ctx, event):
        return Validate(update(self.gen, test, ctx, event))


def validate(gen):
    return Validate(gen)


class GeneratorError(Exception):
    """Wraps exceptions raised inside generators with context
    (friendly-exceptions, generator.clj:701-741)."""


class FriendlyExceptions(Generator):
    __slots__ = ("gen",)

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        try:
            res = op(self.gen, test, ctx)
        except (GeneratorError, InvalidOp):
            raise
        except Exception as e:
            raise GeneratorError(
                f"Generator threw {e!r} when asked for an operation; "
                f"generator: {self.gen!r}") from e
        if res is None:
            return None
        return res[0], FriendlyExceptions(res[1])

    def update(self, test, ctx, event):
        try:
            return FriendlyExceptions(update(self.gen, test, ctx, event))
        except (GeneratorError, InvalidOp):
            raise
        except Exception as e:
            raise GeneratorError(
                f"Generator threw {e!r} when updated with {event!r}; "
                f"generator: {self.gen!r}") from e


def friendly_exceptions(gen):
    return FriendlyExceptions(gen)


class Trace(Generator):
    """Logs op/update calls through this layer (generator.clj:743-787)."""

    __slots__ = ("k", "gen")

    def __init__(self, k, gen):
        self.k = k
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        logger.info("%s op -> %r", self.k, None if res is None else res[0])
        if res is None:
            return None
        return res[0], Trace(self.k, res[1])

    def update(self, test, ctx, event):
        logger.info("%s update <- %r", self.k, event)
        return Trace(self.k, update(self.gen, test, ctx, event))


def trace(k, gen):
    return Trace(k, gen)


# ---------------------------------------------------------------------------
# map / filter
# ---------------------------------------------------------------------------

class GMap(Generator):
    """Transforms emitted ops with f (generator.clj Map, 788-806)."""

    __slots__ = ("f", "gen")

    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is not PENDING:
            o = self.f(o)
            if isinstance(o, dict):
                from ..history import op as _mkop
                o = _mkop(**o)
        return o, GMap(self.f, g2)

    def update(self, test, ctx, event):
        return GMap(self.f, update(self.gen, test, ctx, event))


def gmap(f, gen):
    """`map` for generators (renamed to avoid shadowing builtins)."""
    return GMap(f, gen)


def f_map(fmap: dict, gen):
    """Replaces op :f values via the given mapping; useful with composed
    nemeses (generator.clj:816-824)."""
    return GMap(lambda o: o.copy(f=fmap.get(o.f, o.f)), gen)


class GFilter(Generator):
    """Passes only ops matching pred; PENDING/None bypass
    (generator.clj Filter, 826-848)."""

    __slots__ = ("pred", "gen")

    def __init__(self, pred, gen):
        self.pred = pred
        self.gen = gen

    def op(self, test, ctx):
        gen = self.gen
        while True:
            res = op(gen, test, ctx)
            if res is None:
                return None
            o, g2 = res
            if o is PENDING or self.pred(o):
                return o, GFilter(self.pred, g2)
            gen = g2

    def update(self, test, ctx, event):
        return GFilter(self.pred, update(self.gen, test, ctx, event))


def gfilter(pred, gen):
    return GFilter(pred, gen)


class IgnoreUpdates(Generator):
    __slots__ = ("gen",)

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        return op(self.gen, test, ctx)

    def update(self, test, ctx, event):
        return self


class OnUpdate(Generator):
    """Calls (f this test ctx event) on updates (generator.clj:850-865)."""

    __slots__ = ("f", "gen")

    def __init__(self, f, gen):
        self.f = f
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        return res[0], OnUpdate(self.f, res[1])

    def update(self, test, ctx, event):
        return self.f(self, test, ctx, event)


def on_update(f, gen):
    return OnUpdate(f, gen)


# ---------------------------------------------------------------------------
# Thread restriction
# ---------------------------------------------------------------------------

class OnThreads(Generator):
    """Restricts a generator to threads satisfying pred
    (generator.clj:873-891)."""

    __slots__ = ("pred", "ctx_filter", "gen")

    def __init__(self, pred, ctx_filter, gen):
        self.pred = pred
        self.ctx_filter = ctx_filter
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, self.ctx_filter(ctx))
        if res is None:
            return None
        return res[0], OnThreads(self.pred, self.ctx_filter, res[1])

    def update(self, test, ctx, event):
        thread = ctx.process_to_thread_name(event.process)
        if truthy(self.pred(thread)):
            return OnThreads(self.pred, self.ctx_filter,
                             update(self.gen, test, self.ctx_filter(ctx),
                                    event))
        return self


def on_threads(pred, gen):
    if isinstance(pred, (set, frozenset)):
        s = pred
        pred = lambda t: t in s  # noqa: E731
    return OnThreads(pred, make_thread_filter(pred), gen)


on = on_threads


def clients(client_gen, nemesis_gen=None):
    """Restricts to client threads; with two args, routes clients/nemesis
    (generator.clj:1125-1136)."""
    only_clients = on_threads(all_but(NEMESIS), client_gen)
    if nemesis_gen is None:
        return only_clients
    return any_gen(only_clients, nemesis(nemesis_gen))


def nemesis(nemesis_gen, client_gen=None):
    only_nem = on_threads({NEMESIS}, nemesis_gen)
    if client_gen is None:
        return only_nem
    return any_gen(only_nem, clients(client_gen))


# ---------------------------------------------------------------------------
# soonest-op-map + any
# ---------------------------------------------------------------------------

def soonest_op_map(m1, m2, rng=None):
    """Of two {'op','gen','weight',...} maps, the one whose op occurs
    sooner; ties broken randomly proportional to weight
    (generator.clj:894-938)."""
    rng = rng or _rng
    if m1 is None:
        return m2
    if m2 is None:
        return m1
    op1, op2 = m1["op"], m2["op"]
    if op1 is PENDING:
        return m2
    if op2 is PENDING:
        return m1
    t1, t2 = op1.time, op2.time
    if t1 == t2:
        w1 = m1.get("weight", 1)
        w2 = m2.get("weight", 1)
        chosen = m1 if rng.randrange(w1 + w2) < w1 else m2
        chosen = dict(chosen)
        chosen["weight"] = w1 + w2
        return chosen
    return m1 if t1 < t2 else m2


class Any(Generator):
    """Takes ops from whichever sub-generator is soonest; updates go to all
    (generator.clj:940-964)."""

    __slots__ = ("gens",)

    def __init__(self, gens):
        self.gens = list(gens)

    def op(self, test, ctx):
        soonest = None
        for i, g in enumerate(self.gens):
            res = op(g, test, ctx)
            if res is not None:
                soonest = soonest_op_map(
                    soonest, {"op": res[0], "gen": res[1], "i": i},
                    rng=_ctx_rng(ctx))
        if soonest is None:
            return None
        gens = list(self.gens)
        gens[soonest["i"]] = soonest["gen"]
        return soonest["op"], Any(gens)

    def update(self, test, ctx, event):
        return Any([update(g, test, ctx, event) for g in self.gens])


def any_gen(*gens):
    if len(gens) == 0:
        return None
    if len(gens) == 1:
        return gens[0]
    return Any(gens)


# ---------------------------------------------------------------------------
# each-thread
# ---------------------------------------------------------------------------

class EachThread(Generator):
    """Independent copy of the generator per thread
    (generator.clj:966-1028)."""

    __slots__ = ("fresh_gen", "filters", "gens")

    def __init__(self, fresh_gen, filters, gens):
        self.fresh_gen = fresh_gen
        self.filters = filters  # shared mutable cache: thread -> ctx filter
        self.gens = gens        # thread -> evolved gen

    def _filter_for(self, thread, ctx):
        f = self.filters.get(thread)
        if f is None:
            f = make_thread_filter(lambda t, th=thread: t == th, ctx)
            self.filters[thread] = f
        return f

    def op(self, test, ctx):
        soonest = None
        for thread in ctx.free_thread_names():
            g = self.gens.get(thread, self.fresh_gen)
            tctx = self._filter_for(thread, ctx)(ctx)
            res = op(g, test, tctx)
            if res is not None:
                soonest = soonest_op_map(
                    soonest, {"op": res[0], "gen": res[1],
                              "thread": thread}, rng=_ctx_rng(ctx))
        if soonest is not None:
            gens = dict(self.gens)
            gens[soonest["thread"]] = soonest["gen"]
            return soonest["op"], EachThread(self.fresh_gen, self.filters,
                                             gens)
        if ctx.free_thread_count() != ctx.all_thread_count():
            return PENDING, self
        return None  # every thread exhausted

    def update(self, test, ctx, event):
        thread = ctx.process_to_thread_name(event.process)
        if thread is None:
            return self
        g = self.gens.get(thread, self.fresh_gen)
        tctx = self._filter_for(thread, ctx)(ctx)
        gens = dict(self.gens)
        gens[thread] = update(g, test, tctx, event)
        return EachThread(self.fresh_gen, self.filters, gens)


def each_thread(gen):
    return EachThread(gen, {}, {})


# ---------------------------------------------------------------------------
# reserve
# ---------------------------------------------------------------------------

class Reserve(Generator):
    """Dedicates thread ranges to generators, remaining threads to a
    default (generator.clj:1029-1124)."""

    __slots__ = ("ranges", "ctx_filters", "gens")

    def __init__(self, ranges, ctx_filters, gens):
        self.ranges = ranges          # list of frozenset of thread names
        self.ctx_filters = ctx_filters  # one per range + default last
        self.gens = gens              # one per range + default last

    def op(self, test, ctx):
        soonest = None
        for i, threads in enumerate(self.ranges):
            tctx = self.ctx_filters[i](ctx)
            res = op(self.gens[i], test, tctx)
            if res is not None:
                soonest = soonest_op_map(
                    soonest, {"op": res[0], "gen": res[1],
                              "weight": len(threads), "i": i},
                    rng=_ctx_rng(ctx))
        dctx = self.ctx_filters[-1](ctx)
        res = op(self.gens[-1], test, dctx)
        if res is not None:
            soonest = soonest_op_map(
                soonest, {"op": res[0], "gen": res[1],
                          "weight": dctx.all_thread_count(),
                          "i": len(self.ranges)}, rng=_ctx_rng(ctx))
        if soonest is None:
            return None
        gens = list(self.gens)
        gens[soonest["i"]] = soonest["gen"]
        return soonest["op"], Reserve(self.ranges, self.ctx_filters, gens)

    def update(self, test, ctx, event):
        thread = ctx.process_to_thread_name(event.process)
        i = len(self.ranges)
        for j, threads in enumerate(self.ranges):
            if thread in threads:
                i = j
                break
        gens = list(self.gens)
        gens[i] = update(gens[i], test, ctx, event)
        return Reserve(self.ranges, self.ctx_filters, gens)


def reserve(*args):
    """reserve(5, writes, 10, cas, reads): first 5 threads run writes, next
    10 run cas, the rest run reads."""
    assert len(args) % 2 == 1, "reserve takes count,gen pairs + default gen"
    pairs = list(zip(args[:-1:2], args[1:-1:2]))
    default = args[-1]
    ranges = []
    n = 0
    for count, _g in pairs:
        ranges.append(frozenset(range(n, n + count)))
        n += count
    all_reserved = frozenset().union(*ranges) if ranges else frozenset()
    filters = [make_thread_filter(lambda t, s=s: t in s) for s in ranges]
    filters.append(make_thread_filter(lambda t: t not in all_reserved))
    gens = [g for _c, g in pairs] + [default]
    return Reserve(ranges, filters, gens)


# ---------------------------------------------------------------------------
# mix / limit / repeat / cycle
# ---------------------------------------------------------------------------

class Mix(Generator):
    """Uniform random mixture; ignores updates (generator.clj:1156-1188)."""

    __slots__ = ("i", "gens")

    def __init__(self, i, gens):
        self.i = i
        self.gens = gens

    def op(self, test, ctx):
        rng = _ctx_rng(ctx)
        i, gens = self.i, self.gens
        if i is None:
            i = rng.randrange(len(gens)) if gens else 0
        while gens:
            res = op(gens[i], test, ctx)
            if res is not None:
                new_gens = list(gens)
                new_gens[i] = res[1]
                return res[0], Mix(rng.randrange(len(new_gens)), new_gens)
            gens = gens[:i] + gens[i + 1:]
            i = rng.randrange(len(gens)) if gens else 0
        return None

    def update(self, test, ctx, event):
        return self


def mix(gens):
    gens = list(gens)
    if not gens:
        return None
    return Mix(None, gens)  # first index drawn from the ctx RNG


class Limit(Generator):
    """At most `remaining` ops (generator.clj:1189-1204)."""

    __slots__ = ("remaining", "gen")

    def __init__(self, remaining, gen):
        self.remaining = remaining
        self.gen = gen

    def op(self, test, ctx):
        if self.remaining <= 0:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        if res[0] is PENDING:  # pending probes don't spend the budget
            return res[0], Limit(self.remaining, res[1])
        return res[0], Limit(self.remaining - 1, res[1])

    def update(self, test, ctx, event):
        return Limit(self.remaining, update(self.gen, test, ctx, event))


def limit(n, gen):
    return Limit(n, gen)


def once(gen):
    return Limit(1, gen)


def log(msg):
    """One-shot op that logs a message (generator.clj:1210)."""
    return {"type": "log", "value": msg}


class Repeat(Generator):
    """Emits ops forever (or `remaining` times) without consuming the
    underlying generator's state (generator.clj:1216-1242)."""

    __slots__ = ("remaining", "gen")

    def __init__(self, remaining, gen):
        self.remaining = remaining  # -1 = infinite
        self.gen = gen

    def op(self, test, ctx):
        if self.remaining == 0:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        return res[0], Repeat(max(-1, self.remaining - 1), self.gen)

    def update(self, test, ctx, event):
        return Repeat(self.remaining, update(self.gen, test, ctx, event))


def repeat(a, b=None):
    """repeat(gen) = infinite; repeat(n, gen) = n times."""
    if b is None:
        return Repeat(-1, a)
    assert a >= 0
    return Repeat(a, b)


class Cycle(Generator):
    """Restarts a finite generator when it exhausts
    (generator.clj:1243-1270)."""

    __slots__ = ("remaining", "original", "gen")

    def __init__(self, remaining, original, gen):
        self.remaining = remaining
        self.original = original
        self.gen = gen

    def op(self, test, ctx):
        remaining, gen = self.remaining, self.gen
        while remaining != 0:
            res = op(gen, test, ctx)
            if res is not None:
                return res[0], Cycle(remaining, self.original, res[1])
            remaining -= 1
            gen = self.original
        return None

    def update(self, test, ctx, event):
        return Cycle(self.remaining, self.original,
                     update(self.gen, test, ctx, event))


def cycle(gen, times=-1):
    return Cycle(times, gen, gen)


# ---------------------------------------------------------------------------
# process/time limits
# ---------------------------------------------------------------------------

class ProcessLimit(Generator):
    """Emits ops for up to n distinct processes (generator.clj:1271-1297)."""

    __slots__ = ("n", "procs", "gen")

    def __init__(self, n, procs, gen):
        self.n = n
        self.procs = procs
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return o, ProcessLimit(self.n, self.procs, g2)
        procs = self.procs | frozenset(ctx.all_processes())
        if len(procs) <= self.n:
            return o, ProcessLimit(self.n, procs, g2)
        return None

    def update(self, test, ctx, event):
        return ProcessLimit(self.n, self.procs,
                            update(self.gen, test, ctx, event))


def process_limit(n, gen):
    return ProcessLimit(n, frozenset(), gen)


class TimeLimit(Generator):
    """Emits ops for dt seconds after its first op
    (generator.clj:1298-1323)."""

    __slots__ = ("limit", "cutoff", "gen")

    def __init__(self, limit, cutoff, gen):
        self.limit = limit
        self.cutoff = cutoff
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return o, TimeLimit(self.limit, self.cutoff, g2)
        cutoff = self.cutoff if self.cutoff is not None else o.time + self.limit
        if o.time < cutoff:
            return o, TimeLimit(self.limit, cutoff, g2)
        return None

    def update(self, test, ctx, event):
        return TimeLimit(self.limit, self.cutoff,
                         update(self.gen, test, ctx, event))


def time_limit(dt_secs, gen):
    return TimeLimit(util.secs_to_nanos(dt_secs), None, gen)


# ---------------------------------------------------------------------------
# timing: stagger / delay / sleep
# ---------------------------------------------------------------------------

class Stagger(Generator):
    """Schedules ops at uniformly random intervals in [0, 2*dt), a *total*
    rate across all threads (generator.clj:1324-1399)."""

    __slots__ = ("dt", "next_time", "gen")

    def __init__(self, dt, next_time, gen):
        self.dt = dt
        self.next_time = next_time
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return o, self
        rng = _ctx_rng(ctx)
        next_time = self.next_time if self.next_time is not None else ctx.time
        if next_time <= o.time:
            return o, Stagger(self.dt, o.time + int(rng.random() * self.dt),
                              g2)
        return (o.copy(time=next_time),
                Stagger(self.dt, next_time + int(rng.random() * self.dt),
                        g2))

    def update(self, test, ctx, event):
        return Stagger(self.dt, self.next_time,
                       update(self.gen, test, ctx, event))


def stagger(dt_secs, gen):
    return Stagger(util.secs_to_nanos(2 * dt_secs), None, gen)


class GDelay(Generator):
    """Emits ops exactly dt apart (catching up if behind)
    (generator.clj:1400-1427)."""

    __slots__ = ("dt", "next_time", "gen")

    def __init__(self, dt, next_time, gen):
        self.dt = dt
        self.next_time = next_time
        self.gen = gen

    def op(self, test, ctx):
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return o, GDelay(self.dt, self.next_time, g2)
        next_time = self.next_time if self.next_time is not None else o.time
        o = o.copy(time=max(o.time, next_time))
        return o, GDelay(self.dt, o.time + self.dt, g2)

    def update(self, test, ctx, event):
        return GDelay(self.dt, self.next_time,
                      update(self.gen, test, ctx, event))


def delay(dt_secs, gen):
    return GDelay(util.secs_to_nanos(dt_secs), None, gen)


def sleep(dt_secs):
    """One special op: the receiving process does nothing for dt seconds
    (generator.clj:1428-1433)."""
    return {"type": "sleep", "value": dt_secs}


# ---------------------------------------------------------------------------
# synchronization
# ---------------------------------------------------------------------------

class Synchronize(Generator):
    """Waits for all threads to be free before starting
    (generator.clj:1434-1450)."""

    __slots__ = ("gen",)

    def __init__(self, gen):
        self.gen = gen

    def op(self, test, ctx):
        if ctx.free_thread_count() == ctx.all_thread_count():
            return op(self.gen, test, ctx)
        return PENDING, self

    def update(self, test, ctx, event):
        return Synchronize(update(self.gen, test, ctx, event))


def synchronize(gen):
    return Synchronize(gen)


def phases(*gens):
    """Runs each generator to completion in turn, with a barrier between
    (generator.clj:1452-1457)."""
    return [Synchronize(g) for g in gens]


def then(a, b):
    """b, then (synchronized) a. Note the reversed arg order, matching the
    reference's ->>-friendly `then` (generator.clj:1459-1469)."""
    return [b, Synchronize(a)]


class UntilOk(Generator):
    """Emits ops until one completes :ok (generator.clj:1470-1501)."""

    __slots__ = ("gen", "done", "active")

    def __init__(self, gen, done, active):
        self.gen = gen
        self.done = done
        self.active = active  # frozenset of processes running our ops

    def op(self, test, ctx):
        if self.done:
            return None
        res = op(self.gen, test, ctx)
        if res is None:
            return None
        o, g2 = res
        if o is PENDING:
            return o, UntilOk(g2, self.done, self.active)
        return o, UntilOk(g2, self.done, self.active | {o.process})

    def update(self, test, ctx, event):
        g2 = update(self.gen, test, ctx, event)
        p = event.process
        if p in self.active:
            if event.type == "ok":
                return UntilOk(g2, True, self.active - {p})
            if event.type in ("info", "fail"):
                return UntilOk(g2, self.done, self.active - {p})
        return UntilOk(g2, self.done, self.active)


def until_ok(gen):
    return UntilOk(gen, False, frozenset())


class FlipFlop(Generator):
    """Alternates between generators; stops when any is exhausted
    (generator.clj:1502-1517)."""

    __slots__ = ("gens", "i")

    def __init__(self, gens, i):
        self.gens = gens
        self.i = i

    def op(self, test, ctx):
        res = op(self.gens[self.i], test, ctx)
        if res is None:
            return None
        gens = list(self.gens)
        gens[self.i] = res[1]
        return res[0], FlipFlop(gens, (self.i + 1) % len(gens))

    def update(self, test, ctx, event):
        return self


def flip_flop(a, b):
    return FlipFlop([a, b], 0)


class CycleTimes(Generator):
    """Rotates between generators on a time schedule
    (generator.clj:1518-1608)."""

    __slots__ = ("period", "t0", "intervals", "cutoffs", "gens")

    def __init__(self, period, t0, intervals, cutoffs, gens):
        self.period = period
        self.t0 = t0
        self.intervals = intervals
        self.cutoffs = cutoffs
        self.gens = gens

    def op(self, test, ctx):
        now = ctx.time
        t0 = self.t0 if self.t0 is not None else now
        in_period = (now - t0) % self.period
        cycle_start = now - in_period
        i = 0
        while i < len(self.cutoffs) and in_period >= self.cutoffs[i]:
            i += 1
        t = cycle_start + sum(self.intervals[:i])
        for _ in range(2 * len(self.gens) + 2):
            interval = self.intervals[i]
            t_end = t + interval
            res = op(self.gens[i], test, ctx.with_time(max(now, t)))
            if res is None:
                return None
            o, g2 = res
            if o is PENDING:
                gens = list(self.gens)
                gens[i] = g2
                return PENDING, CycleTimes(self.period, t0, self.intervals,
                                           self.cutoffs, gens)
            if o.time < t_end:
                gens = list(self.gens)
                gens[i] = g2
                return o, CycleTimes(self.period, t0, self.intervals,
                                     self.cutoffs, gens)
            i = (i + 1) % len(self.gens)
            t = t_end
        return PENDING, self

    def update(self, test, ctx, event):
        return CycleTimes(self.period, self.t0, self.intervals, self.cutoffs,
                          [update(g, test, ctx, event) for g in self.gens])


def cycle_times(*specs):
    """cycle_times(5, writes, 10, reads): writes for 5s, reads for 10s,
    repeating. Generator state persists across rotations."""
    assert specs and len(specs) % 2 == 0
    intervals = [util.secs_to_nanos(s) for s in specs[::2]]
    gens = list(specs[1::2])
    period = sum(intervals)
    cutoffs = []
    acc = 0
    for iv in intervals[:-1]:
        acc += iv
        cutoffs.append(acc)
    return CycleTimes(period, None, intervals, cutoffs, gens)
