"""Deterministic, single-threaded generator simulation for tests.

Runs a generator against a synthetic completion function with no cluster
and no threads, producing the history the generator *would* create.

Capability reference: jepsen/src/jepsen/generator/test.clj (simulate
test.clj:35-112, quick/perfect/perfect-info/imperfect 115-187). The
reference rebinds rand-int around a seeded stream; here simulate seeds
the generator module's fallback RNG (its contexts carry no per-test
RNG, so every scheduling draw goes through that fallback).
"""

from __future__ import annotations

from typing import Callable

from . import (PENDING, Validate, context as make_context, op as gen_op,
               set_seed, update as gen_update)
from .context import Context, NEMESIS
from ..history import History, Op

RAND_SEED = 45100

DEFAULT_TEST: dict = {}


def n_plus_nemesis_context(n: int) -> Context:
    """A context with n worker threads plus a nemesis."""
    return make_context({"concurrency": n})


def default_context() -> Context:
    return n_plus_nemesis_context(2)


def simulate(gen, complete_fn: Callable, ctx: Context | None = None,
             test: dict | None = None, seed=RAND_SEED) -> list[Op]:
    """Simulates a generator against complete_fn(ctx, invoke) -> completion.

    Completions are held in a time-sorted in-flight set; an invocation is
    applied when its time precedes every in-flight completion, otherwise
    the earliest completion lands first. Crashed (:info) client ops get a
    fresh process. Mirrors test.clj:56-112.
    """
    if ctx is None:
        ctx = default_context()
    if test is None:
        test = DEFAULT_TEST
    set_seed(seed)
    ops: list[Op] = []
    in_flight: list[Op] = []  # sorted by time
    gen = Validate(gen)
    while True:
        res = gen_op(gen, test, ctx)
        if res is None:
            ops.extend(in_flight)
            return ops
        invoke, gen2 = res
        if invoke is not PENDING and (
                not in_flight or invoke.time <= in_flight[0].time):
            thread = ctx.process_to_thread_name(invoke.process)
            ctx = ctx.busy_thread(max(ctx.time, invoke.time), thread)
            gen = gen_update(gen2, test, ctx, invoke)
            complete = complete_fn(ctx, invoke)
            in_flight.append(complete)
            in_flight.sort(key=lambda o: o.time)
            ops.append(invoke)
        else:
            if not in_flight:
                raise AssertionError(
                    "generator pending but nothing in flight: stuck")
            done = in_flight.pop(0)
            thread = ctx.process_to_thread_name(done.process)
            ctx = ctx.free_thread(done.time, thread)
            gen = gen_update(gen, test, ctx, done)
            if thread != NEMESIS and done.type == "info":
                ctx = ctx.with_next_process(thread)
            ops.append(done)


def invocations(ops) -> list[Op]:
    return [o for o in ops if o.type == "invoke"]


def quick_ops(gen, ctx=None) -> list[Op]:
    """Every op completes :ok instantly with zero latency."""
    return simulate(gen, lambda c, inv: inv.copy(type="ok"), ctx=ctx)


def quick(gen, ctx=None) -> list[Op]:
    return invocations(quick_ops(gen, ctx=ctx))


PERFECT_LATENCY = 10


def perfect_all(gen, ctx=None) -> list[Op]:
    """Every op completes :ok in 10ns; returns the full history."""
    return simulate(
        gen,
        lambda c, inv: inv.copy(type="ok", time=inv.time + PERFECT_LATENCY),
        ctx=ctx)


def perfect(gen, ctx=None) -> list[Op]:
    return invocations(perfect_all(gen, ctx=ctx))


def perfect_info(gen, ctx=None) -> list[Op]:
    """Every op crashes :info in 10ns; returns only invocations."""
    return invocations(simulate(
        gen,
        lambda c, inv: inv.copy(type="info", time=inv.time + PERFECT_LATENCY),
        ctx=ctx))


def imperfect(gen, ctx=None) -> list[Op]:
    """Threads rotate fail -> info -> ok; returns the full history."""
    state: dict = {}
    rotation = {None: "fail", "fail": "info", "info": "ok", "ok": "fail"}

    def complete(c, inv):
        t = c.process_to_thread_name(inv.process)
        state[t] = rotation[state.get(t)]
        return inv.copy(type=state[t], time=inv.time + PERFECT_LATENCY)

    return simulate(gen, complete, ctx=ctx)
