"""Network manipulation: partitions and packet shaping.

Capability reference: jepsen/src/jepsen/net/proto.clj:5-35 (Net and
PartitionAll protocols), jepsen/src/jepsen/net.clj (tc/netem behavior
table and shaping 67-173, iptables impl 175-233, ipfilter impl 235-270),
jepsen/src/jepsen/control/net.clj (IP resolution, reachability).

A Net applies *mechanism*: which packets to drop/delay/corrupt on which
nodes. Grudge *policy* (who should drop whom) lives in nemesis.core and
arrives here as a map node -> set of nodes whose packets it drops.
"""

from __future__ import annotations

import re
from typing import Optional

from . import control
from .control.core import Lit, RemoteError
from .util import real_pmap

TC = "/sbin/tc"


def _trace_net(name: str, **attrs) -> None:
    """Partition/shaping changes as span events on the nemesis op that
    applied them (the nemesis worker's invoke carries the ambient
    trace context), so a cycle that closed across a partition window
    links straight to the iptables/tc change that opened it."""
    from . import tracing

    tracing.event(f"net.{name}", **attrs)


# ---------------------------------------------------------------------------
# IP resolution (control/net.clj)
# ---------------------------------------------------------------------------

class BlankGetentIP(Exception):
    """getent returned no address for a hostname (control/net.clj ip*)."""


def reachable(node) -> bool:
    """Can the current node ping the given node? (control/net.clj:8-12)"""
    try:
        control.exec_("ping", "-w", 1, node)
        return True
    except RemoteError:
        return False


def local_ip() -> str:
    """The current node's IP address (control/net.clj:14-17)."""
    return control.exec_("hostname", "-I").split()[0]


def ip_unmemoized(host) -> str:
    """Looks up an IPv4 address for a hostname on the current node via
    getent ahostsv4 (control/net.clj:19-44). Falls back to local-ip when
    getent returns loopback (Debian Bookworm behavior)."""
    res = control.exec_("getent", "ahostsv4", host)
    first_line = res.splitlines()[0] if res else ""
    addr = first_line.split()[0] if first_line.split() else ""
    if addr.startswith("127"):
        return local_ip()
    if not addr:
        raise BlankGetentIP(f"blank getent ip for host {host!r}: {res!r}")
    return addr


_ip_cache: dict = {}


def ip(host) -> str:
    """Memoized ip_unmemoized (control/net.clj:46-48)."""
    if host not in _ip_cache:
        _ip_cache[host] = ip_unmemoized(host)
    return _ip_cache[host]


def clear_ip_cache() -> None:
    _ip_cache.clear()


def control_ip() -> str:
    """The control node's IP as seen from the current DB node, parsed
    from $SSH_CLIENT (control/net.clj:50-62)."""
    out = control.exec_("bash", "-c", "echo $SSH_CLIENT")
    m = re.match(r"^(.+?)\s", out + " ")
    if not m or not m.group(1):
        raise RuntimeError(f"couldn't parse SSH_CLIENT: {out!r}")
    return m.group(1)


# ---------------------------------------------------------------------------
# tc helpers (net.clj:44-66)
# ---------------------------------------------------------------------------

def net_dev() -> str:
    """The current node's primary network interface, from
    `ip -o link show` minus loopback (net.clj:46-57)."""
    with control.su():
        out = control.exec_("ip", "-o", "link", "show")
    for line in out.splitlines():
        m = re.match(r"\d+: ([^:@]+)", line)
        if m and m.group(1) != "lo":
            return m.group(1)
    raise RuntimeError(f"couldn't determine network interface:\n{out}")


def qdisc_del(dev: str) -> None:
    """Deletes the root qdisc on dev; tolerates there being none
    (net.clj:59-66)."""
    try:
        with control.su():
            control.exec_(TC, "qdisc", "del", "dev", dev, "root")
    except RemoteError as e:
        if e.exit == 2:  # no qdisc to delete
            return
        raise


# Packet behaviors and their default option values (net.clj:68-95).
ALL_PACKET_BEHAVIORS = {
    "delay": {"time": "50ms", "jitter": "10ms", "correlation": "25%",
              "distribution": "normal"},
    "loss": {"percent": "20%", "correlation": "75%"},
    "corrupt": {"percent": "20%", "correlation": "75%"},
    "duplicate": {"percent": "20%", "correlation": "75%"},
    "reorder": {"percent": "20%", "correlation": "75%"},
    "rate": {"rate": "1mbit"},
}

_BEHAVIOR_ORDER = ["delay", "loss", "corrupt", "duplicate", "reorder",
                   "rate"]


def behaviors_to_netem(behaviors: dict) -> list:
    """Netem option list for a behavior map, defaults filled in
    (net.clj:97-126). :reorder requires :delay."""
    behaviors = dict(behaviors)
    if "reorder" in behaviors and "delay" not in behaviors:
        behaviors["delay"] = ALL_PACKET_BEHAVIORS["delay"]
    args: list = []
    for b in _BEHAVIOR_ORDER:
        if b not in behaviors:
            continue
        o = {**ALL_PACKET_BEHAVIORS[b], **(behaviors[b] or {})}
        if b == "delay":
            args += ["delay", o["time"], o["jitter"], o["correlation"],
                     "distribution", o["distribution"]]
        elif b == "rate":
            args += ["rate", o["rate"]]
        else:
            args += [b, o["percent"], o["correlation"]]
    return args


# ---------------------------------------------------------------------------
# Net protocol
# ---------------------------------------------------------------------------

class Net:
    """Network manipulation protocol (net/proto.clj:5-26). Implementors
    may override drop_all for a one-call partition fast path
    (PartitionAll, net/proto.clj:28-35)."""

    def drop(self, test, src, dest) -> None:
        """Drops traffic from src at dest."""
        raise NotImplementedError

    def heal(self, test) -> None:
        """Ends all drops, restoring the network."""
        raise NotImplementedError

    def slow(self, test, mean: int = 50, variance: int = 10,
             distribution: str = "normal") -> None:
        """Delays packets on every node."""
        raise NotImplementedError

    def flaky(self, test) -> None:
        """Introduces randomized packet loss on every node."""
        raise NotImplementedError

    def fast(self, test) -> None:
        """Removes packet delay/loss."""
        raise NotImplementedError

    def shape(self, test, nodes, behavior: dict):
        """Shapes traffic to `nodes` per a behavior map (delay/loss/
        corrupt/duplicate/reorder/rate)."""
        raise NotImplementedError

    def drop_all(self, test, grudge: dict) -> None:
        """Applies a grudge {node: nodes-to-drop}; default expands into
        parallel drop calls (net.clj:26-42)."""
        pairs = [(src, dst) for dst, srcs in grudge.items()
                 for src in srcs]
        real_pmap(lambda p: self.drop(test, p[0], p[1]), pairs)


class NoopNet(Net):
    """Does nothing (net.clj noop)."""

    def drop(self, test, src, dest):
        pass

    def heal(self, test):
        pass

    def slow(self, test, mean=50, variance=10, distribution="normal"):
        pass

    def flaky(self, test):
        pass

    def fast(self, test):
        pass

    def shape(self, test, nodes, behavior):
        pass

    def drop_all(self, test, grudge):
        pass


def _shape_on_node(test, node, targets, behavior):
    """Per-node body of net_shape (net.clj:128-173)."""
    nodes = set(test["nodes"])
    tset = set(targets or ())
    if node in tset:
        tset = nodes - {node}
    dev = net_dev()
    qdisc_del(dev)
    if not (tset and behavior):
        return None
    with control.su():
        # root prio qdisc; bands 1:1-3 are the system default priomap
        control.exec_(TC, "qdisc", "add", "dev", dev, "root", "handle",
                      "1:", "prio", "bands", 4, "priomap",
                      *"1 2 2 2 1 2 0 0 1 1 1 1 1 1 1 1".split())
        # band 1:4 is a netem qdisc with the requested behavior
        control.exec_(TC, "qdisc", "add", "dev", dev, "parent", "1:4",
                      "handle", "40:", "netem",
                      *behaviors_to_netem(behavior))
        # steer each target's dst ip into the netem band
        for target in sorted(tset):
            control.exec_(TC, "filter", "add", "dev", dev, "parent",
                          "1:0", "protocol", "ip", "prio", 3, "u32",
                          "match", "ip", "dst", ip(target),
                          "flowid", "1:4")
    return sorted(tset)


def _net_shape(net, test, targets, behavior):
    results = control.on_nodes(
        test, lambda t, n: _shape_on_node(t, n, targets, behavior))
    if targets and behavior:
        _trace_net("shape", targets=sorted(map(str, targets or ())),
                   behavior=sorted(behavior or ()))
        return ["shaped", results, "netem", behaviors_to_netem(behavior)]
    _trace_net("shape-clear")
    return ["reliable", results]


class IPTables(Net):
    """Default iptables implementation (net.clj:175-233)."""

    def drop(self, test, src, dest):
        def body(t, n):
            with control.su():
                control.exec_("iptables", "-A", "INPUT", "-s", ip(src),
                              "-j", "DROP", "-w")
        control.on_nodes(test, body, [dest])
        _trace_net("drop", src=str(src), dest=str(dest))

    def heal(self, test):
        def body(t, n):
            with control.su():
                control.exec_("iptables", "-F", "-w")
                control.exec_("iptables", "-X", "-w")
        control.on_nodes(test, body)
        _trace_net("heal")

    def slow(self, test, mean=50, variance=10, distribution="normal"):
        def body(t, n):
            with control.su():
                control.exec_(TC, "qdisc", "add", "dev", "eth0", "root",
                              "netem", "delay", f"{mean}ms",
                              f"{variance}ms", "distribution",
                              distribution)
        control.on_nodes(test, body)

    def flaky(self, test):
        def body(t, n):
            with control.su():
                control.exec_(TC, "qdisc", "add", "dev", "eth0", "root",
                              "netem", "loss", "20%", "75%")
        control.on_nodes(test, body)

    def fast(self, test):
        def body(t, n):
            try:
                with control.su():
                    control.exec_(TC, "qdisc", "del", "dev", "eth0",
                                  "root")
            except RemoteError as e:
                if "RTNETLINK answers: No such file or directory" in (
                        (e.err or "") + (e.out or "")):
                    return
                raise
        control.on_nodes(test, body)

    def shape(self, test, nodes, behavior):
        return _net_shape(self, test, nodes, behavior)

    def drop_all(self, test, grudge):
        def snub(t, node):
            srcs = grudge.get(node) or ()
            if not srcs:
                return
            with control.su():
                control.exec_("iptables", "-A", "INPUT", "-s",
                              ",".join(ip(s) for s in sorted(srcs)),
                              "-j", "DROP", "-w")
        control.on_nodes(test, snub, list(grudge.keys()))
        _trace_net("partition",
                   grudge={str(n): sorted(map(str, srcs))
                           for n, srcs in grudge.items() if srcs})


class IPFilter(Net):
    """ipf-based implementation for ipfilter systems (net.clj:235-270)."""

    def drop(self, test, src, dest):
        def body(t, n):
            with control.su():
                control.exec_("echo", "block", "in", "from", src, "to",
                              "any", Lit("|"), "ipf", "-f", "-")
        control.on_nodes(test, body, [dest])
        _trace_net("drop", src=str(src), dest=str(dest))

    def heal(self, test):
        def body(t, n):
            with control.su():
                control.exec_("ipf", "-Fa")
        control.on_nodes(test, body)
        _trace_net("heal")

    slow = IPTables.slow
    flaky = IPTables.flaky
    fast = IPTables.fast

    def shape(self, test, nodes, behavior):
        return _net_shape(self, test, nodes, behavior)


noop = NoopNet()
iptables = IPTables()
ipfilter = IPFilter()
