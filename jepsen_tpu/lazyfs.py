"""lazyfs: filesystem-level durability faults — losing writes that were
never fsynced.

Capability reference: jepsen/src/jepsen/lazyfs.clj — clone + build the
lazyfs FUSE filesystem at a pinned commit (22-108), mount a directory
through it with a TOML config + control FIFO (110-225), a DB wrapper
that mounts on setup / unmounts on teardown and exposes the lazyfs log
(227-244), `lose-unfsynced-writes!` via the FIFO command
`lazyfs::clear-cache` (246-263), `checkpoint!` via
`lazyfs::cache-checkpoint` (265-271), and a nemesis whose
:lose-unfsynced-writes op drops un-fsynced pages on chosen nodes
(273-295).
"""

from __future__ import annotations

from typing import Any

from . import control, db as jdb
from . import nemesis as jnemesis
from .control import util as cu

REPO_URL = "https://github.com/dsrhaslab/lazyfs.git"
COMMIT = "0.2.0"
DIR = "/opt/jepsen/lazyfs"
BIN = f"{DIR}/lazyfs/build/lazyfs"


def lazyfs(dir_or_map) -> dict:
    """Normalizes a directory (or partial map) into a full lazyfs map:
    the mount dir, its backing data dir, config/fifo/log paths, and the
    user to run as (lazyfs.clj `lazyfs`, 110-135)."""
    m = ({"dir": dir_or_map} if isinstance(dir_or_map, str)
         else dict(dir_or_map))
    d = m["dir"].rstrip("/")
    m.setdefault("user", "root")
    m.setdefault("chown", f"{m['user']}:{m['user']}")
    m.setdefault("data-dir", f"{d}.data")
    m.setdefault("lazyfs-dir", f"{d}.lazyfs")
    m.setdefault("config-file", f"{m['lazyfs-dir']}/lazyfs.conf")
    m.setdefault("fifo", f"{m['lazyfs-dir']}/fifo")
    m.setdefault("fifo-completed", f"{m['lazyfs-dir']}/fifo-completed")
    m.setdefault("log-file", f"{m['lazyfs-dir']}/lazyfs.log")
    return m


def config(lz: dict) -> str:
    """The lazyfs TOML config (lazyfs.clj `config`, 42-60)."""
    return f"""[faults]
fifo_path="{lz['fifo']}"

[cache]
apply_eviction=false

[cache.simple]
custom_size="{lz.get('cache-size', '0.5GB')}"
blocks_per_page=1

[filesystem]
logfile="{lz['log-file']}"
log_all_operations=false
"""


def install() -> None:
    """Clones, pins, and builds lazyfs on the node (lazyfs.clj
    `install!`, 62-108)."""
    with control.su():
        control.exec_("mkdir", "-p", DIR)
        if not cu.exists_p(f"{DIR}/.git"):
            control.exec_("git", "clone", REPO_URL, DIR)
        with control.cd(DIR):
            control.exec_("git", "fetch", "--tags")
            control.exec_("git", "checkout", COMMIT)
            with control.cd("libs/libpcache"):
                control.exec_("./build.sh")
            with control.cd("lazyfs"):
                control.exec_("./build.sh")


def mount(lz: dict) -> None:
    """Creates dirs + config and mounts dir through lazyfs backed by
    data-dir (lazyfs.clj `mount!`, 150-185)."""
    with control.su():
        control.exec_("mkdir", "-p", lz["dir"], lz["data-dir"],
                      lz["lazyfs-dir"])
        cu.write_file(config(lz), lz["config-file"])
        control.exec_(
            BIN, lz["dir"],
            "--config-path", lz["config-file"],
            "-o", "allow_other",
            "-o", "modules=subdir",
            "-o", f"subdir={lz['data-dir']}")
        control.exec_("chown", lz["chown"], lz["dir"])


def umount(lz: dict) -> None:
    """Unmounts; ignores failures (already unmounted / node died)."""
    try:
        with control.su():
            control.exec_("fusermount", "-u", lz["dir"])
    except Exception:  # noqa: BLE001
        pass


def fifo(lz: dict, command: str) -> None:
    """Writes a command to the lazyfs control FIFO (lazyfs.clj
    `fifo!`, 187-200)."""
    with control.su():
        control.exec_("sh", "-c",
                      f"echo {command} > {lz['fifo']}",
                      timeout=10.0)


def lose_unfsynced_writes(lz: dict) -> str:
    """Drops every write not yet fsynced (lazyfs.clj:246-263)."""
    fifo(lz, "lazyfs::clear-cache")
    return "done"


def checkpoint(lz: dict) -> str:
    """Flushes all cached writes to disk (lazyfs.clj:265-271)."""
    fifo(lz, "lazyfs::cache-checkpoint")
    return "done"


class LazyFSDB(jdb.DB):
    """Mount-wrapping DB: composes around (or stands alone beside) a
    database whose data lives in the lazyfs dir (lazyfs.clj DB record,
    227-244)."""

    def __init__(self, dir_or_map, inner: jdb.DB | None = None):
        self.lazyfs = lazyfs(dir_or_map)
        self.inner = inner

    def setup(self, test, node):
        install()
        mount(self.lazyfs)
        if self.inner is not None:
            self.inner.setup(test, node)

    def teardown(self, test, node):
        if self.inner is not None:
            self.inner.teardown(test, node)
        umount(self.lazyfs)

    def log_files(self, test, node):
        files = [self.lazyfs["log-file"]]
        if self.inner is not None:
            files += (self.inner.log_files(test, node) or [])
        return files

    # pass through Kill/Pause capability to the wrapped db
    @property
    def supports_kill(self):
        return self.inner is not None and self.inner.supports_kill

    @property
    def supports_pause(self):
        return self.inner is not None and self.inner.supports_pause

    def kill(self, test, node):
        out = (self.inner.kill(test, node)
               if self.inner is not None else None)
        # the interesting moment: process dead, page cache dropped
        lose_unfsynced_writes(self.lazyfs)
        return out

    def start(self, test, node):
        if self.inner is not None:
            return self.inner.start(test, node)

    def pause(self, test, node):
        if self.inner is not None:
            return self.inner.pause(test, node)

    def resume(self, test, node):
        if self.inner is not None:
            return self.inner.resume(test, node)


class LazyFSNemesis(jnemesis.Nemesis):
    """f=lose-unfsynced-writes over value=[node...] (lazyfs.clj
    `nemesis`, 273-295)."""

    def __init__(self, lz: dict):
        self.lazyfs = lazyfs(lz)

    def invoke(self, test, op):
        if op.f != "lose-unfsynced-writes":
            raise ValueError(f"unknown f {op.f!r}")
        nodes = op.value or test["nodes"]

        def one(t, node):
            return lose_unfsynced_writes(self.lazyfs)

        got = control.on_nodes(test, one, nodes)
        return op.copy(value=got)

    def fs(self):
        return {"lose-unfsynced-writes"}


def nemesis(lz) -> LazyFSNemesis:
    return LazyFSNemesis(lz)
