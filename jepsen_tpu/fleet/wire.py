"""The fleet wire protocol: CRC-framed JSON messages over a stream.

The store's jlog framing (store/format.py: [u32 len][u32 crc][payload])
applied to a socket. On disk a torn tail is dropped; on a stream a
torn or corrupt frame means the connection is unusable — the receiver
raises FrameError, the connection closes, and the CLIENT recovers by
reconnecting and resyncing from the server's acked sequence number.
Nothing is ever half-applied: a frame either passes its CRC whole or
the stream dies at that frame.

Messages are JSON dicts with a "type" key:

  client -> server
    hello   {tenant, run, model, weight}   open/resume a stream
    chunk   {seq, ops, tc?}                one batch of history ops
    fin     {chunks, tc?}                  stream complete; check it
    claim   {}                             wait for the run's verdict
    status  {}                             server + per-tenant stats

  server -> client
    helloed {last_seq, verdict?, latency?} admitted (resume point)
    reject  {reason, retry_after}          admission control said no
    ack     {seq}                          chunk journaled (WAL'd)
    verdict {result, latency?}             the run's verdict + cert
    stats   {...}                          status reply
    error   {reason}                       protocol violation

`tc` is the flight recorder's trace context (jepsen_tpu.fleet.
flightrec), minted by the client per (tenant, run, seq): {"t":
monotonic-ns send stamp, "trace"?: the run's optrace trace id}. It is
OPTIONAL and backward-compatible both ways — an old server ignores
it, an old client simply never sends it — so every chunk's lifecycle
links into one cross-process span without a protocol version bump.
`latency` rides NEXT to the verdict for the same reason: the verdict
file's bytes must stay timing-free (byte-identical crash replay).
"""

from __future__ import annotations

import json
import socket
import struct
import zlib

MAGIC = b"JTPUFLT1"
_HDR = struct.Struct("<II")
MAX_FRAME = 16 << 20  # one chunk of ops fits comfortably


class FrameError(Exception):
    """Torn/corrupt frame or dead peer: the connection is unusable."""


def frame_msg(msg: dict) -> bytes:
    payload = json.dumps(msg, separators=(",", ":"),
                         sort_keys=True).encode()
    if len(payload) > MAX_FRAME:
        # ValueError, not FrameError: retrying an oversized frame can
        # never succeed — the caller must split the chunk, not
        # reconnect (the retry layer only absorbs FrameError/OSError)
        raise ValueError(
            f"message too large ({len(payload)} > {MAX_FRAME} bytes);"
            " lower chunk_ops")
    return _HDR.pack(len(payload), zlib.crc32(payload)) + payload


def send_msg(sock: socket.socket, msg: dict) -> None:
    try:
        sock.sendall(frame_msg(msg))
    except OSError as e:
        raise FrameError(f"send failed: {e}") from e


def recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        try:
            part = sock.recv(n - len(buf))
        except OSError as e:
            raise FrameError(f"recv failed: {e}") from e
        if not part:
            raise FrameError("connection closed mid-frame")
        buf.extend(part)
    return bytes(buf)


def recv_msg(sock: socket.socket) -> dict:
    hdr = recv_exact(sock, _HDR.size)
    n, crc = _HDR.unpack(hdr)
    if n > MAX_FRAME:
        raise FrameError(f"frame too large ({n} bytes)")
    payload = recv_exact(sock, n)
    if zlib.crc32(payload) != crc:
        raise FrameError("frame CRC mismatch")
    try:
        msg = json.loads(payload)
    except ValueError as e:
        raise FrameError(f"frame not JSON: {e}") from e
    if not isinstance(msg, dict) or not isinstance(msg.get("type"),
                                                   str):
        raise FrameError("frame not a typed message")
    return msg


def send_magic(sock: socket.socket) -> None:
    try:
        sock.sendall(MAGIC)
    except OSError as e:
        raise FrameError(f"send failed: {e}") from e


def recv_magic(sock: socket.socket) -> None:
    if recv_exact(sock, len(MAGIC)) != MAGIC:
        raise FrameError("bad protocol magic")


# ---------------------------------------------------------------------------
# Op <-> wire round trip (the store codec's JSON view of an Op)
# ---------------------------------------------------------------------------

def ops_to_wire(ops) -> list[dict]:
    from ..store import format as fmt

    return [fmt.jsonable(o.to_dict() if hasattr(o, "to_dict") else o)
            for o in ops]


def ops_from_wire(ds: list) -> list:
    from ..history import op as make_op

    return [make_op(**d) for d in ds]
