"""The fleet client: streams a run's history chunks to the service
mid-run, survives transport chaos, and falls back to local checking.

Retry discipline is the control plane's (control/retry.py): transport
failures reconnect with DECORRELATED JITTER (a fleet of clients
hammering a restarting server must not arrive in waves) and spend a
per-stream RetryBudget — a genuinely dead fleet stops costing the run
anything beyond the budget, and the client honestly reports
`fallen_back` so the caller keeps its local checking authoritative.

Idempotence: chunks carry sequence numbers; the server acks a chunk
only after journaling it, duplicates re-ack without re-journaling, and
the hello handshake returns the server's resume point, so a client can
crash-reconnect-resend forever without the journaled stream ever
diverging. The client keeps its sent chunks until acked+resynced (op
payloads are already in the run's memory; the fleet copy is bounded by
the same run).

`transport` is injectable — jepsen_tpu.chaos.ChaosFleetTransport
wraps it to drop/duplicate/reorder/truncate frames with seeded
probabilities (doc/fleet.md, tests/test_fleet.py).
"""

from __future__ import annotations

import logging
import socket
import threading
import time

from .. import telemetry, tracing
from ..control.retry import RetryBudget, decorrelated_jitter
from ..monitor import LogHistogram
from . import flightrec as frec
from . import wire

logger = logging.getLogger(__name__)

CONNECT_TIMEOUT_S = 5.0
IO_TIMEOUT_S = 15.0
DEFAULT_CHUNK_OPS = 64
RETRIES_PER_OP = 5


class FleetError(Exception):
    """The fleet is unusable for this stream (budget exhausted,
    rejected without retry-after, protocol violation)."""


class FleetRejected(FleetError):
    """Admission control said no. retry_after is the server's backoff
    hint (None = don't retry: the request itself was invalid)."""

    def __init__(self, reason: str, retry_after):
        super().__init__(reason)
        self.reason = reason
        self.retry_after = retry_after


class Transport:
    """The frame I/O seam. The default sends/receives wire frames
    verbatim; chaos wraps this interface."""

    def send(self, sock, msg: dict) -> None:
        wire.send_msg(sock, msg)

    def recv(self, sock) -> dict:
        return wire.recv_msg(sock)


class FleetClient:
    """One (tenant, run) stream. NOT thread-safe: the run's streamer
    owns it from one thread (the interpreter hook uses a dedicated
    flusher thread)."""

    def __init__(self, addr, tenant: str, run: str,
                 model: str = "cas-register", initial=None,
                 weight: float = 1.0,
                 transport: Transport | None = None,
                 budget: RetryBudget | None = None,
                 io_timeout_s: float = IO_TIMEOUT_S,
                 observe: bool = False,
                 connect_timeout_s: float = CONNECT_TIMEOUT_S):
        if isinstance(addr, str):
            host, _, port = addr.rpartition(":")
            addr = (host or "127.0.0.1", int(port))
        self.addr = tuple(addr)
        self.tenant = tenant
        self.run = run
        self.model = model
        self.initial = initial  # register-family starting value
        self.weight = weight
        self.transport = transport if transport is not None \
            else Transport()
        self.budget = budget if budget is not None else RetryBudget()
        self.io_timeout_s = io_timeout_s
        self.observe = observe  # status-only: no run state, no WAL
        self.connect_timeout_s = connect_timeout_s
        self._sock: socket.socket | None = None
        self._chunks: list[list[dict]] = []  # payloads by seq-1
        self._acked = 0
        self._pending_failed = False  # last send_chunk raised
        self._claim_only = False      # claim(): resume is expected
        self.last_verdict: dict | None = None
        # client-observed ack latency (send -> journaled ack), the
        # tenant's half of the fleet SLO story; rides into
        # results['fleet'] via FleetStreamer.result_summary
        self.ack_ms = LogHistogram()
        self.last_latency: dict | None = None  # server's block

    # -- connection ------------------------------------------------------

    def _connect(self) -> None:
        self._disconnect()
        s = socket.create_connection(self.addr,
                                     timeout=self.connect_timeout_s)
        s.settimeout(self.io_timeout_s)
        try:
            wire.send_magic(s)
            hello = {"type": "hello", "tenant": self.tenant,
                     "run": self.run, "model": self.model,
                     "weight": self.weight}
            if self.initial is not None:
                hello["initial"] = self.initial
            if self.observe:
                hello["observe"] = True
            self.transport.send(s, hello)
            reply = self.transport.recv(s)
        except wire.FrameError:
            try:
                s.close()
            except OSError:
                pass
            raise
        if reply["type"] == "reject":
            try:
                s.close()
            except OSError:
                pass
            telemetry.count("fleet.client.rejected")
            raise FleetRejected(reply.get("reason", "rejected"),
                                reply.get("retry_after"))
        if reply["type"] != "helloed":
            try:
                s.close()
            except OSError:
                pass
            raise FleetError(f"unexpected hello reply {reply!r}")
        self._sock = s
        # the server's resume point: everything at or below is
        # durable. It can never exceed what THIS client sent — more
        # journaled chunks mean the run name collides with an older
        # stream, and silently treating its journal as our acks would
        # return a verdict computed on someone else's data.
        srv_seq = int(reply.get("last_seq", 0))
        if isinstance(reply.get("verdict"), dict) \
                and isinstance(reply.get("latency"), dict):
            self.last_latency = reply["latency"]
        if not self.observe and not self._claim_only \
                and srv_seq > len(self._chunks):
            self._disconnect()
            raise FleetError(
                f"run {self.run!r} already has {srv_seq} journaled "
                f"chunk(s) on the server (we sent "
                f"{len(self._chunks)}): stale or colliding run name "
                "— pick a fresh one, or use claim() to fetch the "
                "existing verdict")
        self._acked = srv_seq
        if isinstance(reply.get("verdict"), dict):
            self.last_verdict = reply["verdict"]

    def _disconnect(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _with_retry(self, f):
        """Runs f() against a live connection, reconnecting + resyncing
        on transport failure with decorrelated jitter, bounded by the
        stream's RetryBudget. FleetRejected propagates — admission
        rejections are decisions, not failures."""
        tries = RETRIES_PER_OP
        sleep_s = 0.0
        while True:
            try:
                if self._sock is None:
                    self._connect()
                return f()
            except (wire.FrameError, OSError, socket.timeout) as e:
                self._disconnect()
                tries -= 1
                if tries <= 0 or not self.budget.try_spend():
                    telemetry.count("fleet.client.gave-up")
                    raise FleetError(
                        f"fleet unreachable: {e}") from e
                telemetry.count("fleet.client.retries")
                sleep_s = decorrelated_jitter(sleep_s or 0.05,
                                              base_s=0.05, cap_s=1.0)
                time.sleep(sleep_s)

    # -- the stream ------------------------------------------------------

    def send_chunk(self, ops) -> int:
        """Frames `ops` as the next chunk and drives the stream until
        the server has ACKED (journaled) it. Returns the chunk's seq.

        Retry-safe: a failed send leaves the chunk staged (the server
        may already have journaled it — only its seq can dedup it), so
        a caller retrying the SAME ops resumes that chunk instead of
        double-journaling it under a new seq."""
        payload = wire.ops_to_wire(ops)
        if self._pending_failed and self._chunks \
                and self._chunks[-1] == payload:
            self._pending_failed = False  # the caller's retry
        else:
            self._chunks.append(payload)
            self._pending_failed = False
        seq = len(self._chunks)
        try:
            self._with_retry(lambda: self._drive_to(seq))
        except FleetError:
            self._pending_failed = True
            raise
        self.budget.refund()  # the fleet answered: it is alive
        return seq

    def _tc(self) -> dict:
        """The flight-recorder trace context minted per frame: the
        send stamp on the cross-process monotonic clock, plus the
        caller's optrace span ids when it is inside one — the link
        that joins server-side flight-recorder spans back to the
        run's own trace (wire.py documents the field)."""
        tc = {"t": frec.now()}
        ctx = tracing.current_context()
        if ctx:
            tc.update(ctx)
        return tc

    def _drive_to(self, seq: int) -> None:
        """Sends chunks (self._acked, seq] and consumes acks until the
        server's journal covers seq, rewinding on resync acks."""
        while self._acked < seq:
            nxt = self._acked + 1
            tc = self._tc()
            self.transport.send(self._sock, {
                "type": "chunk", "seq": nxt,
                "ops": self._chunks[nxt - 1], "tc": tc})
            reply = self.transport.recv(self._sock)
            t = reply.get("type")
            if t == "ack":
                acked = int(reply.get("seq", 0))
                if acked >= nxt:
                    # a real advance (not a resync rewind): the
                    # durability promise's round trip, client-side
                    self.ack_ms.add((frec.now() - tc["t"]) / 1e6)
                # a resync ack rewinds; a normal ack advances. Either
                # way the server's number is the truth.
                self._acked = min(max(acked, 0), len(self._chunks))
            elif t == "reject":
                raise FleetRejected(reply.get("reason", "rejected"),
                                    reply.get("retry_after"))
            else:
                raise wire.FrameError(f"unexpected reply {reply!r}")

    def finish(self, timeout_s: float = 120.0) -> dict:
        """Completes the stream and returns the run's verdict (with
        certificate). Reconnect-safe: a lost verdict reply is
        re-claimed on a fresh connection."""
        deadline = time.monotonic() + timeout_s

        def once():
            self._drive_to(len(self._chunks))
            self.transport.send(self._sock, {
                "type": "fin", "chunks": len(self._chunks),
                "tc": self._tc()})
            reply = self.transport.recv(self._sock)
            if reply.get("type") == "ack" and reply.get("resync"):
                raise wire.FrameError("fin resync")  # rewind + retry
            if reply.get("type") != "verdict":
                raise wire.FrameError(
                    f"unexpected fin reply {reply!r}")
            if isinstance(reply.get("latency"), dict):
                self.last_latency = reply["latency"]
            return reply["result"]

        while True:
            try:
                v = self._with_latency(self._with_retry(once))
                self.last_verdict = v
                self.budget.refund()
                return v
            except FleetRejected as e:
                # an admission DECISION: retry only when the server
                # says so (retry_after None = permanently invalid)
                if e.retry_after is None \
                        or time.monotonic() >= deadline:
                    raise
                time.sleep(min(float(e.retry_after),
                               max(deadline - time.monotonic(), 0)))
            except FleetError:
                if time.monotonic() >= deadline:
                    raise
                time.sleep(0.2)

    def claim(self) -> dict:
        """Fetches (waiting if needed) an already-streamed run's
        verdict without re-driving the stream — the recovery/CLI path:
        a fresh client can claim what a crashed one streamed (the
        one legitimate case where the server knows MORE chunks than
        this client ever sent)."""

        def once():
            self.transport.send(self._sock, {"type": "claim"})
            reply = self.transport.recv(self._sock)
            if reply.get("type") != "verdict":
                raise wire.FrameError(
                    f"unexpected claim reply {reply!r}")
            if isinstance(reply.get("latency"), dict):
                self.last_latency = reply["latency"]
            return reply["result"]

        self._claim_only = True
        try:
            v = self._with_latency(self._with_retry(once))
        finally:
            self._claim_only = False
        self.last_verdict = v
        return v

    def _with_latency(self, v):
        """Attaches the verdict's latency block (flightrec critical-
        path decomposition, ridden next to the verdict on the wire)
        onto a COPY of the returned env — the server's verdict dict
        itself stays exactly the verdict file's content."""
        if isinstance(v, dict) and "latency" not in v \
                and isinstance(self.last_latency, dict):
            v = dict(v)
            v["latency"] = self.last_latency
        return v

    def status(self) -> dict:
        return self._with_retry(self._status_once)

    def _status_once(self) -> dict:
        self.transport.send(self._sock, {"type": "status"})
        reply = self.transport.recv(self._sock)
        if reply.get("type") != "stats":
            raise wire.FrameError(f"unexpected reply {reply!r}")
        return reply["stats"]

    def close(self) -> None:
        self._disconnect()


# ---------------------------------------------------------------------------
# The interpreter hook: mirror a live run's history into the fleet
# ---------------------------------------------------------------------------

class FleetStreamer:
    """Wraps the run's history writer: every journaled op ALSO streams
    to the fleet in chunks, from a background flusher thread so the
    interpreter's hot loop never blocks on the network. If the fleet
    becomes unreachable (budget exhausted) the streamer falls back —
    the local run continues untouched and the results carry an honest
    `unavailable` marker instead of a verdict. Local checking stays
    authoritative either way; the fleet verdict (and its certificate)
    rides NEXT to it as results['fleet']."""

    _guarded_by_lock = {"_lock": ("_buf", "_fallen")}

    def __init__(self, inner, client: FleetClient,
                 chunk_ops: int = DEFAULT_CHUNK_OPS,
                 flush_s: float = 0.25):
        self.inner = inner
        self.client = client
        self.chunk_ops = chunk_ops
        self.flush_s = flush_s
        self._lock = threading.Lock()
        self._buf: list = []
        self._fallen: str | None = None
        self._closed = threading.Event()
        self._thread = threading.Thread(target=self._flusher,
                                        name="fleet-streamer",
                                        daemon=True)
        self._started = False

    # the history-writer interface (interpreter.run)
    def append(self, op) -> None:
        self.inner.append(op)
        if self._fallen is not None:
            return
        if not self._started:
            self._started = True
            self._thread.start()
        with self._lock:
            self._buf.append(op)

    def close(self) -> None:
        self._closed.set()
        if self._started:
            self._thread.join(timeout=30)
        self.inner.close()

    def read_back(self):
        return self.inner.read_back()

    # -- flusher ---------------------------------------------------------

    # upper bound on ops per wire chunk: a backlog accumulated while
    # the flusher was stuck reconnecting must drain as several frames,
    # not one frame that trips wire.MAX_FRAME and kills the stream
    MAX_TAKE = 8192

    def _take(self, everything: bool = False) -> list:
        with self._lock:
            if not self._buf:
                return []
            if everything or len(self._buf) >= self.chunk_ops:
                out = self._buf[:self.MAX_TAKE]
                self._buf = self._buf[self.MAX_TAKE:]
                return out
            return []

    def _flusher(self) -> None:
        while not self._closed.wait(timeout=self.flush_s):
            self._flush_some(False)
        self._flush_some(True)  # the tail rides out at close

    def _flush_some(self, everything: bool) -> None:
        while True:
            ops = self._take(everything)
            if not ops or self._fallen is not None:
                return
            try:
                self.client.send_chunk(ops)
            except Exception as e:  # noqa: BLE001 — the stream is
                # advisory: ANY failure falls back to local checking
                with self._lock:
                    self._fallen = str(e)[:200]
                telemetry.count("fleet.client.fallback")
                logger.warning("fleet unreachable; falling back to "
                               "local checking: %s", e)
                return
            if len(ops) < self.MAX_TAKE:
                return  # backlog drained

    @property
    def fallen_back(self) -> str | None:
        with self._lock:
            return self._fallen

    def result_summary(self, timeout_s: float = 60.0) -> dict:
        """The results['fleet'] block: the fleet's verdict or an
        honest unavailability marker."""
        if self.fallen_back is not None:
            self.client.close()
            return {"unavailable": self.fallen_back}
        try:
            v = self.client.finish(timeout_s=timeout_s)
            out = {"verdict": v, "addr": list(self.client.addr),
                   "tenant": self.client.tenant}
            h = self.client.ack_ms
            if h.n:  # the client's own view of the durability SLO
                out["ack_ms"] = {"n": h.n,
                                 "p50": round(h.quantile(0.5), 3),
                                 "p99": round(h.quantile(0.99), 3)}
            return out
        except Exception as e:  # noqa: BLE001 — honest absence
            return {"unavailable": str(e)[:200]}
        finally:
            self.client.close()  # one socket per run, never leaked


class NoStream:
    """The honest stand-in when fleet streaming was REQUESTED but
    could not be attached (no history writer, attach crash): the run
    still gets results['fleet'] = {'unavailable': reason} instead of
    silently missing the key."""

    def __init__(self, reason: str):
        self.reason = reason

    def result_summary(self, timeout_s: float = 0.0) -> dict:
        return {"unavailable": self.reason}


def attach(test: dict):
    """Builds the interpreter hook from test['fleet'] (a dict: addr,
    tenant, model?, run?, weight?, chunk_ops?) and wraps the test's
    history writer. Returns (writer, streamer)."""
    from . import wal as fwal

    cfg = dict(test.get("fleet") or {})
    inner = test.get("history_writer")
    assert inner is not None, "fleet streaming needs a history writer"
    run = str(cfg.get("run") or test.get("name", "run"))
    if not fwal.safe_name(run):  # run names come from test names
        run = "".join(c if c.isalnum() or c in "._-" else "-"
                      for c in run)[:128] or "run"
    client = FleetClient(
        cfg["addr"], cfg.get("tenant", "local"), run,
        model=cfg.get("model", "cas-register"),
        initial=cfg.get("initial"),
        weight=float(cfg.get("weight", 1.0)))
    streamer = FleetStreamer(inner, client,
                             chunk_ops=int(cfg.get("chunk_ops",
                                                   DEFAULT_CHUNK_OPS)))
    return streamer, streamer
