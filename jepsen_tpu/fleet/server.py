"""The fleet server: a persistent, crash-safe, multi-tenant checking
service.

Lifecycle of one tenant stream:

  hello   admission control decides: admitted streams get a resume
          point (last journaled seq, so a reconnecting client re-sends
          only what the crash lost); saturated quotas get a `reject`
          with retry-after — NEW streams are shed, in-flight ones are
          never degraded
  chunk   the frame is CRC-checked by the wire layer, journaled to the
          run's WAL, and only THEN acked — the ack is a durability
          promise a SIGKILL cannot revoke
  fin     the completed history is submitted to the scheduler, which
          packs it with other tenants' work into shared device
          launches; the verdict (with its certificate) is written
          atomically to the verdict file, then sent
  claim   a reconnecting client (or a cold CLI) waits for / fetches an
          already-computed verdict

Crash recovery (`recover()`, run at every start): the WAL directory is
the source of truth. Runs with a journaled fin and no verdict file are
re-submitted; runs mid-stream restore their resume point and keep
accepting chunks. Because verdict serialization is deterministic and
analysis is seeded by the journaled bytes alone, a replayed verdict
file is byte-identical to the one the crash interrupted.

kill() is the test hook for SIGKILL-equivalence: it abandons all
state without flushing or handshaking (WAL appends are already on
disk per-ack, which is the point).
"""

from __future__ import annotations

import logging
import os
import socket
import threading
import time
from pathlib import Path

from .. import telemetry
from ..history import History
from ..tpu import ckpt as tckpt
from . import elle_checks, wgl_models, wire
from . import flightrec as frec
from . import scheduler as fsched
from . import wal as fwal

logger = logging.getLogger(__name__)

ADDR_FILE = "fleet.addr"
VERDICT_TIMEOUT_S = 300.0
# an unfinished run with no live connection and no ingest for this
# long is abandoned: it stops counting against the tenant quota (its
# WAL stays — a late reconnect still resumes it)
ABANDONED_S = 900.0


class Quotas:
    """Admission-control knobs (doc/fleet.md). Defaults size the demo
    pool: 8 concurrent tenants, so the 9th is REJECTED (with
    retry-after) rather than letting overload degrade anyone already
    admitted."""

    def __init__(self, max_tenants: int = 8,
                 max_streams_per_tenant: int = 4,
                 max_total_streams: int = 16,
                 max_ops_per_run: int = 2_000_000,
                 retry_after_s: float = 2.0):
        self.max_tenants = max_tenants
        self.max_streams_per_tenant = max_streams_per_tenant
        self.max_total_streams = max_total_streams
        self.max_ops_per_run = max_ops_per_run
        self.retry_after_s = retry_after_s


class RunState:
    """One (tenant, run) stream. Ingest (chunk/fin) is serialized
    under `lock` — reconnects may hand the run to a new handler thread
    while a half-dead one lingers, and the WAL append + seq advance
    must stay atomic."""

    _guarded_by_lock = {"lock": ("last_seq", "n_ops", "fin",
                                 "verdict", "wal", "t_first", "t_fin",
                                 "wal_ns", "latency", "cum_ops")}

    def __init__(self, tenant: str, run: str, model: str,
                 wal: fwal.RunWAL | None, stream=None, initial=None):
        self.tenant = tenant
        self.run = run
        self.model = model
        self.initial = initial
        self.wal = wal  # None once complete (no fd squatting)
        self.stream = stream  # StreamingRun | None
        self.lock = threading.Lock()
        self.last_seq = 0
        self.n_ops = 0
        # seq -> cumulative op count through that seq: the map that
        # turns a checkpoint's raw-op cut into the highest WAL seq
        # safe to compact through
        self.cum_ops: dict[int, int] = {}
        self.fin = False
        self.touched = time.monotonic()  # last hello/ingest
        self.verdict: dict | None = None
        self.verdict_ready = threading.Event()
        # flight-recorder stamps (frec.now() ns / accumulated append
        # ns). These feed the verdict's latency block — which rides
        # the wire NEXT to the verdict, never inside the verdict file
        # (the byte-identical replay contract).
        self.t_first: int | None = None  # first journaled chunk
        self.t_fin: int | None = None    # fin journaled
        self.wal_ns = 0                  # summed WAL-append wall
        self.latency: dict | None = None

    def retire_wal(self) -> None:
        """Closes the WAL fd once the run can never append again (fin
        + verdict): a long-lived server over thousands of past runs
        must not hold one fd per historical run."""
        with self.lock:
            wal, self.wal = self.wal, None
        if wal is not None:
            wal.close()


def prometheus_from_stats(st: dict) -> str:
    """Prometheus text exposition of a fleet stats dict — per-tenant
    labels on the tenant series, appended to the web /metrics scrape
    (which fetches the stats over the wire, so a scraper needs no
    in-process server handle)."""
    lines = []

    def g(name, value, labels=""):
        lines.append(f"jepsen_fleet_{name}{labels} {value}")

    for k in ("accepted", "rejected", "chunks", "ops", "verdicts",
              "recovered", "frame_errors", "runs", "active_streams"):
        g(k, st.get(k, 0))
    g("wal_sheds", st.get("wal_sheds", 0))
    sch = st.get("scheduler") or {}
    for k in ("launches", "slice_launches", "final_launches",
              "items", "slice_rows", "final_hists",
              "cross_tenant_launches", "pending",
              "quarantine_items", "bisect_launches"):
        g(f"scheduler_{k}", sch.get(k, 0))
    g("quarantined_runs", len(sch.get("quarantine") or []))
    for tenant, ts in sorted((st.get("tenants") or {}).items()):
        lab = '{tenant="%s"}' % tenant
        for k in ("streams", "chunks", "ops", "verdicts",
                  "rejected"):
            g(f"tenant_{k}", ts.get(k, 0), lab)
    # flight-recorder series (jepsen_tpu.fleet.flightrec): SLO
    # quantiles fleet-wide and per tenant, per-class occupancy, the
    # scheduler decision log, device idle. Every sample here must
    # pass flightrec.validate_prometheus (tests gate it).
    fr = st.get("flightrec") or {}
    if fr.get("enabled"):
        def quants(name, qd, extra=""):
            for q in ("p50", "p95", "p99"):
                v = (qd or {}).get(q)
                if isinstance(v, (int, float)):
                    g(name, v, '{%sq="%s"}' % (extra, q))

        quants("verdict_latency_ms", fr.get("verdict_ms"))
        quants("ack_latency_ms", fr.get("ack_ms"))
        for tenant, td in sorted((fr.get("tenants") or {}).items()):
            quants("tenant_verdict_latency_ms",
                   td.get("verdict_ms"), f'tenant="{tenant}",')
            quants("tenant_ack_latency_ms",
                   td.get("ack_ms"), f'tenant="{tenant}",')
        for cls, cd in sorted((fr.get("classes") or {}).items()):
            lab = '{cls="%s"}' % cls
            g("class_launches", cd.get("launches", 0), lab)
            g("class_rows", cd.get("rows", 0), lab)
            g("class_occupancy", cd.get("occupancy", 0.0), lab)
        for reason, n in sorted((fr.get("decisions") or {}).items()):
            g("decisions_total", n, '{reason="%s"}' % reason)
        for action, n in sorted((fr.get("quarantine") or {}).items()):
            g("quarantine_events_total", n, '{action="%s"}' % action)
        idle = fr.get("idle") or {}
        g("device_idle_ms_total", idle.get("total_ms", 0.0))
        g("device_idle_gaps", idle.get("gaps", 0))
    return "\n".join(lines) + "\n"


class FleetServer:
    _guarded_by_lock = {"_lock": ("_runs", "_active", "_stats",
                                  "_conns")}

    def __init__(self, base, host: str = "127.0.0.1", port: int = 0,
                 quotas: Quotas | None = None,
                 scheduler: fsched.Scheduler | None = None,
                 stream_checks: bool = True,
                 flightrec: bool = True):
        self.base = Path(base)
        self.host = host
        self.port = port
        self.quotas = quotas if quotas is not None else Quotas()
        self.scheduler = scheduler if scheduler is not None \
            else fsched.Scheduler()
        self.stream_checks = stream_checks
        # the flight recorder is shared with the scheduler (its
        # launch/decision records land in the same session); disabled
        # it costs nothing (bench.py prices the delta)
        self.flightrec = frec.FlightRecorder(enabled=bool(flightrec))
        if self.scheduler.flightrec is None and flightrec:
            self.scheduler.flightrec = self.flightrec
        self._lock = threading.Lock()
        self._runs: dict[tuple[str, str], RunState] = {}
        self._active: dict[tuple[str, str], int] = {}  # open streams
        self._stats: dict = {"accepted": 0, "rejected": 0,
                             "chunks": 0, "ops": 0, "verdicts": 0,
                             "recovered": 0, "frame_errors": 0,
                             "tenants": {}}
        self._sock: socket.socket | None = None
        self._conns: set = set()  # accepted sockets (for kill/stop)
        self._accept_thread: threading.Thread | None = None
        self._stopping = threading.Event()
        self._killed = False

    # -- lifecycle -------------------------------------------------------

    @property
    def addr(self) -> tuple[str, int]:
        assert self._sock is not None, "server not started"
        return self._sock.getsockname()[:2]

    def start(self) -> "FleetServer":
        self.base.mkdir(parents=True, exist_ok=True)
        # fold the previous incarnation's SLO histograms BEFORE
        # recovery, so replayed verdicts land on restored history
        self.flightrec.load(self.base / frec.SNAPSHOT_FILE)
        self.recover()
        self.scheduler.start()
        s = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        # a restarted server must re-bind its advertised port while
        # the killed instance's connections are still draining
        # (FIN_WAIT sockets held open by clients that are about to
        # reconnect); REUSEADDR alone doesn't cover those on Linux
        if hasattr(socket, "SO_REUSEPORT"):
            try:
                s.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEPORT,
                             1)
            except OSError:  # pragma: no cover — platform quirk
                pass
        s.bind((self.host, self.port))
        s.listen(64)
        self._sock = s
        self._stopping.clear()
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name="fleet-accept", daemon=True)
        self._accept_thread.start()
        host, port = self.addr
        (self.base / ADDR_FILE).write_text(
            f"{host}:{port}\n{os.getpid()}\n")
        logger.info("fleet server on %s:%d (base %s)", host, port,
                    self.base)
        return self

    def stop(self) -> None:
        """Graceful: stop accepting, let the scheduler drain, retire
        the addr file."""
        self._stopping.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self.scheduler.stop()
        self._close_conns()
        with self._lock:
            runs = list(self._runs.values())
        for r in runs:
            if r.wal is not None:
                r.wal.close()
        try:
            (self.base / ADDR_FILE).unlink()
        except OSError:
            pass

    def kill(self) -> None:
        """SIGKILL-equivalent for tests: abandon everything. No WAL
        flush (appends are already on disk — that's the contract), no
        scheduler drain, no addr-file cleanup, connections die
        mid-frame (a killed process's fds ALL close — and the port
        must be immediately re-bindable by the restarted server)."""
        self._killed = True
        self._stopping.set()
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
        self._close_conns()
        # scheduler threads are daemons; in-flight items are abandoned
        # exactly as a real SIGKILL would abandon them
        self.scheduler._stop.set()

    def _close_conns(self) -> None:
        with self._lock:
            conns = list(self._conns)
            self._conns.clear()
        for c in conns:
            try:
                c.close()
            except OSError:
                pass

    # -- streaming checkpoints (checkpoint-and-extend) -------------------

    def _make_ckpt_sink(self, rs: RunState):
        """The StreamingRun's checkpoint sink: atomically persist the
        stream-wgl record, then compact the WAL through the highest
        seq the certified raw-op cut covers — acked bytes before the
        cut no longer need one-by-one replay. Both steps are
        best-effort: a durability fault degrades resume cost, never
        verdicts (and never the ack path — this runs on the stream's
        worker thread)."""
        path = tckpt.fleet_path(self.base, rs.tenant, rs.run)

        def sink(rec: dict) -> None:
            if not tckpt.try_write(path, rec):
                return  # counted in ckpt; stale-but-valid file wins
            cut = rec.get("n_ops", 0)
            with rs.lock:
                through = max(
                    (s for s, c in rs.cum_ops.items() if c <= cut),
                    default=0)
                if through and rs.wal is not None:
                    # compact_through closes/reopens the append fd, so
                    # it must hold the same lock that serializes
                    # appends; the rewrite itself is atomic
                    try:
                        rs.wal.compact_through(through)
                    except OSError:
                        logger.exception("WAL compaction failed")

        return sink

    def _attach_stream(self, rs: RunState):
        if rs.model in wgl_models():
            stream = fsched.StreamingRun(rs.model, self.scheduler,
                                         rs.tenant, rs.run,
                                         initial=rs.initial)
        else:
            from ..tpu import elle as telle

            stream = telle.StreamingElle(rs.model, rs.tenant, rs.run)
        stream.ckpt_sink = self._make_ckpt_sink(rs)
        return stream

    # -- crash recovery --------------------------------------------------

    def recover(self) -> int:
        """Replays every WAL under the base dir; re-submits finished
        runs that never got their verdict written. Returns how many
        verdicts were re-scheduled."""
        n = 0
        for tenant, run, path in fwal.scan_runs(self.base):
            folded = fwal.replay(path)
            hello = folded["hello"] or {}
            model = hello.get("model", "cas-register")
            verdict = fwal.read_verdict(self.base, tenant, run)
            # complete runs are served from their verdict file: no
            # appends can ever happen, so no WAL fd is held (a long
            # base dir of past runs must not exhaust the fd table)
            wal = None if verdict is not None else fwal.RunWAL(path)
            rs = RunState(tenant, run, model, wal,
                          initial=hello.get("initial"))
            rs.last_seq = folded["last_seq"]
            base = folded["base"]
            cum = len(base["ops"]) if base else 0
            if base:
                rs.cum_ops[base["seq"]] = cum
            floor = base["seq"] if base else 0
            for seq in range(floor + 1, folded["last_seq"] + 1):
                cum += len(folded["chunks"][seq])
                rs.cum_ops[seq] = cum
            rs.n_ops = cum
            rs.fin = folded["fin"] is not None
            if not rs.fin and verdict is None and self.stream_checks \
                    and (model in wgl_models()
                         or model in elle_checks()):
                # mid-stream crash: resume the live stream from its
                # last checkpoint (digest-verified against the
                # replayed ops) instead of re-checking from entry 0;
                # a stale/torn/absent checkpoint falls back to full
                rs.stream = self._attach_stream(rs)
                kind = "stream-wgl" if model in wgl_models() \
                    else "elle"
                rs.stream.seed(
                    fwal.replay_ops(folded),
                    tckpt.load(tckpt.fleet_path(self.base, tenant,
                                                run), kind))
                rs.stream.step()
            if verdict is not None:
                rs.verdict = verdict
                # a recovered-from-file verdict still carries a
                # complete latency block — replay-annotated, every
                # slice honestly zero (its timings died with the
                # crashed process)
                if self.flightrec.enabled:
                    rs.latency = frec.replay_block()
                rs.verdict_ready.set()
            with self._lock:
                self._runs[(tenant, run)] = rs
            if rs.fin and verdict is None:
                ops = fwal.replay_ops(folded)
                self._submit_final(rs, ops, replay=True)
                n += 1
                with self._lock:
                    self._stats["recovered"] += 1
        if n:
            logger.info("fleet recovery: re-scheduled %d verdict(s)",
                        n)
        return n

    # -- stats / metrics -------------------------------------------------

    def _tstat_locked(self, tenant: str) -> dict:
        t = self._stats["tenants"].get(tenant)
        if t is None:
            t = self._stats["tenants"][tenant] = {
                "streams": 0, "chunks": 0, "ops": 0, "verdicts": 0,
                "rejected": 0}
        return t

    def stats(self) -> dict:
        with self._lock:
            out = {k: (dict(v) if isinstance(v, dict) else v)
                   for k, v in self._stats.items()}
            out["tenants"] = {t: dict(s)
                              for t, s in self._stats["tenants"].items()}
            out["runs"] = len(self._runs)
            out["active_streams"] = sum(self._active.values())
            streams = [(f"{t}/{r}", rs.stream)
                       for (t, r), rs in self._runs.items()]
        # snapshot first: a handler may null rs.stream concurrently
        streaming = {k: s.status() for k, s in streams
                     if s is not None}
        out["streams"] = streaming
        out["scheduler"] = self.scheduler.stats()
        out["flightrec"] = self.flightrec.snapshot()
        return out

    def prometheus_text(self) -> str:
        return prometheus_from_stats(self.stats())

    # -- accept / connection handling ------------------------------------

    def _accept_loop(self) -> None:
        while not self._stopping.is_set():
            try:
                conn, _peer = self._sock.accept()
            except OSError:
                return  # closed
            threading.Thread(target=self._handle, args=(conn,),
                             name="fleet-conn", daemon=True).start()

    def _handle(self, conn: socket.socket) -> None:
        conn.settimeout(60.0)
        with self._lock:
            self._conns.add(conn)
        rs: RunState | None = None
        streams_key = None
        try:
            wire.recv_magic(conn)
            while not self._stopping.is_set():
                msg = wire.recv_msg(conn)
                t = msg["type"]
                if t == "hello" and msg.get("observe"):
                    # an observer (status CLI, the web scraper): no
                    # admission, no run state, no WAL — just a live
                    # socket for status/claim-free queries
                    wire.send_msg(conn, {"type": "helloed",
                                         "last_seq": 0})
                elif t == "hello":
                    rs, streams_key = self._hello(conn, msg,
                                                  streams_key)
                    if rs is None:
                        return  # rejected (reply already sent)
                elif t == "status":
                    wire.send_msg(conn, {"type": "stats",
                                         "stats": self.stats()})
                elif rs is None:
                    wire.send_msg(conn, {"type": "error",
                                         "reason": "hello first"})
                    return
                elif t == "chunk":
                    self._chunk(conn, rs, msg)
                elif t == "fin":
                    self._fin(conn, rs, msg)
                elif t == "claim":
                    self._claim(conn, rs)
                else:
                    wire.send_msg(conn, {"type": "error",
                                         "reason": f"bad type {t!r}"})
                    return
        except wire.FrameError:
            # torn/corrupt frame or dead peer: the client's retry
            # layer resyncs from its acked seq on a fresh connection
            with self._lock:
                self._stats["frame_errors"] += 1
            telemetry.count("fleet.frame-errors")
        except Exception:  # noqa: BLE001 — one conn never kills the server
            logger.exception("fleet connection handler failed")
        finally:
            with self._lock:
                self._conns.discard(conn)
                if streams_key is not None:
                    n = self._active.get(streams_key, 0)
                    if n <= 1:
                        self._active.pop(streams_key, None)
                    else:
                        self._active[streams_key] = n - 1
            try:
                conn.close()
            except OSError:
                pass

    # -- message handlers ------------------------------------------------

    def _hello(self, conn, msg, prev_key):
        tenant = msg.get("tenant")
        run = msg.get("run")
        model = msg.get("model", "cas-register")
        initial = msg.get("initial")
        if initial is not None and not isinstance(
                initial, (int, float, str, bool)):
            wire.send_msg(conn, {"type": "reject",
                                 "reason": "initial must be a JSON "
                                           "scalar",
                                 "retry_after": None})
            return None, prev_key
        if not (fwal.safe_name(tenant) and fwal.safe_name(run)):
            wire.send_msg(conn, {"type": "reject",
                                 "reason": "bad tenant/run name",
                                 "retry_after": None})
            return None, prev_key
        if model not in wgl_models() and model not in elle_checks():
            wire.send_msg(conn, {"type": "reject",
                                 "reason": f"unknown model {model!r}",
                                 "retry_after": None})
            return None, prev_key
        key = (tenant, run)
        with self._lock:
            rs = self._runs.get(key)
            resuming = rs is not None
            if not resuming:
                # admission control: shed NEW streams only
                reason = self._admit_locked(tenant)
                if reason is not None:
                    self._stats["rejected"] += 1
                    self._tstat_locked(tenant)["rejected"] += 1
                    telemetry.count("fleet.rejected")
                    wire.send_msg(
                        conn, {"type": "reject", "reason": reason,
                               "retry_after":
                                   self.quotas.retry_after_s})
                    return None, prev_key
            if prev_key != key:
                if prev_key is not None:  # re-hello moved streams
                    n = self._active.get(prev_key, 0)
                    if n <= 1:
                        self._active.pop(prev_key, None)
                    else:
                        self._active[prev_key] = n - 1
                self._active[key] = self._active.get(key, 0) + 1
            self._stats["accepted"] += 1
            ts = self._tstat_locked(tenant)
            if not resuming:
                ts["streams"] += 1
        if rs is None:
            weight = msg.get("weight")
            with self._lock:
                # WAL creation + registration are ONE atomic step:
                # two racing first-hellos must not both see a fresh
                # file (each would append its own magic, corrupting
                # every later record) — only the registration winner
                # opens the WAL, so there is exactly one creator
                rs = self._runs.get(key)
                if rs is None:
                    wal = fwal.RunWAL(
                        fwal.wal_path(self.base, tenant, run))
                    rs = RunState(tenant, run, model, wal,
                                  initial=initial)
                    if self.stream_checks:
                        rs.stream = self._attach_stream(rs)
                    hello_rec = {"t": "hello", "tenant": tenant,
                                 "run": run, "model": model,
                                 "weight": weight or 1.0}
                    if initial is not None:
                        hello_rec["initial"] = initial
                    rs.wal.append(hello_rec)
                    self._runs[key] = rs
            if isinstance(weight, (int, float)) and weight > 0:
                self.scheduler.set_weight(tenant, weight)
        rs.touched = time.monotonic()
        reply = {"type": "helloed", "last_seq": rs.last_seq}
        if rs.verdict is not None:
            reply["verdict"] = rs.verdict
            if rs.latency is not None:
                reply["latency"] = rs.latency
        wire.send_msg(conn, reply)
        return rs, key

    def _admit_locked(self, tenant: str) -> str | None:
        """Reason to reject, or None to admit. Caller holds _lock.
        A tenant counts against the tenant quota while it has live
        connections or runs still awaiting their verdict — finished
        tenants age out, they don't squat the pool forever."""
        q = self.quotas
        now = time.monotonic()
        tenants = {t for (t, _r), n in self._active.items() if n} | \
            {t for (t, _r), rs in self._runs.items()
             if not rs.verdict_ready.is_set()
             and now - rs.touched < ABANDONED_S}
        total = sum(self._active.values())
        if tenant not in tenants and len(tenants) >= q.max_tenants:
            return (f"tenant quota saturated "
                    f"({q.max_tenants} tenants)")
        if total >= q.max_total_streams:
            return f"stream quota saturated ({total} streams)"
        per = sum(n for (t, _r), n in self._active.items()
                  if t == tenant)
        if per >= q.max_streams_per_tenant:
            return (f"per-tenant stream quota saturated "
                    f"({per} streams)")
        return None

    def _chunk(self, conn, rs: RunState, msg) -> None:
        t_recv = frec.now()
        seq = msg.get("seq")
        ops = msg.get("ops")
        if not isinstance(seq, int) or seq < 1 \
                or not isinstance(ops, list):
            wire.send_msg(conn, {"type": "error",
                                 "reason": "malformed chunk"})
            return
        with rs.lock:
            if rs.fin:
                wire.send_msg(conn, {"type": "error",
                                     "reason": "stream finished"})
                return
            if seq <= rs.last_seq:
                # duplicate (retransmit after a lost ack, or a chaos
                # duplicate): idempotent re-ack, no re-journal
                wire.send_msg(conn, {"type": "ack",
                                     "seq": rs.last_seq})
                return
            if seq > rs.last_seq + 1:
                # gap (reordered frame): don't journal out of order —
                # re-ack the resume point so the client rewinds
                wire.send_msg(conn, {"type": "ack",
                                     "seq": rs.last_seq,
                                     "resync": True})
                return
            if rs.n_ops + len(ops) > self.quotas.max_ops_per_run:
                wire.send_msg(
                    conn, {"type": "reject",
                           "reason": "run op quota exceeded",
                           "retry_after": None})
                return
            if rs.wal is None:
                # completed/retired run: nothing may append
                wire.send_msg(conn, {"type": "error",
                                     "reason": "stream finished"})
                return
            # WAL BEFORE ack: the ack promises durability
            w0 = frec.now()
            try:
                rs.wal.append({"t": "chunk", "seq": seq, "ops": ops})
            except OSError as e:
                # durability fault (ENOSPC/EIO, real or chaos): the
                # chunk was NOT journaled so it must NOT be acked —
                # shed it with retry-after and an honest degraded
                # stamp; the client re-sends from its acked seq once
                # the store recovers. The server never crashes and
                # never promises durability it doesn't have.
                telemetry.count("fleet.shed.wal")
                with self._lock:
                    self._stats["wal_sheds"] = \
                        self._stats.get("wal_sheds", 0) + 1
                wire.send_msg(
                    conn, {"type": "reject",
                           "reason": f"durability fault: {e}",
                           "degraded": True,
                           "retry_after": self.quotas.retry_after_s})
                return
            wal_ns = frec.now() - w0
            rs.wal_ns += wal_ns
            if rs.t_first is None:
                rs.t_first = t_recv
            rs.last_seq = seq
            rs.n_ops += len(ops)
            rs.cum_ops[seq] = rs.n_ops
            rs.touched = time.monotonic()
            if rs.stream is not None:
                # under rs.lock so a half-dead old handler racing a
                # reconnected one can't feed the stream out of order
                # (add_ops is cheap: the encode runs on the stream's
                # own worker thread, never on this ack path)
                try:
                    rs.stream.add_ops(wire.ops_from_wire(ops))
                except Exception:  # noqa: BLE001 — streaming is
                    logger.exception("streaming check failed")
                    rs.stream = None  # advisory; final check stays
        with self._lock:
            self._stats["chunks"] += 1
            self._stats["ops"] += len(ops)
            ts = self._tstat_locked(rs.tenant)
            ts["chunks"] += 1
            ts["ops"] += len(ops)
        telemetry.count("fleet.chunks")
        wire.send_msg(conn, {"type": "ack", "seq": seq})
        # the span closes at the ack — the durability promise's wall
        # clock. Only JOURNALED chunks reach here: dup re-acks and
        # resync acks above return early, so chaos duplication can
        # never double-count a span.
        tc = msg.get("tc") if isinstance(msg.get("tc"), dict) else {}
        self.flightrec.chunk(
            rs.tenant, rs.run, seq, t_recv, frec.now(), wal_ns,
            len(ops), client_t=tc.get("t"), trace=tc.get("trace"))

    def _fin(self, conn, rs: RunState, msg) -> None:
        with rs.lock:
            chunks = msg.get("chunks")
            if isinstance(chunks, int) and chunks != rs.last_seq:
                # the client believes it sent more than we journaled:
                # NOT a completed stream — make it rewind and re-send
                wire.send_msg(conn, {"type": "ack",
                                     "seq": rs.last_seq,
                                     "resync": True})
                return
            first_fin = not rs.fin and rs.wal is not None
            if first_fin:
                w0 = frec.now()
                try:
                    rs.wal.append({"t": "fin",
                                   "chunks": rs.last_seq})
                except OSError as e:
                    # an un-journaled fin must not produce a verdict
                    # a restarted server wouldn't reproduce: shed
                    telemetry.count("fleet.shed.wal")
                    with self._lock:
                        self._stats["wal_sheds"] = \
                            self._stats.get("wal_sheds", 0) + 1
                    wire.send_msg(
                        conn, {"type": "reject",
                               "reason": f"durability fault: {e}",
                               "degraded": True,
                               "retry_after":
                                   self.quotas.retry_after_s})
                    return
                rs.wal_ns += frec.now() - w0
                rs.t_fin = frec.now()
                rs.fin = True
        if first_fin:
            folded = fwal.replay(fwal.wal_path(self.base, rs.tenant,
                                               rs.run))
            self._submit_final(rs, fwal.replay_ops(folded))
        self._claim(conn, rs)

    def _submit_final(self, rs: RunState, ops: list,
                      replay: bool = False) -> None:
        engine = "wgl" if rs.model in wgl_models() else "elle"
        item = self.scheduler.submit(
            "final", rs.tenant, rs.run,
            {"engine": engine, "model": rs.model,
             "initial": rs.initial, "history": History(ops)})
        threading.Thread(target=self._await_verdict,
                         args=(rs, item, replay),
                         name=f"fleet-verdict-{rs.tenant}-{rs.run}",
                         daemon=True).start()

    def _latency_block(self, rs: RunState, item, serialize_ms: float,
                       replay: bool) -> dict:
        """The per-verdict critical-path decomposition from the run's
        ingest stamps and the item's scheduler stamp sheet. Replayed
        runs (recover()) lost their ingest timings with the crash —
        their slices are zero and the block says so."""
        tm = item.times
        ingest = _wal = 0.0
        if not replay:
            with rs.lock:
                if rs.t_first is not None and rs.t_fin is not None:
                    ingest = (rs.t_fin - rs.t_first) / 1e6
                _wal = rs.wal_ns / 1e6
        queue = batching = 0.0
        if "drain" in tm:
            queue = (tm["drain"] - tm["submit"]) / 1e6
        if "launch0" in tm and "drain" in tm:
            batching = (tm["launch0"] - tm["drain"]) / 1e6
        return frec.latency_block(
            ingest_wait_ms=ingest, wal_fsync_ms=_wal,
            queue_wait_ms=queue, batching_delay_ms=batching,
            encode_ms=tm.get("encode_ms", 0.0),
            device_ms=tm.get("device_ms", 0.0),
            certify_ms=tm.get("certify_ms", 0.0),
            serialize_ms=serialize_ms, replay=replay)

    def _await_verdict(self, rs: RunState, item,
                       replay: bool = False) -> None:
        item.done.wait(timeout=VERDICT_TIMEOUT_S)
        result = item.result if item.done.is_set() else \
            {"valid?": "unknown", "error": "fleet verdict timeout"}
        # NOTE: nothing timing-dependent goes in here — the verdict
        # file must replay byte-identical after a crash (the latency
        # block below rides NEXT to the verdict on the wire and in
        # stats, never inside these bytes; streaming status likewise)
        verdict = {"tenant": rs.tenant, "run": rs.run,
                   "model": rs.model, "n_ops": rs.n_ops,
                   "result": fwal.json_safe(result)}
        s0 = frec.now()
        try:
            fwal.write_verdict(self.base, rs.tenant, rs.run, verdict)
        except OSError:
            logger.exception("writing verdict file failed")
        serialize_ms = (frec.now() - s0) / 1e6
        # a disabled recorder means NO latency accounting anywhere —
        # the wire envelope matches a pre-flightrec server's exactly
        latency = self._latency_block(rs, item, serialize_ms, replay) \
            if self.flightrec.enabled else None
        with rs.lock:
            rs.verdict = verdict
            rs.latency = latency
        # all accounting lands BEFORE verdict_ready fires: a client
        # whose finish() returns must already see the verdict in
        # stats()/prometheus and in the recorder's SLO histograms
        with self._lock:
            self._stats["verdicts"] += 1
            self._tstat_locked(rs.tenant)["verdicts"] += 1
        telemetry.count("fleet.verdicts")
        # SLO clock: fin -> verdict ready (a replayed run's fin died
        # with the crash; its re-submit time is the honest start)
        t0 = rs.t_fin if rs.t_fin is not None \
            else item.times["submit"]
        self.flightrec.verdict(rs.tenant, rs.run, t0, frec.now(),
                               latency)
        # the snapshot also lands before verdict_ready: a client that
        # kills the server the instant finish() returns still finds
        # this verdict's SLO history on disk for the successor to fold
        try:
            self.flightrec.save(self.base / frec.SNAPSHOT_FILE)
        except OSError:  # pragma: no cover — accounting is advisory
            logger.exception("flightrec snapshot failed")
        rs.verdict_ready.set()
        rs.retire_wal()  # the run can never append again
        # post-verdict compaction: the historical journal folds to
        # hello + one base + fin. Replay stays byte-identical (the
        # crash-replay tests pin this); a fault here costs disk, not
        # correctness.
        try:
            fwal.compact(fwal.wal_path(self.base, rs.tenant, rs.run),
                         rs.last_seq)
        except OSError:  # pragma: no cover — compaction is advisory
            logger.exception("post-verdict WAL compaction failed")

    def _claim(self, conn, rs: RunState) -> None:
        deadline = time.monotonic() + VERDICT_TIMEOUT_S
        while time.monotonic() < deadline \
                and not self._stopping.is_set():
            if rs.verdict_ready.wait(timeout=1.0):
                reply = {"type": "verdict", "result": rs.verdict}
                if rs.latency is not None:
                    reply["latency"] = rs.latency
                wire.send_msg(conn, reply)
                return
        wire.send_msg(conn, {"type": "error",
                             "reason": "verdict not ready"})
