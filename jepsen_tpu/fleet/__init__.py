"""Checking-as-a-service: the multi-tenant analysis fleet.

The production framing (ROADMAP item 3): thousands of concurrent test
runs feeding one shared accelerator pool. The last five PRs built the
control plane — Prometheus `/metrics`, the coverage atlas, quarantine
breakers, verdict certificates; this package is the data plane:

  wire.py       CRC-framed messages over a local socket (the jlog
                framing discipline applied to a stream: a torn or
                corrupt frame is detected, never half-applied)
  wal.py        per-(tenant, run) write-ahead journal — every accepted
                chunk hits disk BEFORE its ack, so a SIGKILL'd server
                replays to byte-identical verdicts on restart
  scheduler.py  continuous cross-run batching: per-key/per-segment
                slices and whole-history finals from MANY tenants
                packed into shared wgl/elle launches, drained by
                per-tenant weighted-fair queues
  server.py     the always-on service: admission control that sheds
                load by rejecting NEW streams with retry-after (never
                degrading in-flight ones), crash recovery, per-tenant
                quotas and stats
  client.py     streams chunks during a live run (RetryBudget +
                decorrelated jitter), falls back to local checking
                when the fleet is unreachable; the interpreter hook

Robustness contract (doc/fleet.md, enforced by tests/test_fleet.py):
no lost chunks, no wedged queues, no verdict ever silently wrong or
silently dropped under any crash/overload schedule the chaos rig can
produce. Every verdict ships with its PR-9 certificate so a tenant can
independently validate what the pool computed.
"""

from __future__ import annotations

# model-spec registry: the wire names a model by string; both the
# client (for local fallback) and the server resolve it here. wgl
# entries are model factories; elle entries are check functions keyed
# by family.
def wgl_models() -> dict:
    from ..checker import models

    return {
        "register": models.register,
        "cas-register": models.cas_register,
        "mutex": models.mutex,
        "fifo-queue": models.fifo_queue,
        "unordered-queue": models.unordered_queue,
    }


def elle_checks() -> dict:
    from ..tpu import elle

    return {
        "list-append": elle.check_list_append,
        "rw-register": elle.check_rw_register,
    }


def known_models() -> list[str]:
    return sorted(list(wgl_models()) + list(elle_checks()))


# register-family models take an initial value; the rest don't (a
# queue's initial state IS empty). The wire's hello may carry
# `initial` for exactly these.
_TAKES_INITIAL = ("register", "cas-register")


def build_model(name: str, initial=None):
    """Instantiates a wgl model spec from the wire: (name, initial).
    The initial value matters — a register seeded to 0 by its DB
    checked against an initial-None model is PROVABLY nonlinearizable
    on the first read, so tenants must be able to say what their
    system starts as."""
    factory = wgl_models()[name]
    if initial is not None and name in _TAKES_INITIAL:
        return factory(initial)
    return factory()
