"""The fleet flight recorder: end-to-end verdict-latency SLOs and
utilization accounting for the checking service.

The fleet data plane (client -> ingest -> WAL -> scheduler queue ->
batch launch -> device -> verdict write -> ack) was a black box
between the client's ack and its verdict file. This module records
that pipeline the way a serving stack records requests:

  spans     every journaled chunk, every device launch, and every
            verdict becomes a record on ONE monotonic clock
            (time.monotonic_ns — comparable across processes on the
            same host, which is how the client's `tc` trace context
            joins server-side spans). Records export as a Perfetto
            fleet-session view (reports/trace.fleet_chrome_trace):
            one track per tenant, a device-launch track, WAL and
            scheduler swimlanes.
  latency   every verdict carries a schema-validated `latency` block
            decomposing its wall-clock into the pipeline's slices:
            ingest_wait, wal_fsync, queue_wait, batching_delay,
            encode, device, certify, serialize. The device/certify
            slices join the existing profiler `kernel:` telemetry
            spans inside the launch window; encode is the remaining
            host share of the launch wall. The block rides NEXT to
            the verdict (wire reply, results['fleet']), never inside
            the verdict file — the WAL-replay byte-identity contract
            forbids anything timing-dependent in those bytes.
  SLOs      streaming p50/p95/p99 verdict- and ack-latency via
            monitor.LogHistogram, fleet-wide and per tenant;
            histograms persist to `flightrec.json` (atomic rename)
            after every verdict, so a SIGKILL'd server's replayed
            fleet folds its history back in (LogHistogram.from_dict
            + merge — the cross-process observer path).
  util      per-launch batch occupancy as packed-rows/capacity,
            SEPARATELY per launch class (slice vs final — the old
            blended hists_per_launch over-stated utilization),
            device idle gaps between launches, per-tenant fairness
            counters, and a scheduler decision log recording WHY
            each launch fired (full / timeout / drain / breaker).

Everything is advisory: a disabled recorder (FleetServer(...,
flightrec=False)) turns every hook into an early return, and bench.py
prices the instrumented-vs-disabled delta as the flightrec-overhead
BENCH line (<2% of the fleet-throughput budget).
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
from collections import deque
from pathlib import Path

from .. import telemetry
from ..monitor import LogHistogram

SNAPSHOT_FILE = "flightrec.json"
MAX_RECORDS = 4096

# the per-verdict critical-path decomposition, in pipeline order
LATENCY_KEYS = ("ingest_wait", "wal_fsync", "queue_wait",
                "batching_delay", "encode", "device", "certify",
                "serialize")
# why a launch fired (the scheduler decision log); "quarantine" marks
# a solo host-lane launch serving a poison-isolated run
REASONS = ("full", "timeout", "drain", "breaker", "quarantine")
CLASSES = ("slice", "final")
RECORD_KINDS = ("chunk", "launch", "verdict", "quarantine")
QUARANTINE_ACTIONS = ("quarantined", "released")
QS = (0.5, 0.95, 0.99)


def now() -> int:
    """The recorder clock: raw monotonic ns. Boot-relative on Linux,
    so a client's `tc` timestamp and the server's ingest stamp share
    one clock domain across processes on the same host."""
    return time.monotonic_ns()


def _ms(ns) -> float:
    return ns / 1e6


# ---------------------------------------------------------------------------
# The latency block
# ---------------------------------------------------------------------------

def latency_block(*, ingest_wait_ms=0.0, wal_fsync_ms=0.0,
                  queue_wait_ms=0.0, batching_delay_ms=0.0,
                  encode_ms=0.0, device_ms=0.0, certify_ms=0.0,
                  serialize_ms=0.0, replay: bool = False) -> dict:
    """Builds a schema-valid latency block (ms, rounded; negatives
    from clock ties clamp to 0). total_ms is the slice sum — the
    critical-path decomposition total, not the end-to-end SLO number
    (that one is the verdict histogram's job)."""
    vals = (ingest_wait_ms, wal_fsync_ms, queue_wait_ms,
            batching_delay_ms, encode_ms, device_ms, certify_ms,
            serialize_ms)
    block = {k: round(max(float(v), 0.0), 3)
             for k, v in zip(LATENCY_KEYS, vals)}
    block["total_ms"] = round(sum(block.values()), 3)
    if replay:
        # a crash-replayed verdict: ingest/WAL slices predate the
        # restart and are honestly zero, not remeasured
        block["replay"] = True
    return block


def replay_block() -> dict:
    """The block a recovered-from-file verdict carries: complete
    schema, every slice zero, replay-annotated."""
    return latency_block(replay=True)


def validate_latency(block) -> None:
    """Raises ValueError unless `block` is a schema-valid latency
    block: every slice key present and a non-negative number, a
    consistent total_ms, no unknown keys."""
    if not isinstance(block, dict):
        raise ValueError(f"latency block not a dict: {block!r}")
    allowed = set(LATENCY_KEYS) | {"total_ms", "replay"}
    extra = set(block) - allowed
    if extra:
        raise ValueError(f"latency block unknown keys: {sorted(extra)}")
    for k in LATENCY_KEYS + ("total_ms",):
        v = block.get(k)
        if isinstance(v, bool) or not isinstance(v, (int, float)) \
                or v < 0:
            raise ValueError(f"latency block bad {k!r}: {v!r}")
    if "replay" in block and block["replay"] is not True:
        raise ValueError(
            f"latency block bad replay: {block['replay']!r}")


def dominant_slice(block: dict) -> tuple[str, float]:
    """The slice where this verdict's wall-clock went — what `fleet
    explain` names."""
    k = max(LATENCY_KEYS, key=lambda key: block.get(key, 0.0))
    return k, float(block.get(k, 0.0))


# ---------------------------------------------------------------------------
# Record schema (the Perfetto fleet-session source)
# ---------------------------------------------------------------------------

def validate_records(records) -> int:
    """Schema check for flight-recorder records (run in tier-1 like
    tracing.validate_records): known kinds, required keys, ordered
    non-negative timestamps, occupancy within [0, 1], latency blocks
    schema-valid, and no double-counted chunk spans — a chaos
    transport's duplicated/reordered frames must journal (and so
    record) each seq exactly once. Returns the record count; raises
    ValueError on the first violation."""
    seen_chunks: set = set()
    n = 0
    for i, r in enumerate(records):
        if not isinstance(r, dict):
            raise ValueError(f"record {i}: not a dict")
        kind = r.get("kind")
        if kind not in RECORD_KINDS:
            raise ValueError(f"record {i}: unknown kind {kind!r}")
        t0, t1 = r.get("t0"), r.get("t1")
        if not isinstance(t0, int) or not isinstance(t1, int) \
                or t0 < 0 or t1 < t0:
            raise ValueError(
                f"record {i}: bad span [{t0!r}, {t1!r}]")
        if kind == "chunk":
            for k in ("tenant", "run"):
                if not isinstance(r.get(k), str):
                    raise ValueError(f"record {i}: bad {k!r}")
            seq = r.get("seq")
            if not isinstance(seq, int) or seq < 1:
                raise ValueError(f"record {i}: bad seq {seq!r}")
            key = (r["tenant"], r["run"], seq)
            if key in seen_chunks:
                raise ValueError(
                    f"record {i}: duplicate chunk span {key}")
            seen_chunks.add(key)
            for k in ("wal_ms", "ack_ms"):
                v = r.get(k)
                if not isinstance(v, (int, float)) or v < 0:
                    raise ValueError(f"record {i}: bad {k!r}: {v!r}")
        elif kind == "launch":
            if r.get("cls") not in CLASSES:
                raise ValueError(
                    f"record {i}: bad cls {r.get('cls')!r}")
            if r.get("reason") not in REASONS:
                raise ValueError(
                    f"record {i}: bad reason {r.get('reason')!r}")
            rows, cap = r.get("rows"), r.get("capacity")
            if not isinstance(rows, int) or rows < 0 \
                    or not isinstance(cap, int) or cap < 1:
                raise ValueError(
                    f"record {i}: bad rows/capacity {rows!r}/{cap!r}")
            occ = r.get("occupancy")
            if not isinstance(occ, (int, float)) or not 0 <= occ <= 1:
                raise ValueError(
                    f"record {i}: bad occupancy {occ!r}")
            if not isinstance(r.get("tenants"), list):
                raise ValueError(f"record {i}: bad tenants")
        elif kind == "verdict":
            for k in ("tenant", "run"):
                if not isinstance(r.get(k), str):
                    raise ValueError(f"record {i}: bad {k!r}")
            try:
                validate_latency(r.get("latency"))
            except ValueError as e:
                raise ValueError(f"record {i}: {e}") from e
        elif kind == "quarantine":
            for k in ("tenant", "run", "error"):
                if not isinstance(r.get(k), str):
                    raise ValueError(f"record {i}: bad {k!r}")
            if r.get("action") not in QUARANTINE_ACTIONS:
                raise ValueError(
                    f"record {i}: bad action {r.get('action')!r}")
        n += 1
    return n


def kernel_phases(r0: int, r1: int) -> tuple[float, float]:
    """(device_ms, certify_ms) inside a launch window on the
    TELEMETRY clock (util.relative_time_nanos): the summed profiler
    `kernel:` span overlap joins device compute into the fleet
    decomposition; `certify.attach` spans price certificate
    extraction. With no profiler records in the window (host path,
    telemetry off) both come back 0 and the whole launch wall stays
    in the `encode` host share."""
    device = certify = 0
    try:
        events = telemetry.get().events()
    except Exception:  # noqa: BLE001 — accounting never breaks a launch
        return 0.0, 0.0
    for s in reversed(events):
        t0, t1 = s.get("t0"), s.get("t1")
        if t0 is None or t1 is None:
            continue
        if t1 < r0:
            break  # completion order: everything earlier predates us
        overlap = min(t1, r1) - max(t0, r0)
        if overlap <= 0:
            continue
        name = str(s.get("name", ""))
        if name.startswith("kernel:"):
            device += overlap
        elif name == "certify.attach":
            certify += overlap
    return _ms(device), _ms(certify)


# ---------------------------------------------------------------------------
# Prometheus scrape validation
# ---------------------------------------------------------------------------

_PROM_LINE = re.compile(
    r'^[a-zA-Z_:][a-zA-Z0-9_:]*'
    r'(\{[a-zA-Z_][a-zA-Z0-9_]*="[^"\\]*"'
    r'(,[a-zA-Z_][a-zA-Z0-9_]*="[^"\\]*")*\})?'
    r' [-+]?([0-9]*\.?[0-9]+([eE][-+]?[0-9]+)?|inf|nan)$')


def validate_prometheus(text: str) -> int:
    """Parse-validates a Prometheus text exposition (every sample
    line a well-formed `name{labels} value`). Returns the sample
    count; raises ValueError on the first malformed line — the
    scrape-parse gate for the fleet's tenant-labeled samples."""
    n = 0
    for i, line in enumerate(text.splitlines()):
        if not line or line.startswith("#"):
            continue
        if not _PROM_LINE.match(line):
            raise ValueError(f"line {i}: malformed sample {line!r}")
        n += 1
    return n


# ---------------------------------------------------------------------------
# The recorder
# ---------------------------------------------------------------------------

def _qdict(h: LogHistogram) -> dict:
    out = {"n": h.n}
    for q in QS:
        v = h.quantile(q)
        out[f"p{int(q * 100)}"] = None if v is None else round(v, 3)
    return out


class FlightRecorder:
    """One per FleetServer; shared with its Scheduler. Hooks are
    called from connection handler threads, the scheduler batch loop,
    and verdict threads — every mutation holds `_lock` (hooks are a
    few dict updates; the device launch itself is never under it)."""

    _guarded_by_lock = {"_lock": (
        "_records", "_verdict_ms", "_ack_ms", "_tenant_verdict",
        "_tenant_ack", "_classes", "_decisions", "_fairness",
        "_idle_ms", "_idle_gaps", "_last_launch_end", "_verdicts",
        "_quarantine_events")}

    def __init__(self, enabled: bool = True,
                 max_records: int = MAX_RECORDS):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._save_lock = threading.Lock()  # one snapshot at a time
        self._records: deque = deque(maxlen=max_records)
        self._verdict_ms = LogHistogram()
        self._ack_ms = LogHistogram()
        self._tenant_verdict: dict[str, LogHistogram] = {}
        self._tenant_ack: dict[str, LogHistogram] = {}
        # per launch class: launches / packed rows / occupancy sum
        self._classes = {c: {"launches": 0, "rows": 0,
                             "occupancy_sum": 0.0} for c in CLASSES}
        self._decisions = {r: 0 for r in REASONS}
        self._fairness: dict[str, dict] = {}
        self._idle_ms = 0.0
        self._idle_gaps = 0
        self._last_launch_end: int | None = None
        self._verdicts = 0
        self._quarantine_events = {a: 0 for a in QUARANTINE_ACTIONS}

    # -- ingest path (server) -------------------------------------------

    def chunk(self, tenant: str, run: str, seq: int, t0: int,
              t1: int, wal_ns: int, n_ops: int,
              client_t=None, trace=None) -> None:
        """One JOURNALED chunk: recv -> WAL append -> ack. Duplicate
        re-acks and resyncs never reach here, so chaos dup/reorder
        cannot double-count a span. `client_t` (the tc trace context)
        extends the span back to the client's send when the clocks
        are plausibly the same domain."""
        if not self.enabled:
            return
        if isinstance(client_t, int) and 0 < client_t <= t0 \
                and t0 - client_t < 60_000_000_000:
            t_start = client_t
        else:
            t_start = t0
        ack_ms = _ms(t1 - t_start)
        rec = {"kind": "chunk", "tenant": tenant, "run": run,
               "seq": seq, "t0": t_start, "t1": t1,
               "wal_ms": round(_ms(wal_ns), 3), "ops": n_ops,
               "ack_ms": round(ack_ms, 3)}
        if trace is not None:
            rec["trace"] = trace
        with self._lock:
            self._records.append(rec)
            self._ack_ms.add(ack_ms)
            h = self._tenant_ack.get(tenant)
            if h is None:
                h = self._tenant_ack[tenant] = LogHistogram()
            h.add(ack_ms)

    # -- scheduler path --------------------------------------------------

    def launch(self, cls: str, reason: str, t0: int, t1: int,
               rows: int, capacity: int, items,
               device_ms: float = 0.0,
               certify_ms: float = 0.0) -> None:
        """One device launch = one decision-log entry. Occupancy is
        packed rows over the launch class's capacity; the gap since
        the previous launch ended is device idle time."""
        if not self.enabled:
            return
        tenants = sorted({i.tenant for i in items})
        occupancy = min(rows / max(capacity, 1), 1.0)
        rec = {"kind": "launch", "cls": cls, "reason": reason,
               "t0": t0, "t1": t1, "rows": rows,
               "capacity": capacity,
               "occupancy": round(occupancy, 4),
               "tenants": tenants,
               "device_ms": round(device_ms, 3),
               "certify_ms": round(certify_ms, 3)}
        with self._lock:
            self._records.append(rec)
            c = self._classes[cls]
            c["launches"] += 1
            c["rows"] += rows
            c["occupancy_sum"] += occupancy
            self._decisions[reason] = \
                self._decisions.get(reason, 0) + 1
            if self._last_launch_end is not None \
                    and t0 > self._last_launch_end:
                self._idle_ms += _ms(t0 - self._last_launch_end)
                self._idle_gaps += 1
            self._last_launch_end = max(
                self._last_launch_end or 0, t1)
            per = {t: sum(1 for i in items if i.tenant == t)
                   for t in tenants}
            total_items = max(len(items), 1)
            for t, k in per.items():
                f = self._fairness.get(t)
                if f is None:
                    f = self._fairness[t] = {
                        "items": 0, "rows": 0, "launches": 0}
                f["items"] += k
                f["launches"] += 1
                # rows split by the tenant's item share of the launch
                f["rows"] += round(rows * k / total_items)

    # -- verdict path ----------------------------------------------------

    def verdict(self, tenant: str, run: str, t0: int, t1: int,
                latency: dict) -> None:
        """One verdict: fin (or recovery submit) -> verdict written.
        Feeds the SLO histograms and the per-tenant tracks."""
        if not self.enabled:
            return
        verdict_ms = _ms(max(t1 - t0, 0))
        rec = {"kind": "verdict", "tenant": tenant, "run": run,
               "t0": min(t0, t1), "t1": t1, "latency": latency}
        with self._lock:
            self._records.append(rec)
            self._verdicts += 1
            self._verdict_ms.add(verdict_ms)
            h = self._tenant_verdict.get(tenant)
            if h is None:
                h = self._tenant_verdict[tenant] = LogHistogram()
            h.add(verdict_ms)

    # -- quarantine path -------------------------------------------------

    def quarantine(self, tenant: str, run: str, action: str,
                   error: str) -> None:
        """One poison-isolation transition: a run entering or leaving
        the solo host lane. Instantaneous events on the recorder
        clock (t0 == t1)."""
        if not self.enabled:
            return
        t = now()
        rec = {"kind": "quarantine", "tenant": tenant, "run": run,
               "action": action, "error": str(error)[:200],
               "t0": t, "t1": t}
        with self._lock:
            self._records.append(rec)
            self._quarantine_events[action] = \
                self._quarantine_events.get(action, 0) + 1

    # -- views -----------------------------------------------------------

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._records)

    def snapshot(self) -> dict:
        """The stats()['flightrec'] block: SLO quantiles, per-class
        occupancy, the decision-log counts (their sum == total
        launches recorded), idle accounting, fairness counters."""
        if not self.enabled:
            return {"enabled": False}
        with self._lock:
            classes = {}
            for cls, c in self._classes.items():
                launches = c["launches"]
                classes[cls] = {
                    "launches": launches,
                    "rows": c["rows"],
                    "rows_per_launch": round(
                        c["rows"] / launches, 3) if launches else 0.0,
                    "occupancy": round(
                        c["occupancy_sum"] / launches, 4)
                    if launches else 0.0}
            tenants = sorted(set(self._tenant_verdict)
                             | set(self._tenant_ack))
            return {
                "enabled": True,
                "verdicts": self._verdicts,
                "verdict_ms": _qdict(self._verdict_ms),
                "ack_ms": _qdict(self._ack_ms),
                "tenants": {
                    t: {"verdict_ms": _qdict(
                            self._tenant_verdict.get(t)
                            or LogHistogram()),
                        "ack_ms": _qdict(
                            self._tenant_ack.get(t)
                            or LogHistogram())}
                    for t in tenants},
                "classes": classes,
                "launches": sum(c["launches"]
                                for c in self._classes.values()),
                "decisions": dict(self._decisions),
                "quarantine": dict(self._quarantine_events),
                "idle": {"gaps": self._idle_gaps,
                         "total_ms": round(self._idle_ms, 3)},
                "fairness": {t: dict(f)
                             for t, f in self._fairness.items()}}

    # -- persistence (SIGKILL survival + cross-process folding) ----------

    def to_dict(self) -> dict:
        with self._lock:
            return {
                "verdicts": self._verdicts,
                "verdict_ms": self._verdict_ms.to_dict(),
                "ack_ms": self._ack_ms.to_dict(),
                "tenant_verdict": {
                    t: h.to_dict()
                    for t, h in self._tenant_verdict.items()},
                "tenant_ack": {
                    t: h.to_dict()
                    for t, h in self._tenant_ack.items()},
                "classes": {c: dict(v)
                            for c, v in self._classes.items()},
                "decisions": dict(self._decisions),
                "quarantine": dict(self._quarantine_events),
                "idle_ms": self._idle_ms,
                "idle_gaps": self._idle_gaps,
                "fairness": {t: dict(f)
                             for t, f in self._fairness.items()},
                "records": list(self._records)}

    def fold(self, d: dict) -> None:
        """Folds a persisted snapshot back in (restart recovery, or
        an observer merging several servers' files). Histograms merge
        associatively (LogHistogram); counters add."""
        if not isinstance(d, dict):
            return
        with self._lock:
            self._verdicts += int(d.get("verdicts") or 0)
            self._verdict_ms = self._verdict_ms.merge(
                LogHistogram.from_dict(d.get("verdict_ms") or {}))
            self._ack_ms = self._ack_ms.merge(
                LogHistogram.from_dict(d.get("ack_ms") or {}))
            for key, dst in (("tenant_verdict", self._tenant_verdict),
                             ("tenant_ack", self._tenant_ack)):
                for t, hd in (d.get(key) or {}).items():
                    cur = dst.get(t) or LogHistogram()
                    dst[t] = cur.merge(LogHistogram.from_dict(hd))
            for cls, v in (d.get("classes") or {}).items():
                if cls in self._classes and isinstance(v, dict):
                    c = self._classes[cls]
                    c["launches"] += int(v.get("launches") or 0)
                    c["rows"] += int(v.get("rows") or 0)
                    c["occupancy_sum"] += float(
                        v.get("occupancy_sum") or 0.0)
            for r, k in (d.get("decisions") or {}).items():
                self._decisions[r] = \
                    self._decisions.get(r, 0) + int(k)
            for a, k in (d.get("quarantine") or {}).items():
                self._quarantine_events[a] = \
                    self._quarantine_events.get(a, 0) + int(k)
            self._idle_ms += float(d.get("idle_ms") or 0.0)
            self._idle_gaps += int(d.get("idle_gaps") or 0)
            for t, f in (d.get("fairness") or {}).items():
                cur = self._fairness.setdefault(
                    t, {"items": 0, "rows": 0, "launches": 0})
                for k in cur:
                    cur[k] += int((f or {}).get(k) or 0)
            for rec in (d.get("records") or []):
                self._records.append(rec)

    def save(self, path) -> None:
        """Atomic tmp+rename, after every verdict: the durability
        cadence matches the WAL's promise — what was acked (and
        decided) survives the SIGKILL."""
        if not self.enabled:
            return
        p = Path(path)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.with_name(p.name + ".tmp")
        # concurrent verdict threads all save; the lock keeps one
        # writer's tmp from being renamed out from under another's
        with self._save_lock:
            tmp.write_text(json.dumps(self.to_dict(),
                                      separators=(",", ":")))
            os.replace(tmp, p)

    def load(self, path) -> bool:
        if not self.enabled:
            return False
        try:
            d = json.loads(Path(path).read_text())
        except (OSError, ValueError):
            return False
        self.fold(d)
        return True
