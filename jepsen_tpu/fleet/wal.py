"""Per-(tenant, run) write-ahead journal + atomic verdict files.

Every chunk a tenant streams is journaled here BEFORE its ack goes
back on the wire, so the ack is a durability promise: a SIGKILL'd
server replays its WAL on restart and reaches byte-identical verdicts
for every acked byte. The framing is the jlog discipline
(store/format.py): CRC-framed JSON records, torn/corrupt tail dropped
on read. Each append is ONE os.write on an O_APPEND fd — the same
single-write discipline the shared cross-run ledgers use — so even a
buggy second writer could not interleave partial records.

Records (JSON dicts with a "t" key):

  {"t": "hello", "tenant", "run", "model", "weight", "ts"}
  {"t": "chunk", "seq", "ops": [...]}     seq starts at 1
  {"t": "base",  "seq": n, "ops": [...]}  compaction: chunks 1..n
                                          coalesced into one record
  {"t": "fin",   "chunks": n}

Replay folds duplicates idempotently (a retrying client may re-send a
chunk the crash lost the ack for: first intact copy of a seq wins) and
ignores seqs past a torn tail — exactly what the client will re-send
after its resume handshake.

Compaction (checkpoint-and-extend, doc/robustness.md): once a
streaming checkpoint certifies a prefix — or the final verdict lands —
the chunk records before that point no longer need replaying one by
one, so `compact()` rewrites the journal as hello + one "base" record
(the coalesced wire-format ops of seqs 1..n) + the surviving suffix
records. The rewrite is itself crash-safe: the complete new journal is
built in a tmp file, fsync'd, then os.replace'd — until that atomic
swap the PRE-compaction file wins, and a torn tmp is invisible to
readers. A half-written base record inside the swapped file is caught
by the same CRC framing as any other record. Replay of a compacted
journal yields the identical ops list, so verdicts stay byte-identical
across compact-then-crash at any instant.

Verdicts are written ONCE per run as
`verdicts/<tenant>/<run>.json`, via tmp + rename (atomic on POSIX),
with deterministic serialization (sorted keys) so the crash-replay
test can compare verdict files byte-for-byte.
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from pathlib import Path

# the repo's one short-write loop (a silently-torn record behind an
# ack would break the durability promise; better no ack than a half-
# journaled chunk)
from ..ledger import write_all

WAL_MAGIC = b"JTPUWAL1"
_HDR = struct.Struct("<II")

# chaos hook: called as hook(path, rec) before every journal append;
# may raise OSError (ENOSPC/EIO injection — the server sheds the
# chunk with retry-after instead of crashing or acking un-journaled
# bytes). Installed/cleared under _hook_lock (chaos.DurabilityChaos).
_fault_hook = None
_hook_lock = threading.Lock()


def set_fault_hook(hook) -> None:
    global _fault_hook
    with _hook_lock:
        _fault_hook = hook

# tenant/run names become path components: keep them boring. Enforced
# at admission (server) AND here (defense in depth).
_SAFE = set("abcdefghijklmnopqrstuvwxyz"
            "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-")


def safe_name(name) -> bool:
    s = str(name)
    return (0 < len(s) <= 128 and set(s) <= _SAFE
            and not s.startswith("."))


def wal_path(base, tenant: str, run: str) -> Path:
    assert safe_name(tenant) and safe_name(run), (tenant, run)
    return Path(base) / "wal" / tenant / f"{run}.wal"


def verdict_path(base, tenant: str, run: str) -> Path:
    assert safe_name(tenant) and safe_name(run), (tenant, run)
    return Path(base) / "verdicts" / tenant / f"{run}.json"




class RunWAL:
    """Append-only journal for one (tenant, run) stream. The server
    serializes appends per run (RunState lock); the O_APPEND
    single-write is belt-and-braces against any second fd on the same
    file (e.g. a half-dead handler thread surviving a kill())."""

    def __init__(self, path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        fresh = not self.path.exists() or \
            self.path.stat().st_size == 0
        self._fd = os.open(self.path,
                           os.O_APPEND | os.O_CREAT | os.O_WRONLY)
        if fresh:
            write_all(self._fd, WAL_MAGIC)  # a short magic poisons
            # the whole WAL for every future reader — loop or raise

    def append(self, rec: dict) -> None:
        with _hook_lock:
            hook = _fault_hook
        if hook is not None:
            hook(self.path, rec)  # may raise OSError (injected fault)
        payload = json.dumps(rec, separators=(",", ":"),
                             sort_keys=True).encode()
        write_all(self._fd,
                  _HDR.pack(len(payload), zlib.crc32(payload))
                  + payload)

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None

    def compact_through(self, through_seq: int) -> bool:
        """Atomically rewrites this journal with seqs 1..through_seq
        coalesced into one "base" record. Caller serializes against
        appends (RunState lock). The fd is reopened on the new file so
        later appends land after the swap."""
        if self._fd is None:
            return compact(self.path, through_seq)
        os.close(self._fd)
        self._fd = None
        try:
            return compact(self.path, through_seq)
        finally:
            self._fd = os.open(self.path,
                               os.O_APPEND | os.O_CREAT | os.O_WRONLY)


def read_records(path) -> list[dict]:
    """Intact records in append order; torn/corrupt tail dropped (the
    jlog recovery rule)."""
    p = Path(path)
    try:
        buf = p.read_bytes()
    except OSError:
        return []
    if buf[:len(WAL_MAGIC)] != WAL_MAGIC:
        return []
    out: list[dict] = []
    pos = len(WAL_MAGIC)
    while pos + _HDR.size <= len(buf):
        n, crc = _HDR.unpack(buf[pos:pos + _HDR.size])
        payload = buf[pos + _HDR.size:pos + _HDR.size + n]
        if len(payload) < n or zlib.crc32(payload) != crc:
            break  # torn tail: the client will re-send from last_seq
        try:
            rec = json.loads(payload)
        except ValueError:
            break
        if isinstance(rec, dict) and isinstance(rec.get("t"), str):
            out.append(rec)
        pos += _HDR.size + n
    return out


def replay(path) -> dict:
    """Folds a WAL into {'hello', 'base', 'chunks': {seq: ops},
    'last_seq', 'fin'}. Duplicate seqs keep the FIRST intact copy (a
    client retransmit after a lost ack carries identical ops — and if
    a buggy client ever sent different ones, first-wins keeps replay
    stable across restarts). A "base" record (compaction) floors the
    seq space: chunks at or below its seq are already coalesced into
    it. last_seq is the highest CONTIGUOUS seq from the base (or 1) —
    the resume point the hello handshake reports; a gap means the
    missing chunk was never journaled, so everything after it will be
    re-sent."""
    hello = None
    base = None
    chunks: dict[int, list] = {}
    fin = None
    for rec in read_records(path):
        t = rec.get("t")
        if t == "hello" and hello is None:
            hello = rec
        elif t == "base" and base is None:
            seq = rec.get("seq")
            if isinstance(seq, int) and seq >= 0:
                base = {"seq": seq, "ops": rec.get("ops") or []}
        elif t == "chunk":
            seq = rec.get("seq")
            if isinstance(seq, int) and seq >= 1 \
                    and seq not in chunks:
                chunks[seq] = rec.get("ops") or []
        elif t == "fin" and fin is None:
            fin = rec
    floor = base["seq"] if base else 0
    last = floor
    while (last + 1) in chunks:
        last += 1
    return {"hello": hello,
            "base": base,
            "chunks": {s: o for s, o in chunks.items()
                       if floor < s <= last},
            "last_seq": last,
            "fin": fin}


def replay_ops(folded: dict) -> list:
    """The journaled history ops, in stream order, as Op objects —
    identical whether or not the journal was compacted (the base
    record IS seqs 1..base['seq'], coalesced)."""
    from . import wire

    out: list = []
    base = folded.get("base")
    start = 1
    if base:
        out.extend(wire.ops_from_wire(base["ops"]))
        start = base["seq"] + 1
    for seq in range(start, folded["last_seq"] + 1):
        out.extend(wire.ops_from_wire(folded["chunks"][seq]))
    return out


def _frame(rec: dict) -> bytes:
    payload = json.dumps(rec, separators=(",", ":"),
                         sort_keys=True).encode()
    return _HDR.pack(len(payload), zlib.crc32(payload)) + payload


def compact(path, through_seq: int) -> bool:
    """Rewrites the journal at `path` with seqs 1..through_seq folded
    into one "base" record. Atomic (tmp + fsync + os.replace): a crash
    at ANY instant leaves either the old journal or the complete new
    one — never a mix — so replay stays byte-identical. Returns False
    (journal untouched) when there is nothing to fold: through_seq at
    or below the existing base, beyond the contiguous tail, or a
    magic-less/empty file."""
    from .. import telemetry

    p = Path(path)
    folded = replay(p)
    floor = folded["base"]["seq"] if folded["base"] else 0
    if folded["hello"] is None or not floor < through_seq \
            <= folded["last_seq"]:
        return False
    base_ops: list = []
    if folded["base"]:
        base_ops.extend(folded["base"]["ops"])
    for seq in range(floor + 1, through_seq + 1):
        base_ops.extend(folded["chunks"][seq])
    out = bytearray(WAL_MAGIC)
    out += _frame(folded["hello"])
    out += _frame({"t": "base", "seq": through_seq, "ops": base_ops})
    for seq in range(through_seq + 1, folded["last_seq"] + 1):
        out += _frame({"t": "chunk", "seq": seq,
                       "ops": folded["chunks"][seq]})
    if folded["fin"] is not None:
        out += _frame(folded["fin"])
    tmp = p.with_suffix(".compact-tmp")
    fd = os.open(tmp, os.O_CREAT | os.O_TRUNC | os.O_WRONLY)
    try:
        write_all(fd, bytes(out))
        os.fsync(fd)
    finally:
        os.close(fd)
    os.replace(tmp, p)
    telemetry.count("fleet.wal.compactions")
    return True


def scan_runs(base) -> list[tuple[str, str, Path]]:
    """Every (tenant, run, wal_path) under the base dir — the crash
    recovery walk."""
    root = Path(base) / "wal"
    out = []
    if not root.is_dir():
        return out
    for tdir in sorted(root.iterdir()):
        if not tdir.is_dir() or not safe_name(tdir.name):
            continue
        for w in sorted(tdir.glob("*.wal")):
            run = w.name[:-4]
            if safe_name(run):
                out.append((tdir.name, run, w))
    return out


def json_safe(v):
    """JSON-representable (and deterministically serializable) view of
    an analysis result — the store codec's rule (sets sorted, Ops as
    dicts, non-data values degrade to repr)."""
    from ..store import format as fmt

    return fmt.jsonable(v)


def verdict_bytes(verdict: dict) -> bytes:
    """Deterministic serialization — the byte-identical-replay
    contract (and the tamper-evidence story: a tenant can hash this)."""
    return (json.dumps(verdict, separators=(",", ":"), sort_keys=True)
            + "\n").encode()


def write_verdict(base, tenant: str, run: str, verdict: dict) -> Path:
    p = verdict_path(base, tenant, run)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_suffix(".tmp")
    tmp.write_bytes(verdict_bytes(verdict))
    os.replace(tmp, p)  # atomic: readers see old-or-new, never torn
    return p


def read_verdict(base, tenant: str, run: str) -> dict | None:
    try:
        return json.loads(verdict_path(base, tenant, run).read_text())
    except (OSError, ValueError):
        return None
