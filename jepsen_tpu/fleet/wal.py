"""Per-(tenant, run) write-ahead journal + atomic verdict files.

Every chunk a tenant streams is journaled here BEFORE its ack goes
back on the wire, so the ack is a durability promise: a SIGKILL'd
server replays its WAL on restart and reaches byte-identical verdicts
for every acked byte. The framing is the jlog discipline
(store/format.py): CRC-framed JSON records, torn/corrupt tail dropped
on read. Each append is ONE os.write on an O_APPEND fd — the same
single-write discipline the shared cross-run ledgers use — so even a
buggy second writer could not interleave partial records.

Records (JSON dicts with a "t" key):

  {"t": "hello", "tenant", "run", "model", "weight", "ts"}
  {"t": "chunk", "seq", "ops": [...]}     seq starts at 1
  {"t": "fin",   "chunks": n}

Replay folds duplicates idempotently (a retrying client may re-send a
chunk the crash lost the ack for: first intact copy of a seq wins) and
ignores seqs past a torn tail — exactly what the client will re-send
after its resume handshake.

Verdicts are written ONCE per run as
`verdicts/<tenant>/<run>.json`, via tmp + rename (atomic on POSIX),
with deterministic serialization (sorted keys) so the crash-replay
test can compare verdict files byte-for-byte.
"""

from __future__ import annotations

import json
import os
import struct
import zlib
from pathlib import Path

# the repo's one short-write loop (a silently-torn record behind an
# ack would break the durability promise; better no ack than a half-
# journaled chunk)
from ..ledger import write_all

WAL_MAGIC = b"JTPUWAL1"
_HDR = struct.Struct("<II")

# tenant/run names become path components: keep them boring. Enforced
# at admission (server) AND here (defense in depth).
_SAFE = set("abcdefghijklmnopqrstuvwxyz"
            "ABCDEFGHIJKLMNOPQRSTUVWXYZ0123456789._-")


def safe_name(name) -> bool:
    s = str(name)
    return (0 < len(s) <= 128 and set(s) <= _SAFE
            and not s.startswith("."))


def wal_path(base, tenant: str, run: str) -> Path:
    assert safe_name(tenant) and safe_name(run), (tenant, run)
    return Path(base) / "wal" / tenant / f"{run}.wal"


def verdict_path(base, tenant: str, run: str) -> Path:
    assert safe_name(tenant) and safe_name(run), (tenant, run)
    return Path(base) / "verdicts" / tenant / f"{run}.json"




class RunWAL:
    """Append-only journal for one (tenant, run) stream. The server
    serializes appends per run (RunState lock); the O_APPEND
    single-write is belt-and-braces against any second fd on the same
    file (e.g. a half-dead handler thread surviving a kill())."""

    def __init__(self, path):
        self.path = Path(path)
        self.path.parent.mkdir(parents=True, exist_ok=True)
        fresh = not self.path.exists() or \
            self.path.stat().st_size == 0
        self._fd = os.open(self.path,
                           os.O_APPEND | os.O_CREAT | os.O_WRONLY)
        if fresh:
            write_all(self._fd, WAL_MAGIC)  # a short magic poisons
            # the whole WAL for every future reader — loop or raise

    def append(self, rec: dict) -> None:
        payload = json.dumps(rec, separators=(",", ":"),
                             sort_keys=True).encode()
        write_all(self._fd,
                  _HDR.pack(len(payload), zlib.crc32(payload))
                  + payload)

    def close(self) -> None:
        if self._fd is not None:
            os.close(self._fd)
            self._fd = None


def read_records(path) -> list[dict]:
    """Intact records in append order; torn/corrupt tail dropped (the
    jlog recovery rule)."""
    p = Path(path)
    try:
        buf = p.read_bytes()
    except OSError:
        return []
    if buf[:len(WAL_MAGIC)] != WAL_MAGIC:
        return []
    out: list[dict] = []
    pos = len(WAL_MAGIC)
    while pos + _HDR.size <= len(buf):
        n, crc = _HDR.unpack(buf[pos:pos + _HDR.size])
        payload = buf[pos + _HDR.size:pos + _HDR.size + n]
        if len(payload) < n or zlib.crc32(payload) != crc:
            break  # torn tail: the client will re-send from last_seq
        try:
            rec = json.loads(payload)
        except ValueError:
            break
        if isinstance(rec, dict) and isinstance(rec.get("t"), str):
            out.append(rec)
        pos += _HDR.size + n
    return out


def replay(path) -> dict:
    """Folds a WAL into {'hello', 'chunks': {seq: ops}, 'last_seq',
    'fin'}. Duplicate seqs keep the FIRST intact copy (a client
    retransmit after a lost ack carries identical ops — and if a buggy
    client ever sent different ones, first-wins keeps replay stable
    across restarts). last_seq is the highest CONTIGUOUS seq from 1 —
    the resume point the hello handshake reports; a gap means the
    missing chunk was never journaled, so everything after it will be
    re-sent."""
    hello = None
    chunks: dict[int, list] = {}
    fin = None
    for rec in read_records(path):
        t = rec.get("t")
        if t == "hello" and hello is None:
            hello = rec
        elif t == "chunk":
            seq = rec.get("seq")
            if isinstance(seq, int) and seq >= 1 \
                    and seq not in chunks:
                chunks[seq] = rec.get("ops") or []
        elif t == "fin" and fin is None:
            fin = rec
    last = 0
    while (last + 1) in chunks:
        last += 1
    return {"hello": hello,
            "chunks": {s: o for s, o in chunks.items() if s <= last},
            "last_seq": last,
            "fin": fin}


def replay_ops(folded: dict) -> list:
    """The journaled history ops, in stream order, as Op objects."""
    from . import wire

    out: list = []
    for seq in range(1, folded["last_seq"] + 1):
        out.extend(wire.ops_from_wire(folded["chunks"][seq]))
    return out


def scan_runs(base) -> list[tuple[str, str, Path]]:
    """Every (tenant, run, wal_path) under the base dir — the crash
    recovery walk."""
    root = Path(base) / "wal"
    out = []
    if not root.is_dir():
        return out
    for tdir in sorted(root.iterdir()):
        if not tdir.is_dir() or not safe_name(tdir.name):
            continue
        for w in sorted(tdir.glob("*.wal")):
            run = w.name[:-4]
            if safe_name(run):
                out.append((tdir.name, run, w))
    return out


def json_safe(v):
    """JSON-representable (and deterministically serializable) view of
    an analysis result — the store codec's rule (sets sorted, Ops as
    dicts, non-data values degrade to repr)."""
    from ..store import format as fmt

    return fmt.jsonable(v)


def verdict_bytes(verdict: dict) -> bytes:
    """Deterministic serialization — the byte-identical-replay
    contract (and the tamper-evidence story: a tenant can hash this)."""
    return (json.dumps(verdict, separators=(",", ":"), sort_keys=True)
            + "\n").encode()


def write_verdict(base, tenant: str, run: str, verdict: dict) -> Path:
    p = verdict_path(base, tenant, run)
    p.parent.mkdir(parents=True, exist_ok=True)
    tmp = p.with_suffix(".tmp")
    tmp.write_bytes(verdict_bytes(verdict))
    os.replace(tmp, p)  # atomic: readers see old-or-new, never torn
    return p


def read_verdict(base, tenant: str, run: str) -> dict | None:
    try:
        return json.loads(verdict_path(base, tenant, run).read_text())
    except (OSError, ValueError):
        return None
