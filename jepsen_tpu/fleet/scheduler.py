"""Continuous cross-run batching: the fleet's shared-device scheduler.

Inference serving stays viable under load by continuously batching
heterogeneous requests into shared device launches; the fleet applies
the same discipline to checker work. Tenants submit two kinds of work:

  final   a complete streamed history -> a full wgl/elle verdict with
          its certificate (the authoritative answer, identical to what
          a solo run computes)
  slice   one (encoded segment, start state) reach row of a LIVE
          stream's prefix — the streaming check that tightens a
          tenant's verdict while chunks are still arriving

The batch loop drains per-tenant queues by deficit-weighted round
robin (a tenant with weight 2 gets twice the rows of a weight-1
tenant when both are backlogged; an idle tenant's unused share is
redistributed, not wasted), then packs everything it drained into as
few device launches as possible: slices from ALL tenants go into ONE
`wgl.check_slices` launch; finals group per model and go through
`wgl.analysis_batch_streamed` (P-compositionality, arXiv:1504.00204,
justifies slicing; certificates make the shared pool trustworthy —
the tenant validates the proof, not the pool).

Failure discipline: a work item's `done` event is ALWAYS set (try/
finally), so no crash in a batch can wedge a queue; device failures
inside the kernels walk the PR-5 degradation ladder; if whole batches
keep dying, a circuit breaker (closed/open/half-open, the
control/health.py discipline) routes finals to the pure-host
algorithm until a half-open probe succeeds — slower, never wrong.
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Any

from .. import telemetry, util
from ..history import History
from . import flightrec as frec

logger = logging.getLogger(__name__)


def _ckpt_mod():
    from ..tpu import ckpt

    return ckpt


MAX_BATCH = 64          # items drained per batch round
WINDOW_S = 0.02         # how long the loop waits to accumulate work
QUANTUM = 8.0           # deficit credit per round per unit weight
BREAKER_THRESHOLD = 3   # consecutive dead batches before opening
BREAKER_COOLDOWN_S = 5.0
QUARANTINE_COOLDOWN_S = 30.0  # per-run breaker: solo device probe due


class WorkItem:
    """One unit of checker work. `done` is set exactly once, after
    `result` (never both unset across an exception path). `times` is
    the flight recorder's stamp sheet (submit/drain/launch0/launch1
    in frec.now() ns, plus the launch's per-item encode/device/
    certify ms shares) — written only by the submitting thread and
    the batch loop, read after `done`."""

    __slots__ = ("kind", "tenant", "run", "payload", "result", "done",
                 "times")

    def __init__(self, kind: str, tenant: str, run: str, payload):
        self.kind = kind          # 'final' | 'slice'
        self.tenant = tenant
        self.run = run
        self.payload = payload
        self.result: Any = None
        self.done = threading.Event()
        self.times: dict = {"submit": frec.now()}

    def finish(self, result) -> None:
        self.result = result
        self.done.set()


class _TenantQueue:
    __slots__ = ("items", "weight", "deficit")

    def __init__(self, weight: float = 1.0):
        self.items: deque[WorkItem] = deque()
        self.weight = max(float(weight), 0.1)
        self.deficit = 0.0


class _DeviceBreaker:
    """Fleet-level device circuit: opens after consecutive WHOLE-batch
    failures (the in-kernel ladder already absorbs partial ones),
    half-opens after a cooldown to probe with one batch. Called only
    from the batch thread — no lock needed."""

    def __init__(self, threshold: int = BREAKER_THRESHOLD,
                 cooldown_s: float = BREAKER_COOLDOWN_S):
        self.threshold = threshold
        self.cooldown_s = cooldown_s
        self.failures = 0
        self.opened_at: float | None = None

    def allow_device(self) -> bool:
        if self.opened_at is None:
            return True
        if time.monotonic() - self.opened_at >= self.cooldown_s:
            return True  # half-open probe
        return False

    def record(self, ok: bool) -> None:
        if ok:
            if self.opened_at is not None:
                telemetry.count("fleet.breaker.closed")
            self.failures = 0
            self.opened_at = None
            return
        self.failures += 1
        if self.failures >= self.threshold and self.opened_at is None:
            self.opened_at = time.monotonic()
            telemetry.count("fleet.breaker.opened")
            logger.warning("fleet device breaker OPEN after %d "
                           "consecutive batch failures", self.failures)
        elif self.opened_at is not None:
            self.opened_at = time.monotonic()  # failed probe re-arms


class Quarantine:
    """Poison-run isolation (doc/robustness.md): when ONE run's
    history reliably kills shared device launches, quarantining it to
    a solo host lane keeps every other tenant device-batched — instead
    of three dead batches opening the FLEET breaker and dragging the
    whole pool to the host floor. Each quarantined run carries its own
    tiny breaker: after a cooldown the next visit probes the device
    SOLO (never inside a shared launch), releasing on success,
    re-arming on failure. The fleet-wide _DeviceBreaker still owns
    genuinely systemic failure — it trips only when attribution shows
    EVERY run in a dead batch failing solo.

    add/probe/release run on the batch thread; snapshot() serves
    /fleet, prometheus and stats() from other threads — hence the
    lock."""

    _guarded_by_lock = {"_lock": ("_runs",)}

    def __init__(self, cooldown_s: float = QUARANTINE_COOLDOWN_S):
        self.cooldown_s = cooldown_s
        self._lock = threading.Lock()
        # (tenant, run) -> {"since", "error", "probes", "probe_at"}
        self._runs: dict[tuple[str, str], dict] = {}

    def add(self, tenant: str, run: str, error: str) -> bool:
        """Quarantines a run; True when newly quarantined."""
        with self._lock:
            if (tenant, run) in self._runs:
                return False
            self._runs[(tenant, run)] = {
                "since": time.time(), "error": error, "probes": 0,
                "probe_at": time.monotonic() + self.cooldown_s}
        telemetry.count("fleet.quarantine.added")
        logger.warning("fleet quarantine: %s/%s -> solo host lane "
                       "(%s)", tenant, run, error)
        return True

    def is_quarantined(self, tenant: str, run: str) -> bool:
        with self._lock:
            return (tenant, run) in self._runs

    def probe_due(self, tenant: str, run: str) -> bool:
        with self._lock:
            st = self._runs.get((tenant, run))
            return st is not None and \
                time.monotonic() >= st["probe_at"]

    def record_probe(self, tenant: str, run: str, ok: bool) -> None:
        """A solo device probe's outcome: success releases the run
        back to shared launches; failure re-arms its cooldown."""
        with self._lock:
            st = self._runs.get((tenant, run))
            if st is None:
                return
            st["probes"] += 1
            if ok:
                del self._runs[(tenant, run)]
            else:
                st["probe_at"] = time.monotonic() + self.cooldown_s
        if ok:
            telemetry.count("fleet.quarantine.released")
            logger.info("fleet quarantine: %s/%s released after "
                        "device probe", tenant, run)
        else:
            telemetry.count("fleet.quarantine.probe-failed")

    def snapshot(self) -> list[dict]:
        with self._lock:
            return [{"tenant": t, "run": r,
                     "since": st["since"], "error": st["error"],
                     "probes": st["probes"]}
                    for (t, r), st in sorted(self._runs.items())]


class Scheduler:
    """The batch loop + per-tenant weighted-fair queues."""

    _guarded_by_lock = {"_lock": ("_queues", "_order", "_pending",
                                  "_stats")}

    def __init__(self, max_batch: int = MAX_BATCH,
                 window_s: float = WINDOW_S,
                 quantum: float = QUANTUM,
                 flightrec: "frec.FlightRecorder | None" = None):
        self.max_batch = max_batch
        self.window_s = window_s
        self.quantum = quantum
        # the flight recorder is attached once, before start() (the
        # server shares its own) — a lifecycle attr, not shared state
        self.flightrec = flightrec
        self._lock = threading.Lock()
        self._work = threading.Condition(self._lock)
        self._queues: dict[str, _TenantQueue] = {}
        self._order: deque[str] = deque()  # round-robin ring
        self._pending = 0
        self._stats = {"launches": 0, "slice_launches": 0,
                       "final_launches": 0, "items": 0,
                       "slice_rows": 0, "final_hists": 0,
                       "cross_tenant_launches": 0,
                       "max_tenants_in_launch": 0, "host_floor": 0,
                       "quarantine_items": 0, "bisect_launches": 0}
        self._breaker = _DeviceBreaker()
        self.quarantine = Quarantine()
        self._stop = threading.Event()
        self._drain_req = threading.Event()
        self._thread: threading.Thread | None = None

    # -- submission ------------------------------------------------------

    def set_weight(self, tenant: str, weight: float) -> None:
        with self._lock:
            self._tq_locked(tenant).weight = max(float(weight), 0.1)

    def _tq_locked(self, tenant: str) -> _TenantQueue:
        q = self._queues.get(tenant)
        if q is None:
            q = self._queues[tenant] = _TenantQueue()
            self._order.append(tenant)
        return q

    def submit(self, kind: str, tenant: str, run: str,
               payload) -> WorkItem:
        item = WorkItem(kind, tenant, run, payload)
        with self._lock:
            self._tq_locked(tenant).items.append(item)
            self._pending += 1
            self._work.notify()
        telemetry.count(f"fleet.submit.{kind}")
        return item

    def pending(self, tenant: str | None = None) -> int:
        with self._lock:
            if tenant is None:
                return self._pending
            q = self._queues.get(tenant)
            return len(q.items) if q is not None else 0

    def stats(self) -> dict:
        with self._lock:
            out = dict(self._stats)
            out["pending"] = self._pending
        out["breaker_open"] = self._breaker.opened_at is not None
        out["quarantine"] = self.quarantine.snapshot()
        return out

    # -- lifecycle -------------------------------------------------------

    def start(self) -> "Scheduler":
        self._stop.clear()
        self._drain_req.clear()
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-scheduler",
                                        daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        # graceful stop FLUSHES: the loop drains what is already
        # queued into final launches (decision-log reason "drain")
        # before exiting. kill() sets _stop alone — a SIGKILL
        # abandons in-flight work, it doesn't flush it.
        self._drain_req.set()
        self._stop.set()
        with self._lock:
            self._work.notify_all()
        t = self._thread
        if t is not None:
            t.join(timeout=30)
        # no wedged queues, even at shutdown: whatever is still queued
        # resolves 'unknown' instead of blocking its waiter forever
        with self._lock:
            leftovers = [i for q in self._queues.values()
                         for i in q.items]
            for q in self._queues.values():
                q.items.clear()
            self._pending = 0
        for i in leftovers:
            i.finish({"valid?": "unknown",
                      "error": "fleet scheduler stopped"})

    # -- the batch loop --------------------------------------------------

    def _drain_fair_locked(self) -> list[WorkItem]:
        """Deficit-weighted round robin over the tenant ring, up to
        max_batch items. Caller holds the lock."""
        batch: list[WorkItem] = []
        if not self._pending:
            return batch
        ring = [t for t in self._order if self._queues[t].items]
        for t in ring:
            self._queues[t].deficit += self.quantum * \
                self._queues[t].weight
        # rotate the ring so no tenant is always first
        self._order.rotate(-1)
        progress = True
        while len(batch) < self.max_batch and progress:
            progress = False
            for t in ring:
                q = self._queues[t]
                # interleaved sweeps, ~weight items per visit: the
                # weight ratio holds WITHIN a batch (not just across
                # batches), so one backlogged tenant can never starve
                # another out of a launch
                take = max(1, int(round(q.weight)))
                while (take > 0 and q.items and q.deficit >= 1.0
                       and len(batch) < self.max_batch):
                    batch.append(q.items.popleft())
                    self._pending -= 1
                    q.deficit -= 1.0
                    take -= 1
                    progress = True
        for t in ring:  # an emptied queue carries no credit forward
            q = self._queues[t]
            if not q.items:
                q.deficit = 0.0
        return batch

    def _loop(self) -> None:
        while not self._stop.is_set():
            with self._lock:
                if not self._pending:
                    self._work.wait(timeout=0.2)
                    if not self._pending:
                        continue
                # a short accumulation window so concurrent tenants'
                # submissions land in ONE launch (continuous batching)
            # the window wait is interruptible: a graceful stop
            # mid-window falls through to the drain flush below
            # instead of sleeping the window out while stop() times
            # out and resolves the queued work "unknown"
            if self._stop.wait(timeout=self.window_s):
                break
            with self._lock:
                batch = self._drain_fair_locked()
            if batch:
                # the decision log's why: a full batch fired on size,
                # a partial one on window expiry
                reason = "full" if len(batch) >= self.max_batch \
                    else "timeout"
                self._run_batch(batch, reason)
        # graceful stop (stop(), never kill()): flush what is already
        # queued so accepted work gets real verdicts, not "unknown"
        while self._drain_req.is_set():
            with self._lock:
                batch = self._drain_fair_locked()
            if not batch:
                break
            self._run_batch(batch, "drain")

    def _run_batch(self, batch: list[WorkItem],
                   reason: str = "timeout") -> None:
        t_drain = frec.now()
        for i in batch:
            i.times["drain"] = t_drain
        tenants = {i.tenant for i in batch}
        with self._lock:
            st = self._stats
            st["items"] += len(batch)
            if len(tenants) > 1:
                st["cross_tenant_launches"] += 1
            st["max_tenants_in_launch"] = max(
                st["max_tenants_in_launch"], len(tenants))
        telemetry.count("fleet.batch.rounds")
        telemetry.count("fleet.batch.items", len(batch))
        telemetry.gauge_max("fleet.batch.tenants", len(tenants))
        slices = [i for i in batch if i.kind == "slice"]
        finals = [i for i in batch if i.kind == "final"]
        try:
            if slices:
                self._run_slices(slices, reason)
        finally:
            # finals still run if the slice pass died; and every item
            # resolves no matter what (finish() below is uncond.)
            if finals:
                self._run_finals(finals, reason)

    def _stamp_launch(self, items: list[WorkItem], t0: int, t1: int,
                      device_ms: float, certify_ms: float) -> None:
        """Per-item stamp of the shared launch: the group's measured
        phase totals split evenly across its items (a batched launch
        has no truer per-item attribution than its fair share)."""
        n = max(len(items), 1)
        wall_ms = (t1 - t0) / 1e6
        encode_ms = max(wall_ms - device_ms - certify_ms, 0.0)
        for i in items:
            i.times.update(
                launch0=t0, launch1=t1,
                encode_ms=encode_ms / n, device_ms=device_ms / n,
                certify_ms=certify_ms / n)

    def _run_slices(self, items: list[WorkItem],
                    reason: str) -> None:
        # already-quarantined runs never enter the shared launch:
        # their items go straight to the solo host lane (with a
        # cooldown-gated device probe), so one poison history can't
        # keep killing everyone else's batches
        shared: list[WorkItem] = []
        solo: dict[tuple[str, str], list[WorkItem]] = {}
        for i in items:
            if self.quarantine.is_quarantined(i.tenant, i.run):
                solo.setdefault((i.tenant, i.run), []).append(i)
            else:
                shared.append(i)
        if shared:
            self._launch_slices(shared, reason)
        for (tenant, run), group in solo.items():
            self._quarantined_slices(tenant, run, group)

    def _launch_slices(self, items: list[WorkItem],
                       reason: str) -> None:
        from ..tpu import wgl

        pairs = [i.payload for i in items]  # (Encoded, start_state)
        try:
            t0, r0 = frec.now(), util.relative_time_nanos()
            out, unk = wgl.check_slices(pairs)
            t1, r1 = frec.now(), util.relative_time_nanos()
            device_ms, certify_ms = frec.kernel_phases(r0, r1)
            self._stamp_launch(items, t0, t1, device_ms, certify_ms)
            with self._lock:
                self._stats["launches"] += 1
                self._stats["slice_launches"] += 1
                self._stats["slice_rows"] += len(pairs)
            if self.flightrec is not None:
                self.flightrec.launch(
                    "slice", reason, t0, t1, rows=len(pairs),
                    capacity=self.max_batch, items=items,
                    device_ms=device_ms, certify_ms=certify_ms)
            self._breaker.record(True)
            for i, mask, u in zip(items, out, unk):
                i.finish({"mask": int(mask), "unknown": bool(u)})
        except Exception as e:  # noqa: BLE001 — never wedge a queue
            logger.exception("fleet slice batch failed")
            self._attribute_slice_failure(items, repr(e))

    def _attribute_slice_failure(self, items: list[WorkItem],
                                 error: str) -> None:
        """A dead shared launch: find WHICH run poisoned it by
        bisecting the batch along run boundaries. Runs whose slices
        succeed solo get their real masks; a run that fails alone is
        the poison — quarantine it and serve it from the host lane.
        Only when EVERY run fails solo is the failure systemic, and
        only then does the fleet breaker see it."""
        from ..tpu import wgl

        groups: dict[tuple[str, str], list[WorkItem]] = {}
        for i in items:
            groups.setdefault((i.tenant, i.run), []).append(i)
        keys = list(groups)
        ok_runs: list[tuple[str, str]] = []
        bad_runs: list[tuple[str, str]] = []

        def bisect(ks: list[tuple[str, str]]) -> None:
            sub = [i for k in ks for i in groups[k]]
            try:
                with self._lock:
                    self._stats["bisect_launches"] += 1
                out, unk = wgl.check_slices([i.payload for i in sub])
            except Exception:  # noqa: BLE001 — attribution probe
                if len(ks) == 1:
                    bad_runs.append(ks[0])
                    return
                mid = len(ks) // 2
                bisect(ks[:mid])
                bisect(ks[mid:])
                return
            ok_runs.extend(ks)
            for i, mask, u in zip(sub, out, unk):
                i.finish({"mask": int(mask), "unknown": bool(u)})

        if len(keys) == 1:
            bad_runs.append(keys[0])
        else:
            bisect(keys)
        telemetry.count("fleet.quarantine.attributions")
        if not ok_runs and len(keys) > 1:
            # every run fails solo: the DEVICE is sick, not a history
            # — this is what the fleet breaker is for
            self._breaker.record(False)
            for k in keys:
                for i in groups[k]:
                    if not i.done.is_set():
                        i.finish({"mask": 0, "unknown": True,
                                  "error": error})
            return
        if ok_runs:
            self._breaker.record(True)  # attributed: device is fine
        for k in bad_runs:
            self._quarantine_run(k[0], k[1], error)
            self._quarantined_slices(k[0], k[1], groups[k],
                                     probe=False)

    def _quarantine_run(self, tenant: str, run: str,
                        error: str) -> None:
        if self.quarantine.add(tenant, run, error) \
                and self.flightrec is not None:
            self.flightrec.quarantine(tenant, run, "quarantined",
                                      error)

    def _quarantined_slices(self, tenant: str, run: str,
                            items: list[WorkItem],
                            probe: bool = True) -> None:
        """The solo lane for a quarantined run's slices: a
        cooldown-gated SOLO device probe first (success releases the
        run), then the host reach search — exact masks, never wrong,
        just not sharing anyone's launch."""
        from ..tpu import wgl

        t0 = frec.now()
        if probe and self.quarantine.probe_due(tenant, run):
            try:
                out, unk = wgl.check_slices(
                    [i.payload for i in items])
                self.quarantine.record_probe(tenant, run, True)
                if self.flightrec is not None:
                    self.flightrec.quarantine(tenant, run,
                                              "released", "")
                for i, mask, u in zip(items, out, unk):
                    i.finish({"mask": int(mask), "unknown": bool(u)})
                return
            except Exception:  # noqa: BLE001 — probe failure re-arms
                self.quarantine.record_probe(tenant, run, False)
        for i in items:
            try:
                enc, s = i.payload
                mask = int(wgl.search_host_reach(enc.with_init(s)))
                i.finish({"mask": mask, "unknown": False})
            except Exception as e:  # noqa: BLE001 — never wedge
                i.finish({"mask": 0, "unknown": True,
                          "error": repr(e)})
        t1 = frec.now()
        self._stamp_launch(items, t0, t1, 0.0, 0.0)
        with self._lock:
            self._stats["quarantine_items"] += len(items)
        telemetry.count("fleet.quarantine.host-items", len(items))
        if self.flightrec is not None:
            self.flightrec.launch(
                "slice", "quarantine", t0, t1, rows=len(items),
                capacity=self.max_batch, items=items,
                device_ms=0.0, certify_ms=0.0)

    def _run_finals(self, items: list[WorkItem],
                    reason: str) -> None:
        """Finals grouped per model spec -> one batched launch per
        group. payload: {'engine': 'wgl'|'elle', 'model': name,
        'history': History}."""
        groups: dict[tuple, list[WorkItem]] = {}
        for i in items:
            # the initial value is part of the model spec: different
            # initials can't share one batched launch
            key = (i.payload["model"], i.payload.get("initial"))
            groups.setdefault(key, []).append(i)
        for (model_name, initial), group in groups.items():
            self._run_final_group(model_name, initial, group, reason)

    def _run_final_group(self, model_name: str, initial,
                         group: list[WorkItem],
                         reason: str) -> None:
        from . import build_model, elle_checks

        engine = group[0].payload["engine"]
        # quarantined runs' finals go to the solo host lane too (with
        # the same cooldown-gated probe), so one poison history can't
        # kill the whole model group's batched launch
        if engine == "wgl":
            solo = [g for g in group
                    if self.quarantine.is_quarantined(g.tenant,
                                                      g.run)]
            if solo:
                group = [g for g in group if g not in solo]
                runs: dict[tuple[str, str], list[WorkItem]] = {}
                for g in solo:
                    runs.setdefault((g.tenant, g.run), []).append(g)
                for (tenant, run), sub in runs.items():
                    self._quarantined_finals(model_name, initial,
                                             tenant, run, sub)
            if not group:
                return
        hists = [g.payload["history"] for g in group]
        # the breaker decision is made HERE, once per group, so the
        # decision log can attribute the launch to it
        host = engine == "wgl" and not self._breaker.allow_device()
        if host:
            reason = "breaker"
        try:
            t0, r0 = frec.now(), util.relative_time_nanos()
            if engine == "wgl":
                results = self._wgl_finals(
                    build_model(model_name, initial), hists, host)
            else:
                check = elle_checks()[model_name]
                results = [check(h, {"certify": True})
                           for h in hists]
            t1, r1 = frec.now(), util.relative_time_nanos()
            device_ms, certify_ms = frec.kernel_phases(r0, r1)
            self._stamp_launch(group, t0, t1, device_ms, certify_ms)
            with self._lock:
                self._stats["launches"] += 1
                self._stats["final_launches"] += 1
                self._stats["final_hists"] += len(hists)
            if self.flightrec is not None:
                self.flightrec.launch(
                    "final", reason, t0, t1, rows=len(hists),
                    capacity=self.max_batch, items=group,
                    device_ms=device_ms, certify_ms=certify_ms)
            self._breaker.record(True)
            for g, r in zip(group, results):
                g.finish(r)
        except Exception as e:  # noqa: BLE001 — never wedge a queue
            logger.exception("fleet final batch failed (%s)",
                             model_name)
            if engine == "wgl" and not host:
                self._attribute_final_failure(model_name, initial,
                                              group, repr(e))
            else:
                self._breaker.record(False)
                for g in group:
                    if not g.done.is_set():
                        g.finish({"valid?": "unknown",
                                  "error": repr(e)})

    def _attribute_final_failure(self, model_name: str, initial,
                                 group: list[WorkItem],
                                 error: str) -> None:
        """Per-run attribution for a dead finals launch, mirroring
        _attribute_slice_failure: solo device retries per run; the run
        that still dies alone is quarantined and served by the host
        algorithm; all runs dying solo is systemic and goes to the
        fleet breaker."""
        from . import build_model

        groups: dict[tuple[str, str], list[WorkItem]] = {}
        for g in group:
            groups.setdefault((g.tenant, g.run), []).append(g)
        keys = list(groups)
        ok_runs: list[tuple[str, str]] = []
        bad_runs: list[tuple[str, str]] = []
        for k in keys:
            sub = [g for g in groups[k] if not g.done.is_set()]
            if not sub:
                ok_runs.append(k)
                continue
            try:
                with self._lock:
                    self._stats["bisect_launches"] += 1
                results = self._wgl_finals(
                    build_model(model_name, initial),
                    [g.payload["history"] for g in sub], False)
                for g, r in zip(sub, results):
                    g.finish(r)
                ok_runs.append(k)
            except Exception:  # noqa: BLE001 — attribution probe
                bad_runs.append(k)
        telemetry.count("fleet.quarantine.attributions")
        if not ok_runs and len(keys) > 1:
            self._breaker.record(False)
            for k in keys:
                for g in groups[k]:
                    if not g.done.is_set():
                        g.finish({"valid?": "unknown",
                                  "error": error})
            return
        if ok_runs:
            self._breaker.record(True)
        else:
            # a single-run batch that died solo: quarantine serves it
            # from the host lane; its own probe decides when it may
            # rejoin the shared pool
            self._breaker.record(False)
        for k in bad_runs:
            self._quarantine_run(k[0], k[1], error)
            self._quarantined_finals(model_name, initial, k[0], k[1],
                                     groups[k], probe=False)

    def _quarantined_finals(self, model_name: str, initial,
                            tenant: str, run: str,
                            group: list[WorkItem],
                            probe: bool = True) -> None:
        """Solo lane for a quarantined run's finals: cooldown-gated
        device probe, then the pure-host algorithm — the same
        slower-never-wrong floor the fleet breaker uses, but scoped to
        ONE run."""
        from . import build_model
        from ..tpu import wgl

        group = [g for g in group if not g.done.is_set()]
        if not group:
            return
        model = build_model(model_name, initial)
        hists = [g.payload["history"] for g in group]
        t0 = frec.now()
        if probe and self.quarantine.probe_due(tenant, run):
            try:
                results = self._wgl_finals(model, hists, False)
                self.quarantine.record_probe(tenant, run, True)
                if self.flightrec is not None:
                    self.flightrec.quarantine(tenant, run,
                                              "released", "")
                for g, r in zip(group, results):
                    g.finish(r)
                return
            except Exception:  # noqa: BLE001 — probe failure re-arms
                self.quarantine.record_probe(tenant, run, False)
        for g, h in zip(group, hists):
            try:
                g.finish(wgl.analysis(model, h, algorithm="wgl",
                                      certify=True))
            except Exception as e:  # noqa: BLE001 — never wedge
                g.finish({"valid?": "unknown", "error": repr(e)})
        t1 = frec.now()
        self._stamp_launch(group, t0, t1, 0.0, 0.0)
        with self._lock:
            self._stats["quarantine_items"] += len(group)
        telemetry.count("fleet.quarantine.host-items", len(group))
        if self.flightrec is not None:
            self.flightrec.launch(
                "final", "quarantine", t0, t1, rows=len(group),
                capacity=self.max_batch, items=group,
                device_ms=0.0, certify_ms=0.0)

    def _wgl_finals(self, model, hists,
                    host: bool = False) -> list[dict]:
        from ..tpu import wgl

        if host:
            # breaker open: the pure-host reference search — the fleet
            # degrades to slower, never to wrong or wedged
            with self._lock:
                self._stats["host_floor"] += len(hists)
            telemetry.count("fleet.breaker.host-finals", len(hists))
            return [wgl.analysis(model, h, algorithm="wgl",
                                 certify=True) for h in hists]
        return wgl.analysis_batch_streamed(model, hists, certify=True)


# ---------------------------------------------------------------------------
# Streaming checks: verdicts tighten as chunks arrive
# ---------------------------------------------------------------------------

class StreamingRun:
    """The online watchdog generalized into streaming wgl: as a
    tenant's chunks arrive, the accumulated prefix is encoded, cut at
    real-time-safe points, and each new segment is submitted as reach
    slices (one row per live start state) that the scheduler packs
    ACROSS tenants into shared launches. The live state-mask chain
    tightens the verdict mid-run: mask 0 at any cut proves the full
    history cannot linearize (tentative-invalid, minutes before fin);
    a surviving chain reports how much of the stream is already
    certified-reachable.

    Soundness: a cut is taken only where the PREFIX's real-time order
    forces all earlier entries before all later ones AND no streamed
    op is still open (an open invocation encodes as crashed ret=INF,
    which valid_cut_points already treats as forbidding later cuts).
    Ops not yet streamed invoke later in history order than everything
    already cut, so prefix cuts remain cuts of the final history.

    add_ops is called under the server's per-run ingest lock (so
    reconnect races can't reorder chunks) and is CHEAP — it appends
    and, at most, spawns the step worker. All encode/cut work runs on
    that worker thread, never on the chunk-ack path; slice results
    return on the scheduler thread. Shared state advances under
    `_lock`. (The worker re-encodes the whole prefix each step — fine
    at streaming-chunk cadence; incremental encoding is the known
    scaling lever when streams reach millions of ops.)
    """

    _guarded_by_lock = {"_lock": ("_ops", "_since", "_mask",
                                  "_checked", "_state", "_inflight",
                                  "_frac")}

    STREAM_EVERY = 128  # re-encode the prefix every N new ops
    MAX_SEG = 2048      # preferred segment length (soundness first:
    # a further-out cut is taken when no valid cut lands within it)

    def __init__(self, model_name: str, scheduler: Scheduler,
                 tenant: str, run: str, initial=None):
        from . import build_model, wgl_models

        self.tenant = tenant
        self.run = run
        self.scheduler = scheduler
        self.model_name = model_name
        self._model = build_model(model_name, initial) \
            if model_name in wgl_models() else None
        self._ops: list = []
        self._since = 0
        self._lock = threading.Lock()
        self._mask: int | None = None  # None until first segment
        self._checked = 0              # entries certified so far
        self._frac = 0.0
        self._state = "streaming" if self._model is not None \
            else "unsupported"
        self._inflight = False
        # set once at attach time (before streaming starts): called
        # with each stream-wgl checkpoint record after a segment's
        # mask lands — the server persists it and compacts the WAL
        self.ckpt_sink = None

    def add_ops(self, ops: list) -> None:
        with self._lock:
            self._ops.extend(ops)
            self._since += len(ops)
            # the due-credit is NOT reset here: step() consumes it
            # only when a worker actually launches, so ops arriving
            # while a segment is in flight keep their claim and the
            # finishing _collect re-kicks for them
            due = self._since >= self.STREAM_EVERY
        if due:
            self.step()

    def status(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "checked-frac": round(self._frac, 4),
                    "ops": len(self._ops)}

    def seed(self, ops: list, rec: dict | None) -> bool:
        """Restart recovery: adopts the replayed ops AND, when the
        checkpoint record proves it describes a prefix of them
        (kind/model match + ops_digest over the first n_ops), resumes
        the certified frontier — checked entries, live state mask —
        so the stream re-checks only the suffix instead of replaying
        from entry 0. A stale/mismatched record is ignored (counted),
        never trusted: the stream falls back to a full re-check."""
        from ..tpu import ckpt

        resumed = False
        if rec is not None and self._model is not None:
            ok = (rec.get("kind") == "stream-wgl"
                  and rec.get("model") == self.model_name
                  and rec.get("n_ops", 0) <= len(ops)
                  and ckpt.ops_digest(ops, rec["n_ops"])
                  == rec.get("digest"))
            if ok:
                resumed = True
                telemetry.count("ckpt.resumed")
            else:
                telemetry.count("ckpt.stale")
        with self._lock:
            self._ops = list(ops)
            if resumed:
                self._checked = int(rec["checked"])
                self._mask = int(rec["mask"])
                if self._mask == 0 and self._state == "streaming":
                    self._state = "tentative-invalid"
            # everything past the checkpoint is due immediately
            self._since = max(len(ops), self.STREAM_EVERY)
        return resumed

    # -- the streaming step ---------------------------------------------

    def step(self) -> None:
        """Kicks the step worker (if the run is streaming and no
        segment is already in flight). The worker encodes the prefix
        snapshot and submits the next unchecked segment's reach slices
        — one segment in flight per run: its result chains into the
        next segment's start states, while OTHER runs' slices fill the
        same launch."""
        with self._lock:
            if self._state != "streaming" or self._inflight:
                return
            self._inflight = True
            self._since = 0  # the worker now owns the due-credit
        threading.Thread(
            target=self._step_work,
            name=f"fleet-stream-{self.tenant}-{self.run}",
            daemon=True).start()

    def _step_work(self) -> None:
        from ..tpu import encode as enc_mod
        from ..tpu import wgl

        def settle(state: str | None = None) -> None:
            """Early exit WITHOUT launching: the due-credit step()
            consumed goes back (add_ops documents that credit is only
            spent on a real launch), so the ops that earned it get
            their check as soon as the next chunk — or _collect's
            re-kick — fires."""
            with self._lock:
                self._inflight = False
                if state is not None:
                    self._state = state
                elif self._since < self.STREAM_EVERY:
                    self._since = self.STREAM_EVERY

        try:
            with self._lock:
                snapshot = list(self._ops)
                lo = self._checked
                mask = self._mask
            try:
                enc = enc_mod.encode(self._model, History(snapshot))
            except enc_mod.EncodingError:
                return settle("unsupported")
            if enc.n_states > 32:
                return settle("unsupported")
            if mask is None:
                mask = 1 << enc.init_state
            cuts = [int(c) for c in wgl.valid_cut_points(enc)
                    if c > lo]
            if not cuts:
                return settle()
            # furthest valid cut within the preferred segment length;
            # when none lands inside it, the NEAREST cut beyond wins —
            # never a non-cut boundary, which would let ops span the
            # segment edge and fabricate a tentative-invalid
            within = [c for c in cuts if c <= lo + self.MAX_SEG]
            hi = within[-1] if within else cuts[0]
            if hi - lo < self.STREAM_EVERY // 2 and hi < enc.m:
                return settle()  # too little new work to launch for
            seg = enc.segment(lo, hi)
            states = [s for s in range(enc.n_states)
                      if (mask >> s) & 1]
            if not states:
                return settle()
            # ONE Encoded shared across the start-state rows:
            # check_slices dedupes by identity, so the segment's
            # tensors are packed once however many states are live
            # (the row carries s)
            items = [self.scheduler.submit(
                "slice", self.tenant, self.run, (seg, s))
                for s in states]
            # the checkpoint cut in RAW-op coordinates: every entry
            # below a valid cut completed, so the furthest completion
            # position bounds the raw prefix the cut certifies
            raw_cut = int(enc.ret_t[:hi].max()) + 1 if hi > 0 else 0
            ck = {"n_ops": raw_cut,
                  "digest": _ckpt_mod().ops_digest(snapshot, raw_cut)}
        except Exception:  # noqa: BLE001 — streaming is advisory
            logger.exception("streaming step failed")
            return settle("unknown")
        self._collect(items, lo, hi, enc.m, ck)

    def _collect(self, items: list[WorkItem], lo: int, hi: int,
                 total_m: int, ck: dict | None = None) -> None:
        new_mask = 0
        unknown = False
        for i in items:
            i.done.wait(timeout=120)
            r = i.result or {}
            if not i.done.is_set() or r.get("unknown"):
                unknown = True
            new_mask |= int(r.get("mask") or 0)
        with self._lock:
            self._inflight = False
            if self._state != "streaming":
                return
            if unknown:
                # the device couldn't decide this segment: streaming
                # stops tightening; the final check stays authoritative
                self._state = "unknown"
                return
            self._mask = new_mask
            self._checked = hi
            self._frac = hi / max(total_m, 1)
            if new_mask == 0:
                # no state survives the prefix: the full history can
                # never linearize — the verdict tightened to invalid
                # BEFORE the stream even finished
                self._state = "tentative-invalid"
                telemetry.count("fleet.stream.tentative-invalid")
        telemetry.count("fleet.stream.segments")
        sink = self.ckpt_sink
        if sink is not None and ck is not None:
            # checkpoint OUTSIDE the lock: the sink does file I/O
            # (atomic ckpt write + WAL compaction) and must never
            # block add_ops/status
            try:
                ckpt = _ckpt_mod()
                sink({"v": ckpt.VERSION, "kind": "stream-wgl",
                      "model": self.model_name, "checked": hi,
                      "mask": new_mask, "n_ops": ck["n_ops"],
                      "digest": ck["digest"]})
            except Exception:  # noqa: BLE001 — checkpoints are
                logger.exception("stream checkpoint sink failed")
                # advisory: a failed write degrades resume, not verdicts
        with self._lock:
            pending = (self._state == "streaming"
                       and self._since >= self.STREAM_EVERY)
        if pending:
            # ops that arrived while this segment was in flight kept
            # their due-credit: check them now, don't wait for more
            self.step()
