"""Per-op causal tracing across the client/remote layers.

Telemetry (PR 2) and the live monitor (PR 3) stop at harness-level
spans: an op's lifetime is one opaque interval. This module is the
request-scoped layer underneath — the analog of the reference's
op-scoped client tracing (dgraph/src/jepsen/dgraph/trace.clj wraps
every client call in a span tied to the invoking op): the interpreter
mints a trace context per invocation, and everything that happens on
behalf of that op — client calls, remote (SSH) command executions,
transport retries, reconnects, partition changes — records as child
spans and events under it, so an anomaly can be walked back to the
exact commands and faults that produced it.

Model:

  *Trace* — one per invocation; the trace id IS the invocation's op
  index, so trace records join the history (and the timeline/Perfetto
  reports) with no extra bookkeeping.
  *Span*  — a timed record {trace, span, parent, kind, name, op,
  process, t0, t1, attrs}. Kinds: "op" (the worker-side invoke, the
  trace root), "client" (one client call), "remote" (one remote
  command: cmd, node, exit, retries). Context propagates per thread:
  each thread keeps a stack of open spans; a span's parent is the
  innermost open span on the same thread.
  *Event* — a zero-duration record (kind "event"): reconnects,
  transport failures, partition changes. Events outside any op
  context (e.g. during db setup) record with trace None.

Timestamps ride the test's linear clock (util.relative_time_nanos,
the same clock ops and telemetry spans are stamped with), so client
and remote spans nest exactly under the op-lifetime slices in the
Perfetto export.

Serialization: `optrace.jsonl` in the run's store directory, one JSON
object per completed record, streamed as records complete (a separate
process can tail it; a torn trailing line is dropped on read — the
shared crash-tolerance contract of telemetry.jsonl).

The recorder is OFF by default: `test["trace?"] = True` opts a run in
(core.run wires the lifecycle), and every record call begins with one
`enabled` check, so a disabled tracer costs nothing on the
interpreter's hot path (bench.py's trace-overhead line records the
enabled cost).
"""

from __future__ import annotations

import itertools
import json
import logging
import threading
from contextlib import contextmanager
from pathlib import Path
from typing import Iterator

from . import util

logger = logging.getLogger(__name__)

TRACE_FILE = "optrace.jsonl"

KINDS = ("op", "client", "remote", "event")

# Stream-write cadence: a background writer thread drains completed
# records every interval, serializing and writing OFF the interpreter
# hot path (per-record dumps+write there cost the dummy-op bench ~3x;
# the hot path pays one lock + two list appends per record). Rare
# interesting kinds (remote, event) wake the writer immediately so
# tailers see faults as they land.
_WRITER_INTERVAL_S = 0.3


class Tracer:
    """A per-run trace recorder. Thread-safe; one global instance
    (get()) serves the process, but tests may make their own."""

    def __init__(self, enabled: bool = False):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._local = threading.local()
        self._records: list[dict] = []
        self._out = None
        self._pending: list[dict] = []  # completed, not yet written
        self._writer: threading.Thread | None = None
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._epoch = 0
        # itertools.count is atomic under the GIL: span ids without a
        # lock round-trip on the interpreter hot path
        self._ids = itertools.count(1)

    @property
    def epoch(self) -> int:
        """Bumped by every reset(). Spans capture it when they open
        and drop their record if a reset intervened before they
        closed — a straggler worker thread from an abnormally-exited
        run must not leak foreign records (with colliding span ids
        from the restarted counter) into the next run's trace. The
        same rule telemetry applies to deferred counter flushes."""
        return self._epoch

    # -- context -----------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    def current(self) -> dict | None:
        """The innermost open span on this thread, if any."""
        st = self._stack()
        return st[-1] if st else None

    def _span_id(self) -> int:
        return next(self._ids)

    # -- recording ---------------------------------------------------------

    @contextmanager
    def op_span(self, op):
        """Root span for one invocation: mints the op's trace context
        (trace id = the invocation's op index). The interpreter wraps
        every worker invoke in this; a crash closes the span with
        status 'crashed'."""
        if not self.enabled or op.index is None or op.index < 0:
            yield None
            return
        epoch0 = self._epoch
        rec: dict = {"trace": int(op.index), "span": self._span_id(),
                     "parent": None, "kind": "op", "name": str(op.f),
                     "op": int(op.index),
                     "process": util.name_str(op.process),
                     "t0": util.relative_time_nanos()}
        st = self._stack()
        st.append(rec)
        try:
            yield rec
        except BaseException:
            rec["status"] = "crashed"
            raise
        finally:
            rec["t1"] = util.relative_time_nanos()
            st.pop()
            self._emit(rec, epoch0)

    @contextmanager
    def span(self, kind: str, name: str, **attrs):
        """A child span under the ambient op context. Yields the
        mutable record (add attrs mid-flight); yields None — and
        records nothing — when tracing is off or no op context is open
        on this thread (e.g. a remote command during db setup)."""
        if not self.enabled:
            yield None
            return
        parent = self.current()
        if parent is None:
            yield None
            return
        epoch0 = self._epoch
        rec: dict = {"trace": parent["trace"], "span": self._span_id(),
                     "parent": parent["span"], "kind": kind,
                     "name": str(name), "op": parent["op"],
                     "process": parent["process"],
                     "t0": util.relative_time_nanos()}
        if attrs:
            rec["attrs"] = {k: v for k, v in attrs.items()
                            if v is not None}
        st = self._stack()
        st.append(rec)
        try:
            yield rec
        finally:
            rec["t1"] = util.relative_time_nanos()
            st.pop()
            self._emit(rec, epoch0)

    @contextmanager
    def attach(self, parent: dict | None):
        """Binds an already-open span as this thread's ambient context
        — how control.on_nodes carries an op's trace across its worker
        pool, so the parallel per-node remote commands still record as
        children of the nemesis/client op that issued them. The parent
        record is only read (children copy its trace/span ids), so
        sharing it across threads is safe."""
        if not self.enabled or parent is None:
            yield
            return
        st = self._stack()
        st.append(parent)
        try:
            yield
        finally:
            st.pop()

    def annotate(self, **attrs) -> None:
        """Merges attrs into the innermost open span on this thread
        (how the retry layer stamps its count onto the remote span)."""
        if not self.enabled:
            return
        cur = self.current()
        if cur is not None:
            cur.setdefault("attrs", {}).update(attrs)

    def event(self, name: str, **attrs) -> None:
        """A zero-duration record: reconnects, transport failures,
        partition changes. Attaches to the ambient op context when one
        is open; records context-free (trace None) otherwise."""
        if not self.enabled:
            return
        epoch0 = self._epoch
        parent = self.current()
        now = util.relative_time_nanos()
        rec: dict = {"trace": parent["trace"] if parent else None,
                     "span": self._span_id(),
                     "parent": parent["span"] if parent else None,
                     "kind": "event", "name": str(name),
                     "op": parent["op"] if parent else None,
                     "process": parent["process"] if parent else None,
                     "t0": now, "t1": now}
        if attrs:
            rec["attrs"] = {k: v for k, v in attrs.items()
                            if v is not None}
        self._emit(rec, epoch0)

    def _emit(self, rec: dict, epoch0: int) -> None:
        with self._lock:
            if self._epoch != epoch0:
                return  # straggler from a reset-away run (see epoch)
            self._records.append(rec)
            if self._out is None:
                return
            self._pending.append(rec)
        if rec.get("kind") in ("remote", "event"):
            self._wake.set()

    def _drain(self) -> None:
        """Serializes and writes everything pending (writer thread /
        close). A record is immutable once emitted, so dumping outside
        the lock is safe."""
        with self._lock:
            batch, self._pending = self._pending, []
            out = self._out
        if not batch or out is None:
            return
        try:
            out.write("".join(
                json.dumps(r, default=repr) + "\n" for r in batch))
            out.flush()
        except (OSError, ValueError):  # closed file loses the batch
            logger.exception("optrace write failed")
            with self._lock:
                self._out = None

    def _writer_loop(self) -> None:
        while not self._stop.is_set():
            self._wake.wait(_WRITER_INTERVAL_S)
            self._wake.clear()
            self._drain()
        self._drain()

    # -- lifecycle ---------------------------------------------------------

    def reset(self, enabled: bool | None = None) -> None:
        """Clears records and closes any stream; optionally flips the
        enabled flag. core.run calls this per run."""
        self.close()
        with self._lock:
            self._records = []
            self._pending = []
            self._ids = itertools.count(1)
            self._epoch += 1
        if enabled is not None:
            self.enabled = enabled

    def open(self, path) -> None:
        """Starts streaming records to `path` (optrace.jsonl)."""
        try:
            p = Path(path)
            p.parent.mkdir(parents=True, exist_ok=True)
            with self._lock:
                self._out = open(p, "w")
                self._pending = []
        except OSError:  # tracing must never sink the run
            logger.exception("optrace artifact unavailable")
            with self._lock:
                self._out = None
            return
        self._stop.clear()
        self._wake.clear()
        self._writer = threading.Thread(
            target=self._writer_loop, name="jepsen-optrace", daemon=True)
        self._writer.start()

    def flush(self) -> None:
        """Synchronously writes everything pending — core.run calls
        this between the case and analysis, so checkers (timeline
        hover detail, trace excerpts) read a complete artifact."""
        self._drain()

    def close(self) -> None:
        if self._writer is not None:
            self._stop.set()
            self._wake.set()
            self._writer.join(timeout=5)
            self._writer = None
        self._drain()
        with self._lock:
            if self._out is not None:
                try:
                    self._out.close()
                except OSError:
                    pass
                self._out = None

    def records(self) -> list[dict]:
        """Completed records, append order."""
        with self._lock:
            return list(self._records)

    def save(self, directory) -> Path:
        """Writes optrace.jsonl into `directory` (for tracers that
        never streamed); returns the path."""
        d = Path(directory)
        d.mkdir(parents=True, exist_ok=True)
        out = d / TRACE_FILE
        with open(out, "w") as f:
            for rec in self.records():
                f.write(json.dumps(rec, default=repr))
                f.write("\n")
        return out


# ---------------------------------------------------------------------------
# Process-global tracer + module-level façade
# ---------------------------------------------------------------------------

_global = Tracer()


def get() -> Tracer:
    return _global


def span(kind: str, name: str, **attrs):
    return _global.span(kind, name, **attrs)


def event(name: str, **attrs) -> None:
    _global.event(name, **attrs)


def annotate(**attrs) -> None:
    _global.annotate(**attrs)


def current_context() -> dict | None:
    """The calling thread's innermost span as a COMPACT propagatable
    context ({"trace", "span"}), or None outside any span. This is
    the cross-process propagation seam: the fleet client stamps it
    into the wire `tc` field so server-side flight-recorder spans
    (jepsen_tpu.fleet.flightrec) link back to the run's own optrace
    — one causal chain from the op that produced a chunk to the
    device launch that checked it."""
    cur = _global.current()
    if not isinstance(cur, dict):
        return None
    out = {}
    for k in ("trace", "span"):
        if cur.get(k) is not None:
            out[k] = cur[k]
    return out or None


# ---------------------------------------------------------------------------
# Reading + validating stored artifacts
# ---------------------------------------------------------------------------

def read_records(path) -> Iterator[dict]:
    """Records from an optrace.jsonl; a torn trailing line is dropped
    (telemetry.read_jsonl, the shared parser)."""
    from . import telemetry

    return telemetry.read_jsonl(path)


def describe(rec: dict) -> str:
    """A compact one-line description of a trace record — the shared
    formatter behind the timeline hover titles and the anomaly trace
    excerpts (reports/explain)."""
    attrs = rec.get("attrs") or {}
    parts = [f"{rec.get('kind')} {rec.get('name')}"]
    if (rec.get("kind") != "event" and isinstance(rec.get("t0"), int)
            and isinstance(rec.get("t1"), int)):
        parts.append(f"{(rec['t1'] - rec['t0']) / 1e6:.2f}ms")
    if rec.get("status"):
        parts.append(f"status={rec['status']}")
    for k in ("node", "exit", "retries", "type", "error"):
        if k in attrs:
            parts.append(f"{k}={attrs[k]}")
    if "cmd" in attrs:
        parts.append(str(attrs["cmd"])[:48])
    return " ".join(parts)


def by_op(records) -> dict[int, list[dict]]:
    """Indexes records by op (invocation) index — the join key the
    reports and anomaly-provenance excerpts use. Context-free events
    (trace None) are excluded."""
    out: dict[int, list[dict]] = {}
    for rec in records:
        op = rec.get("op")
        if isinstance(op, int):
            out.setdefault(op, []).append(rec)
    return out


_REQUIRED = ("span", "kind", "name", "t0", "t1")


def validate_records(records) -> int:
    """Schema check for an optrace record stream: required keys,
    monotonic timestamps (t1 >= t0 >= 0), known kinds, unique span
    ids, and parent-span referential integrity (every parent id names
    a record in the same trace). Returns the record count; raises
    ValueError on the first violation. Run in tier-1 against both
    generated and stored traces."""
    records = list(records)
    spans: dict[int, dict] = {}
    for i, rec in enumerate(records):
        for key in _REQUIRED:
            if key not in rec:
                raise ValueError(f"record {i} missing {key!r}: {rec}")
        if rec["kind"] not in KINDS:
            raise ValueError(f"record {i} unknown kind: {rec['kind']!r}")
        if not (isinstance(rec["t0"], int) and isinstance(rec["t1"], int)):
            raise ValueError(f"record {i} non-integer timestamps: {rec}")
        if rec["t0"] < 0 or rec["t1"] < rec["t0"]:
            raise ValueError(f"record {i} non-monotonic ts: {rec}")
        sid = rec["span"]
        if sid in spans:
            raise ValueError(f"record {i} duplicate span id {sid}")
        spans[sid] = rec
        if rec["kind"] == "op":
            if rec.get("parent") is not None:
                raise ValueError(f"op record {i} must be a trace root")
            if rec.get("op") != rec.get("trace"):
                raise ValueError(
                    f"op record {i}: op != trace id: {rec}")
    for i, rec in enumerate(records):
        parent = rec.get("parent")
        if parent is None:
            continue
        pr = spans.get(parent)
        if pr is None:
            raise ValueError(
                f"record {i} parent {parent} not in stream: {rec}")
        if pr.get("trace") != rec.get("trace"):
            raise ValueError(
                f"record {i} parent {parent} belongs to another trace")
    return len(records)
