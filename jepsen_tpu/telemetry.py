"""Framework-wide telemetry: nested spans, counters, and gauges.

The rest of the repo answers "is the history valid?"; this module
answers "where did the run spend its time?". It is the missing
observability layer SURVEY §5 calls for on top of the post-hoc perf
plots (reports/perf.py) and the xprof hook (util.profile_trace): a
zero-dependency, thread-safe tracing + metrics recorder that the whole
pipeline reports through —

  - core.run / analyze:  lifecycle phase spans (os-setup, db-cycle,
    case, snarf-logs, per-checker timing)
  - interpreter:         per-worker dispatch + generator-stall counters
  - nemesis:             fault-activation spans
  - tpu/wgl.py:          kernel compile vs execute time, while-loop
    iteration counts, PackedBatch occupancy, checkpoint save/load
  - tpu/elle_device.py + tpu/scc.py: graph sizes, SCC sizes,
    host-vs-device path taken

Model:

  *Spans* are named intervals on the test's linear clock
  (util.relative_time_nanos — the same clock ops are stamped with, so
  spans line up with the history). Nesting is per thread: each thread
  keeps its own span stack, a span's parent is the innermost open span
  on the SAME thread, and spans opened on worker threads are roots.
  *Counters* are monotonically accumulated ints; *gauges* record the
  last value set.

Serialization (written by core.run into the test's store directory):

  telemetry.jsonl   one JSON object per completed span, append order,
                    CRC-free plain lines (crash-tolerant: a torn last
                    line is dropped on read)
  metrics.json      the aggregate: per-span-name {count, total_ns,
                    max_ns}, counters, gauges

The process-global recorder is always on; record calls are a dict
update under one lock, cheap enough for per-op counters. reset() is
called at the top of each core.run so artifacts are scoped per run.
"""

from __future__ import annotations

import json
import logging
import threading
from contextlib import contextmanager
from pathlib import Path
from typing import Any, Callable, Iterator

from . import util

logger = logging.getLogger(__name__)

TRACE_FILE = "telemetry.jsonl"
METRICS_FILE = "metrics.json"


class Telemetry:
    """A span/counter/gauge recorder. Thread-safe; one global instance
    (get()) serves the whole process, but tests may make their own."""

    # concurrency-lint contract (jepsen_tpu.analysis.concurrency,
    # doc/static-analysis.md): shared-mutable state is written under
    # _lock only. Per-thread span stacks live in _local (unshared by
    # construction) and are deliberately not listed.
    _guarded_by_lock = {"_lock": ("_spans", "_open", "_counters",
                                  "_gauges", "_next_id", "_epoch")}

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._local = threading.local()
        self._spans: list[dict] = []
        self._open: dict[int, dict] = {}
        self._counters: dict[str, int] = {}
        self._gauges: dict[str, Any] = {}
        self._next_id = 0
        self._epoch = 0

    # -- spans -------------------------------------------------------------

    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = self._local.stack = []
        return st

    @contextmanager
    def span(self, name: str, **attrs):
        """Context manager recording a named interval. Yields the span
        record (mutable: add attrs mid-flight via rec['attrs'])."""
        if not self.enabled:
            yield None
            return
        with self._lock:
            self._next_id += 1
            sid = self._next_id
            epoch0 = self._epoch
        stack = self._stack()
        rec: dict = {
            "id": sid,
            "parent": stack[-1]["id"] if stack else None,
            "name": name,
            "thread": threading.current_thread().name,
            "t0": util.relative_time_nanos(),
        }
        if attrs:
            rec["attrs"] = attrs
        stack.append(rec)
        with self._lock:
            # shared open-span registry (the thread-local stacks can't
            # be enumerated across threads): the live monitor samples
            # it to stream what the run is doing *right now*
            if self._epoch == epoch0:
                self._open[sid] = rec
        try:
            yield rec
        finally:
            rec["t1"] = util.relative_time_nanos()
            stack.pop()
            with self._lock:
                self._open.pop(sid, None)
                # a straggler thread completing a span after reset()
                # must not leak it into the next run's trace: its id
                # would collide with the new run's ids and its clock
                # origin is stale (same rule as deferred counter
                # flushes — see epoch)
                if self._epoch == epoch0:
                    self._spans.append(rec)

    def record_span(self, name: str, t0: int, t1: int,
                    attrs: dict | None = None,
                    epoch: int | None = None) -> dict | None:
        """Appends an already-timed span (no nesting, parent=None) —
        the path device-launch records take: the profiler times the
        launch phases itself and mirrors the completed interval here so
        it lands in telemetry.jsonl / the Perfetto device track.
        `epoch` (captured via .epoch when the interval STARTED) applies
        the same straggler guard as span(): a reset() between capture
        and append means t0/t1 were measured against a previous run's
        clock origin, and the span is dropped, not misfiled."""
        if not self.enabled:
            return None
        rec: dict = {"name": name, "parent": None,
                     "thread": threading.current_thread().name,
                     "t0": int(t0), "t1": int(t1)}
        if attrs:
            rec["attrs"] = dict(attrs)
        with self._lock:
            if epoch is not None and epoch != self._epoch:
                return None
            self._next_id += 1
            rec["id"] = self._next_id
            self._spans.append(rec)
        return rec

    def timed(self, name: str) -> Callable:
        """Decorator form of span()."""

        def deco(f):
            def wrapper(*args, **kwargs):
                with self.span(name):
                    return f(*args, **kwargs)

            wrapper.__name__ = getattr(f, "__name__", name)
            return wrapper

        return deco

    # -- counters / gauges -------------------------------------------------

    def count(self, name: str, n: int = 1) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._counters[name] = self._counters.get(name, 0) + int(n)

    def gauge(self, name: str, value) -> None:
        if not self.enabled:
            return
        with self._lock:
            self._gauges[name] = value

    def gauge_max(self, name: str, value) -> None:
        """Keeps the maximum across sets — for 'worst seen this run'
        gauges (largest SCC, deepest frontier), where last-write-wins
        would report whichever call happened to run last."""
        if not self.enabled:
            return
        with self._lock:
            cur = self._gauges.get(name)
            if cur is None or value > cur:
                self._gauges[name] = value

    # -- views -------------------------------------------------------------

    def events(self) -> list[dict]:
        """Completed spans, append order."""
        with self._lock:
            return list(self._spans)

    def open_spans(self) -> list[dict]:
        """Snapshot copies of currently-open spans (any thread), start
        order — what the process is doing right now."""
        with self._lock:
            return [dict(r) for r in sorted(self._open.values(),
                                            key=lambda r: r["id"])]

    def counters(self) -> dict:
        with self._lock:
            return dict(self._counters)

    def gauges(self) -> dict:
        with self._lock:
            return dict(self._gauges)

    def metrics(self) -> dict:
        """The aggregate view serialized as metrics.json."""
        spans: dict[str, dict] = {}
        for s in self.events():
            if "t1" not in s:
                continue
            agg = spans.setdefault(
                s["name"], {"count": 0, "total_ns": 0, "max_ns": 0})
            dur = s["t1"] - s["t0"]
            agg["count"] += 1
            agg["total_ns"] += dur
            agg["max_ns"] = max(agg["max_ns"], dur)
        return {"spans": spans, "counters": self.counters(),
                "gauges": self.gauges()}

    def summary(self) -> dict:
        """Compact per-run summary attached to checker results
        (core.analyze): lifecycle phase durations, per-checker timings
        (checker:<name> spans), and all counters/gauges — the kernel
        profile included. Durations in milliseconds."""
        m = self.metrics()
        phases: dict = {}
        checkers: dict = {}
        for name, agg in m["spans"].items():
            ms = round(agg["total_ns"] / 1e6, 3)
            if name.startswith("checker:"):
                checkers[name[len("checker:"):]] = ms
            elif ":" not in name:
                phases[name] = ms
        return {"phases": phases, "checkers": checkers,
                "counters": m["counters"], "gauges": m["gauges"]}

    # -- lifecycle ---------------------------------------------------------

    @property
    def epoch(self) -> int:
        """Bumped by every reset(). Deferred flushers (e.g. worker
        threads batching hot-loop counters) capture it at start and
        skip their flush if a reset intervened, so a straggler thread
        from a crashed run can't pollute the next run's metrics."""
        return self._epoch

    def reset(self) -> None:
        with self._lock:
            self._spans = []
            self._open = {}
            self._counters = {}
            self._gauges = {}
            self._next_id = 0
            self._epoch += 1

    def save(self, directory) -> tuple[Path, Path]:
        """Writes telemetry.jsonl + metrics.json into `directory`;
        returns the two paths."""
        d = Path(directory)
        d.mkdir(parents=True, exist_ok=True)
        trace = d / TRACE_FILE
        with open(trace, "w") as f:
            for s in self.events():
                f.write(json.dumps(s, default=repr))
                f.write("\n")
        metrics = d / METRICS_FILE
        with open(metrics, "w") as f:
            json.dump(self.metrics(), f, indent=1, default=repr)
        return trace, metrics


# ---------------------------------------------------------------------------
# Process-global recorder + module-level façade
# ---------------------------------------------------------------------------

_global = Telemetry()


def get() -> Telemetry:
    return _global


def span(name: str, **attrs):
    return _global.span(name, **attrs)


def count(name: str, n: int = 1) -> None:
    _global.count(name, n)


def gauge(name: str, value) -> None:
    _global.gauge(name, value)


def gauge_max(name: str, value) -> None:
    _global.gauge_max(name, value)


def record_span(name: str, t0: int, t1: int,
                attrs: dict | None = None,
                epoch: int | None = None) -> dict | None:
    return _global.record_span(name, t0, t1, attrs, epoch)


def timed(name: str) -> Callable:
    return _global.timed(name)


def reset() -> None:
    _global.reset()


def save(directory) -> tuple[Path, Path]:
    return _global.save(directory)


# ---------------------------------------------------------------------------
# Reading stored artifacts
# ---------------------------------------------------------------------------

def read_jsonl(path) -> Iterator[dict]:
    """Records from a JSONL artifact; a torn/corrupt trailing line
    (the writer died — or is still — mid-write) is dropped rather than
    raised. The shared crash-tolerance contract of telemetry.jsonl and
    the monitor's timeseries.jsonl."""
    p = Path(path)
    if not p.exists():
        return
    with open(p) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                yield json.loads(line)
            except ValueError:
                return


def read_events(path) -> Iterator[dict]:
    """Spans from a telemetry.jsonl (see read_jsonl)."""
    return read_jsonl(path)


def read_metrics(path) -> dict | None:
    p = Path(path)
    if not p.exists():
        return None
    try:
        with open(p) as f:
            return json.load(f)
    except (OSError, ValueError):
        return None


def validate_metrics(metrics: dict) -> int:
    """Schema check for a metrics.json document (the tracing.
    validate_records analog for the metrics artifact, run in tier-1):
    the three sections exist with the right shapes, every span
    aggregate carries non-negative integer count/total_ns/max_ns with
    max <= total, and counters are integers. Returns the total entry
    count; raises ValueError on the first violation."""
    if not isinstance(metrics, dict):
        raise ValueError("metrics must be a dict")
    for section in ("spans", "counters", "gauges"):
        if not isinstance(metrics.get(section), dict):
            raise ValueError(f"metrics missing {section!r} dict")
    n = 0
    for name, agg in metrics["spans"].items():
        if not isinstance(agg, dict):
            raise ValueError(f"span {name!r}: aggregate must be a dict")
        for key in ("count", "total_ns", "max_ns"):
            v = agg.get(key)
            if not isinstance(v, int) or v < 0:
                raise ValueError(
                    f"span {name!r}: bad {key}: {v!r}")
        if agg["max_ns"] > agg["total_ns"]:
            raise ValueError(
                f"span {name!r}: max_ns {agg['max_ns']} exceeds "
                f"total_ns {agg['total_ns']}")
        if agg["count"] == 0 and agg["total_ns"]:
            raise ValueError(f"span {name!r}: time without count")
        n += 1
    for name, v in metrics["counters"].items():
        if not isinstance(v, int):
            raise ValueError(f"counter {name!r}: non-integer {v!r}")
        n += 1
    n += len(metrics["gauges"])
    return n
