"""Database automation protocols: install, start, stop, kill, pause.

Capability reference: jepsen/src/jepsen/db.clj (DB/Kill/Pause/Primary/
LogFiles protocols 12-48, log-files-map 50-80, cycle! 158-199; tcpdump DB
88-156 lives in jepsen_tpu.dbs.tcpdump).
"""

from __future__ import annotations

import logging
from typing import Any

from . import util

logger = logging.getLogger(__name__)


class DB:
    """Sets up and tears down a database on a node."""

    def setup(self, test, node) -> None:
        pass

    def teardown(self, test, node) -> None:
        pass

    # -- optional protocols (db.clj:20-48). Implement by overriding and
    # setting the matching `supports_*` flag.
    supports_kill = False
    supports_pause = False
    supports_primaries = False

    def kill(self, test, node) -> None:
        """Forcibly terminates DB processes (Kill protocol)."""
        raise NotImplementedError

    def start(self, test, node) -> None:
        """Starts DB processes (Kill protocol)."""
        raise NotImplementedError

    def pause(self, test, node) -> None:
        """SIGSTOPs DB processes (Pause protocol)."""
        raise NotImplementedError

    def resume(self, test, node) -> None:
        """SIGCONTs DB processes (Pause protocol)."""
        raise NotImplementedError

    def primaries(self, test) -> list:
        """Nodes the DB currently considers primaries (Primary protocol)."""
        raise NotImplementedError

    def setup_primary(self, test, node) -> None:
        """One-time primary-node setup (Primary protocol)."""
        pass

    def log_files(self, test, node):
        """Log file paths (or {path: local-name} map) to download from the
        node (LogFiles protocol)."""
        return []


class NoopDB(DB):
    pass


noop = NoopDB()


def log_files_map(db: DB, test, node) -> dict:
    """Normalizes log_files output to {remote-path: local-filename}
    (db.clj:50-80)."""
    lf = db.log_files(test, node) or []
    if isinstance(lf, dict):
        return dict(lf)
    out = {}
    seen: dict[str, int] = {}
    for path in lf:
        name = str(path).rstrip("/").split("/")[-1]
        if name in seen:
            seen[name] += 1
            name = f"{name}.{seen[name]}"
        else:
            seen[name] = 0
        out[path] = name
    return out


def cycle(db: DB, test, node, retries: int = 3) -> None:
    """teardown! then setup!, retrying on failure (db.clj:158-199)."""
    def once():
        db.teardown(test, node)
        db.setup(test, node)

    util.with_retry(once, retries=retries, backoff=1.0)
