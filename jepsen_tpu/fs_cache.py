"""Control-node filesystem cache for expensive artifacts.

Capability reference: jepsen/src/jepsen/fs_cache.clj — cached values
live under logical paths (vectors of strings/keywords/numbers,
url-encoded into directories, 58-120), writers are atomic
(temp-file-then-rename, 141-186), values store as strings, data, local
files, or node files pulled over the control connection, and
deploy_remote pushes a cached file back onto a node (250-276). A
named-lock table serializes expensive cache misses (278-282).
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import urllib.parse
from contextlib import contextmanager
from pathlib import Path

from . import control, util

DEFAULT_DIR = Path("/tmp/jepsen/cache")

_locks = util.named_locks()


def _base() -> Path:
    return Path(os.environ.get("JEPSEN_TPU_CACHE_DIR", DEFAULT_DIR))


def _encode_part(part) -> str:
    """One path element -> a safe directory name (fs_cache.clj Encode,
    58-103: keywords/numbers/bools/strings, url-escaped)."""
    if isinstance(part, bool):
        s = "true" if part else "false"
    elif part is None:
        s = "nil"
    else:
        s = str(part)
    # quote leaves '.' unreserved, so '.'/'..' parts would escape the
    # cache root — encode dots too
    return urllib.parse.quote(s, safe="").replace(".", "%2E")


def file(path) -> Path:
    """The cache File for a logical path (a list/tuple of parts)."""
    if not isinstance(path, (list, tuple)):
        path = [path]
    return _base().joinpath(*[_encode_part(p) for p in path])


def cached_p(path) -> bool:
    return file(path).is_file()


@contextmanager
def _atomic(final: Path):
    """Write to a temp file in the same directory, rename into place
    (fs_cache.clj write-atomic!, 160-186)."""
    final.parent.mkdir(parents=True, exist_ok=True)
    fd, tmp = tempfile.mkstemp(dir=final.parent,
                               prefix=f".{final.name}.", suffix=".tmp")
    os.close(fd)
    tmp_p = Path(tmp)
    try:
        yield tmp_p
        os.replace(tmp_p, final)
    finally:
        tmp_p.unlink(missing_ok=True)


def save_string(s: str, path) -> str:
    with _atomic(file(path)) as tmp:
        tmp.write_text(s)
    return s


def load_string(path) -> str | None:
    f = file(path)
    return f.read_text() if f.is_file() else None


def save_data(value, path):
    """JSON analog of save-edn!. Non-JSON values raise at save time:
    silently storing reprs would corrupt the round-trip."""
    with _atomic(file(path)) as tmp:
        with open(tmp, "w") as fh:
            json.dump(value, fh)
    return value


def load_data(path):
    f = file(path)
    if not f.is_file():
        return None
    with open(f) as fh:
        return json.load(fh)


def save_file(local, path):
    """Copies a local file into the cache."""
    with _atomic(file(path)) as tmp:
        shutil.copy2(local, tmp)
    return local


def load_file(path) -> Path | None:
    f = file(path)
    return f if f.is_file() else None


def save_remote(remote_path: str, path) -> str:
    """Downloads a node file (over the current control session) into
    the cache (fs_cache.clj save-remote!, 250-258)."""
    with _atomic(file(path)) as tmp:
        control.download([remote_path], tmp)
    return remote_path


def deploy_remote(path, remote_path: str) -> str:
    """Pushes a cached file onto the node at remote_path, replacing it
    (fs_cache.clj deploy-remote!, 260-276)."""
    if not cached_p(path):
        raise RuntimeError(f"path {path!r} is not cached and cannot "
                           "be deployed")
    import re

    if not re.match(r"/\w+/.+", remote_path):
        raise ValueError(
            f"remote path {remote_path!r} looks relative or "
            "suspiciously short — this might be dangerous!")
    control.exec_("rm", "-rf", remote_path)
    parent = str(Path(remote_path).parent)
    control.exec_("mkdir", "-p", parent)
    control.upload([str(file(path))], remote_path)
    return remote_path


@contextmanager
def locking(path):
    """Serializes expensive cache misses per logical path
    (fs_cache.clj locking, 278-282)."""
    key = (tuple(path) if isinstance(path, (list, tuple))
           else (path,))  # same normalization file() applies
    with _locks.hold(key):
        yield


def clear() -> None:
    """Wipes the whole cache (tests)."""
    shutil.rmtree(_base(), ignore_errors=True)
