"""Static analysis of the checker pipeline (graftlint).

`jepsen_tpu.tpu.lint` holds the rule suite over traced kernels;
this package holds the drivers:

  registry.py     enumerates every compiled entry point and traces it
                  abstractly at representative shape buckets
  concurrency.py  AST lock-discipline lint over the threaded harness
                  modules (the _guarded_by_lock convention)
  driver.py       runs registry x rules + the concurrency lint,
                  aggregates, renders, and gates against the
                  committed lint-baseline.json

Surfaced via `python -m jepsen_tpu lint`, the web /lint page, bench's
lint-wall line, and `lint.*` telemetry counters. doc/static-analysis.md
is the rule catalog.
"""

from .driver import LintReport, run_lint  # noqa: F401
