"""AST lock-discipline lint for the threaded harness modules.

The observability fabric (telemetry, monitor, nodeprobe, profiler)
and the interpreter all share mutable state across threads behind
per-instance locks. The convention this lint enforces
(doc/static-analysis.md):

  - A class declares which lock guards which attributes:

        _guarded_by_lock = {"_lock": ("_records", "_pending")}

    (or a bare tuple/list, meaning guarded by `self._lock`).

  - Every WRITE to a guarded attribute — assignment, augmented
    assignment, `del`, subscript store, or a known mutator call like
    `self._records.append(...)` — must happen inside a
    `with self.<lock>:` block. `__init__` is exempt (the object isn't
    shared yet).

  - Methods named `*_locked` assert "caller holds the lock": their
    bodies are analyzed as lock-held (C1 passes), and CALLING one
    outside a lock block is its own finding (C2).

  - A class that creates a `self.*lock*` but declares no
    `_guarded_by_lock` gets an advisory finding (C3) so new threaded
    classes opt into the convention.

Reads are deliberately unchecked (snapshot-read-then-copy idioms are
pervasive and safe here); the lint polices the writes that corrupt.
Nested functions are analyzed as lock-NOT-held even when defined
inside a with-block: a closure may run later, on another thread,
after the lock was released.
"""

from __future__ import annotations

import ast
import inspect

from ..tpu.lint import Finding

ANNOTATION = "_guarded_by_lock"

# Method calls on a guarded attribute that mutate it in place.
MUTATORS = frozenset({
    "append", "extend", "insert", "add", "update", "setdefault",
    "pop", "popitem", "remove", "discard", "clear", "appendleft",
    "popleft", "sort", "reverse",
})


def scan_module(module) -> list[Finding]:
    try:
        src = inspect.getsource(module)
        fname = inspect.getsourcefile(module)
    except (OSError, TypeError):
        return []
    modname = module.__name__.rsplit(".", 1)[-1]
    return scan_source(src, fname, modname)


def scan_source(src: str, fname: str | None,
                modname: str) -> list[Finding]:
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []
    out: list[Finding] = []
    for node in ast.walk(tree):
        if isinstance(node, ast.ClassDef):
            out.extend(_Class(node, fname, modname).scan())
    return out


def _annotation_of(cls: ast.ClassDef) -> dict[str, set[str]] | None:
    """{lock_attr: {guarded attrs}} from the class's _guarded_by_lock
    (dict, or bare sequence meaning lock '_lock'); None if absent."""
    for stmt in cls.body:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = stmt.targets
        elif isinstance(stmt, ast.AnnAssign) and stmt.value is not None:
            targets = [stmt.target]
        if not any(isinstance(t, ast.Name) and t.id == ANNOTATION
                   for t in targets):
            continue
        try:
            val = ast.literal_eval(stmt.value)
        except ValueError:
            return None
        if isinstance(val, dict):
            return {str(k): set(map(str, v)) for k, v in val.items()}
        return {"_lock": set(map(str, val))}
    return None


def _creates_lock(cls: ast.ClassDef) -> tuple[str, int] | None:
    """(attr, line) of a `self.<something containing 'lock'> = ...`
    in __init__, for the C3 advisory."""
    for fn in cls.body:
        if isinstance(fn, ast.FunctionDef) and fn.name == "__init__":
            for node in ast.walk(fn):
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if (isinstance(t, ast.Attribute)
                                and isinstance(t.value, ast.Name)
                                and t.value.id == "self"
                                and "lock" in t.attr.lower()):
                            return t.attr, node.lineno
    return None


def _self_attr(node) -> str | None:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


class _Class:
    def __init__(self, cls: ast.ClassDef, fname: str | None,
                 modname: str):
        self.cls = cls
        self.fname = fname
        self.kernel = f"{modname}.{cls.name}"
        self.out: list[Finding] = []
        ann = _annotation_of(cls)
        self.attr_lock = {} if ann is None else \
            {a: lock for lock, attrs in ann.items() for a in attrs}
        self.locks = set(ann or ())
        self.annotated = ann is not None

    def scan(self) -> list[Finding]:
        if not self.annotated:
            made = _creates_lock(self.cls)
            if made is not None:
                attr, line = made
                self.out.append(Finding(
                    rule="C3", kernel=self.kernel, site=attr,
                    severity="info",
                    message=f"{self.cls.name} creates `self.{attr}` "
                            f"but declares no {ANNOTATION}: the "
                            "concurrency lint can't check its shared "
                            "writes",
                    file=self.fname, line=line,
                    hint=f"declare {ANNOTATION} = {{'{attr}': "
                         "(...guarded attrs...)}"))
            return self.out
        for fn in self.cls.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__":
                continue
            held = frozenset(self.locks) if fn.name.endswith("_locked") \
                else frozenset()
            self._block(fn.body, held, fn.name)
        return self.out

    # -- recursive statement walk -----------------------------------------

    def _block(self, stmts, held: frozenset, method: str) -> None:
        for stmt in stmts:
            self._stmt(stmt, held, method)

    def _stmt(self, stmt, held: frozenset, method: str) -> None:
        if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # closures run whenever — never credited with the lock
            self._block(stmt.body, frozenset(),
                        f"{method}.{stmt.name}")
            return
        if isinstance(stmt, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in stmt.items:
                a = _self_attr(item.context_expr)
                if a in self.locks:
                    inner.add(a)
            # context expressions themselves run outside the new lock
            for item in stmt.items:
                self._exprs(item.context_expr, held, method)
            self._block(stmt.body, frozenset(inner), method)
            return
        if isinstance(stmt, (ast.If, ast.While)):
            self._exprs(stmt.test, held, method)
            self._block(stmt.body, held, method)
            self._block(stmt.orelse, held, method)
            return
        if isinstance(stmt, (ast.For, ast.AsyncFor)):
            self._exprs(stmt.iter, held, method)
            self._block(stmt.body, held, method)
            self._block(stmt.orelse, held, method)
            return
        if isinstance(stmt, ast.Try):
            self._block(stmt.body, held, method)
            for h in stmt.handlers:
                self._block(h.body, held, method)
            self._block(stmt.orelse, held, method)
            self._block(stmt.finalbody, held, method)
            return
        if isinstance(stmt, ast.Match):
            self._exprs(stmt.subject, held, method)
            for case in stmt.cases:
                if case.guard is not None:
                    self._exprs(case.guard, held, method)
                self._block(case.body, held, method)
            return
        if isinstance(stmt, ast.ClassDef):
            return
        # simple statement: check writes + calls
        self._writes(stmt, held, method)
        self._exprs(stmt, held, method)

    # -- checks ------------------------------------------------------------

    def _need(self, attr: str, held: frozenset) -> str | None:
        lock = self.attr_lock.get(attr)
        if lock is not None and lock not in held:
            return lock
        return None

    def _writes(self, stmt, held: frozenset, method: str) -> None:
        targets = []
        if isinstance(stmt, ast.Assign):
            targets = list(stmt.targets)
        elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
            targets = [stmt.target]
        elif isinstance(stmt, ast.Delete):
            targets = list(stmt.targets)
        for t in targets:
            for el in getattr(t, "elts", None) or [t]:
                base = el.value if isinstance(
                    el, ast.Subscript) else el
                a = _self_attr(base)
                lock = a and self._need(a, held)
                if lock:
                    self.out.append(Finding(
                        rule="C1", kernel=self.kernel,
                        site=f"{method}:{a}",
                        message=f"write to `self.{a}` (guarded by "
                                f"`self.{lock}`) outside the lock "
                                f"in {method}()",
                        file=self.fname, line=stmt.lineno,
                        hint=f"wrap the write in `with self.{lock}:`"
                             " or move it into a *_locked method"))

    def _exprs(self, node, held: frozenset, method: str) -> None:
        """Mutator calls + *_locked calls anywhere inside one simple
        statement / expression. Lambda bodies are closures like
        nested defs: scanned with the lock NOT credited (they may run
        later, on another thread, after the lock was released)."""
        stack = [node]
        while stack:
            n = stack.pop()
            if isinstance(n, ast.Lambda):
                self._exprs(n.body, frozenset(),
                            f"{method}.<lambda>")
                continue
            stack.extend(ast.iter_child_nodes(n))
            if not (isinstance(n, ast.Call)
                    and isinstance(n.func, ast.Attribute)):
                continue
            if n.func.attr in MUTATORS:
                base = n.func.value
                if isinstance(base, ast.Subscript):
                    base = base.value
                a = _self_attr(base)
                lock = a and self._need(a, held)
                if lock:
                    self.out.append(Finding(
                        rule="C1", kernel=self.kernel,
                        site=f"{method}:{a}",
                        message=f"mutating call `self.{a}."
                                f"{n.func.attr}(...)` (guarded by "
                                f"`self.{lock}`) outside the lock "
                                f"in {method}()",
                        file=self.fname, line=n.lineno,
                        hint=f"wrap it in `with self.{lock}:`"))
            elif n.func.attr.endswith("_locked") and \
                    _self_attr(n.func) is not None and not held:
                self.out.append(Finding(
                    rule="C2", kernel=self.kernel,
                    site=f"{method}:{n.func.attr}",
                    message=f"call to self.{n.func.attr}() outside "
                            f"any declared lock in {method}() — "
                            "*_locked methods assert the caller "
                            "holds it",
                    file=self.fname, line=n.lineno,
                    hint="acquire the lock around the call"))
