"""graftlint driver: registry x rules + concurrency lint + ratchet.

`run_lint()` is the one entry point every surface shares — the
`python -m jepsen_tpu lint` CLI, the web /lint page, bench's
lint-wall line and tier-1's baseline gate. It traces every registry
entry at its shape buckets (abstract tracing only: no execution, no
devices, CPU-safe), runs R1-R6 over each trace, AST-audits the host
feeder modules (R2) and the threaded harness modules (C1-C3), and
reports through the house observability fabric (`lint.*` telemetry
counters/gauges).
"""

from __future__ import annotations

import json
import logging
import time
from dataclasses import dataclass, field

from .. import telemetry
from ..tpu import lint as lint_mod
from ..tpu.lint import Finding
from . import concurrency, registry

logger = logging.getLogger(__name__)

# Threaded harness modules under the C1-C3 lock-annotation convention.
CONCURRENCY_MODULE_NAMES = (
    "jepsen_tpu.telemetry",
    "jepsen_tpu.monitor",
    "jepsen_tpu.nodeprobe",
    "jepsen_tpu.interpreter",
    "jepsen_tpu.tpu.profiler",
    # the fleet data plane: every threaded class annotated, C1/C2/C3
    # gated in tier-1 (tests/test_lint.py + tests/test_fleet.py)
    "jepsen_tpu.fleet.scheduler",
    "jepsen_tpu.fleet.server",
    "jepsen_tpu.fleet.client",
    "jepsen_tpu.fleet.flightrec",
    "jepsen_tpu.chaos",
    # checkpoint-and-extend (doc/robustness.md): the store's fault
    # hook and the streaming elle consumer are both threaded
    "jepsen_tpu.tpu.ckpt",
    "jepsen_tpu.tpu.elle",
)


@dataclass
class LintReport:
    findings: list[Finding] = field(default_factory=list)
    traces: list[dict] = field(default_factory=list)  # per-trace meta
    errors: list[dict] = field(default_factory=list)  # entry -> error
    wall_s: float = 0.0
    ratchet: dict | None = None                       # vs a baseline

    def by_rule(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.rule] = out.get(f.rule, 0) + 1
        return dict(sorted(out.items()))

    def aggregates(self) -> dict:
        """The numbers the perf ledger tracks toward zero across the
        SPMD rebuild (ROADMAP items 1-2): R3 non-donated bytes, R4
        replicated bytes and unsharded batch-axis count."""
        non_donated = sum(f.cost_bytes or 0 for f in self.findings
                          if f.rule == "R3")
        replicated = sum(f.cost_bytes or 0 for f in self.findings
                         if f.rule == "R4"
                         and f.site.startswith("replicated:"))
        unsharded = sum(1 for f in self.findings
                        if f.rule == "R4"
                        and f.site.startswith("unsharded-axis:"))
        return {"non_donated_bytes": int(non_donated),
                "replicated_bytes": int(replicated),
                "unsharded_axes": int(unsharded),
                "findings": self.by_rule()}

    def to_dict(self) -> dict:
        out = {"findings": [f.to_dict() for f in self.findings],
               "aggregates": self.aggregates(),
               "traces": self.traces, "wall_s": round(self.wall_s, 3)}
        if self.errors:
            out["errors"] = self.errors
        if self.ratchet is not None:
            out["ratchet"] = {
                "new": [f.to_dict() for f in self.ratchet["new"]],
                "baselined": len(self.ratchet["baselined"]),
                "stale": self.ratchet["stale"],
            }
        return out

    def text(self) -> str:
        lines = []
        agg = self.aggregates()
        lines.append(
            f"graftlint: {len(self.findings)} finding(s) across "
            f"{len(self.traces)} trace(s) in {self.wall_s:.2f}s — "
            + (" ".join(f"{r}={n}" for r, n in agg["findings"].items())
               or "clean"))
        lines.append(
            f"  R3 non-donated {agg['non_donated_bytes'] / 1024:.0f} "
            f"KiB · R4 replicated {agg['replicated_bytes'] / 1024:.0f}"
            f" KiB · R4 unsharded axes {agg['unsharded_axes']}")
        new = set()
        if self.ratchet is not None:
            new = {f.key for f in self.ratchet["new"]}
            lines.append(
                f"  baseline: {len(self.ratchet['baselined'])} "
                f"pinned, {len(new)} NEW, "
                f"{len(self.ratchet['stale'])} stale (fixed)")
        for f in sorted(self.findings,
                        key=lambda f: (f.key not in new, f.key)):
            mark = "NEW " if f.key in new else ""
            where = f"  [{f.file}:{f.line}]" if f.file else ""
            lines.append(f"{mark}{f.rule} {f.kernel} "
                         f"{f.site}{where}")
            lines.append(f"    {f.message}")
            if f.hint:
                lines.append(f"    fix: {f.hint}")
        if self.ratchet and self.ratchet["stale"]:
            lines.append("stale baseline entries (fixed — rerun with "
                         "--update to prune):")
            for k in self.ratchet["stale"]:
                lines.append(f"  {k}")
        for e in self.errors:
            lines.append(f"TRACE ERROR {e['entry']}/{e['bucket']}: "
                         f"{e['error']}")
        return "\n".join(lines)


def _concurrency_modules() -> list:
    import importlib

    mods = []
    for name in CONCURRENCY_MODULE_NAMES:
        try:
            mods.append(importlib.import_module(name))
        except ImportError:  # pragma: no cover — partial installs
            logger.warning("concurrency lint: cannot import %s", name)
    return mods


def run_lint(runtime_buckets: bool = False,
             concurrency_lint: bool = True,
             trace_kernels: bool = True,
             full: bool = False,
             rules: set[str] | None = None) -> LintReport:
    """The full pass. runtime_buckets=False keeps the report
    deterministic (the committed baseline's contract); True
    additionally traces the shapes this process actually compiled
    (profiler.shape_buckets()) and runs R5's bucket-cardinality
    cross-check. full=False stops at jax tracing (~0.1s/kernel, the
    tier-1/bench mode); full=True also lowers each kernel for R4's
    HLO collective scan and XLA cost analysis (seconds)."""
    t0 = time.monotonic()
    rep = LintReport()
    if trace_kernels:
        for entry in registry.entries():
            buckets = list(entry.buckets)
            if runtime_buckets and entry.name == "wgl":
                from ..tpu import profiler

                raw = profiler.shape_buckets().get("wgl", ())
                known = {b["label"] for b in buckets}
                buckets += [b for b in
                            registry.runtime_wgl_buckets(raw)
                            if b["label"] not in known]
            for b in buckets:
                try:
                    trace = entry.trace(b, full=full)
                except Exception as e:  # noqa: BLE001 — keep linting
                    logger.exception("tracing %s/%s failed",
                                     entry.name, b.get("label"))
                    rep.errors.append({"entry": entry.name,
                                       "bucket": b.get("label"),
                                       "error": repr(e)})
                    continue
                rep.findings.extend(lint_mod.run_rules(trace))
                rep.traces.append({
                    "kernel": trace.name, "bucket": trace.bucket,
                    "args_bytes": sum(a.nbytes for a in trace.args),
                    "donated_bytes": sum(a.nbytes for a in trace.args
                                         if a.donated),
                    **{k: trace.cost[k] for k in ("flops",
                                                  "bytes accessed")
                       if k in trace.cost},
                })
        for mod in registry.host_feeder_modules():
            rep.findings.extend(lint_mod.scan_module_dtypes(mod))
        if runtime_buckets:
            from ..tpu import profiler

            rep.findings.extend(lint_mod.runtime_bucket_findings(
                profiler.shape_buckets()))
    if concurrency_lint:
        for mod in _concurrency_modules():
            rep.findings.extend(concurrency.scan_module(mod))
    if rules is not None:
        rep.findings = [f for f in rep.findings if f.rule in rules]
    rep.findings.sort(key=lambda f: (f.rule, f.kernel, f.site))
    rep.wall_s = time.monotonic() - t0
    _mirror_telemetry(rep)
    return rep


def _mirror_telemetry(rep: LintReport) -> None:
    tel = telemetry.get()
    tel.count("lint.runs")
    tel.count("lint.traces", len(rep.traces))
    for rule, n in rep.by_rule().items():
        tel.count(f"lint.findings.{rule}", n)
    agg = rep.aggregates()
    tel.gauge("lint.non-donated-bytes", agg["non_donated_bytes"])
    tel.gauge("lint.replicated-bytes", agg["replicated_bytes"])
    tel.gauge("lint.unsharded-axes", agg["unsharded_axes"])
    tel.gauge("lint.wall-s", round(rep.wall_s, 3))


def gate(report: LintReport, baseline_path,
         rules: set[str] | None = None) -> LintReport:
    """Applies the baseline ratchet to a report (sets .ratchet);
    callers fail on report.ratchet['new']. When the report was
    rule-filtered, pass the same `rules` so pinned findings of OTHER
    rules aren't mislabeled as stale (fixed)."""
    baseline = lint_mod.load_baseline(baseline_path)
    if rules is not None:
        baseline = dict(baseline, findings=[
            e for e in baseline.get("findings", ())
            if e.get("rule") in rules])
    report.ratchet = lint_mod.ratchet(report.findings, baseline)
    telemetry.get().count("lint.new-findings",
                          len(report.ratchet["new"]))
    return report


def main(argv=None) -> int:
    """`python -m jepsen_tpu lint` behind cli.lint_cmd: report, gate
    against --baseline (exit 1 on NEW findings), --update rewrites
    the baseline (pinning current findings, pruning stale keys)."""
    import argparse

    p = argparse.ArgumentParser(prog="lint")
    p.add_argument("--baseline", default=None, metavar="FILE",
                   help="Gate against this committed baseline: only "
                        "NEW findings (not pinned there) fail.")
    p.add_argument("--update", action="store_true",
                   help="Rewrite --baseline with the current "
                        "findings (prunes stale entries).")
    p.add_argument("--json", action="store_true", dest="json_",
                   help="Emit the full report as JSON.")
    p.add_argument("--runtime-buckets", action="store_true",
                   help="Also trace the shape buckets this process "
                        "compiled (non-deterministic; never used for "
                        "the committed baseline).")
    p.add_argument("--full", action="store_true",
                   help="Also LOWER each kernel: R4's HLO collective "
                        "scan + XLA cost analysis (slower; may find "
                        "more than the tracing-only baseline mode).")
    p.add_argument("--rules", default=None, metavar="R1,R2,...",
                   help="Restrict to these rule ids.")
    opts = p.parse_args(argv)
    rules = (set(x.strip() for x in opts.rules.split(","))
             if opts.rules else None)
    if opts.update and rules is not None:
        # a rule-filtered report pins only those rules: writing it
        # would silently drop every other rule's baseline entries,
        # and the next full gate would fail on all of them as NEW
        print("--update with --rules would drop the other rules' "
              "pinned findings; run --update without --rules")
        return 254
    if opts.update and (opts.runtime_buckets or opts.full):
        # the committed baseline's contract is the deterministic
        # default mode: pinning runtime-bucket or lowered-HLO
        # findings leaves entries every default-mode gate (tier-1,
        # web, bench) reports as permanently stale
        print("--update pins the deterministic default mode only; "
              "drop --runtime-buckets/--full")
        return 254
    rep = run_lint(runtime_buckets=opts.runtime_buckets,
                   full=opts.full, rules=rules)
    if opts.update:
        if not opts.baseline:
            print("--update requires --baseline")
            return 254
        lint_mod.write_baseline(opts.baseline, rep.findings)
        print(f"wrote {opts.baseline} "
              f"({len(rep.findings)} finding(s) pinned)")
        return 0
    if opts.baseline:
        gate(rep, opts.baseline, rules=rules)
    if opts.json_:
        print(json.dumps(rep.to_dict(), indent=1))
    else:
        print(rep.text())
    if rep.errors:
        return 2
    if rep.ratchet is not None and rep.ratchet["new"]:
        return 1
    return 0
