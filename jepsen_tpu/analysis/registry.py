"""The kernel registry: every compiled entry point, abstractly traced.

Each entry names one compiled entry point of the checker pipeline
(wgl `check_batch`/`check_batch_reach`/`check_segmented`, the
mesh-sharded ensemble path, the SCC coloring kernel — the device
engine under both elle check functions — and the host-side
encode/PackedBatch feeders) and knows how to trace it at a shape
bucket: ShapeDtypeStructs through the REAL jit factories
(`wgl._jitted_kernel`, `ensemble._jitted_sharded`, `scc._jitted_scc`),
so donation flags, static config and partition layout are read off
the artifacts that actually launch — no execution, no devices beyond
one, CPU-safe (tier-1 runs this).

Default buckets are fixed and deterministic (the committed baseline
must not depend on what this process happened to compile); pass
runtime buckets from profiler.shape_buckets() to additionally trace
the shapes a live run actually used.
"""

from __future__ import annotations

import functools
import inspect
from dataclasses import dataclass, field
from typing import Callable

from ..tpu.lint import ArgSpec, KernelTrace

# Arg names come from the kernel modules themselves
# (ensemble.SHARD_ARGS, scc.SCC_ARGS — imported lazily in the trace
# functions): one definition next to each signature, so the registry
# can't hold a stale parallel copy of the layout it prices.


def _provenance(fn) -> tuple[str | None, int | None]:
    try:
        f = inspect.unwrap(fn)
        return (inspect.getsourcefile(f),
                inspect.getsourcelines(f)[1])
    except (OSError, TypeError):
        return None, None


def _argspecs(names, sds_args, donated) -> list[ArgSpec]:
    import numpy as np

    out = []
    for name, a, d in zip(names, sds_args, donated):
        n = 1
        for dim in a.shape:
            n *= int(dim)
        out.append(ArgSpec(name=name, shape=tuple(a.shape),
                           dtype=str(a.dtype),
                           nbytes=n * np.dtype(a.dtype).itemsize,
                           donated=bool(d)))
    return out


def _donated_flags(staged, n_args: int) -> list[bool]:
    """Donation flags off a jax.stages.Traced/Lowered args_info
    pytree (positional)."""
    try:
        import jax.tree_util as jtu

        flat, _ = jtu.tree_flatten(
            staged.args_info, is_leaf=lambda x: hasattr(x, "donated"))
        flags = [bool(getattr(a, "donated", False)) for a in flat]
        if len(flags) == n_args:
            return flags
    except Exception:  # noqa: BLE001 — jax API drift degrades to False
        pass
    return [False] * n_args


def _cost(lowered) -> dict:
    try:
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        return {k: float(ca[k]) for k in ("flops", "bytes accessed")
                if isinstance(ca, dict) and ca.get(k) is not None}
    except Exception:  # noqa: BLE001 — cost is best-effort
        return {}


@dataclass
class Entry:
    """One registry entry. trace(bucket, full=False) -> KernelTrace;
    buckets are dicts whose 'label' keys the finding sites (stable
    across PRs). full=False (the canonical/baseline mode) stops at
    jax tracing — jaxpr + donation flags, ~100ms/kernel; full=True
    additionally LOWERS the program for HLO text (R4's collective
    scan) and XLA cost analysis."""

    name: str
    trace: Callable[..., KernelTrace]
    buckets: list = field(default_factory=list)
    doc: str = ""


# ---------------------------------------------------------------------------
# wgl batched search (check_batch / check_batch_reach / check_segmented)
# ---------------------------------------------------------------------------

def _wgl_sds(b: dict):
    import jax
    import numpy as np

    K, M, S, rows = b["K"], b["M"], b["S"], b["rows"]
    sds = jax.ShapeDtypeStruct
    return (sds((K, M), np.int32), sds((K, M), np.int32),
            sds((K, M, S), np.int32), sds((K,), np.int32),
            sds((K, M + 1), np.int32), sds((rows,), np.int32),
            sds((rows,), np.int32), sds((rows,), np.int32))


def _mesh1():
    """A 1-device mesh: the sharded jit factories are the REAL launch
    artifacts on any mesh size, and tracing them on one device keeps
    the registry deterministic and CPU-safe (tier-1 runs this)."""
    import numpy as np

    import jax

    from ..tpu import spmd

    return jax.sharding.Mesh(np.array(jax.devices()[:1]), (spmd.AXIS,))


def _staged(traced, full: bool):
    """(jaxpr, donated_source, hlo_text, cost) off a Traced; lowering
    only in full mode."""
    if not full:
        return traced.jaxpr, traced, None, {}
    lowered = traced.lower()
    return traced.jaxpr, lowered, lowered.as_text(), _cost(lowered)


def _wgl_trace(b: dict, kernel_name: str,
               full: bool = False) -> KernelTrace:
    """Every wgl entry point launches through the SPMD program
    (ensemble._jitted_sharded via wgl._launch) whenever the process
    has >1 device, so THAT factory is what the registry traces: the
    partition layout, donation flags and jaxpr are read off the
    artifact the mesh actually runs. The partition metadata comes
    from the same rule table the launch sites use (tpu/spmd.py) —
    graftlint R4 prices the real layout, not a parallel description."""
    from ..tpu import ensemble, spmd, wgl

    fn = ensemble._jitted_sharded(_mesh1(), b["W"], b["F"],
                                  b["M"] + 4, b.get("reach", False),
                                  b.get("crash_free", False))
    args = _wgl_sds(b)
    traced = fn.trace(*args)
    jaxpr, staged, hlo, cost = _staged(traced, full)
    f, ln = _provenance(ensemble.check_batch_sharded
                        if kernel_name == "wgl-sharded"
                        else wgl._kernel)
    return KernelTrace(
        name=kernel_name, bucket=b["label"], jaxpr=jaxpr,
        args=_argspecs(ensemble.SHARD_ARGS, args,
                       _donated_flags(staged, len(args))),
        hlo_text=hlo, cost=cost,
        partition=spmd.describe_partition(spmd.WGL_RULES,
                                          ensemble.SHARD_ARGS),
        batch_axes=[("row_seg", 0,
                     "independent search rows: one history / "
                     "(segment, start-state) pair per row")],
        bucket_policy="pow2", file=f, line=ln)


# ---------------------------------------------------------------------------
# mesh-sharded ensemble path (check_batch_sharded)
# ---------------------------------------------------------------------------

def _sharded_trace(b: dict, full: bool = False) -> KernelTrace:
    return _wgl_trace(b, kernel_name="wgl-sharded", full=full)


def _single_trace(b: dict, full: bool = False) -> KernelTrace:
    """The plain single-device jit path (wgl._jitted_kernel) — what
    wgl._launch runs on a 1-device process, under JEPSEN_TPU_SPMD=0
    (the documented differential reference), and at the bottom of the
    degradation ladder. The SPMD program owns the production batch
    axis (R4 prices it on the entries above), so this trace declares
    no batch axes; it exists to keep R1/R2/R3/R6 coverage of the
    fallback's jaxpr — a donation or dtype regression in a
    plain-path-only branch must not hide behind the sharded trace."""
    from ..tpu import ensemble, wgl

    kw = dict(W=b["W"], F=b["F"], max_iters=b["M"] + 4,
              reach=b.get("reach", False),
              crash_free=b.get("crash_free", False))
    args = _wgl_sds(b)[:7]  # no inv_perm: the plain kernel signature
    traced = wgl._jitted_kernel().trace(*args, **kw)
    jaxpr, staged, hlo, cost = _staged(traced, full)
    f, ln = _provenance(wgl._kernel)
    return KernelTrace(
        name="wgl-single", bucket=b["label"], jaxpr=jaxpr,
        args=_argspecs(ensemble.SHARD_ARGS[:7], args,
                       _donated_flags(staged, len(args))),
        hlo_text=hlo, cost=cost,
        partition=None,
        batch_axes=[],
        bucket_policy="pow2", file=f, line=ln)


# ---------------------------------------------------------------------------
# SCC coloring kernel (scc_device — the device engine under both
# elle_device check functions)
# ---------------------------------------------------------------------------

def _scc_trace(b: dict, full: bool = False,
               kernel_name: str = "scc") -> KernelTrace:
    import jax
    import numpy as np

    from ..tpu import scc, spmd

    n_pad, e_pad = b["n_pad"], b["e_pad"]
    single = kernel_name == "scc-single"
    if single:
        # the plain single-device compile — what scc_device runs on a
        # 1-device process and under JEPSEN_TPU_SPMD=0 (same rationale
        # as wgl-single: the fallback's donation/dtype/carry must not
        # hide behind the sharded trace). No batch axes declared: the
        # sharded entry owns the R4 story.
        fn = scc._jitted_scc(n_pad, e_pad, scc.SWEEP_CAP,
                             scc.ROUND_CAP)
    else:
        fn = scc._jitted_scc_sharded(_mesh1(), n_pad, e_pad,
                                     scc.SWEEP_CAP, scc.ROUND_CAP)
    sds = jax.ShapeDtypeStruct
    args = (sds((n_pad,), np.bool_), sds((e_pad,), np.int32),
            sds((e_pad,), np.int32), sds((e_pad,), np.bool_))
    traced = fn.trace(*args)
    jaxpr, staged, hlo, cost = _staged(traced, full)
    f, ln = _provenance(scc.scc_device)
    return KernelTrace(
        name=kernel_name, bucket=b["label"], jaxpr=jaxpr,
        args=_argspecs(scc.SCC_ARGS, args,
                       _donated_flags(staged, len(args))),
        hlo_text=hlo, cost=cost,
        partition=None if single else
        spmd.describe_partition(spmd.SCC_RULES, scc.SCC_ARGS),
        batch_axes=[] if single else
        [("src", 0,
          "edge list: scatter-max sweeps are per-edge "
          "data-parallel")],
        # edge buckets step linearly in 128Ki chunks above 2^17
        # (scc._edge_pad) — R5 prices that policy
        bucket_policy="linear", file=f, line=ln)


# ---------------------------------------------------------------------------
# Default shape buckets (deterministic; mirror the profiler's real
# buckets from the bench configs: ensemble batches, segmented long
# histories, elle SCC graphs)
# ---------------------------------------------------------------------------

WGL_BUCKETS = [
    # check_batch over a 64-history bucket (the ensemble chunk shape)
    {"label": "B64xM512xS8", "K": 65, "M": 512, "S": 8, "rows": 64,
     "W": 32, "F": 64, "reach": False, "crash_free": False},
]
WGL_REACH_BUCKETS = [
    {"label": "B64xM512xS8", "K": 65, "M": 512, "S": 8, "rows": 64,
     "W": 32, "F": 32, "reach": True, "crash_free": False},
]
WGL_SEG_BUCKETS = [
    # check_segmented: K segments x S start states of one long history
    {"label": "K8xM8192xS8", "K": 9, "M": 8192, "S": 8, "rows": 128,
     "W": 24, "F": 48, "reach": True, "crash_free": False},
]
SHARDED_BUCKETS = [
    # the 1024-history ensemble bench (BASELINE config 5)
    {"label": "B1024xM512xS8", "K": 1025, "M": 512, "S": 8,
     "rows": 1024, "W": 32, "F": 64, "reach": False},
]
SLICES_BUCKETS = [
    # the fleet's cross-run slice launch (jepsen_tpu.fleet.scheduler
    # via wgl.check_slices): ~32 tenant stream segments x a handful of
    # live start states each — short segments, small state spaces,
    # many rows (doc/fleet.md)
    {"label": "B32xM512xS8", "K": 33, "M": 512, "S": 8, "rows": 128,
     "W": 24, "F": 48, "reach": True, "crash_free": False},
]
SCC_BUCKETS = [
    # elle dependency graphs at the 100k-txn bench scale
    {"label": "N131072xE262144", "n_pad": 131072, "e_pad": 262144},
]


def entries() -> list[Entry]:
    return [
        Entry("wgl", functools.partial(_wgl_trace,
                                       kernel_name="wgl"),
              WGL_BUCKETS, "check_batch batched frontier search"),
        Entry("wgl-reach",
              functools.partial(_wgl_trace, kernel_name="wgl-reach"),
              WGL_REACH_BUCKETS,
              "check_batch_reach exhaustive reachability"),
        Entry("wgl-segmented",
              functools.partial(_wgl_trace,
                                kernel_name="wgl-segmented"),
              WGL_SEG_BUCKETS,
              "check_segmented per-segment reach rows"),
        Entry("wgl-sharded", _sharded_trace, SHARDED_BUCKETS,
              "check_batch_sharded mesh ensemble path"),
        Entry("wgl-single", _single_trace, WGL_BUCKETS,
              "single-device fallback jit (1-device processes, "
              "JEPSEN_TPU_SPMD=0, ladder floor)"),
        Entry("wgl-slices",
              functools.partial(_wgl_trace,
                                kernel_name="wgl-slices"),
              SLICES_BUCKETS,
              "check_slices fleet cross-tenant reach rows"),
        Entry("scc", _scc_trace, SCC_BUCKETS,
              "Orzan coloring SCC (elle_device cycle engine)"),
        Entry("scc-single",
              functools.partial(_scc_trace,
                                kernel_name="scc-single"),
              SCC_BUCKETS,
              "single-device SCC compile (1-device processes, "
              "JEPSEN_TPU_SPMD=0)"),
    ]


def host_feeder_modules() -> list:
    """Modules whose host-side array code feeds the kernels in int32
    house style — the R2 dtype audit targets. elle/elle_device are
    deliberately exempt: their packed (key, value) edge codes need 64
    bits by design, and scc.py narrows them to int32 at the device
    boundary."""
    from ..tpu import encode, ensemble, scc, wgl

    return [encode, wgl, scc, ensemble]


def runtime_wgl_buckets(raw_buckets) -> list[dict]:
    """Translates wgl._compiled_buckets tuples — via
    profiler.shape_buckets()['wgl'] — back into traceable bucket
    dicts. Unparseable tuples (mesh-sharded entries carry a live Mesh)
    are skipped: runtime buckets only ever ADD traces."""
    out = []
    for t in sorted(raw_buckets, key=repr):
        try:
            (K, M), S, rows, W, F, max_iters, reach, has_crashed = t
        except (TypeError, ValueError):
            continue
        if not all(isinstance(x, int)
                   for x in (K, M, S, rows, W, F, max_iters)):
            continue
        out.append({"label": f"rt-B{rows}xM{M}xS{S}"
                             + ("r" if reach else ""),
                    "K": K, "M": M, "S": S, "rows": rows, "W": W,
                    "F": F, "reach": bool(reach),
                    "crash_free": not has_crashed})
    return out
