/* bitflip: flip random bits in a file, in place.
 *
 * Usage: bitflip spray <percent> <file>
 *
 * Flips each bit of the file independently with probability
 * percent/100 (so "spray 0.1 f" corrupts ~1/1000 of f's bits). The
 * capability mirror of the Go tool the reference downloads
 * (jepsen/src/jepsen/nemesis.clj:550-599, aybabtme/bitflip); built
 * from source on DB nodes instead of fetching a release binary.
 */
#define _POSIX_C_SOURCE 200809L
#include <stdio.h>
#include <stdlib.h>
#include <string.h>
#include <time.h>

int main(int argc, char **argv) {
  double percent, p_byte;
  FILE *f;
  long size, pos;
  unsigned long long flipped = 0;

  if (argc < 4 || strcmp(argv[1], "spray") != 0) {
    fprintf(stderr, "usage: %s spray <percent> <file>\n", argv[0]);
    return 1;
  }
  percent = atof(argv[2]);
  if (percent < 0 || percent > 100) {
    fprintf(stderr, "percent must be in [0, 100]\n");
    return 1;
  }

  f = fopen(argv[3], "r+b");
  if (!f) {
    perror("fopen");
    return 1;
  }
  if (fseek(f, 0, SEEK_END) != 0 || (size = ftell(f)) < 0) {
    perror("fseek");
    return 1;
  }

  srand((unsigned)time(NULL) ^ (unsigned)size);
  /* P(byte untouched) = (1 - p_bit)^8; sample per byte, then pick a
   * uniform bit — a close, cheap approximation for small p. */
  p_byte = 1.0 - percent / 100.0;
  p_byte = 1.0 - p_byte * p_byte * p_byte * p_byte *
                 p_byte * p_byte * p_byte * p_byte;

  for (pos = 0; pos < size; pos++) {
    if ((double)rand() / RAND_MAX < p_byte) {
      int c;
      if (fseek(f, pos, SEEK_SET) != 0) { perror("fseek"); return 1; }
      c = fgetc(f);
      if (c == EOF) break;
      c ^= 1 << (rand() % 8);
      if (fseek(f, pos, SEEK_SET) != 0) { perror("fseek"); return 1; }
      if (fputc(c, f) == EOF) { perror("fputc"); return 1; }
      flipped += 1;
    }
  }
  fclose(f);
  printf("flipped %llu bits in %s\n", flipped, argv[3]);
  return 0;
}
