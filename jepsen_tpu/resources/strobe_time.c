/* strobe_time: oscillate the wall clock by +delta ms and back, every
 * period ms, for duration seconds, then restore it.
 *
 * Usage: strobe_time <delta-ms> <period-ms> <duration-s>
 *
 * Tracks the real offset against CLOCK_MONOTONIC so the restore at the
 * end is exact regardless of how many flips ran. Prints the number of
 * clock adjustments made. Compiled on DB nodes by the clock nemesis
 * (capability reference: jepsen/resources/strobe-time.c, driven by
 * nemesis/time.clj:98-102).
 */
#define _POSIX_C_SOURCE 200809L
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

#define NS_PER_SEC 1000000000LL

/* timespec <-> signed nanoseconds; int64 covers ~292 years */
static long long ts_ns(struct timespec t) {
  return (long long)t.tv_sec * NS_PER_SEC + t.tv_nsec;
}

static struct timespec ns_ts(long long ns) {
  struct timespec t;
  t.tv_sec = ns / NS_PER_SEC;
  t.tv_nsec = ns % NS_PER_SEC;
  if (t.tv_nsec < 0) {
    t.tv_sec -= 1;
    t.tv_nsec += NS_PER_SEC;
  }
  return t;
}

static long long now_ns(clockid_t clock) {
  struct timespec t;
  if (clock_gettime(clock, &t) != 0) {
    perror("clock_gettime");
    exit(1);
  }
  return ts_ns(t);
}

static void set_wall_ns(long long ns) {
  struct timespec t = ns_ts(ns);
  if (clock_settime(CLOCK_REALTIME, &t) != 0) {
    perror("clock_settime");
    exit(2);
  }
}

int main(int argc, char **argv) {
  long long delta_ns, period_ns, duration_ns, base_offset, end;
  long long flips = 0;
  int skewed = 0;
  struct timespec period;

  if (argc < 4) {
    fprintf(stderr,
            "usage: %s <delta-ms> <period-ms> <duration-s>\n"
            "Every period ms, toggles the wall clock between its true\n"
            "value and true+delta ms, for duration seconds.\n",
            argv[0]);
    return 1;
  }
  delta_ns = (long long)(atof(argv[1]) * 1e6);
  period_ns = (long long)(atof(argv[2]) * 1e6);
  duration_ns = (long long)(atof(argv[3]) * 1e9);
  period = ns_ts(period_ns);

  /* wall = monotonic + base_offset, as of program start */
  base_offset = now_ns(CLOCK_REALTIME) - now_ns(CLOCK_MONOTONIC);
  end = now_ns(CLOCK_MONOTONIC) + duration_ns;

  while (now_ns(CLOCK_MONOTONIC) < end) {
    skewed = !skewed;
    set_wall_ns(now_ns(CLOCK_MONOTONIC) + base_offset +
                (skewed ? delta_ns : 0));
    flips += 1;
    if (nanosleep(&period, NULL) != 0) {
      perror("nanosleep");
      exit(3);
    }
  }

  set_wall_ns(now_ns(CLOCK_MONOTONIC) + base_offset);
  printf("%lld\n", flips);
  return 0;
}
