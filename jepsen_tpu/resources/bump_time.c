/* bump_time: step CLOCK_REALTIME by a signed millisecond offset.
 *
 * Usage: bump_time <delta-ms>
 *
 * Prints the post-adjustment wall-clock time as "<sec>.<nsec>" so the
 * control plane can compute the node's clock offset. Compiled on DB
 * nodes by the clock nemesis (capability reference:
 * jepsen/resources/bump-time.c, driven by nemesis/time.clj:92-96).
 */
#define _POSIX_C_SOURCE 200809L
#include <stdio.h>
#include <stdlib.h>
#include <time.h>

#define NS_PER_SEC 1000000000LL

static void normalize(struct timespec *t) {
  while (t->tv_nsec < 0) {
    t->tv_sec -= 1;
    t->tv_nsec += NS_PER_SEC;
  }
  while (t->tv_nsec >= NS_PER_SEC) {
    t->tv_sec += 1;
    t->tv_nsec -= NS_PER_SEC;
  }
}

int main(int argc, char **argv) {
  struct timespec t;
  long long delta_ns;

  if (argc < 2) {
    fprintf(stderr, "usage: %s <delta-ms>\n", argv[0]);
    return 1;
  }
  delta_ns = (long long)(atof(argv[1]) * 1e6);

  if (clock_gettime(CLOCK_REALTIME, &t) != 0) {
    perror("clock_gettime");
    return 1;
  }
  t.tv_sec += delta_ns / NS_PER_SEC;
  t.tv_nsec += delta_ns % NS_PER_SEC;
  normalize(&t);
  if (clock_settime(CLOCK_REALTIME, &t) != 0) {
    perror("clock_settime");
    return 2;
  }
  if (clock_gettime(CLOCK_REALTIME, &t) != 0) {
    perror("clock_gettime");
    return 1;
  }
  printf("%lld.%09ld\n", (long long)t.tv_sec, t.tv_nsec);
  return 0;
}
