"""Debian node preparation.

Capability reference: jepsen/src/jepsen/os/debian.clj — hostfile setup
(17-31), apt update throttling (33-48), installed/install with per-node
locks (50-127), add-key!/add-repo! (129-150), the Debian OS impl
(160-190).
"""

from __future__ import annotations

import logging
import re

from .. import control, util
from ..control import util as cu
from ..control.core import Lit
from . import OS

logger = logging.getLogger(__name__)

# Prevents concurrent apt operations on the same node (debian.clj:13-15).
node_locks = util.named_locks()


def setup_hostfile() -> None:
    """Ensures /etc/hosts has a plain loopback entry
    (debian.clj:17-31)."""
    hosts = control.exec_("cat", "/etc/hosts")
    lines = [("127.0.0.1\tlocalhost"
              if re.match(r"^127\.0\.0\.1\t", line) else line)
             for line in hosts.split("\n")]
    hosts2 = "\n".join(lines)
    if hosts != hosts2:
        with control.su():
            control.exec_("echo", hosts2, Lit(">"), "/etc/hosts")


def time_since_last_update() -> int:
    """Seconds since the last apt-get update (debian.clj:33-38).
    Unparseable output (e.g. the dummy remote's empty replies) reads
    as stale, so the harmless apt-get update runs."""
    try:
        now = int(control.exec_("date", "+%s"))
        then = control.exec_("stat", "-c", "%Y",
                             "/var/cache/apt/pkgcache.bin", Lit("||"),
                             "echo", 0)
        return now - int(then or 0)
    except ValueError:
        return 10**9


def update() -> None:
    """apt-get update, serialized per node (debian.clj:40-44)."""
    with node_locks.hold(control.current_node()):
        with control.su():
            control.exec_("apt-get", "--allow-releaseinfo-change",
                          "update")


def maybe_update() -> None:
    """apt-get update if stale by more than a day (debian.clj:46-48)."""
    if time_since_last_update() > 86400:
        update()


def installed(pkgs) -> set:
    """The subset of pkgs currently installed (debian.clj:50-62).
    Lists all selections and filters host-side: dpkg exits 1 when a
    named pattern matches nothing (i.e. on any fresh node)."""
    pkgs = {str(p) for p in pkgs}
    out = control.exec_("dpkg", "--get-selections")
    got = set()
    for line in out.split("\n"):
        parts = line.split()
        if len(parts) >= 2 and parts[1] == "install":
            got.add(re.sub(r":amd64|:i386", "", parts[0]))
    return got & pkgs


def installed_p(pkg_or_pkgs) -> bool:
    pkgs = (pkg_or_pkgs if isinstance(pkg_or_pkgs, (list, tuple, set))
            else [pkg_or_pkgs])
    return set(map(str, pkgs)) <= installed(pkgs)


def installed_version(pkg) -> str | None:
    """Installed version of a package, or None (debian.clj:73-79)."""
    out = control.exec_("apt-cache", "policy", str(pkg))
    m = re.search(r"Installed: ([^\s]+)", out)
    v = m.group(1) if m else None
    return None if v in (None, "(none)") else v


def uninstall(pkg_or_pkgs) -> None:
    """Removes packages (debian.clj:64-71)."""
    pkgs = (pkg_or_pkgs if isinstance(pkg_or_pkgs, (list, tuple, set))
            else [pkg_or_pkgs])
    pkgs = installed(pkgs)
    if not pkgs:
        return
    with node_locks.hold(control.current_node()):
        with control.su():
            control.exec_("apt-get", "remove", "--purge", "-y",
                          *sorted(pkgs))


def install(pkgs, apt_opts=()) -> None:
    """Ensures packages are installed; a dict pins versions
    (debian.clj:81-127)."""
    if isinstance(pkgs, dict):
        for pkg, version in pkgs.items():
            if version != installed_version(pkg):
                with node_locks.hold(control.current_node()):
                    logger.info("Installing %s %s", pkg, version)
                    with control.su():
                        control.exec_(
                            "env", "DEBIAN_FRONTEND=noninteractive",
                            "apt-get", "install", "-y",
                            "--allow-downgrades",
                            "--allow-change-held-packages", *apt_opts,
                            f"{pkg}={version}")
        return
    pkgs = {str(p) for p in pkgs}
    missing = pkgs - installed(pkgs)
    if not missing:
        return
    with node_locks.hold(control.current_node()):
        logger.info("Installing %s", sorted(missing))
        with control.su():
            control.exec_("env", "DEBIAN_FRONTEND=noninteractive",
                          "apt-get", "install", "-y",
                          "--allow-downgrades",
                          "--allow-change-held-packages", *apt_opts,
                          *sorted(missing))


def add_key(keyserver, key) -> None:
    """Receives an apt key (debian.clj:129-135)."""
    with control.su():
        control.exec_("apt-key", "adv", "--keyserver", keyserver,
                      "--recv", key)


def add_repo(repo_name, apt_line, keyserver=None, key=None) -> None:
    """Adds an apt repo and optional key (debian.clj:137-150)."""
    list_file = f"/etc/apt/sources.list.d/{repo_name}.list"
    if cu.exists_p(list_file):
        return
    logger.info("setting up %s apt repo", repo_name)
    if keyserver or key:
        add_key(keyserver, key)
    control.exec_("echo", apt_line, Lit(">"), list_file)
    update()


DEFAULT_PACKAGES = [
    "apt-transport-https", "libzip4", "wget", "curl", "vim", "man-db",
    "faketime", "netcat-openbsd", "ntpdate", "unzip", "iptables",
    "psmisc", "tar", "bzip2", "iputils-ping", "iproute2", "rsyslog",
    "logrotate", "dirmngr", "tcpdump",
]


class Debian(OS):
    """Debian box preparation (debian.clj:160-190)."""

    packages = DEFAULT_PACKAGES

    def setup(self, test, node) -> None:
        logger.info("%s setting up debian", node)
        setup_hostfile()
        maybe_update()
        install(self.packages)
        net = test.get("net")
        if net is not None:
            util.meh(lambda: net.heal(test))

    def teardown(self, test, node) -> None:
        pass


os = Debian()
