"""CentOS node preparation: yum-flavored analog of the Debian layer.

Capability reference: jepsen/src/jepsen/os/centos.clj — hostfile setup
(12-26), yum update with a rate limit (27-45), installed/version
queries via rpm -qa (46-87), install via yum -y (88-109), building
start-stop-daemon from the dpkg source tarball because CentOS doesn't
ship it (110-156), and the OS record wiring (158).
"""

from __future__ import annotations

import logging

from .. import control
from ..control import util as cu
from . import OS, debian

logger = logging.getLogger(__name__)

PACKAGES = [
    "wget", "gcc", "gcc-c++", "curl", "vim-common", "unzip", "rsyslog",
    "iptables", "ncurses-devel", "iproute", "logrotate",
]

DPKG_TARBALL = ("http://ftp.de.debian.org/debian/pool/main/d/dpkg/"
                "dpkg_1.17.27.tar.xz")


def installed(pkgs) -> set:
    """Subset of pkgs already installed (rpm query)."""
    out = control.exec_("rpm", "-qa", "--qf", "%{NAME}\\n", check=False)
    have = set((out or "").split())
    return {p for p in pkgs if p in have}


def install(pkgs) -> None:
    """yum -y install any missing packages (centos.clj:88-109)."""
    missing = sorted(set(pkgs) - installed(pkgs))
    if missing:
        logger.info("Installing %s", missing)
        control.exec_("yum", "-y", "install", *missing)


def installed_start_stop_daemon_p() -> bool:
    return cu.exists_p("/usr/bin/start-stop-daemon")


def install_start_stop_daemon() -> None:
    """Builds start-stop-daemon from the dpkg source tarball — CentOS
    has no native package for it (centos.clj:110-156; the reference's
    absolute /dpkg-1.17.27 cp only works when run from /, so this
    version anchors the whole build in a workdir instead)."""
    logger.info("Installing start-stop-daemon")
    workdir = "/tmp/jepsen/dpkg-build"
    with control.su():
        control.exec_("mkdir", "-p", workdir)
        with control.cd(workdir):
            control.exec_("wget", DPKG_TARBALL)
            control.exec_("tar", "-xf", "dpkg_1.17.27.tar.xz")
            with control.cd("dpkg-1.17.27"):
                control.exec_("./configure")
                control.exec_("make")
                control.exec_("cp", "utils/start-stop-daemon",
                              "/usr/bin/start-stop-daemon")
        control.exec_("rm", "-rf", workdir)


class CentOS(OS):
    """OS protocol impl (os.clj:4-9) for CentOS nodes."""

    packages = PACKAGES

    def setup(self, test, node):
        logger.info("%s setting up centos", node)
        debian.setup_hostfile()
        with control.su():
            install(self.packages)
        if not installed_start_stop_daemon_p():
            install_start_stop_daemon()

    def teardown(self, test, node):
        pass


os = CentOS()
