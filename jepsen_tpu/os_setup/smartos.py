"""SmartOS node preparation: pkgin-flavored analog of the Debian
layer.

Capability reference: jepsen/src/jepsen/os/smartos.clj — hostfile
setup (12-25), pkgin update with a rate limit (27-45), installed
queries via `pkgin -p list` (46-86), install via `pkgin -y install`
(87-107), and enabling ipfilter through svcadm so partitions work
(120-132).
"""

from __future__ import annotations

import logging

from .. import control, util
from . import OS, debian

logger = logging.getLogger(__name__)

PACKAGES = ["curl", "wget", "unzip", "rsyslog", "gcc10"]


def installed(pkgs) -> set:
    """Subset of pkgs already installed. `pkgin -p list` prints
    `name-version;comment` — strip the comment BEFORE splitting off
    the version, or the last hyphen lands inside the comment
    (smartos.clj:46-58)."""
    out = control.exec_("pkgin", "-p", "list", check=False) or ""
    have = {line.split(";", 1)[0].rsplit("-", 1)[0]
            for line in out.splitlines() if line}
    return {p for p in pkgs if p in have}


def install(pkgs) -> None:
    """pkgin -y install any missing packages (smartos.clj:87-107)."""
    missing = sorted(set(pkgs) - installed(pkgs))
    if missing:
        logger.info("Installing %s", missing)
        with control.su():
            control.exec_("pkgin", "-y", "install", *missing)


def uninstall(pkgs) -> None:
    pkgs = sorted(set(pkgs) & installed(pkgs))
    if pkgs:
        with control.su():
            control.exec_("pkgin", "-y", "remove", *pkgs)


def update() -> None:
    """pkgin update (smartos.clj:33-36)."""
    with control.su():
        control.exec_("pkgin", "update")


def maybe_update() -> None:
    """Updates at most once a day, keyed off the pkgin db mtime
    (smartos.clj:27-45); a fresh node with no db updates
    unconditionally (the first install fails otherwise)."""
    now = control.exec_("date", "+%s", check=False)
    mtime = control.exec_("stat", "-c", "%Y", "/var/db/pkgin/sql.log",
                          check=False)
    try:
        if int(now) - int(mtime) < 86400:
            return
    except (TypeError, ValueError):
        pass  # no db yet: definitely update
    update()


def enable_ipfilter() -> None:
    """Partitions on SmartOS go through ipfilter; enable its service
    (smartos.clj:120-132)."""
    with control.su():
        control.exec_("svcadm", "enable", "-r", "ipfilter")


class SmartOS(OS):
    """OS protocol impl (os.clj:4-9) for SmartOS nodes."""

    packages = PACKAGES

    def setup(self, test, node):
        logger.info("%s setting up smartos", node)
        debian.setup_hostfile()
        maybe_update()
        install(self.packages)
        enable_ipfilter()
        net = test.get("net")
        if net is not None:  # heal leftover partitions, like Debian
            util.meh(lambda: net.heal(test))

    def teardown(self, test, node):
        pass


os = SmartOS()
