"""Operating-system automation.

Capability reference: jepsen/src/jepsen/os.clj (OS protocol, os.clj:4-9)
plus the distro implementations in os/debian.clj, os/centos.clj,
os/ubuntu.clj (ported in sibling modules).
"""

from __future__ import annotations


class OS:
    """Prepares and tears down an operating system on a node."""

    def setup(self, test, node) -> None:
        pass

    def teardown(self, test, node) -> None:
        pass


class NoopOS(OS):
    pass


noop = NoopOS()
