"""Ubuntu node preparation: Debian flows minus a few packages.

Capability reference: jepsen/src/jepsen/os/ubuntu.clj (whole file; it
delegates hostfile/update/install to os/debian.clj).
"""

from __future__ import annotations

import logging

from .. import util
from . import OS
from . import debian

logger = logging.getLogger(__name__)

PACKAGES = [
    "apt-transport-https", "wget", "curl", "vim", "man-db", "faketime",
    "ntpdate", "unzip", "iptables", "psmisc", "tar", "bzip2",
    "iputils-ping", "iproute2", "rsyslog", "sudo", "logrotate",
]


class Ubuntu(OS):
    packages = PACKAGES

    def setup(self, test, node) -> None:
        logger.info("%s setting up ubuntu", node)
        debian.setup_hostfile()
        debian.maybe_update()
        debian.install(self.packages)
        net = test.get("net")
        if net is not None:
            util.meh(lambda: net.heal(test))

    def teardown(self, test, node) -> None:
        pass


os = Ubuntu()
