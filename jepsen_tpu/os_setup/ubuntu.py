"""Ubuntu node preparation: the Debian flows with a different package
set.

Capability reference: jepsen/src/jepsen/os/ubuntu.clj (whole file; it
delegates hostfile/update/install to os/debian.clj).
"""

from __future__ import annotations

from . import debian

PACKAGES = [
    "apt-transport-https", "wget", "curl", "vim", "man-db", "faketime",
    "ntpdate", "unzip", "iptables", "psmisc", "tar", "bzip2",
    "iputils-ping", "iproute2", "rsyslog", "sudo", "logrotate",
]


class Ubuntu(debian.Debian):
    packages = PACKAGES


os = Ubuntu()
