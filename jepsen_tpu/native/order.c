/* Realtime-order edge sweep for the elle engines.
 *
 * The reference's realtime relation comes from elle's
 * process/realtime graphs (jepsen/src/jepsen/tests/cycle/append.clj
 * wires elle.core's realtime-graph); the Python engine reduces it
 * with a completion-frontier sweep (tpu/elle.py order_edge_arrays).
 * This is that exact sweep in C: events sorted by (position,
 * completion-before-invocation), a covering frontier of completed
 * txns, an edge from every frontier member to each invoking txn.
 * Indices in/out are dense 0..n-1 positions into the caller's txn
 * arrays.
 *
 * Returns the edge count, -1 if cap was too small (caller retries
 * with a bigger buffer), -2 on allocation failure.
 */

#include <stdint.h>
#include <stdlib.h>

#ifdef __cplusplus
extern "C" {
#endif


typedef struct {
    int64_t pos;
    int32_t is_inv;
    int64_t t;
} jt_event;

static int jt_ev_cmp(const void *a, const void *b) {
    const jt_event *x = (const jt_event *)a;
    const jt_event *y = (const jt_event *)b;
    if (x->pos != y->pos)
        return x->pos < y->pos ? -1 : 1;
    if (x->is_inv != y->is_inv)
        return x->is_inv < y->is_inv ? -1 : 1;
    return x->t < y->t ? -1 : (x->t > y->t ? 1 : 0);
}

int64_t jt_realtime_edges(const int64_t *inv, const int64_t *comp,
                          int64_t n, int64_t *out_src,
                          int64_t *out_dst, int64_t cap) {
    if (n <= 0)
        return 0;
    jt_event *events =
        (jt_event *)malloc(sizeof(jt_event) * 2 * (size_t)n);
    int64_t *frontier =
        (int64_t *)malloc(sizeof(int64_t) * (size_t)n);
    if (!events || !frontier) {
        free(events);
        free(frontier);
        return -2;
    }
    for (int64_t i = 0; i < n; i++) {
        events[2 * i].pos = inv[i];
        events[2 * i].is_inv = 1;
        events[2 * i].t = i;
        events[2 * i + 1].pos = comp[i];
        events[2 * i + 1].is_inv = 0;
        events[2 * i + 1].t = i;
    }
    qsort(events, 2 * (size_t)n, sizeof(jt_event), jt_ev_cmp);
    int64_t fn = 0, m = 0;
    for (int64_t e = 0; e < 2 * n; e++) {
        int64_t t = events[e].t;
        if (events[e].is_inv) {
            /* edge from every covering completed txn */
            for (int64_t j = 0; j < fn; j++) {
                int64_t a = frontier[j];
                if (a == t)
                    continue;
                if (m >= cap) {
                    free(events);
                    free(frontier);
                    return -1;
                }
                out_src[m] = a;
                out_dst[m] = t;
                m++;
            }
        } else {
            /* completion: drop frontier members this txn covers
             * (their completion precedes its invocation) */
            int64_t keep = 0;
            for (int64_t j = 0; j < fn; j++)
                if (comp[frontier[j]] >= inv[t])
                    frontier[keep++] = frontier[j];
            fn = keep;
            frontier[fn++] = t;
        }
    }
    free(events);
    free(frontier);
    return m;
}

#ifdef __cplusplus
}  /* extern "C" */
#endif
