"""Native (C) components and their loader.

The reference keeps native code on its store hot path
(jepsen/src/jepsen/store/FressianReader.java — a patched binary
deserializer — and FileOffsetOutputStream.java); here the analog is a
small C codec for the CRC-framed history log, compiled on first use
with the system compiler and loaded over ctypes. Everything has a pure
Python fallback, so a missing toolchain only costs speed.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path

logger = logging.getLogger(__name__)

_SRCS = (Path(__file__).with_name("jlog.c"),
         Path(__file__).with_name("order.c"))
_LOCK = threading.Lock()
_lib = None
_tried = False


def _build_dir() -> Path:
    d = Path(os.environ.get("JEPSEN_TPU_NATIVE_DIR",
                            Path.home() / ".cache" / "jepsen_tpu"))
    d.mkdir(parents=True, exist_ok=True)
    return d


def _compile_src(srcs, stem: str, extra_args=()) -> Path | None:
    """Compile C sources to a content-hash-named .so in the build
    cache. Cache keyed on source CONTENT: mtime comparisons break when
    a stale .so outlives a package upgrade (archive mtimes can sort
    older), and loading one without the newer symbols would brick the
    whole codec for the process."""
    import hashlib

    digest = hashlib.sha256()
    for s in srcs:
        digest.update(s.read_bytes())
    build = _build_dir()
    out = build / f"{stem}-{digest.hexdigest()[:16]}.so"
    if out.exists():
        return out
    for cc in ("cc", "gcc", "g++"):
        # Compile to a private temp name and os.replace into place: a
        # killed compile (or a concurrent process — _LOCK is
        # thread-local) must never leave a half-written .so at the
        # cache path, where it would be trusted forever
        tmp = build / f".{stem}-{os.getpid()}.so.tmp"
        try:
            # extra_args trail the sources: -l libraries must follow
            # the objects that use them under --as-needed linkers
            proc = subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", *map(str, srcs),
                 "-o", str(tmp), *extra_args],
                capture_output=True, text=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            tmp.unlink(missing_ok=True)
            continue
        if proc.returncode == 0:
            os.replace(tmp, out)
            # prune superseded builds (incl. the legacy fixed name)
            for old in build.glob(f"{stem}*.so"):
                if old != out:
                    old.unlink(missing_ok=True)
            return out
        tmp.unlink(missing_ok=True)
        logger.debug("%s failed to build %s.so: %s", cc, stem,
                     proc.stderr)
    return None


def _compile() -> Path | None:
    return _compile_src(_SRCS, "jlog", extra_args=("-lz",))


def jlog() -> ctypes.CDLL | None:
    """The compiled codec, or None (callers use the Python path)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _LOCK:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            path = _compile()
            if path is None:
                return None
            lib = ctypes.CDLL(str(path))
            lib.jlog_scan.restype = ctypes.c_int64
            lib.jlog_scan.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64)]
            lib.jlog_frame.restype = ctypes.c_int64
            lib.jlog_frame.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.c_char_p]
            lib.jt_realtime_edges.restype = ctypes.c_int64
            lib.jt_realtime_edges.argtypes = [
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
            _lib = lib
        except Exception:  # noqa: BLE001 — never break the store
            logger.exception("loading native jlog codec failed")
            _lib = None
        return _lib


def scan(buf: bytes, start: int) -> tuple[list[tuple[int, int]], int]:
    """(payload (start, end) offsets, valid_prefix_end) via the C
    codec; raises RuntimeError if the codec is unavailable."""
    import numpy as np

    lib = jlog()
    if lib is None:
        raise RuntimeError("native jlog codec unavailable")
    # generous bound: every record needs >= 8 bytes of header
    max_records = max((len(buf) - start) // 8 + 1, 1)
    offsets = (ctypes.c_int64 * (2 * max_records))()
    valid_end = ctypes.c_int64(start)
    n = lib.jlog_scan(buf, len(buf), start, offsets, max_records,
                      ctypes.byref(valid_end))
    # one C-speed materialization — per-item ctypes access costs more
    # than the scan itself
    arr = np.ctypeslib.as_array(offsets)[:2 * n].reshape(-1, 2)
    return arr.tolist(), int(valid_end.value)


def frame(payloads: list[bytes]) -> bytes:
    """Concatenated framed records for payloads via the C codec;
    raises RuntimeError if unavailable."""
    lib = jlog()
    if lib is None:
        raise RuntimeError("native jlog codec unavailable")
    blob = b"".join(payloads)
    lens = (ctypes.c_int64 * len(payloads))(*map(len, payloads))
    out = ctypes.create_string_buffer(len(blob) + 8 * len(payloads))
    written = lib.jlog_frame(blob, lens, len(payloads), out)
    return out.raw[:written]


# ---------------------------------------------------------------------------
# elleflat: C-API flattener for the elle device engine
# ---------------------------------------------------------------------------

_EF_SRC = Path(__file__).with_name("elleflat.c")
_ef_lib = None
_ef_tried = False

# field ids — must match elleflat.c's enum
EF_APPEND_FIELDS = ("t_type", "t_proc", "t_inv", "t_comp", "t_opidx",
                    "ap_txn", "ap_key", "ap_val",
                    "rd_txn", "rd_key", "rd_len", "re_vals", "flag_rd")
EF_RW_FIELDS = ("t_type", "t_proc", "t_inv", "t_comp", "t_opidx",
                "wr_txn", "wr_key", "wr_val", "wr_nonfinal",
                "rd_txn", "rd_key", "rd_val",
                "fr_txn", "fr_key", "fr_prev", "fr_new",
                "er_txn", "er_key", "er_val", "int_row", "int_expected")


def _compile_ef() -> Path | None:
    import sysconfig

    inc = sysconfig.get_paths().get("include")
    if not inc:
        return None
    return _compile_src((_EF_SRC,), "elleflat",
                        extra_args=(f"-I{inc}",))


def elleflat() -> ctypes.PyDLL | None:
    """The compiled flattener (PyDLL: it calls the CPython C-API under
    the GIL), or None — callers use the Python flattening path."""
    global _ef_lib, _ef_tried
    if _ef_lib is not None or _ef_tried:
        return _ef_lib
    with _LOCK:
        if _ef_lib is not None or _ef_tried:
            return _ef_lib
        _ef_tried = True
        try:
            path = _compile_ef()
            if path is None:
                return None
            lib = ctypes.PyDLL(str(path))
            lib.ef_flatten.restype = ctypes.c_void_p
            lib.ef_flatten.argtypes = [ctypes.py_object, ctypes.c_int64]
            lib.ef_status.restype = ctypes.c_int64
            lib.ef_status.argtypes = [ctypes.c_void_p]
            lib.ef_len.restype = ctypes.c_int64
            lib.ef_len.argtypes = [ctypes.c_void_p, ctypes.c_int64]
            lib.ef_copy.restype = None
            lib.ef_copy.argtypes = [ctypes.c_void_p, ctypes.c_int64,
                                    ctypes.POINTER(ctypes.c_int64)]
            lib.ef_keys.restype = ctypes.py_object
            lib.ef_keys.argtypes = [ctypes.c_void_p]
            lib.ef_free.restype = None
            lib.ef_free.argtypes = [ctypes.c_void_p]
            _ef_lib = lib
        except Exception:  # noqa: BLE001 — flattening has a Python path
            logger.exception("loading native elleflat failed")
            _ef_lib = None
        return _ef_lib


class NotVectorizable(Exception):
    """The native flattener found non-int values / too many keys."""


def elle_flatten(ops: list, kind: int) -> tuple[dict, list]:
    """One C pass over a history's op list. kind 0 = list-append,
    1 = rw-register. Returns ({field: int64 array}, key list); raises
    RuntimeError if the native flattener is unavailable and
    NotVectorizable when the history can't take the int fast path."""
    import numpy as np

    lib = elleflat()
    if lib is None:
        raise RuntimeError("native elleflat unavailable")
    h = lib.ef_flatten(ops, kind)
    if not h:
        raise RuntimeError("native elleflat failed")
    try:
        if lib.ef_status(h):
            raise NotVectorizable()
        fields = EF_RW_FIELDS if kind else EF_APPEND_FIELDS
        out = {}
        p = ctypes.POINTER(ctypes.c_int64)
        for fid, name in enumerate(fields):
            n = lib.ef_len(h, fid)
            arr = np.empty(n, dtype=np.int64)
            if n:
                lib.ef_copy(h, fid, arr.ctypes.data_as(p))
            out[name] = arr
        keys = lib.ef_keys(h)
        return out, keys
    finally:
        lib.ef_free(h)


def realtime_edges(inv, comp):
    """(src_idx, dst_idx) int64 arrays of reduced realtime-order edges
    over dense txn positions, via the C sweep (order.c); raises
    RuntimeError if the codec is unavailable. inv/comp are int64
    arrays of invocation/completion history positions."""
    import numpy as np

    lib = jlog()
    if lib is None or not hasattr(lib, "jt_realtime_edges"):
        raise RuntimeError("native order sweep unavailable")
    inv = np.ascontiguousarray(inv, dtype=np.int64)
    comp = np.ascontiguousarray(comp, dtype=np.int64)
    n = len(inv)
    if n == 0:
        return (np.empty(0, dtype=np.int64),
                np.empty(0, dtype=np.int64))
    p = ctypes.POINTER(ctypes.c_int64)
    cap = max(8 * n, 1024)
    while True:
        src = np.empty(cap, dtype=np.int64)
        dst = np.empty(cap, dtype=np.int64)
        m = lib.jt_realtime_edges(
            inv.ctypes.data_as(p), comp.ctypes.data_as(p), n,
            src.ctypes.data_as(p), dst.ctypes.data_as(p), cap)
        if m == -1:
            cap *= 4
            continue
        if m < 0:
            raise RuntimeError(f"native order sweep failed ({m})")
        return src[:m].copy(), dst[:m].copy()
