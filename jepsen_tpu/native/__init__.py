"""Native (C) components and their loader.

The reference keeps native code on its store hot path
(jepsen/src/jepsen/store/FressianReader.java — a patched binary
deserializer — and FileOffsetOutputStream.java); here the analog is a
small C codec for the CRC-framed history log, compiled on first use
with the system compiler and loaded over ctypes. Everything has a pure
Python fallback, so a missing toolchain only costs speed.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path

logger = logging.getLogger(__name__)

_SRC = Path(__file__).with_name("jlog.c")
_LOCK = threading.Lock()
_lib = None
_tried = False


def _build_dir() -> Path:
    d = Path(os.environ.get("JEPSEN_TPU_NATIVE_DIR",
                            Path.home() / ".cache" / "jepsen_tpu"))
    d.mkdir(parents=True, exist_ok=True)
    return d


def _compile() -> Path | None:
    out = _build_dir() / "jlog.so"
    if out.exists() and out.stat().st_mtime >= _SRC.stat().st_mtime:
        return out
    for cc in ("cc", "gcc", "g++"):
        try:
            proc = subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", str(_SRC),
                 "-o", str(out), "-lz"],
                capture_output=True, text=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            continue
        if proc.returncode == 0:
            return out
        logger.debug("%s failed to build jlog.so: %s", cc, proc.stderr)
    return None


def jlog() -> ctypes.CDLL | None:
    """The compiled codec, or None (callers use the Python path)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _LOCK:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            path = _compile()
            if path is None:
                return None
            lib = ctypes.CDLL(str(path))
            lib.jlog_scan.restype = ctypes.c_int64
            lib.jlog_scan.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64)]
            lib.jlog_frame.restype = ctypes.c_int64
            lib.jlog_frame.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.c_char_p]
            _lib = lib
        except Exception:  # noqa: BLE001 — never break the store
            logger.exception("loading native jlog codec failed")
            _lib = None
        return _lib


def scan(buf: bytes, start: int) -> tuple[list[tuple[int, int]], int]:
    """(payload (start, end) offsets, valid_prefix_end) via the C
    codec; raises RuntimeError if the codec is unavailable."""
    import numpy as np

    lib = jlog()
    if lib is None:
        raise RuntimeError("native jlog codec unavailable")
    # generous bound: every record needs >= 8 bytes of header
    max_records = max((len(buf) - start) // 8 + 1, 1)
    offsets = (ctypes.c_int64 * (2 * max_records))()
    valid_end = ctypes.c_int64(start)
    n = lib.jlog_scan(buf, len(buf), start, offsets, max_records,
                      ctypes.byref(valid_end))
    # one C-speed materialization — per-item ctypes access costs more
    # than the scan itself
    arr = np.ctypeslib.as_array(offsets)[:2 * n].reshape(-1, 2)
    return arr.tolist(), int(valid_end.value)


def frame(payloads: list[bytes]) -> bytes:
    """Concatenated framed records for payloads via the C codec;
    raises RuntimeError if unavailable."""
    lib = jlog()
    if lib is None:
        raise RuntimeError("native jlog codec unavailable")
    blob = b"".join(payloads)
    lens = (ctypes.c_int64 * len(payloads))(*map(len, payloads))
    out = ctypes.create_string_buffer(len(blob) + 8 * len(payloads))
    written = lib.jlog_frame(blob, lens, len(payloads), out)
    return out.raw[:written]
