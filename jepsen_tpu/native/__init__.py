"""Native (C) components and their loader.

The reference keeps native code on its store hot path
(jepsen/src/jepsen/store/FressianReader.java — a patched binary
deserializer — and FileOffsetOutputStream.java); here the analog is a
small C codec for the CRC-framed history log, compiled on first use
with the system compiler and loaded over ctypes. Everything has a pure
Python fallback, so a missing toolchain only costs speed.
"""

from __future__ import annotations

import ctypes
import logging
import os
import subprocess
import threading
from pathlib import Path

logger = logging.getLogger(__name__)

_SRCS = (Path(__file__).with_name("jlog.c"),
         Path(__file__).with_name("order.c"))
_LOCK = threading.Lock()
_lib = None
_tried = False


def _build_dir() -> Path:
    d = Path(os.environ.get("JEPSEN_TPU_NATIVE_DIR",
                            Path.home() / ".cache" / "jepsen_tpu"))
    d.mkdir(parents=True, exist_ok=True)
    return d


def _compile() -> Path | None:
    # Cache keyed on source CONTENT: mtime comparisons break when a
    # stale .so outlives a package upgrade (archive mtimes can sort
    # older), and loading one without the newer symbols would brick
    # the whole codec for the process
    import hashlib

    digest = hashlib.sha256()
    for s in _SRCS:
        digest.update(s.read_bytes())
    build = _build_dir()
    out = build / f"jlog-{digest.hexdigest()[:16]}.so"
    if out.exists():
        return out
    for cc in ("cc", "gcc", "g++"):
        # Compile to a private temp name and os.replace into place: a
        # killed compile (or a concurrent process — _LOCK is
        # thread-local) must never leave a half-written .so at the
        # cache path, where it would be trusted forever
        tmp = build / f".jlog-{os.getpid()}.so.tmp"
        try:
            proc = subprocess.run(
                [cc, "-O2", "-shared", "-fPIC", *map(str, _SRCS),
                 "-o", str(tmp), "-lz"],
                capture_output=True, text=True, timeout=120)
        except (OSError, subprocess.TimeoutExpired):
            tmp.unlink(missing_ok=True)
            continue
        if proc.returncode == 0:
            os.replace(tmp, out)
            # prune superseded builds (incl. the legacy fixed name)
            for old in build.glob("jlog*.so"):
                if old != out:
                    old.unlink(missing_ok=True)
            return out
        tmp.unlink(missing_ok=True)
        logger.debug("%s failed to build jlog.so: %s", cc, proc.stderr)
    return None


def jlog() -> ctypes.CDLL | None:
    """The compiled codec, or None (callers use the Python path)."""
    global _lib, _tried
    if _lib is not None or _tried:
        return _lib
    with _LOCK:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        try:
            path = _compile()
            if path is None:
                return None
            lib = ctypes.CDLL(str(path))
            lib.jlog_scan.restype = ctypes.c_int64
            lib.jlog_scan.argtypes = [
                ctypes.c_char_p, ctypes.c_int64, ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64)]
            lib.jlog_frame.restype = ctypes.c_int64
            lib.jlog_frame.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                ctypes.c_int64, ctypes.c_char_p]
            lib.jt_realtime_edges.restype = ctypes.c_int64
            lib.jt_realtime_edges.argtypes = [
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int64), ctypes.c_int64]
            _lib = lib
        except Exception:  # noqa: BLE001 — never break the store
            logger.exception("loading native jlog codec failed")
            _lib = None
        return _lib


def scan(buf: bytes, start: int) -> tuple[list[tuple[int, int]], int]:
    """(payload (start, end) offsets, valid_prefix_end) via the C
    codec; raises RuntimeError if the codec is unavailable."""
    import numpy as np

    lib = jlog()
    if lib is None:
        raise RuntimeError("native jlog codec unavailable")
    # generous bound: every record needs >= 8 bytes of header
    max_records = max((len(buf) - start) // 8 + 1, 1)
    offsets = (ctypes.c_int64 * (2 * max_records))()
    valid_end = ctypes.c_int64(start)
    n = lib.jlog_scan(buf, len(buf), start, offsets, max_records,
                      ctypes.byref(valid_end))
    # one C-speed materialization — per-item ctypes access costs more
    # than the scan itself
    arr = np.ctypeslib.as_array(offsets)[:2 * n].reshape(-1, 2)
    return arr.tolist(), int(valid_end.value)


def frame(payloads: list[bytes]) -> bytes:
    """Concatenated framed records for payloads via the C codec;
    raises RuntimeError if unavailable."""
    lib = jlog()
    if lib is None:
        raise RuntimeError("native jlog codec unavailable")
    blob = b"".join(payloads)
    lens = (ctypes.c_int64 * len(payloads))(*map(len, payloads))
    out = ctypes.create_string_buffer(len(blob) + 8 * len(payloads))
    written = lib.jlog_frame(blob, lens, len(payloads), out)
    return out.raw[:written]


def realtime_edges(inv, comp):
    """(src_idx, dst_idx) int64 arrays of reduced realtime-order edges
    over dense txn positions, via the C sweep (order.c); raises
    RuntimeError if the codec is unavailable. inv/comp are int64
    arrays of invocation/completion history positions."""
    import numpy as np

    lib = jlog()
    if lib is None or not hasattr(lib, "jt_realtime_edges"):
        raise RuntimeError("native order sweep unavailable")
    inv = np.ascontiguousarray(inv, dtype=np.int64)
    comp = np.ascontiguousarray(comp, dtype=np.int64)
    n = len(inv)
    if n == 0:
        return (np.empty(0, dtype=np.int64),
                np.empty(0, dtype=np.int64))
    p = ctypes.POINTER(ctypes.c_int64)
    cap = max(8 * n, 1024)
    while True:
        src = np.empty(cap, dtype=np.int64)
        dst = np.empty(cap, dtype=np.int64)
        m = lib.jt_realtime_edges(
            inv.ctypes.data_as(p), comp.ctypes.data_as(p), n,
            src.ctypes.data_as(p), dst.ctypes.data_as(p), cap)
        if m == -1:
            cap *= 4
            continue
        if m < 0:
            raise RuntimeError(f"native order sweep failed ({m})")
        return src[:m].copy(), dst[:m].copy()
