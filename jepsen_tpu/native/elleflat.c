/* elleflat.c — C-API flattener for the elle device engine.
 *
 * One pass over a history's op list produces the dense int64 arrays
 * the device analysis consumes (txn metadata, append/write rows, read
 * rows, flattened read elements), replacing the Python collect+Flat
 * loops that dominated the device path's host time. The capability
 * mirror is the same as jepsen_tpu/tpu/elle_device.py (elle 0.2.1
 * behind jepsen/src/jepsen/tests/cycle/append.clj:6-27); this file is
 * an implementation detail of that module and must stay semantically
 * identical to its Python fallback (differential-tested).
 *
 * Loaded via ctypes.PyDLL (GIL held: we call the CPython C-API).
 * Handle-based interface: ef_flatten() walks the ops and returns an
 * opaque handle; the caller queries field lengths, memcpys each field
 * into a numpy buffer, fetches the interned key list, and frees the
 * handle. Status 1 = history not vectorizable (non-int values, too
 * many keys): caller falls back to the Python path.
 */

#include <Python.h>
#include <stdint.h>
#include <stdlib.h>
#include <string.h>

#define VAL_LIMIT (((int64_t)1) << 40)
#define KEY_LIMIT (((int64_t)1) << 23)
#define EF_MAXF 21

/* txn type codes — must match elle_device._TYPE_* */
#define T_OK 0
#define T_INFO 1
#define T_FAIL 2

typedef struct { int64_t *d; int64_t n, cap; } vec;

static int vpush(vec *v, int64_t x) {
    if (v->n == v->cap) {
        int64_t nc = v->cap ? v->cap * 2 : 1024;
        int64_t *nd = (int64_t *)realloc(v->d, (size_t)nc * 8);
        if (!nd) return -1;
        v->d = nd;
        v->cap = nc;
    }
    v->d[v->n++] = x;
    return 0;
}

/* per-key scratch (generation-stamped so it clears in O(1) per txn) */
typedef struct {
    int64_t *gen;
    int64_t *val;
    int64_t cap;
} kscratch;

static int kgrow(kscratch *s, int64_t kid) {
    if (kid < s->cap) return 0;
    int64_t nc = s->cap ? s->cap : 256;
    while (nc <= kid) nc *= 2;
    int64_t *ng = (int64_t *)realloc(s->gen, (size_t)nc * 8);
    if (!ng) return -1;
    memset(ng + s->cap, 0, (size_t)(nc - s->cap) * 8);
    s->gen = ng;
    int64_t *nv = (int64_t *)realloc(s->val, (size_t)nc * 8);
    if (!nv) return -1;
    s->val = nv;
    s->cap = nc;
    return 0;
}

typedef struct {
    vec f[EF_MAXF];
    PyObject *keys; /* list of key objects in intern order */
    int status;     /* 0 ok, 1 unvectorizable */
} ef_handle;

/* field ids — must match native/__init__.py */
enum {
    F_T_TYPE, F_T_PROC, F_T_INV, F_T_COMP, F_T_OPIDX,
    /* append kind */
    F_AP_TXN = 5, F_AP_KEY, F_AP_VAL,
    F_RD_TXN, F_RD_KEY, F_RD_LEN, F_RE_VALS, F_FLAG_RD,
    /* rw kind (t_* shared) */
    F_WR_TXN = 5, F_WR_KEY, F_WR_VAL, F_WR_NONFINAL,
    F_RW_RD_TXN, F_RW_RD_KEY, F_RW_RD_VAL,
    F_FR_TXN, F_FR_KEY, F_FR_PREV, F_FR_NEW,
    F_ER_TXN, F_ER_KEY, F_ER_VAL, F_INT_ROW, F_INT_EXPECTED
};

static PyObject *s_type, *s_process, *s_value;
static PyObject *s_invoke, *s_ok, *s_fail, *s_info;

static int ensure_names(void) {
    if (s_type) return 0;
    s_type = PyUnicode_InternFromString("type");
    s_process = PyUnicode_InternFromString("process");
    s_value = PyUnicode_InternFromString("value");
    s_invoke = PyUnicode_InternFromString("invoke");
    s_ok = PyUnicode_InternFromString("ok");
    s_fail = PyUnicode_InternFromString("fail");
    s_info = PyUnicode_InternFromString("info");
    return (s_type && s_process && s_value && s_invoke && s_ok &&
            s_fail && s_info) ? 0 : -1;
}

static void ef_free_handle(ef_handle *h) {
    if (!h) return;
    for (int i = 0; i < EF_MAXF; i++) free(h->f[i].d);
    Py_XDECREF(h->keys);
    free(h);
}

/* intern a key object -> dense id; returns -1 on python error,
 * -2 on overflow */
static int64_t intern_key(PyObject *kdict, PyObject *klist, PyObject *k) {
    PyObject *kid = PyDict_GetItemWithError(kdict, k); /* borrowed */
    if (kid) return PyLong_AsLongLong(kid);
    if (PyErr_Occurred()) return -1;
    int64_t id = PyList_GET_SIZE(klist);
    if (id >= KEY_LIMIT) return -2;
    kid = PyLong_FromLongLong(id);
    if (!kid) return -1;
    if (PyDict_SetItem(kdict, k, kid) < 0) { Py_DECREF(kid); return -1; }
    Py_DECREF(kid);
    if (PyList_Append(klist, k) < 0) return -1;
    return id;
}

/* exact machine int in [0, VAL_LIMIT), or -1 (unvectorizable) */
static int64_t as_val(PyObject *v) {
    if (!PyLong_CheckExact(v)) return -1;
    int overflow = 0;
    long long x = PyLong_AsLongLongAndOverflow(v, &overflow);
    if (overflow || x < 0 || x >= VAL_LIMIT) return -1;
    return (int64_t)x;
}

/* ---- per-txn mop walks ------------------------------------------------ */

typedef struct {
    ef_handle *h;
    PyObject *kdict;
    kscratch own;      /* append: own-append gen; rw: written gen */
    kscratch expected; /* rw */
    kscratch lastread; /* rw */
    kscratch erseen;   /* rw */
    kscratch prevw;    /* rw: previous nonfail write row per key */
} walk_state;

/* returns 0 ok, 1 unvectorizable, -1 python error */
static int walk_append_txn(walk_state *w, int64_t ti, int code,
                           PyObject *mops) {
    ef_handle *h = w->h;
    if (mops == Py_None) return 0;
    PyObject *fast = PySequence_Fast(mops, "mops not a sequence");
    if (!fast) { PyErr_Clear(); return 1; }
    Py_ssize_t nm = PySequence_Fast_GET_SIZE(fast);
    PyObject **items = PySequence_Fast_ITEMS(fast);
    int64_t g = ti + 1;
    int consider_reads = (code == T_OK);
    for (Py_ssize_t i = 0; i < nm; i++) {
        PyObject *mop = items[i];
        PyObject *mfast = PySequence_Fast(mop, "mop not a sequence");
        if (!mfast) { PyErr_Clear(); Py_DECREF(fast); return 1; }
        if (PySequence_Fast_GET_SIZE(mfast) < 3) {
            Py_DECREF(mfast); Py_DECREF(fast); return 1;
        }
        PyObject *f = PySequence_Fast_GET_ITEM(mfast, 0);
        PyObject *k = PySequence_Fast_GET_ITEM(mfast, 1);
        PyObject *v = PySequence_Fast_GET_ITEM(mfast, 2);
        int is_append = 0, is_r = 0;
        if (PyUnicode_Check(f)) {
            if (PyUnicode_CompareWithASCIIString(f, "append") == 0)
                is_append = 1;
            else if (PyUnicode_CompareWithASCIIString(f, "r") == 0)
                is_r = 1;
        }
        /* intern BEFORE the f dispatch: the Python flattener assigns
         * key ids to every mop, so unknown mop types must still claim
         * their intern slot or the two paths' key ids diverge */
        int64_t kid = intern_key(w->kdict, h->keys, k);
        if (kid == -1) { Py_DECREF(mfast); Py_DECREF(fast); return -1; }
        if (kid == -2) { Py_DECREF(mfast); Py_DECREF(fast); return 1; }
        if (!is_append && !is_r) { Py_DECREF(mfast); continue; }
        if (kgrow(&w->own, kid) < 0) {
            Py_DECREF(mfast); Py_DECREF(fast); return -1;
        }
        if (is_append) {
            int64_t x = as_val(v);
            if (x < 0) { Py_DECREF(mfast); Py_DECREF(fast); return 1; }
            if (vpush(&h->f[F_AP_TXN], ti) || vpush(&h->f[F_AP_KEY], kid)
                    || vpush(&h->f[F_AP_VAL], x)) {
                Py_DECREF(mfast); Py_DECREF(fast); return -1;
            }
            w->own.gen[kid] = g;
        } else { /* r */
            if (v == Py_None || !consider_reads) {
                Py_DECREF(mfast); continue;
            }
            PyObject *vf = PySequence_Fast(v, "read not a sequence");
            if (!vf) { PyErr_Clear(); Py_DECREF(mfast); Py_DECREF(fast);
                       return 1; }
            Py_ssize_t nv = PySequence_Fast_GET_SIZE(vf);
            PyObject **velems = PySequence_Fast_ITEMS(vf);
            for (Py_ssize_t j = 0; j < nv; j++) {
                int64_t x = as_val(velems[j]);
                if (x < 0) { Py_DECREF(vf); Py_DECREF(mfast);
                             Py_DECREF(fast); return 1; }
                if (vpush(&h->f[F_RE_VALS], x)) {
                    Py_DECREF(vf); Py_DECREF(mfast); Py_DECREF(fast);
                    return -1;
                }
            }
            int64_t row = h->f[F_RD_TXN].n;
            if (vpush(&h->f[F_RD_TXN], ti) || vpush(&h->f[F_RD_KEY], kid)
                    || vpush(&h->f[F_RD_LEN], (int64_t)nv)) {
                Py_DECREF(vf); Py_DECREF(mfast); Py_DECREF(fast);
                return -1;
            }
            /* txn appended this key earlier: python re-checks the
             * own-suffix rule for this read row */
            if (w->own.gen[kid] == g && vpush(&h->f[F_FLAG_RD], row)) {
                Py_DECREF(vf); Py_DECREF(mfast); Py_DECREF(fast);
                return -1;
            }
            Py_DECREF(vf);
        }
        Py_DECREF(mfast);
    }
    Py_DECREF(fast);
    return 0;
}

static int walk_rw_txn(walk_state *w, int64_t ti, int code,
                       PyObject *mops) {
    ef_handle *h = w->h;
    if (mops == Py_None) return 0;
    PyObject *fast = PySequence_Fast(mops, "mops not a sequence");
    if (!fast) { PyErr_Clear(); return 1; }
    Py_ssize_t nm = PySequence_Fast_GET_SIZE(fast);
    PyObject **items = PySequence_Fast_ITEMS(fast);
    int64_t g = ti + 1;
    int ok = (code == T_OK), nonfail = (code != T_FAIL);
    int rc = 0;
    for (Py_ssize_t i = 0; i < nm && rc == 0; i++) {
        PyObject *mfast = PySequence_Fast(items[i], "mop");
        if (!mfast) { PyErr_Clear(); rc = 1; break; }
        if (PySequence_Fast_GET_SIZE(mfast) < 3) {
            Py_DECREF(mfast); rc = 1; break;
        }
        PyObject *f = PySequence_Fast_GET_ITEM(mfast, 0);
        PyObject *k = PySequence_Fast_GET_ITEM(mfast, 1);
        PyObject *v = PySequence_Fast_GET_ITEM(mfast, 2);
        int is_w = 0, is_r = 0;
        if (PyUnicode_Check(f)) {
            if (PyUnicode_CompareWithASCIIString(f, "w") == 0) is_w = 1;
            else if (PyUnicode_CompareWithASCIIString(f, "r") == 0)
                is_r = 1;
        }
        /* intern before the f dispatch — key-id parity with the
         * Python flattener (see walk_append_txn) */
        int64_t kid = intern_key(w->kdict, h->keys, k);
        if (kid == -1) { Py_DECREF(mfast); rc = -1; break; }
        if (kid == -2) { Py_DECREF(mfast); rc = 1; break; }
        if (!is_w && !is_r) { Py_DECREF(mfast); continue; }
        if (kgrow(&w->own, kid) < 0 || kgrow(&w->expected, kid) < 0
                || kgrow(&w->lastread, kid) < 0
                || kgrow(&w->erseen, kid) < 0
                || kgrow(&w->prevw, kid) < 0) {
            Py_DECREF(mfast); rc = -1; break;
        }
        if (is_w) {
            int64_t x = as_val(v);
            if (x < 0) { Py_DECREF(mfast); rc = 1; break; }
            int64_t row = h->f[F_WR_TXN].n;
            if (vpush(&h->f[F_WR_TXN], ti) || vpush(&h->f[F_WR_KEY], kid)
                    || vpush(&h->f[F_WR_VAL], x)) {
                Py_DECREF(mfast); rc = -1; break;
            }
            if (nonfail) {
                if (w->prevw.gen[kid] == g
                        && vpush(&h->f[F_WR_NONFINAL],
                                 w->prevw.val[kid])) {
                    Py_DECREF(mfast); rc = -1; break;
                }
                w->prevw.gen[kid] = g;
                w->prevw.val[kid] = row;
            }
            if (ok) {
                if (w->lastread.gen[kid] == g) {
                    if (vpush(&h->f[F_FR_TXN], ti)
                            || vpush(&h->f[F_FR_KEY], kid)
                            || vpush(&h->f[F_FR_PREV],
                                     w->lastread.val[kid])
                            || vpush(&h->f[F_FR_NEW], x)) {
                        Py_DECREF(mfast); rc = -1; break;
                    }
                    w->lastread.gen[kid] = 0; /* one-shot pop */
                }
                w->expected.gen[kid] = g;
                w->expected.val[kid] = x;
            }
            w->own.gen[kid] = g; /* written */
        } else if (ok) { /* r, committed txn */
            if (v == Py_None) {
                /* a None first read IS the key's external read */
                if (w->own.gen[kid] != g) w->erseen.gen[kid] = g;
                Py_DECREF(mfast); continue;
            }
            int64_t x = as_val(v);
            if (x < 0) { Py_DECREF(mfast); rc = 1; break; }
            int64_t row = h->f[F_RW_RD_TXN].n;
            if (vpush(&h->f[F_RW_RD_TXN], ti)
                    || vpush(&h->f[F_RW_RD_KEY], kid)
                    || vpush(&h->f[F_RW_RD_VAL], x)) {
                Py_DECREF(mfast); rc = -1; break;
            }
            if (w->expected.gen[kid] == g && w->expected.val[kid] != x) {
                if (vpush(&h->f[F_INT_ROW], row)
                        || vpush(&h->f[F_INT_EXPECTED],
                                 w->expected.val[kid])) {
                    Py_DECREF(mfast); rc = -1; break;
                }
            }
            w->expected.gen[kid] = g;
            w->expected.val[kid] = x;
            w->lastread.gen[kid] = g;
            w->lastread.val[kid] = x;
            if (w->own.gen[kid] != g && w->erseen.gen[kid] != g) {
                w->erseen.gen[kid] = g;
                if (vpush(&h->f[F_ER_TXN], ti)
                        || vpush(&h->f[F_ER_KEY], kid)
                        || vpush(&h->f[F_ER_VAL], x)) {
                    Py_DECREF(mfast); rc = -1; break;
                }
            }
        }
        Py_DECREF(mfast);
    }
    Py_DECREF(fast);
    return rc;
}

/* ---- main walk -------------------------------------------------------- */

/* kind: 0 = list-append, 1 = rw-register.
 * Returns a handle, or NULL on allocation/python error (caller falls
 * back to the Python flattener). */
void *ef_flatten(PyObject *ops, int64_t kind) {
    /* every NULL return must leave the error indicator CLEAR: under
     * ctypes.PyDLL a pending exception would be raised from ef_flatten
     * itself, bypassing the caller's RuntimeError -> Python-fallback
     * contract (the fail: label below does the same) */
    if (ensure_names() < 0) { PyErr_Clear(); return NULL; }
    if (!PyList_Check(ops)) { PyErr_Clear(); return NULL; }
    ef_handle *h = (ef_handle *)calloc(1, sizeof(ef_handle));
    if (!h) { PyErr_Clear(); return NULL; }
    h->keys = PyList_New(0);
    PyObject *kdict = NULL, *open = NULL;
    walk_state w;
    memset(&w, 0, sizeof(w));
    w.h = h;
    if (!h->keys) goto fail;
    kdict = PyDict_New();
    open = PyDict_New(); /* process -> invoke pos */
    if (!kdict || !open) goto fail;
    w.kdict = kdict;

    Py_ssize_t n = PyList_GET_SIZE(ops);
    for (Py_ssize_t pos = 0; pos < n; pos++) {
        PyObject *op = PyList_GET_ITEM(ops, pos);
        PyObject *proc = PyObject_GetAttr(op, s_process);
        if (!proc) goto fail;
        if (!PyLong_Check(proc)) { Py_DECREF(proc); continue; }
        PyObject *typ = PyObject_GetAttr(op, s_type);
        if (!typ) { Py_DECREF(proc); goto fail; }
        int code = -1;
        if (!PyUnicode_Check(typ)) {
            /* non-string type: skip the op like the host path does —
             * PyUnicode_CompareWithASCIIString on a non-string is
             * undefined behavior (mirrors the mop `f` guard) */
            Py_DECREF(typ); Py_DECREF(proc);
            continue;
        }
        if (typ == s_invoke
                || PyUnicode_CompareWithASCIIString(typ, "invoke") == 0) {
            PyObject *pp = PyLong_FromSsize_t(pos);
            int r = pp ? PyDict_SetItem(open, proc, pp) : -1;
            Py_XDECREF(pp);
            Py_DECREF(typ); Py_DECREF(proc);
            if (r < 0) goto fail;
            continue;
        } else if (typ == s_ok
                || PyUnicode_CompareWithASCIIString(typ, "ok") == 0) {
            code = T_OK;
        } else if (typ == s_info
                || PyUnicode_CompareWithASCIIString(typ, "info") == 0) {
            code = T_INFO;
        } else if (typ == s_fail
                || PyUnicode_CompareWithASCIIString(typ, "fail") == 0) {
            code = T_FAIL;
        }
        Py_DECREF(typ);
        if (code < 0) { Py_DECREF(proc); continue; }
        PyObject *ip = PyDict_GetItemWithError(open, proc); /* borrowed */
        if (!ip) {
            Py_DECREF(proc);
            if (PyErr_Occurred()) goto fail;
            continue;
        }
        int64_t inv_pos = PyLong_AsLongLong(ip);
        int64_t pv = PyLong_AsLongLong(proc);
        if (PyDict_DelItem(open, proc) < 0) { Py_DECREF(proc); goto fail; }
        Py_DECREF(proc);
        /* mops: completion value for ok (unless None), else invoke's */
        PyObject *mops = NULL;
        if (code == T_OK) {
            mops = PyObject_GetAttr(op, s_value);
            if (!mops) goto fail;
            if (mops == Py_None) { Py_DECREF(mops); mops = NULL; }
        }
        if (!mops) {
            PyObject *inv_op = PyList_GET_ITEM(ops, (Py_ssize_t)inv_pos);
            mops = PyObject_GetAttr(inv_op, s_value);
            if (!mops) goto fail;
        }
        int64_t ti = h->f[F_T_TYPE].n;
        if (vpush(&h->f[F_T_TYPE], code) || vpush(&h->f[F_T_PROC], pv)
                || vpush(&h->f[F_T_INV], inv_pos)
                || vpush(&h->f[F_T_COMP], pos)
                || vpush(&h->f[F_T_OPIDX], pos)) {
            Py_DECREF(mops); goto fail;
        }
        int rc = kind ? walk_rw_txn(&w, ti, code, mops)
                      : walk_append_txn(&w, ti, code, mops);
        Py_DECREF(mops);
        if (rc < 0) goto fail;
        if (rc > 0) { h->status = 1; goto done; }
    }
    /* leftover open invocations -> indeterminate txns, insertion order */
    {
        Py_ssize_t ppos = 0;
        PyObject *pk, *pval;
        while (PyDict_Next(open, &ppos, &pk, &pval)) {
            int64_t inv_pos = PyLong_AsLongLong(pval);
            int64_t pv = PyLong_AsLongLong(pk);
            PyObject *inv_op = PyList_GET_ITEM(ops, (Py_ssize_t)inv_pos);
            PyObject *mops = PyObject_GetAttr(inv_op, s_value);
            if (!mops) goto fail;
            int64_t ti = h->f[F_T_TYPE].n;
            if (vpush(&h->f[F_T_TYPE], T_INFO)
                    || vpush(&h->f[F_T_PROC], pv)
                    || vpush(&h->f[F_T_INV], inv_pos)
                    || vpush(&h->f[F_T_COMP], ((int64_t)1) << 60)
                    || vpush(&h->f[F_T_OPIDX], inv_pos)) {
                Py_DECREF(mops); goto fail;
            }
            int rc = kind ? walk_rw_txn(&w, ti, T_INFO, mops)
                          : walk_append_txn(&w, ti, T_INFO, mops);
            Py_DECREF(mops);
            if (rc < 0) goto fail;
            if (rc > 0) { h->status = 1; goto done; }
        }
    }
done:
    Py_DECREF(kdict);
    Py_DECREF(open);
    free(w.own.gen); free(w.own.val);
    free(w.expected.gen); free(w.expected.val);
    free(w.lastread.gen); free(w.lastread.val);
    free(w.erseen.gen); free(w.erseen.val);
    free(w.prevw.gen); free(w.prevw.val);
    return h;
fail:
    PyErr_Clear();
    Py_XDECREF(kdict);
    Py_XDECREF(open);
    free(w.own.gen); free(w.own.val);
    free(w.expected.gen); free(w.expected.val);
    free(w.lastread.gen); free(w.lastread.val);
    free(w.erseen.gen); free(w.erseen.val);
    free(w.prevw.gen); free(w.prevw.val);
    ef_free_handle(h);
    return NULL;
}

int64_t ef_status(void *hp) { return ((ef_handle *)hp)->status; }

int64_t ef_len(void *hp, int64_t field) {
    if (field < 0 || field >= EF_MAXF) return -1;
    return ((ef_handle *)hp)->f[field].n;
}

void ef_copy(void *hp, int64_t field, int64_t *dest) {
    ef_handle *h = (ef_handle *)hp;
    if (field < 0 || field >= EF_MAXF) return;
    memcpy(dest, h->f[field].d, (size_t)h->f[field].n * 8);
}

/* returns a NEW reference (ctypes py_object restype takes ownership) */
PyObject *ef_keys(void *hp) {
    PyObject *k = ((ef_handle *)hp)->keys;
    Py_INCREF(k);
    return k;
}

void ef_free(void *hp) { ef_free_handle((ef_handle *)hp); }
