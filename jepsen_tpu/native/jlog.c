/* CRC-framed history-log codec: the native hot path behind
 * jepsen_tpu/store/format.py.
 *
 * Capability reference: the reference's store layer pairs Clojure with
 * native-code codecs (jepsen/src/jepsen/store/FressianReader.java,
 * FileOffsetOutputStream.java) for exactly this job: fast, offset-
 * tracked scanning and writing of the block-structured history file
 * (store/format.clj:36-200). Here the format is simpler — magic +
 * [u32 len][u32 crc32(payload)][payload] records — and this codec
 * provides C-speed record scanning (offset table + torn-tail
 * detection) and batch framing for writers.
 *
 * Build: gcc/g++ -O2 -shared -fPIC jlog.c -o jlog.so -lz
 */

#include <stdint.h>
#include <string.h>
#include <zlib.h>

#ifdef __cplusplus
extern "C" {
#endif


#define HDR 8 /* u32 len + u32 crc */

/* Scans framed records in buf[0..len). Writes up to max_records pairs
 * (payload_start, payload_end) into offsets (2*max_records int64s).
 * Returns the number of intact records found; *valid_end gets the byte
 * offset just past the last intact record. Scanning starts at `start`
 * (the caller skips the magic). A torn or corrupt tail stops the scan:
 * exactly the Python reader's crash-recovery rule. */
int64_t jlog_scan(const uint8_t *buf, int64_t len, int64_t start,
                  int64_t *offsets, int64_t max_records,
                  int64_t *valid_end) {
    int64_t pos = start;
    int64_t n = 0;
    *valid_end = start;
    while (pos + HDR <= len) {
        uint32_t plen, crc;
        memcpy(&plen, buf + pos, 4);
        memcpy(&crc, buf + pos + 4, 4);
        if (pos + HDR + (int64_t)plen > len)
            break; /* torn payload */
        uint32_t got = (uint32_t)crc32(0L, buf + pos + HDR, plen);
        if (got != crc)
            break; /* corrupt record */
        if (n < max_records) {
            offsets[2 * n] = pos + HDR;
            offsets[2 * n + 1] = pos + HDR + plen;
        }
        n++;
        pos += HDR + plen;
        *valid_end = pos;
    }
    return n;
}

/* Frames `count` payloads (concatenated in payloads, lengths in lens)
 * into out, which must hold sum(lens) + count*HDR bytes. Returns bytes
 * written. */
int64_t jlog_frame(const uint8_t *payloads, const int64_t *lens,
                   int64_t count, uint8_t *out) {
    int64_t in_pos = 0, out_pos = 0;
    for (int64_t i = 0; i < count; i++) {
        uint32_t plen = (uint32_t)lens[i];
        uint32_t crc = (uint32_t)crc32(0L, payloads + in_pos, plen);
        memcpy(out + out_pos, &plen, 4);
        memcpy(out + out_pos + 4, &crc, 4);
        memcpy(out + out_pos + HDR, payloads + in_pos, plen);
        in_pos += plen;
        out_pos += HDR + plen;
    }
    return out_pos;
}

#ifdef __cplusplus
}  /* extern "C" */
#endif
