"""Node observability plane: per-node resource, clock-skew, and DB-log
telemetry for the nodes *under test*.

Five observability layers instrumented the harness and the device
kernels; the DB nodes stayed dark — `db.log_files` copies logs only at
teardown, `check-offsets` clock readings land in the history and are
never surfaced, and the quarantine breakers see nothing but transport
failures. This module is the node-side sensory plane (the fleet
service's admission/health input, ROADMAP item 2):

  *Sampler.* One lightweight compound shell probe per node per tick
  over the existing remote layer — a single `execute()` reading
  `/proc/stat`, `/proc/meminfo`, `/proc/diskstats`, `/proc/net/dev`,
  a clock reading (`date +%s.%N`, turned into a control-vs-node offset
  via nemesis/time.clock_offset), and incremental byte-offset tails of
  the DB's log files. Each tick appends schema-validated records to
  `nodes.jsonl` in the run's store directory.

  *Honest gaps.* A node that can't be probed — partitioned, dead, or
  quarantined (open circuit: skipped without touching the transport) —
  gets a `gap` record naming the reason. Missing samples are never
  interpolated; a blank stretch in the lane IS the observation.

  *Log taxonomy.* Tailed log bytes are scanned against a small pattern
  taxonomy (panic/assert, OOM-kill, election/leader-change, corruption,
  restart) producing structured `log` records. A parseable in-line
  timestamp is normalized by the node's measured clock offset onto the
  run's clock ("this election happened *during* the partition window",
  even on a node whose clock the nemesis bumped 200s); lines without
  one are stamped at observation time (`ts: "observed"`).

  *Clock-skew series.* The per-tick offsets merge with the history's
  `check-offsets` observations (`clock-offsets` completions, which
  previously were recorded and never surfaced) into one per-node skew
  series; its worst absolute value is the `clock-skew-bound` stamped
  onto realtime-order verdicts (wgl linearizability, elle strict
  variants) — in the AccelSync spirit, a `-realtime` claim carries the
  observability evidence that bounds it.

Surfacing: web run pages render per-node lanes (reports/nodes.py), the
Perfetto export gains one process per node with counter tracks + event
slices (reports/trace.py), anomaly trace excerpts include the node
events inside the anomaly's op window (reports/explain.py), and the
Prometheus `/metrics?run=` endpoint exposes the latest node samples.
The dummy remote answers the probe with seeded synthetic `/proc` data
(synthetic_responder) so demo runs and tier-1 exercise the full path
clusterless. See doc/observability.md, "The node observability plane".
"""

from __future__ import annotations

import json
import logging
import re
import threading
import time as _time
from decimal import Decimal, InvalidOperation
from pathlib import Path
from typing import Any, Iterable

from . import telemetry, util

logger = logging.getLogger(__name__)

NODES_FILE = "nodes.jsonl"
SCHEMA = 1

DEFAULT_INTERVAL_S = 1.0
TAIL_MAX_BYTES = 65536
MAX_EVENTS_PER_TICK = 32   # per file — a log storm can't flood the plane
LINE_LIMIT = 240           # stored log-line excerpt length

# One marker line per probe section; echoed by the compound command,
# split on read. The string never occurs in /proc output or sane logs.
MARK = "=====jepsen-nodeprobe"
# Echoed immediately after each log tail: `echo` starts at the cursor,
# so the sentinel lands on its own line iff the tail ended with a
# newline — which makes the byte-offset accounting exact even though
# the transport hands us the output re-split into lines.
EOT = "=====jepsen-probe-tail-eot"  # NOT a MARK prefix: it must
# survive parse_probe's section split as ordinary content

KINDS = ("sample", "gap", "log", "breaker")
GAP_REASONS = ("unreachable", "quarantined", "no-data")
BREAKER_STATES = ("closed", "open", "half-open")

# The DB-log pattern taxonomy, first match wins (a panic line that
# mentions the leader is a panic). Patterns are deliberately broad —
# they tag *candidate* events for a human/correlator, they are not
# verdicts.
LOG_PATTERNS: list[tuple[str, re.Pattern]] = [
    ("panic-assert", re.compile(
        r"panic|assert(ion)?\s+fail|fatal error|segfault|stack trace",
        re.IGNORECASE)),
    ("oom-kill", re.compile(
        r"out of memory|oom[- ]?kill|killed process|"
        r"cannot allocate memory", re.IGNORECASE)),
    ("corruption", re.compile(
        r"corrupt|checksum mismatch|checksum error|bad magic|"
        r"invalid block", re.IGNORECASE)),
    ("election", re.compile(
        r"election|elected|became leader|leader.{0,16}(change|lost|"
        r"down|elect)|new leader|stepping down|became follower|"
        r"voted for", re.IGNORECASE)),
    ("restart", re.compile(
        r"starting server|server started|shutting down|"
        r"received signal|restarting|ready to accept", re.IGNORECASE)),
]

LOG_CLASSES = tuple(name for name, _p in LOG_PATTERNS)


def classify_line(line: str) -> str | None:
    """The taxonomy class of one log line, or None for lines the node
    plane has nothing to say about."""
    for name, pat in LOG_PATTERNS:
        if pat.search(line):
            return name
    return None


# Timestamps the tailer can normalize: ISO-8601-ish (the common DB log
# prefix) and bracketed epoch seconds.
_ISO_TS = re.compile(
    r"(\d{4})-(\d{2})-(\d{2})[T ](\d{2}):(\d{2}):(\d{2})(\.\d+)?")
_EPOCH_TS = re.compile(r"[\[(](\d{9,10}(?:\.\d+)?)[\])]")


def parse_log_timestamp(line: str) -> float | None:
    """Node-clock epoch seconds parsed from a log line, or None.
    ISO timestamps are taken as UTC — DB logs under test overwhelmingly
    log UTC, and a timezone mis-guess is bounded and visible next to
    the raw line, unlike a silently dropped timestamp."""
    m = _ISO_TS.search(line)
    if m:
        import calendar

        y, mo, d, h, mi, s = (int(m.group(i)) for i in range(1, 7))
        frac = float(m.group(7) or 0.0)
        try:
            return calendar.timegm((y, mo, d, h, mi, s)) + frac
        except (ValueError, OverflowError):
            return None
    m = _EPOCH_TS.search(line)
    if m:
        try:
            return float(m.group(1))
        except ValueError:
            return None
    return None


# ---------------------------------------------------------------------------
# The compound probe command + its parse
# ---------------------------------------------------------------------------

def probe_cmd(log_files: dict[str, int] | None = None) -> str:
    """The one shell command a tick runs: echoes a marker line before
    each section so the reply splits unambiguously. `log_files` maps
    log path -> byte offset already consumed (tail resumes there)."""
    from .control.core import escape

    parts = []
    for name, path in (("stat", "/proc/stat"),
                       ("meminfo", "/proc/meminfo"),
                       ("diskstats", "/proc/diskstats"),
                       ("net", "/proc/net/dev")):
        parts.append(f"echo '{MARK} {name}'; cat {path} 2>/dev/null")
    for path, off in (log_files or {}).items():
        # tr maps bytes 1:1, so replacing every byte that isn't
        # printable-ASCII/\n/\t with '?' keeps the offset accounting
        # exact while guaranteeing the reply survives the transport:
        # a binary splat in a crashed DB's log (the corruption
        # taxonomy's own target) must not wedge a strict-UTF-8
        # transport into permanent gaps, and \r must die HERE —
        # text-mode transports translate \r\n to \n, which would
        # silently eat one byte per CRLF line and drift the offsets
        parts.append(f"echo '{MARK} log {path}'; "
                     f"tail -c +{int(off) + 1} {escape(path)} "
                     f"2>/dev/null | head -c {TAIL_MAX_BYTES} "
                     "| tr -c '[:print:]\\n\\t' '?'; "
                     f"echo '{EOT}'")
    # clock LAST: the reading is compared against control time when
    # the reply is parsed, so everything between `date` running and
    # that comparison (here: just the reply transfer, not also the
    # 64KB-per-file log tails) biases the offset negative. The
    # residual one-way latency is inherent — the same bias the
    # nemesis's check-offsets reading carries.
    parts.append(f"echo '{MARK} clock'; date +%s.%N")
    return "; ".join(parts)


def split_tail(section: str) -> str | None:
    """The exact bytes a log tail returned, recovered from its
    section text via the EOT sentinel: a trailing newline survives as
    the sentinel sitting on its own line. None when the sentinel is
    missing (reply torn mid-section — consume nothing, retry next
    tick)."""
    if section == EOT:
        return ""
    if section.endswith("\n" + EOT):
        return section[:-len(EOT)]          # tail ended with \n
    if section.endswith(EOT):
        return section[:-len(EOT)]          # tail ended mid-line
    return None


def parse_probe(out: str) -> dict:
    """Splits a probe reply into {'stat': text, ..., 'clock': text,
    'logs': {path: text}} by marker line."""
    sections: dict[str, Any] = {"logs": {}}
    name = None
    buf: list[str] = []

    def flush():
        if name is None:
            return
        text = "\n".join(buf)
        if name.startswith("log "):
            sections["logs"][name[len("log "):]] = text
        else:
            sections[name] = text

    # split on "\n" ONLY (not splitlines): a CRLF log line keeps its
    # \r byte and \x85/U+2028-style characters stay intact, so the
    # tail sections rejoin to the EXACT bytes the node sent and the
    # byte-offset accounting in _scan_logs cannot drift
    for line in out.split("\n"):
        if line.startswith(MARK):
            flush()
            name = line[len(MARK):].strip()
            buf = []
        else:
            buf.append(line)
    flush()
    return sections


def parse_stat(text: str) -> dict | None:
    """Aggregate cpu jiffies from /proc/stat's first `cpu ` line:
    {'busy': j, 'total': j} (busy = total - idle - iowait)."""
    for line in text.splitlines():
        if line.startswith("cpu "):
            try:
                fields = [int(x) for x in line.split()[1:]]
            except ValueError:
                return None
            if len(fields) < 4:
                return None
            total = sum(fields)
            idle = fields[3] + (fields[4] if len(fields) > 4 else 0)
            return {"busy": total - idle, "total": total}
    return None


def parse_meminfo(text: str) -> dict | None:
    """{'total_kb': n, 'free_kb': n} (MemAvailable preferred over
    MemFree — it is what the OOM killer effectively reasons about)."""
    vals: dict[str, int] = {}
    for line in text.splitlines():
        parts = line.split()
        if len(parts) >= 2 and parts[0].rstrip(":") in (
                "MemTotal", "MemFree", "MemAvailable"):
            try:
                vals[parts[0].rstrip(":")] = int(parts[1])
            except ValueError:
                pass
    if "MemTotal" not in vals:
        return None
    free = vals.get("MemAvailable", vals.get("MemFree"))
    if free is None:
        return None
    return {"total_kb": vals["MemTotal"], "free_kb": free}


def parse_diskstats(text: str) -> dict | None:
    """Summed sectors read/written across real devices (loop/ram
    excluded)."""
    read = written = 0
    seen = False
    for line in text.splitlines():
        f = line.split()
        if len(f) < 10 or f[2].startswith(("loop", "ram")):
            continue
        try:
            read += int(f[5])
            written += int(f[9])
            seen = True
        except ValueError:
            continue
    return {"read_sectors": read, "write_sectors": written} if seen \
        else None


def parse_netdev(text: str) -> dict | None:
    """Summed rx/tx bytes across interfaces, loopback excluded."""
    rx = tx = 0
    seen = False
    for line in text.splitlines():
        if ":" not in line:
            continue
        iface, rest = line.split(":", 1)
        if iface.strip() == "lo":
            continue
        f = rest.split()
        if len(f) < 9:
            continue
        try:
            rx += int(f[0])
            tx += int(f[8])
            seen = True
        except ValueError:
            continue
    return {"rx_bytes": rx, "tx_bytes": tx} if seen else None


def parse_clock(text: str) -> float | None:
    """Control-vs-node clock offset in seconds from a `date +%s.%N`
    reading (nemesis/time.clock_offset — the check-offsets math)."""
    from .nemesis.time import clock_offset

    try:
        return clock_offset(Decimal(text.strip()))
    except (InvalidOperation, ValueError, ArithmeticError):
        return None


# ---------------------------------------------------------------------------
# The probe
# ---------------------------------------------------------------------------

class _NodeState:
    """Per-node tail offsets + previous counters for rate deltas."""

    def __init__(self, log_files: list[str]):
        self.offsets: dict[str, int] = {p: 0 for p in log_files}
        self.carry: dict[str, str] = {p: "" for p in log_files}
        self.prev: dict | None = None
        self.prev_t: int | None = None
        self.last_offset: float | None = None  # last MEASURED clock
        self.session = None
        self.breaker_state: str | None = None
        self.advised: set[str] = set()


class NodeProbe:
    """Background per-node sampler. Lifecycle mirrors the monitor:
    NodeProbe(test) -> start(path) -> [samples] -> stop(). Tests may
    drive `tick(node)` directly, without threads.

    Each node gets its own thread and its own control session (not the
    run's shared worker sessions — a hung probe must never stall a
    client op), reconnecting lazily after transport failures. With
    quarantine active (test["health"]), an open-circuit node is
    skipped entirely — one `gap` record, zero transport traffic."""

    # advisory thresholds (satellite: health summaries feed the
    # registry as warnings, never as breaker verdicts)
    MEM_FREE_WARN_FRAC = 0.05
    CPU_BUSY_WARN_FRAC = 0.98

    # concurrency-lint contract (jepsen_tpu.analysis.concurrency,
    # doc/static-analysis.md): per-node threads all funnel records
    # through _emit under _lock. Per-node _NodeState objects are
    # owned by their node's thread (unshared) and the lifecycle
    # attrs by the controlling thread; neither is listed.
    _guarded_by_lock = {"_lock": ("_records",)}

    def __init__(self, test: dict | None = None,
                 interval_s: float | None = None):
        test = test or {}
        self.test = test
        self.interval_s = float(
            interval_s if interval_s is not None
            else test.get("nodeprobe_interval_s", DEFAULT_INTERVAL_S))
        self.nodes = list(test.get("nodes") or [])
        self._lock = threading.Lock()
        self._records: list[dict] = []
        self._out = None
        self._stop = threading.Event()
        self._stopped = False
        self._threads: list[threading.Thread] = []
        self._states = {n: _NodeState(self._log_files(test, n))
                        for n in self.nodes}
        # run-clock origin in epoch seconds: what normalizes parsed
        # node-log timestamps onto the relative timeline
        self.origin_epoch = (_time.time()
                             - util.relative_time_nanos() / 1e9)

    @staticmethod
    def _log_files(test: dict, node) -> list[str]:
        """Log paths to tail on `node`: the explicit
        test["node_log_files"] override (demo runs, tests), else
        whatever the DB declares via the LogFiles protocol."""
        explicit = test.get("node_log_files")
        if explicit:
            return [str(p) for p in explicit]
        db = test.get("db")
        if db is None:
            return []
        try:
            from . import db as jdb

            return list(jdb.log_files_map(db, test, node))
        except Exception:  # noqa: BLE001 — probing must never raise
            logger.exception("resolving log files for %s failed", node)
            return []

    # -- record plumbing ---------------------------------------------------

    def _emit(self, rec: dict) -> None:
        with self._lock:
            self._records.append(rec)
            if self._out is not None:
                try:
                    self._out.write(json.dumps(rec, default=repr))
                    self._out.write("\n")
                    self._out.flush()  # web lanes tail this cross-process
                except OSError:
                    logger.exception("nodes.jsonl write failed")
                    self._out = None

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._records)

    # -- one tick ----------------------------------------------------------

    def _breaker(self, node):
        hr = self.test.get("health")
        if hr is None:
            return None
        try:
            return hr.breaker(node)
        except Exception:  # noqa: BLE001
            return None

    def _record_breaker_transition(self, node, st: _NodeState) -> None:
        b = self._breaker(node)
        if b is None:
            return
        state = b.state()
        if state != st.breaker_state:
            st.breaker_state = state
            self._emit({"kind": "breaker", "node": str(node),
                        "t": util.relative_time_nanos(),
                        "state": state})

    def _gap(self, node, reason: str) -> None:
        telemetry.count(f"nodeprobe.gaps.{reason}")
        self._emit({"kind": "gap", "node": str(node),
                    "t": util.relative_time_nanos(),
                    "reason": reason})

    def _session(self, node, st: _NodeState):
        if st.session is None:
            from . import control

            # UNguarded: probe traffic must never feed the quarantine
            # breakers — a probe failure/s during a partition would
            # open every circuit on its own, and a probe success
            # would reset the consecutive-failure count between real
            # client failures. The probe only READS breaker state
            # (tick() skips open circuits).
            st.session = control.session(self.test, node,
                                         guarded=False)
        return st.session

    def _drop_session(self, st: _NodeState) -> None:
        sess, st.session = st.session, None
        if sess is not None:
            try:
                sess.disconnect()
            except Exception:  # noqa: BLE001 — already failing
                pass

    def tick(self, node) -> None:
        """One probe of one node: sample + log tail, or an honest gap.
        Never raises."""
        from .control.core import Action, TransportError

        st = self._states.setdefault(
            node, _NodeState(self._log_files(self.test, node)))
        self._record_breaker_transition(node, st)
        b = self._breaker(node)
        if b is not None and b.is_open:
            # quarantined: skipped without touching the transport (the
            # breaker would fail-fast anyway; skipping also spares the
            # rejected-command counter noise)
            self._gap(node, "quarantined")
            return
        cmd = probe_cmd(st.offsets)
        try:
            sess = self._session(node, st)
            res = sess.execute(Action(
                cmd=cmd, timeout=max(10.0, self.interval_s * 5)))
            out = res.out if res.exit == 0 else ""
        except TransportError:
            self._drop_session(st)
            self._gap(node, "unreachable")
            return
        except Exception:  # noqa: BLE001 — the probe must never die
            logger.exception("nodeprobe tick failed on %s", node)
            self._drop_session(st)
            self._gap(node, "unreachable")
            return
        self._record_breaker_transition(node, st)
        t = util.relative_time_nanos()
        sections = parse_probe(out or "")
        sample = self._build_sample(node, st, sections, t)
        if sample is None:
            # reachable but mute (e.g. the bare dummy remote's empty
            # success): an honest no-data gap, not a zeroed sample
            self._gap(node, "no-data")
        else:
            telemetry.count("nodeprobe.samples")
            self._emit(sample)
            self._advise(node, st, sample)
        # normalize log timestamps with the freshest MEASURED offset;
        # a tick whose clock section was torn falls back to the last
        # measurement, and with none ever taken the events are
        # stamped "observed", never "parsed"-with-a-made-up-zero
        offset = (sample or {}).get("clock_offset_s")
        if offset is not None:
            st.last_offset = offset
        self._scan_logs(node, st, sections.get("logs") or {}, t,
                        st.last_offset)

    def _build_sample(self, node, st: _NodeState, sections: dict,
                      t: int) -> dict | None:
        cpu = parse_stat(sections.get("stat", ""))
        mem = parse_meminfo(sections.get("meminfo", ""))
        disk = parse_diskstats(sections.get("diskstats", ""))
        net = parse_netdev(sections.get("net", ""))
        offset = parse_clock(sections.get("clock", ""))
        if cpu is None and mem is None and offset is None:
            return None
        rec: dict = {"kind": "sample", "node": str(node), "t": t}
        if mem is not None:
            used = mem["total_kb"] - mem["free_kb"]
            rec["mem"] = {"total_kb": mem["total_kb"],
                          "free_kb": mem["free_kb"],
                          "used_frac": round(
                              used / mem["total_kb"], 4)
                          if mem["total_kb"] else 0.0}
        if offset is not None:
            rec["clock_offset_s"] = round(offset, 6)
        # rate-like series need a previous tick; the first sample
        # carries only absolutes (never a made-up zero rate)
        prev, prev_t = st.prev, st.prev_t
        dt_s = (t - prev_t) / 1e9 if prev_t is not None else None
        if cpu is not None and prev and prev.get("cpu") and dt_s:
            d_busy = cpu["busy"] - prev["cpu"]["busy"]
            d_total = cpu["total"] - prev["cpu"]["total"]
            if d_total > 0:
                rec["cpu"] = {"busy": round(
                    max(0.0, min(1.0, d_busy / d_total)), 4)}
        if disk is not None and prev and prev.get("disk") and dt_s:
            rec["disk"] = {
                "read_bytes_s": round(max(0, (
                    disk["read_sectors"]
                    - prev["disk"]["read_sectors"])) * 512 / dt_s, 1),
                "write_bytes_s": round(max(0, (
                    disk["write_sectors"]
                    - prev["disk"]["write_sectors"])) * 512 / dt_s, 1)}
        if net is not None and prev and prev.get("net") and dt_s:
            rec["net"] = {
                "rx_bytes_s": round(max(0, (
                    net["rx_bytes"]
                    - prev["net"]["rx_bytes"])) / dt_s, 1),
                "tx_bytes_s": round(max(0, (
                    net["tx_bytes"]
                    - prev["net"]["tx_bytes"])) / dt_s, 1)}
        st.prev = {"cpu": cpu, "disk": disk, "net": net}
        st.prev_t = t
        return rec

    def _scan_logs(self, node, st: _NodeState, logs: dict[str, str],
                   t: int, clock_offset_s: float | None) -> None:
        for path, section in logs.items():
            text = split_tail(section)
            if not text:
                continue  # empty tail, or torn reply: retry next tick
            st.offsets[path] = st.offsets.get(path, 0) \
                + len(text.encode("utf-8", "replace"))
            # the tail may end mid-line (head -c truncation, or the
            # writer caught mid-append); carry the fragment into the
            # next tick instead of classifying half a line
            text = st.carry.get(path, "") + text
            lines = text.split("\n")
            st.carry[path] = lines.pop() if lines else ""
            n = 0
            for line in lines:
                cls = classify_line(line)
                if cls is None:
                    continue
                if n >= MAX_EVENTS_PER_TICK:
                    telemetry.count("nodeprobe.log-events-dropped")
                    break
                n += 1
                self._emit(self._log_event(node, path, line, cls, t,
                                           clock_offset_s))
            if st.carry[path] and len(st.carry[path]) > TAIL_MAX_BYTES:
                st.carry[path] = ""  # a pathological unterminated line

    def _log_event(self, node, path: str, line: str, cls: str,
                   t: int, clock_offset_s: float | None) -> dict:
        telemetry.count(f"nodeprobe.log.{cls}")
        rec = {"kind": "log", "node": str(node), "file": path,
               "class": cls, "line": line.strip()[:LINE_LIMIT]}
        ts_node = parse_log_timestamp(line)
        if ts_node is not None and clock_offset_s is not None:
            # node-clock epoch -> control epoch -> run-relative ns;
            # the measured offset is the normalizer (a bumped clock's
            # "future" log lines land where they really happened)
            rel = (ts_node - clock_offset_s - self.origin_epoch) * 1e9
            rec["t"] = max(0, int(rel))
            rec["ts"] = "parsed"
            rec["t_node_s"] = round(ts_node, 3)
        else:
            rec["t"] = t
            rec["ts"] = "observed"
        return rec

    def _advise(self, node, st: _NodeState, sample: dict) -> None:
        """Advisory health summaries for the breaker registry: warn,
        never trip — a loaded node is not a dead node."""
        hr = self.test.get("health")
        if hr is None or not hasattr(hr, "advise"):
            return
        worries = {}
        mem = sample.get("mem") or {}
        if mem.get("total_kb") and (mem.get("free_kb", 0)
                                    / mem["total_kb"]
                                    < self.MEM_FREE_WARN_FRAC):
            worries["low-memory"] = mem.get("free_kb")
        cpu = sample.get("cpu") or {}
        if cpu.get("busy", 0.0) > self.CPU_BUSY_WARN_FRAC:
            worries["cpu-saturated"] = cpu["busy"]
        for reason, value in worries.items():
            if reason not in st.advised:
                st.advised.add(reason)
                hr.advise(node, reason, value)
        st.advised &= set(worries)  # cleared worries may re-warn later

    # -- lifecycle ---------------------------------------------------------

    def start(self, out_path=None) -> "NodeProbe":
        if out_path is not None:
            try:
                p = Path(out_path)
                p.parent.mkdir(parents=True, exist_ok=True)
                self._out = open(p, "w")
            except OSError:  # observability must never sink the run
                logger.exception("nodes.jsonl unavailable")
                self._out = None
        self._stop.clear()

        def run(node):
            while not self._stop.wait(self.interval_s):
                try:
                    self.tick(node)
                except Exception:  # noqa: BLE001 — sampler must not die
                    logger.exception("nodeprobe loop failed on %s",
                                     node)

        for node in self.nodes:
            th = threading.Thread(target=run, args=(node,),
                                  name=f"jepsen-nodeprobe-{node}",
                                  daemon=True)
            th.start()
            self._threads.append(th)
        return self

    def stop(self) -> None:
        """Stops the samplers and closes sessions + the output file.
        Idempotent (core.run stops before analyze AND in its finally)."""
        if self._stopped:
            return
        self._stopped = True
        self._stop.set()
        for th in self._threads:
            th.join(timeout=5)
        self._threads = []
        for st in self._states.values():
            self._drop_session(st)
        with self._lock:
            if self._out is not None:
                self._out.close()
                self._out = None


# ---------------------------------------------------------------------------
# Reading + validating stored records
# ---------------------------------------------------------------------------

def read_records(path) -> Iterable[dict]:
    """Records from a nodes.jsonl; torn trailing line dropped (the
    shared jsonl crash-tolerance contract)."""
    return telemetry.read_jsonl(path)


def load_records(store_dir) -> list[dict]:
    """All node-plane records of a stored run ([] when the run
    predates, or disabled, the probe)."""
    if not store_dir:
        return []
    return list(read_records(Path(store_dir) / NODES_FILE))


def validate_records(records) -> int:
    """Schema check for nodes.jsonl records (tier-1, the house style
    alongside telemetry/ledger/coverage validators): every record has
    a known kind, a node, and a non-negative integer t; samples carry
    numeric metrics and non-decreasing per-node times; gaps/breakers
    carry known reasons/states; log events carry a taxonomy class and
    a ts provenance tag. Returns the record count; raises ValueError
    on the first violation."""
    n = 0
    last_sample_t: dict[str, int] = {}
    for i, rec in enumerate(records):
        if not isinstance(rec, dict):
            raise ValueError(f"record {i}: not a dict")
        kind = rec.get("kind")
        if kind not in KINDS:
            raise ValueError(f"record {i}: unknown kind {kind!r}")
        node = rec.get("node")
        if not isinstance(node, str) or not node:
            raise ValueError(f"record {i}: bad node {node!r}")
        t = rec.get("t")
        if not isinstance(t, int) or t < 0:
            raise ValueError(f"record {i}: bad t {t!r}")
        if kind == "sample":
            if t < last_sample_t.get(node, 0):
                raise ValueError(
                    f"record {i}: sample time regressed on {node}")
            last_sample_t[node] = t
            for section in ("cpu", "mem", "disk", "net"):
                v = rec.get(section)
                if v is None:
                    continue
                if not isinstance(v, dict) or not all(
                        isinstance(x, (int, float))
                        for x in v.values()):
                    raise ValueError(
                        f"record {i}: bad {section}: {v!r}")
            off = rec.get("clock_offset_s")
            if off is not None and not isinstance(off, (int, float)):
                raise ValueError(f"record {i}: bad clock_offset_s")
        elif kind == "gap":
            if rec.get("reason") not in GAP_REASONS:
                raise ValueError(
                    f"record {i}: bad gap reason {rec.get('reason')!r}")
        elif kind == "breaker":
            if rec.get("state") not in BREAKER_STATES:
                raise ValueError(
                    f"record {i}: bad breaker state "
                    f"{rec.get('state')!r}")
        elif kind == "log":
            if rec.get("class") not in LOG_CLASSES:
                raise ValueError(
                    f"record {i}: unknown log class "
                    f"{rec.get('class')!r}")
            if rec.get("ts") not in ("parsed", "observed"):
                raise ValueError(
                    f"record {i}: bad ts provenance {rec.get('ts')!r}")
            if not isinstance(rec.get("line"), str):
                raise ValueError(f"record {i}: log without line")
        n += 1
    return n


# ---------------------------------------------------------------------------
# Clock-skew series: probe samples merged with check-offsets history
# ---------------------------------------------------------------------------

def clock_series(records, history=None) -> dict[str, list]:
    """{node: [[t_ns, offset_s], ...]} merging the probe's per-tick
    clock offsets with the history's `check-offsets` observations
    (`clock-offsets` completions — recorded since the clock nemesis
    landed, surfaced here for the first time), time-sorted."""
    series: dict[str, list] = {}
    for rec in records or []:
        if rec.get("kind") == "sample" and "clock_offset_s" in rec:
            series.setdefault(str(rec["node"]), []).append(
                [rec["t"], rec["clock_offset_s"]])
    for op in history or []:
        offsets = None
        try:
            offsets = op.get("clock-offsets")
        except AttributeError:
            pass
        if not offsets:
            continue
        t = getattr(op, "time", 0) or 0
        for node, off in offsets.items():
            if isinstance(off, (int, float)):
                series.setdefault(str(node), []).append(
                    [int(t), float(off)])
    for pts in series.values():
        pts.sort(key=lambda p: p[0])
    return series


def clock_skew_bound(records, history=None) -> float | None:
    """The worst absolute clock offset observed across the merged
    probe + check-offsets series, in seconds — the bound a realtime
    verdict honestly carries. None when nothing was measured (an
    unmeasured run must not claim a zero bound)."""
    worst = None
    for pts in clock_series(records, history).values():
        for _t, off in pts:
            a = abs(off)
            if worst is None or a > worst:
                worst = a
    return round(worst, 6) if worst is not None else None


# Anomaly classes whose checks lean on realtime order: the wgl
# linearizability verdict, and the elle graphs (both engines build
# realtime edges — cycles closing only through them get -realtime
# names). A result tagged with any of these gets the bound.
_REALTIME_MARKERS = ("nonlinearizable", "G0", "G-single")


def _uses_realtime(result: dict) -> bool:
    classes = result.get("anomaly-classes")
    if not isinstance(classes, dict):
        return False
    return any(m in classes for m in _REALTIME_MARKERS) or any(
        str(c).endswith("-realtime") for c in classes)


def stamp_results(results, bound: float, depth: int = 0) -> int:
    """Attaches `clock-skew-bound` to every realtime-order verdict in
    a results tree (wgl linearizability, elle strict variants) — the
    AccelSync framing: a `-realtime` claim carries the measured skew
    that bounds it. Returns the number of verdicts stamped."""
    if not isinstance(results, dict) or depth > 6:
        return 0
    n = 0
    if _uses_realtime(results):
        results["clock-skew-bound"] = bound
        n += 1
    for k, v in results.items():
        if k in ("anomalies", "anomaly-classes"):
            continue
        if isinstance(v, dict):
            n += stamp_results(v, bound, depth + 1)
        elif isinstance(v, list):
            for item in v:
                if isinstance(item, dict):
                    n += stamp_results(item, bound, depth + 1)
    return n


# ---------------------------------------------------------------------------
# Prometheus exposition (web.py /metrics?run=)
# ---------------------------------------------------------------------------

def _prom_label(v) -> str:
    return str(v).replace("\\", "_").replace('"', "_")


def prometheus_lines(records) -> list[str]:
    """Node-plane samples for the run's /metrics scrape: the latest
    resource gauges per node, plus log-event / gap counters."""
    latest: dict[str, dict] = {}
    logs: dict[tuple, int] = {}
    gaps: dict[tuple, int] = {}
    for rec in records or []:
        node = _prom_label(rec.get("node"))
        if rec.get("kind") == "sample":
            latest[node] = rec
        elif rec.get("kind") == "log":
            key = (node, _prom_label(rec.get("class")))
            logs[key] = logs.get(key, 0) + 1
        elif rec.get("kind") == "gap":
            key = (node, _prom_label(rec.get("reason")))
            gaps[key] = gaps.get(key, 0) + 1
    lines: list[str] = []
    gauges = (
        ("jepsen_tpu_node_cpu_busy", lambda r: (r.get("cpu") or {})
         .get("busy")),
        ("jepsen_tpu_node_mem_used_fraction",
         lambda r: (r.get("mem") or {}).get("used_frac")),
        ("jepsen_tpu_node_clock_offset_seconds",
         lambda r: r.get("clock_offset_s")),
        ("jepsen_tpu_node_net_rx_bytes_per_second",
         lambda r: (r.get("net") or {}).get("rx_bytes_s")),
        ("jepsen_tpu_node_net_tx_bytes_per_second",
         lambda r: (r.get("net") or {}).get("tx_bytes_s")),
    )
    for name, getter in gauges:
        rows = [(node, getter(rec)) for node, rec in sorted(
            latest.items())]
        rows = [(node, v) for node, v in rows if v is not None]
        if not rows:
            continue
        lines.append(f"# TYPE {name} gauge")
        lines.extend(f'{name}{{node="{node}"}} {v}'
                     for node, v in rows)
    if logs:
        lines.append("# TYPE jepsen_tpu_node_log_events counter")
        lines.extend(
            f'jepsen_tpu_node_log_events{{node="{n}",class="{c}"}} {v}'
            for (n, c), v in sorted(logs.items()))
    if gaps:
        lines.append("# TYPE jepsen_tpu_node_probe_gaps counter")
        lines.extend(
            f'jepsen_tpu_node_probe_gaps{{node="{n}",reason="{r}"}} {v}'
            for (n, r), v in sorted(gaps.items()))
    return lines


# ---------------------------------------------------------------------------
# Seeded synthetic /proc responder (dummy-remote demo + tier-1 path)
# ---------------------------------------------------------------------------

_TAIL_RE = re.compile(r"tail -c \+(\d+) (\S+)")
_LOG_MARK_RE = re.compile(re.escape(MARK) + r" log (\S+)'")


class synthetic_responder:  # noqa: N801 — callable factory, used as one
    """A DummyRemote responder answering the probe with deterministic,
    seeded, *evolving* synthetic node state: counters grow tick over
    tick, each node's clock carries a distinct constant skew, and the
    synthetic DB log gains seeded taxonomy lines (one election early,
    one OOM-kill later) so demo runs produce tagged node events.

    Composable: returns None for commands it doesn't recognize, so it
    chains behind other responders (jepsen_tpu.__main__ chains it
    after the partitioner's getent/ip-link answers)."""

    # concurrency-lint contract: the dummy remote calls this from
    # every probe thread; node state mutates under _lock only
    _guarded_by_lock = {"_lock": ("_nodes",)}

    def __init__(self, seed: int = 7):
        self.seed = seed
        self._lock = threading.Lock()
        self._nodes: dict[str, dict] = {}

    # per-tick increments are seeded per node: deterministic across
    # runs, distinct across nodes; _locked suffix = caller holds _lock
    def _state_locked(self, node) -> dict:
        key = str(node)
        st = self._nodes.get(key)
        if st is None:
            import random

            rng = random.Random(f"{self.seed}:{key}")
            idx = len(self._nodes)
            st = self._nodes[key] = {
                "rng": rng, "tick": 0,
                "busy": 0, "idle": 0, "rd": 0, "wr": 0,
                "rx": 0, "tx": 0,
                # distinct, finite skew per node; n1 gets -120ms,
                # n2 +240ms, ... — visibly nonzero, obviously bounded
                "skew": ((-1) ** idx) * 0.12 * (idx + 1),
                "log": "",
            }
        return st

    def _advance(self, st: dict) -> None:
        rng = st["rng"]
        st["tick"] += 1
        st["busy"] += rng.randrange(20, 80)
        st["idle"] += rng.randrange(100, 300)
        st["rd"] += rng.randrange(0, 512)
        st["wr"] += rng.randrange(0, 2048)
        st["rx"] += rng.randrange(1_000, 50_000)
        st["tx"] += rng.randrange(1_000, 50_000)
        # the seeded log schedule: a leader election on tick 2, an
        # OOM-kill on tick 4, chatter otherwise
        t = st["tick"]
        iso = _time.strftime("%Y-%m-%d %H:%M:%S",
                             _time.gmtime(_time.time() + st["skew"]))
        if t == 2:
            line = f"{iso} I | raft: became leader at term {t}\n"
        elif t == 4:
            line = (f"{iso} W | Out of memory: Killed process 4242 "
                    "(db-server)\n")
        else:
            line = f"{iso} D | compaction pass {t} ok\n"
        st["log"] += line

    def __call__(self, node, action):
        cmd = getattr(action, "cmd", "") or ""
        if MARK not in cmd:
            return None
        with self._lock:
            st = self._state_locked(node)
            self._advance(st)
            mem_free = max(512_000, 4_096_000 - st["tick"] * 37_000)
            out = [
                f"{MARK} stat",
                f"cpu  {st['busy']} 0 0 {st['idle']} 0 0 0 0",
                f"{MARK} meminfo",
                "MemTotal:        8192000 kB",
                f"MemFree:         {mem_free} kB",
                f"MemAvailable:    {mem_free} kB",
                f"{MARK} diskstats",
                f"   8       0 sda 100 0 {st['rd']} 10 50 0 "
                f"{st['wr']} 20 0 30 30",
                f"{MARK} net",
                "Inter-|   Receive  |  Transmit",
                f" eth0: {st['rx']} 10 0 0 0 0 0 0 {st['tx']} "
                "10 0 0 0 0 0 0",
                f"{MARK} clock",
                f"{_time.time() + st['skew']:.9f}",
            ]
            # answer each log section the command asked for, honoring
            # its tail offset against the synthetic log's full content
            offsets = {path: int(off) - 1 for off, path
                       in _TAIL_RE.findall(cmd)}
            for path in _LOG_MARK_RE.findall(cmd):
                off = max(0, offsets.get(path, 0))
                out.append(f"{MARK} log {path}")
                chunk = st["log"].encode()[off:off + TAIL_MAX_BYTES]
                # exactly what `tail | head; echo EOT` would print:
                # the sentinel follows the chunk's own (non-)newline
                out.append(chunk.decode("utf-8", "replace") + EOT)
            return "\n".join(out)
