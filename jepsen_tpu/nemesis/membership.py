"""Membership nemesis: a state machine for adding/removing cluster
nodes, with per-node view polling.

Capability reference: jepsen/src/jepsen/nemesis/membership.clj:109-247
and membership/state.clj — a State protocol (node_view, merge_views,
op, invoke, resolve, resolve_op), background per-node view updaters
feeding a merged authoritative view, an invoke path that records
[op, op'] pairs as pending until resolved, and a generator that asks
the state machine which operations are currently legal.

The state object carries three bookkeeping fields the nemesis manages
for it (state.clj:6-17): `node_views` (node -> that node's view of the
cluster), `view` (merged authoritative view) and `pending` (applied
[op, op'] pairs not yet confirmed)."""

from __future__ import annotations

import threading
from typing import Any

from .. import generator as gen
from . import core as n

NODE_VIEW_INTERVAL = 5.0  # seconds between node view polls


class MembershipState:
    """Subclass and implement the cluster-specific parts. Instances are
    mutated only under the nemesis lock."""

    def __init__(self):
        self.node_views: dict = {}
        self.view: Any = None
        self.pending: set = set()

    # -- cluster-specific hooks -------------------------------------------

    def setup(self, test) -> None:
        """One-time initialization (open connections etc.)."""

    def node_view(self, test, node):
        """This node's view of the cluster, or None if unknown."""
        raise NotImplementedError

    def merge_views(self, test):
        """Derive the authoritative view from self.node_views."""
        raise NotImplementedError

    def fs(self) -> set:
        """All op :f values this state machine can generate."""
        raise NotImplementedError

    def op(self, test):
        """A legal op to perform now, gen.PENDING when none is."""
        raise NotImplementedError

    def invoke(self, test, op: dict) -> dict:
        """Applies a generated op; returns the completed op."""
        raise NotImplementedError

    def resolve(self, test) -> bool:
        """One evolution step toward a stable state; True if changed."""
        return False

    def resolve_op(self, test, pair) -> bool:
        """True iff the [op, op'] pair is now resolved (it is then
        dropped from pending)."""
        return False

    def teardown(self, test) -> None:
        """Dispose of resources."""


def _resolve(state: MembershipState, test) -> None:
    """Fixed point of resolve + resolve_op (membership.clj:80-106)."""
    for _ in range(100):  # fixed-point iteration guard
        changed = bool(state.resolve(test))
        for pair in list(state.pending):
            if state.resolve_op(test, pair):
                state.pending.discard(pair)
                changed = True
        if not changed:
            return


class MembershipNemesis(n.Nemesis):
    """Runs the state machine: background view updaters + locked
    invoke/resolve (membership.clj Nemesis record, 159-221)."""

    def __init__(self, state: MembershipState,
                 interval: float = NODE_VIEW_INTERVAL):
        self.state = state
        self.interval = interval
        self.lock = threading.RLock()
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []

    def _update_node_view(self, test, node):
        nv = self.state.node_view(test, node)
        if nv is None:
            return
        with self.lock:
            self.state.node_views[node] = nv
            self.state.view = self.state.merge_views(test)
            _resolve(self.state, test)

    def _view_loop(self, test, node):
        while not self._stop.is_set():
            try:
                self._update_node_view(test, node)
            except Exception:  # noqa: BLE001 — keep polling (clj warn+retry)
                pass
            self._stop.wait(self.interval)

    def setup(self, test):
        with self.lock:
            self.state.setup(test)
        for node in test.get("nodes", []):
            t = threading.Thread(target=self._view_loop,
                                 args=(test, node), daemon=True,
                                 name=f"membership-view-{node}")
            t.start()
            self._threads.append(t)
        return self

    def invoke(self, test, op):
        with self.lock:
            done = self.state.invoke(test, op)
            self.state.pending.add(
                (_freeze_op(getattr(op, "to_dict", lambda: op)()),
                 _freeze_op(getattr(done, "to_dict", lambda: done)())))
            _resolve(self.state, test)
            return done

    def teardown(self, test):
        self._stop.set()
        for t in self._threads:
            t.join(timeout=1.0)
        self.state.teardown(test)

    def fs(self):
        return set(self.state.fs())

    def fault_kinds(self):
        # every membership transition is a pulse of the one kind: the
        # coverage cell asks "was membership churned", not which verb
        return {f: ("membership", "pulse") for f in self.state.fs()}


def _freeze_op(op) -> tuple:
    if isinstance(op, dict):
        return tuple(sorted((k, _freeze_op(v)) for k, v in op.items()))
    if isinstance(op, (list, tuple)):
        return tuple(_freeze_op(x) for x in op)
    if isinstance(op, set):
        return frozenset(_freeze_op(x) for x in op)
    return op


class MembershipGenerator(gen.Generator):
    """Asks the state machine for a legal op (membership.clj Generator,
    226-237)."""

    __slots__ = ("nemesis",)

    def __init__(self, nemesis: MembershipNemesis):
        self.nemesis = nemesis

    def op(self, test, ctx):
        with self.nemesis.lock:
            o = self.nemesis.state.op(test)
        if o is None:
            return None
        if o is gen.PENDING or o == "pending":
            return gen.PENDING, self
        o = dict(o)
        o.setdefault("type", "info")
        filled = gen.fill_in_op(o, ctx)
        if filled is gen.PENDING:
            return gen.PENDING, self
        return filled, self

    def update(self, test, ctx, event):
        return self


def package(opts: dict) -> dict:
    """{nemesis, generator, state} package, active when 'membership'
    is in opts['faults'] (membership.clj package, 242-270). Membership
    opts: {'state': a MembershipState, 'interval': view poll seconds}."""
    if "membership" not in set(opts.get("faults", ())):
        return None
    mopts = dict(opts.get("membership") or {})
    state = mopts.get("state")
    if state is None:
        raise ValueError(
            "the 'membership' fault needs a cluster-specific state "
            "machine: pass opts['membership']['state'] (a "
            "MembershipState, e.g. suites.etcd.EtcdMembership)")
    nem = MembershipNemesis(
        state, interval=mopts.get("view-interval", NODE_VIEW_INTERVAL))
    g = gen.stagger(opts.get("interval", 10), MembershipGenerator(nem))
    return {
        "state": state,
        "nemesis": nem,
        "generator": g,
        "final_generator": None,
        "perf": {("membership", frozenset(state.fs()),
                  frozenset(), "#A197F9")},
    }
