"""Nemesis protocol, validation, composition, and partition grudge math.

Capability reference: jepsen/src/jepsen/nemesis.clj (Nemesis protocol
12-22, Validate 50-91, grudges 121-277, compose/f-map 286-430). Network
application of grudges lives in jepsen_tpu.net; this module computes
*which* links to cut, as pure functions over node lists.
"""

from __future__ import annotations

import random
import threading
from typing import Any, Callable, Iterable

from .. import telemetry
from ..history import Op


class Nemesis:
    """Fault injector driven by generator ops on the :nemesis thread."""

    def setup(self, test) -> "Nemesis":
        return self

    def invoke(self, test, op: Op) -> Op:
        raise NotImplementedError

    def teardown(self, test) -> None:
        pass

    def fs(self) -> set:
        """The set of op :f values this nemesis handles (Reflection
        protocol, nemesis.clj:17-22)."""
        return set()

    def fault_kinds(self) -> dict:
        """{f: (fault-kind, phase)} — the structured coverage taxonomy
        tag for each op :f this nemesis speaks. phase is 'begin'/'end'
        (window-bounding ops like start-partition/stop-partition) or
        'pulse' (point faults like bitflip). The default derives from
        fs() via the shared registry (jepsen_tpu.coverage.F_KINDS), so
        nemeses speaking the standard fs are covered automatically;
        override to declare custom kinds."""
        from .. import coverage

        return coverage.default_kinds(self.fs())


class NoopNemesis(Nemesis):
    """Does nothing."""

    def invoke(self, test, op):
        return op


noop = NoopNemesis()


class InvalidNemesisCompletion(Exception):
    pass


class Validate(Nemesis):
    """Asserts nemesis protocol invariants (nemesis.clj:50-91). Every
    nemesis call passes through here (core.run_case wraps the test's
    nemesis in validate()), so this is also where fault activations
    get their telemetry spans."""

    def __init__(self, nemesis: Nemesis):
        self.nemesis = nemesis

    def setup(self, test):
        with telemetry.span("nemesis:setup"):
            res = self.nemesis.setup(test)
        if not isinstance(res, Nemesis):
            raise InvalidNemesisCompletion(
                f"setup should return a Nemesis, got {res!r}")
        return Validate(res)

    def invoke(self, test, op):
        with telemetry.span(f"nemesis:{op.f}") as span_rec:
            op2 = self.nemesis.invoke(test, op)
        if not isinstance(op2, Op):
            raise InvalidNemesisCompletion(
                f"invoke should return an Op, got {op2!r}")
        if op2.process != op.process:
            raise InvalidNemesisCompletion(
                f"process changed: {op!r} -> {op2!r}")
        # coverage taxonomy: every fault activation that completed is
        # recorded with its nemesis-declared kind + the span's window
        # (jepsen_tpu.coverage; fs without a kind — observational ops
        # like check-offsets — are not faults and stay unrecorded)
        try:
            got = self.nemesis.fault_kinds().get(op.f)
            if got is not None and span_rec is not None:
                from .. import coverage

                kind, phase = got
                coverage.record_fault(kind, op.f, phase,
                                      span_rec["t0"], span_rec["t1"])
        except Exception:  # noqa: BLE001 — coverage is best-effort
            import logging

            logging.getLogger(__name__).exception(
                "recording fault coverage failed")
        return op2

    def teardown(self, test):
        with telemetry.span("nemesis:teardown"):
            self.nemesis.teardown(test)

    def fs(self):
        return self.nemesis.fs()

    def fault_kinds(self):
        return self.nemesis.fault_kinds()


def validate(nemesis: Nemesis) -> Validate:
    return Validate(nemesis)


# Functional façade
def setup(nemesis, test):
    return nemesis.setup(test)


def invoke(nemesis, test, op):
    return nemesis.invoke(test, op)


def teardown(nemesis, test):
    return nemesis.teardown(test)


# ---------------------------------------------------------------------------
# Composition
# ---------------------------------------------------------------------------

class Compose(Nemesis):
    """Routes ops to sub-nemeses by :f (nemesis.clj:286-430).

    Holds (fspec, nemesis) pairs where fspec is either a set of fs
    (forwarded unchanged) or a dict {outer-f: inner-f} (the op's :f is
    rewritten to the inner name on the way in and restored on the way
    out)."""

    def __init__(self, pairs: list):
        self.pairs = list(pairs)

    def _route(self, f):
        for fspec, nem in self.pairs:
            if isinstance(fspec, dict):
                if f in fspec:
                    return fspec[f], nem
            elif f in fspec:
                return f, nem
        return None, None

    def setup(self, test):
        return Compose([(spec, nem.setup(test))
                        for spec, nem in self.pairs])

    def invoke(self, test, op):
        inner_f, nem = self._route(op.f)
        if nem is None:
            raise ValueError(f"no nemesis handles f={op.f!r}")
        op2 = nem.invoke(test, op.copy(f=inner_f))
        return op2.copy(f=op.f)

    def teardown(self, test):
        for _spec, nem in self.pairs:
            nem.teardown(test)

    def fs(self):
        out = set()
        for fspec, nem in self.pairs:
            if isinstance(fspec, dict):
                out |= set(fspec.keys())
            else:
                out |= set(fspec)
        return out

    def fault_kinds(self):
        out = {}
        for fspec, nem in self.pairs:
            inner = nem.fault_kinds()
            if isinstance(fspec, dict):
                for outer_f, inner_f in fspec.items():
                    if inner_f in inner:
                        out[outer_f] = inner[inner_f]
            else:
                for f in fspec:
                    if f in inner:
                        out[f] = inner[f]
        return out


def compose(nemeses) -> Nemesis:
    """Takes (fspec, nemesis) pairs — fspec a set of fs or a dict
    {outer-f: inner-f} — or a plain list of nemeses routed by their
    declared fs()."""
    pairs = []
    for item in nemeses:
        if isinstance(item, (tuple, list)) and len(item) == 2 and (
                isinstance(item[0], (set, frozenset, dict))):
            pairs.append((item[0], item[1]))
        else:
            fs = frozenset(item.fs())
            if not fs:
                raise ValueError(
                    f"{item!r} declares no fs; pass (fspec, nemesis) pairs")
            pairs.append((fs, item))
    return Compose(pairs)


class FMap(Nemesis):
    """Renames the fs a nemesis speaks: outer f -> inner f via `fmap`
    (nemesis.clj f-map)."""

    def __init__(self, fmap: dict, nemesis: Nemesis):
        self.fmap = fmap
        self.inv = {v: k for k, v in fmap.items()}
        self.nemesis = nemesis

    def setup(self, test):
        return FMap(self.fmap, self.nemesis.setup(test))

    def invoke(self, test, op):
        op2 = self.nemesis.invoke(test, op.copy(f=self.fmap.get(op.f, op.f)))
        return op2.copy(f=self.inv.get(op2.f, op2.f))

    def teardown(self, test):
        self.nemesis.teardown(test)

    def fs(self):
        inv = self.inv
        return {inv.get(f, f) for f in self.nemesis.fs()}

    def fault_kinds(self):
        inv = self.inv
        return {inv.get(f, f): kind
                for f, kind in self.nemesis.fault_kinds().items()}


def f_map(fmap: dict, nemesis: Nemesis) -> FMap:
    return FMap(fmap, nemesis)


# ---------------------------------------------------------------------------
# Grudges: who can't talk to whom. A grudge maps node -> set of nodes whose
# packets it drops (nemesis.clj:121-277).
# ---------------------------------------------------------------------------

def bisect(nodes: list) -> list:
    """Splits a list in half: [[smaller-half], [larger-half]]."""
    mid = len(nodes) // 2
    return [list(nodes[:mid]), list(nodes[mid:])]


def split_one(node, nodes: list) -> list:
    """[[node], [everyone else]]."""
    return [[node], [n for n in nodes if n != node]]


def complete_grudge(components: list) -> dict:
    """Given components (lists of nodes), each node drops every node
    outside its component (nemesis.clj:121-133)."""
    grudge = {}
    all_nodes = [n for comp in components for n in comp]
    for comp in components:
        outside = set(all_nodes) - set(comp)
        for n in comp:
            grudge[n] = set(outside)
    return grudge


def bridge(nodes: list) -> dict:
    """Bisects the cluster but leaves one 'bridge' node connected to both
    halves (nemesis.clj:145-156)."""
    nodes = list(nodes)
    mid = len(nodes) // 2
    bridge_node = nodes[mid]
    a = nodes[:mid]
    b = nodes[mid + 1:]
    grudge = {}
    for n in a:
        grudge[n] = set(b)
    for n in b:
        grudge[n] = set(a)
    grudge[bridge_node] = set()
    return grudge


def majorities_ring(nodes: list, rng: random.Random | None = None) -> dict:
    """Every node sees a bare majority, but no two nodes see the same
    majority: arranges nodes in a (shuffled) ring, each node talking only
    to the nodes nearest it until a majority is visible
    (nemesis.clj:203-277)."""
    rng = rng or random
    nodes = list(nodes)
    n = len(nodes)
    if n < 3:
        return {node: set() for node in nodes}
    shuffled = list(nodes)
    rng.shuffle(shuffled)
    m = n // 2 + 1            # bare majority, including the node itself
    left = (m - 1) // 2       # neighbors on each side (asymmetric if even)
    right = (m - 1) - left
    grudge = {}
    for i, node in enumerate(shuffled):
        visible = {shuffled[(i + d) % n] for d in range(-left, right + 1)}
        grudge[node] = set(shuffled) - visible
    return grudge


# ---------------------------------------------------------------------------
# Partitioner nemesis
# ---------------------------------------------------------------------------

class Partitioner(Nemesis):
    """start/stop nemesis cutting links per a grudge function
    (nemesis.clj:158-184). grudge_fn: nodes -> grudge dict."""

    def __init__(self, grudge_fn: Callable[[list], dict]):
        self.grudge_fn = grudge_fn

    def setup(self, test):
        net = test.get("net")
        if net is not None:
            net.heal(test)
        return self

    def invoke(self, test, op):
        if op.f == "start":
            nodes = list(test["nodes"])
            grudge = (op.value if isinstance(op.value, dict)
                      else self.grudge_fn(nodes))
            test["net"].drop_all(test, grudge)
            pretty = {k: sorted(v) for k, v in grudge.items()}
            return op.copy(value=["isolated", pretty])
        if op.f == "stop":
            test["net"].heal(test)
            return op.copy(value="network healed")
        raise ValueError(f"partitioner doesn't understand f={op.f!r}")

    def teardown(self, test):
        net = test.get("net")
        if net is not None:
            net.heal(test)

    def fs(self):
        return {"start", "stop"}

    def fault_kinds(self):
        return {"start": ("partition", "begin"),
                "stop": ("partition", "end")}


def partitioner(grudge_fn) -> Partitioner:
    return Partitioner(grudge_fn)


# ---------------------------------------------------------------------------
# Process and file nemeses (nemesis.clj:430-599)
# ---------------------------------------------------------------------------

class NodeStartStopper(Nemesis):
    """Responds to start/stop by running start_fn/stop_fn on targeted
    nodes with an ambient control session (nemesis.clj:453-496).
    targeter: (test, nodes) -> node(s) or None to skip. `kind` names
    the coverage fault kind the start/stop window injects (default
    'process-pause', the hammer_time use)."""

    def __init__(self, targeter, start_fn, stop_fn,
                 kind: str = "process-pause"):
        self.targeter = targeter
        self.start_fn = start_fn
        self.stop_fn = stop_fn
        self.kind = kind
        self._nodes = None
        self._lock = threading.Lock()

    def invoke(self, test, op):
        from .. import control

        with self._lock:
            if op.f == "start":
                ns = self.targeter(test, list(test["nodes"]))
                if ns is None:
                    return op.copy(value="no-target")
                if not isinstance(ns, (list, tuple, set)):
                    ns = [ns]
                ns = list(ns)
                if self._nodes is not None:
                    return op.copy(
                        value=f"nemesis already disrupting {self._nodes}")
                self._nodes = ns
                res = control.on_nodes(
                    test, lambda t, n: self.start_fn(t, n), ns)
                return op.copy(value=res)
            if op.f == "stop":
                if self._nodes is None:
                    return op.copy(value="not-started")
                res = control.on_nodes(
                    test, lambda t, n: self.stop_fn(t, n), self._nodes)
                self._nodes = None
                return op.copy(value=res)
            raise ValueError(f"unknown f {op.f!r}")

    def fs(self):
        return {"start", "stop"}

    def fault_kinds(self):
        return {"start": (self.kind, "begin"),
                "stop": (self.kind, "end")}


def node_start_stopper(targeter, start_fn, stop_fn,
                       kind: str = "process-pause") -> NodeStartStopper:
    return NodeStartStopper(targeter, start_fn, stop_fn, kind=kind)


def _rand_node_targeter(test, nodes):
    return random.choice(nodes)


def hammer_time(process: str, targeter=None) -> NodeStartStopper:
    """Pauses a named process with SIGSTOP on start, resumes with
    SIGCONT on stop (nemesis.clj:498-513)."""
    from .. import control

    def start(test, node):
        with control.su():
            control.exec_("killall", "-s", "STOP", process)
        return ["paused", process]

    def stop(test, node):
        with control.su():
            control.exec_("killall", "-s", "CONT", process)
        return ["resumed", process]

    return NodeStartStopper(targeter or _rand_node_targeter, start, stop)


def _resolve_target_file(file: str) -> str:
    """file itself if it's a regular file, else a random entry of the
    directory (nemesis.clj truncate/bitflip target selection). Probes as
    root — DB data dirs are typically unreadable to the login user."""
    from .. import control
    from ..control import util as cu

    with control.su():
        if cu.file_p(file):
            return file
        return random.choice(cu.ls_full(file))


class TruncateFile(Nemesis):
    """Drops trailing bytes from files: op value is
    {node: {'file': path-or-dir, 'drop': n-bytes}}
    (nemesis.clj:514-548)."""

    def invoke(self, test, op):
        from .. import control

        assert op.f == "truncate"
        plan = op.value

        def body(t, node):
            spec = plan[node]
            file, drop = spec["file"], spec["drop"]
            assert isinstance(file, str) and isinstance(drop, int)
            file = _resolve_target_file(file)
            with control.su():
                control.exec_("truncate", "-c", "-s", f"-{drop}", file)
            return {"file": file, "drop": drop}

        res = control.on_nodes(test, body, list(plan.keys()))
        return op.copy(value=res)

    def fs(self):
        return {"truncate"}

    def fault_kinds(self):
        return {"truncate": ("file-truncate", "pulse")}


def truncate_file() -> TruncateFile:
    return TruncateFile()


class Bitflip(Nemesis):
    """Flips random bits in files: op value is
    {node: {'file': path-or-dir, 'probability': p}}. The reference
    downloads a Go release binary (nemesis.clj:550-599); we compile our
    own C tool (resources/bitflip.c) on each node instead."""

    def setup(self, test):
        import os as _os

        from .. import control
        from .time import compile_c

        src = _os.path.join(_os.path.dirname(_os.path.dirname(
            _os.path.abspath(__file__))), "resources", "bitflip.c")
        control.on_nodes(test, lambda t, n: compile_c(src, "bitflip"))
        return self

    def invoke(self, test, op):
        from .. import control

        plan = op.value

        def flip(t, node):
            spec = plan[node]
            file = spec.get("file")
            if not file:
                raise ValueError("bitflip op needs a :file")
            file = _resolve_target_file(file)
            probability = spec.get("probability", 0.01)
            percent = 100 * probability
            from .time import DIR
            with control.su():
                control.exec_(f"{DIR}/bitflip", "spray",
                              f"{percent:.32f}", file)
            return {"file": file, "probability": probability}

        res = control.on_nodes(test, flip, list(plan.keys()))
        return op.copy(value=res)

    def fs(self):
        return {"bitflip"}

    def fault_kinds(self):
        return {"bitflip": ("file-bitflip", "pulse")}


def bitflip() -> Bitflip:
    return Bitflip()


def partition_halves() -> Partitioner:
    """Cuts the network into two halves (first half vs rest)."""
    return Partitioner(lambda nodes: complete_grudge(bisect(nodes)))


def partition_random_halves() -> Partitioner:
    """Cuts into two randomly chosen halves."""

    def grudge(nodes):
        nodes = list(nodes)
        random.shuffle(nodes)
        return complete_grudge(bisect(nodes))

    return Partitioner(grudge)


def partition_random_node() -> Partitioner:
    """Isolates a single random node."""

    def grudge(nodes):
        return complete_grudge(split_one(random.choice(list(nodes)), nodes))

    return Partitioner(grudge)


def partition_majorities_ring() -> Partitioner:
    """Overlapping-majorities ring partition."""
    return Partitioner(majorities_ring)


def start_stop_cycle(period: float = 5.0):
    """The canonical nemesis schedule: sleep, start fault, sleep, stop,
    repeat (the gen/cycle in every tutorial-grade suite,
    zookeeper.clj:129-133)."""
    from .. import generator as gen

    return gen.cycle(gen.phases(gen.sleep(period),
                                {"type": "info", "f": "start"},
                                gen.sleep(period),
                                {"type": "info", "f": "stop"}))
