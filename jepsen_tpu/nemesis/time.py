"""Clock manipulation: stepping, strobing, and resetting node clocks.

Capability reference: jepsen/src/jepsen/nemesis/time.clj — on-node C
helper compilation (21-67), reset/bump/strobe (86-102), clock-nemesis
ops :reset/:strobe/:bump/:check-offsets recording :clock-offsets
(104-167), randomized generators bumping +-2^2..2^18 ms (182-217).
The C sources live in jepsen_tpu/resources/ (our own implementations
of resources/bump-time.c and strobe-time.c).
"""

from __future__ import annotations

import logging
import os
import time as _time
from decimal import Decimal

from .. import control
from .. import generator as gen
from .. import util
from ..control import util as cu
from ..control.core import RemoteError
from .core import Nemesis

logger = logging.getLogger(__name__)

DIR = "/opt/jepsen"

_RESOURCES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "resources")


def compile_c(source_path: str, bin_name: str) -> str:
    """Uploads a local C source to /opt/jepsen/<bin>.c and gcc-compiles
    it, unless the binary already exists (time.clj:21-48)."""
    with control.su():
        if not cu.exists_p(f"{DIR}/{bin_name}"):
            logger.info("Compiling %s", bin_name)
            control.exec_("mkdir", "-p", DIR)
            control.exec_("chmod", "a+rwx", DIR)
            with open(source_path) as f:
                cu.write_file(f.read(), f"{DIR}/{bin_name}.c")
            with control.cd(DIR):
                control.exec_("gcc", "-O2", "-o", bin_name,
                              f"{bin_name}.c")
    return bin_name


def compile_tools() -> None:
    compile_c(os.path.join(_RESOURCES, "bump_time.c"), "bump-time")
    compile_c(os.path.join(_RESOURCES, "strobe_time.c"), "strobe-time")


def install() -> None:
    """Compiles the clock tools on the current node, installing gcc
    first if missing (time.clj:50-67)."""
    try:
        compile_tools()
    except RemoteError as e:
        if e.exit == 127 and "command not found" in (e.err or ""):
            from ..os_setup import debian
            debian.install(["build-essential"])
            compile_tools()
        else:
            raise


def parse_time(s: str) -> Decimal:
    return Decimal(s.strip())


def clock_offset(remote_time: Decimal) -> float:
    """Offset of a node clock reading against control wall time, in
    seconds (time.clj:73-84)."""
    return float(remote_time - Decimal(_time.time()))


def current_offset() -> float:
    return clock_offset(parse_time(control.exec_("date", "+%s.%N")))


def reset_time() -> None:
    """Resets the current node's clock via NTP (time.clj:86-90)."""
    with control.su():
        control.exec_("ntpdate", "-b", "time.google.com")


def bump_time(delta_ms) -> float:
    """Steps the clock by delta ms; returns the resulting offset in
    seconds (time.clj:92-96)."""
    with control.su():
        return clock_offset(parse_time(
            control.exec_(f"{DIR}/bump-time", delta_ms)))


def strobe_time(delta_ms, period_ms, duration_s) -> None:
    """Oscillates the clock by delta ms every period ms for duration s
    (time.clj:98-102)."""
    with control.su():
        control.exec_(f"{DIR}/strobe-time", delta_ms, period_ms,
                      duration_s)


def _meh_reset() -> None:
    """reset-time! tolerant of containers where stepping time is
    impossible (time.clj:118-131 commentary)."""
    try:
        reset_time()
    except RemoteError as e:
        if e.exit == 1:
            return
        raise


class ClockNemesis(Nemesis):
    """Manipulates node clocks (time.clj:104-167). Ops:

        {'f': 'reset',  'value': [node, ...]}
        {'f': 'strobe', 'value': {node: {'delta': ms, 'period': ms,
                                         'duration': s}, ...}}
        {'f': 'bump',   'value': {node: delta_ms, ...}}
        {'f': 'check-offsets'}

    Completions carry 'clock-offsets' {node: seconds}. The node
    observability plane merges these observations with its own
    per-tick offset readings (same clock_offset math) into the skew
    series that clock plots, Perfetto node tracks, and the
    `clock-skew-bound` on realtime verdicts are built from
    (jepsen_tpu.nodeprobe.clock_series)."""

    def setup(self, test):
        def body(t, n):
            install()
            # the daemon is 'ntpd' on RHEL-likes, 'ntp' on Debian
            for svc in ("ntpd", "ntp"):
                try:
                    with control.su():
                        control.exec_("service", svc, "stop")
                except RemoteError:
                    pass
            _meh_reset()
        control.on_nodes(test, body)
        return self

    def invoke(self, test, op):
        if op.f == "reset":
            res = control.on_nodes(
                test, lambda t, n: (_meh_reset(), current_offset())[1],
                op.value)
        elif op.f == "check-offsets":
            res = control.on_nodes(test,
                                   lambda t, n: current_offset())
        elif op.f == "strobe":
            m = op.value

            def strobe(t, n):
                s = m[n]
                strobe_time(s["delta"], s["period"], s["duration"])
                return current_offset()

            res = control.on_nodes(test, strobe, list(m.keys()))
        elif op.f == "bump":
            m = op.value
            res = control.on_nodes(test, lambda t, n: bump_time(m[n]),
                                   list(m.keys()))
        else:
            raise ValueError(f"clock nemesis: unknown f {op.f!r}")
        return op.copy(**{"clock-offsets": res})

    def teardown(self, test):
        control.on_nodes(test, lambda t, n: _meh_reset())

    def fs(self):
        return {"reset", "strobe", "bump", "check-offsets"}

    def fault_kinds(self):
        # check-offsets is observational, not a fault: no kind, so the
        # coverage layer never records it as an injected disruption
        return {"bump": ("clock-bump", "pulse"),
                "strobe": ("clock-strobe", "pulse"),
                "reset": ("clock-reset", "pulse")}


def clock_nemesis() -> ClockNemesis:
    return ClockNemesis()


def _default_select(test):
    return util.random_nonempty_subset(test["nodes"])


def reset_gen_select(select):
    """Generator of reset ops over (select test) nodes
    (time.clj:169-180)."""
    def g(test, ctx):
        return {"type": "info", "f": "reset", "value": select(test)}
    return g


def bump_gen_select(select):
    """Clock bumps from -262s to +262s, exponentially distributed
    (time.clj:182-195)."""
    import random

    def g(test, ctx):
        return {"type": "info", "f": "bump",
                "value": {n: int(random.choice([-1, 1])
                                 * 2 ** (2 + random.random() * 16))
                          for n in (select(test) or [])}}
    return g


def strobe_gen_select(select):
    """Clock strobes: delta 4ms..262s, period 1ms..1s, duration 0-32s
    (time.clj:197-211)."""
    import random

    def g(test, ctx):
        return {"type": "info", "f": "strobe",
                "value": {n: {"delta": int(2 ** (2 + random.random()
                                                 * 16)),
                              "period": int(2 ** (random.random() * 10)),
                              "duration": random.random() * 32}
                          for n in (select(test) or [])}}
    return g


reset_gen = reset_gen_select(_default_select)
bump_gen = bump_gen_select(_default_select)
strobe_gen = strobe_gen_select(_default_select)


def clock_gen():
    """Random schedule of clock skew ops, starting with a
    check-offsets to establish a baseline (time.clj:213-220)."""
    return gen.phases({"type": "info", "f": "check-offsets"},
                      gen.mix([reset_gen, bump_gen, strobe_gen]))
