"""Fault-injection nemeses.

Capability reference: jepsen/src/jepsen/nemesis.clj. The core protocol,
pure grudge/partition math, and process/file nemeses live in `core`;
composed packages in `combined`; clock manipulation in `time`.
"""

from .core import (Nemesis, NoopNemesis, Validate, noop, validate, invoke,
                   setup, teardown, compose, f_map,
                   bisect, split_one, complete_grudge, bridge,
                   majorities_ring, partitioner, partition_halves,
                   partition_random_halves, partition_random_node,
                   partition_majorities_ring,
                   node_start_stopper, hammer_time, truncate_file,
                   bitflip, start_stop_cycle)

__all__ = [
    "Nemesis", "NoopNemesis", "Validate", "noop", "validate", "invoke",
    "setup", "teardown", "compose", "f_map",
    "bisect", "split_one", "complete_grudge", "bridge", "majorities_ring",
    "partitioner", "partition_halves", "partition_random_halves",
    "partition_random_node", "partition_majorities_ring",
    "node_start_stopper", "hammer_time", "truncate_file", "bitflip",
    "start_stop_cycle",
]
