"""Composable nemesis packages: clock skew, crashes, pauses,
partitions, packet mangling, file corruption — each as a
{nemesis, generator, final_generator, perf} bundle that composes.

Capability reference: jepsen/src/jepsen/nemesis/combined.clj —
node-spec language db-nodes (40-71), db-package kill/pause flip-flops
(72-163), partition-package (164-249), packet-package (250-328),
clock-package (329-362), file-corruption-package (363-460), f-map +
compose-packages + nemesis-package (461-568).
"""

from __future__ import annotations

import random
from typing import Iterable

from .. import control
from .. import generator as gen
from .. import util
from . import core as n
from . import time as nt

DEFAULT_INTERVAL = 10
"""Default seconds between nemesis operations (combined.clj:29-31)."""

NOOP_PACKAGE = {
    "generator": None,
    "final_generator": None,
    "nemesis": n.noop,
    "perf": set(),
}


def db_nodes(test, db, node_spec):
    """Nodes selected by a node spec (combined.clj:40-63):
    None | 'one' | 'minority' | 'majority' | 'minority-third' |
    'primaries' | 'all' | explicit list."""
    nodes = list(test["nodes"])
    if node_spec is None:
        return util.random_nonempty_subset(nodes)
    if node_spec == "one":
        return [random.choice(nodes)]
    if node_spec == "minority":
        random.shuffle(nodes)
        return nodes[:util.majority(len(nodes)) - 1]
    if node_spec == "majority":
        random.shuffle(nodes)
        return nodes[:util.majority(len(nodes))]
    if node_spec == "minority-third":
        random.shuffle(nodes)
        return nodes[:util.minority_third(len(nodes))]
    if node_spec == "primaries":
        return util.random_nonempty_subset(db.primaries(test))
    if node_spec == "all":
        return nodes
    return node_spec


def node_specs(db) -> list:
    """All node specs valid for a DB (combined.clj:65-71)."""
    specs = [None, "one", "minority-third", "minority", "majority",
             "all"]
    if db is not None and db.supports_primaries:
        specs.append("primaries")
    return specs


class DbNemesis(n.Nemesis):
    """start/kill/pause/resume on nodes picked by a node spec
    (combined.clj:73-103)."""

    def __init__(self, db):
        self.db = db

    def invoke(self, test, op):
        f = {"start": self.db.start, "kill": self.db.kill,
             "pause": self.db.pause, "resume": self.db.resume}[op.f]
        # None (e.g. 'primaries' with no known primaries) must no-op,
        # not fall through to on_nodes' all-nodes default
        nodes = db_nodes(test, self.db, op.value) or []
        res = control.on_nodes(test, lambda t, node: f(t, node), nodes)
        return op.copy(value=res)

    def fs(self):
        return {"start", "kill", "pause", "resume"}

    def fault_kinds(self):
        # here 'start' HEALS a kill window (the db restarts), unlike
        # the partitioner's 'start' — exactly why kinds are declared
        # per nemesis rather than guessed from f names
        return {"kill": ("db-kill", "begin"),
                "start": ("db-kill", "end"),
                "pause": ("db-pause", "begin"),
                "resume": ("db-pause", "end")}


def db_generators(opts: dict) -> dict:
    """kill/pause flip-flop generators for a DB (combined.clj:105-146)."""
    db = opts["db"]
    faults = opts["faults"]
    kill_p = db.supports_kill and "kill" in faults
    pause_p = db.supports_pause and "pause" in faults
    kill_targets = (opts.get("kill") or {}).get("targets",
                                                node_specs(db))
    pause_targets = (opts.get("pause") or {}).get("targets",
                                                  node_specs(db))

    start = {"type": "info", "f": "start", "value": "all"}
    resume = {"type": "info", "f": "resume", "value": "all"}

    def kill(test, ctx):
        return {"type": "info", "f": "kill",
                "value": random.choice(kill_targets)}

    def pause(test, ctx):
        return {"type": "info", "f": "pause",
                "value": random.choice(pause_targets)}

    modes, final = [], []
    if pause_p:
        modes.append(gen.flip_flop(pause, gen.repeat(resume)))
        final.append(resume)
    if kill_p:
        modes.append(gen.flip_flop(kill, gen.repeat(start)))
        final.append(start)
    return {"generator": gen.mix(modes) if modes else None,
            "final_generator": final or None}


def db_package(opts: dict) -> dict:
    """Kill/pause package (combined.clj:148-163). With no db (e.g. a
    membership-only nemesis) there is nothing to kill: noop."""
    if opts.get("db") is None:
        return dict(NOOP_PACKAGE)
    needed = bool({"kill", "pause"} & set(opts["faults"]))
    gens = db_generators(opts)
    generator = gens["generator"]
    if generator is not None:
        generator = gen.stagger(opts.get("interval", DEFAULT_INTERVAL),
                                generator)
    return {
        "generator": generator if needed else None,
        "final_generator": gens["final_generator"] if needed else None,
        "nemesis": DbNemesis(opts["db"]),
        "perf": {("kill", frozenset({"kill"}), frozenset({"start"}),
                  "#E9A4A0"),
                 ("pause", frozenset({"pause"}), frozenset({"resume"}),
                  "#A0B1E9")},
    }


def grudge(test, db, part_spec) -> dict:
    """Grudge for a partition spec (combined.clj:166-190): 'one' |
    'majority' | 'majorities-ring' | 'minority-third' | 'primaries' |
    explicit grudge dict."""
    nodes = list(test["nodes"])
    if part_spec == "one":
        return n.complete_grudge(n.split_one(random.choice(nodes),
                                             nodes))
    if part_spec == "majority":
        random.shuffle(nodes)
        return n.complete_grudge(n.bisect(nodes))
    if part_spec == "majorities-ring":
        return n.majorities_ring(nodes)
    if part_spec == "minority-third":
        random.shuffle(nodes)
        k = util.minority_third(len(nodes))
        return n.complete_grudge([nodes[:k], nodes[k:]])
    if part_spec == "primaries":
        primaries = util.random_nonempty_subset(db.primaries(test)) or []
        others = [x for x in nodes if x not in set(primaries)]
        return n.complete_grudge([others] + [[p] for p in primaries])
    return part_spec


def partition_specs(db) -> list:
    """All partition specs for a DB (combined.clj:192-196)."""
    specs = ["one", "minority-third", "majority", "majorities-ring"]
    if db is not None and db.supports_primaries:
        specs.append("primaries")
    return specs


class PartitionNemesis(n.Nemesis):
    """Wraps a Partitioner with partition-spec support
    (combined.clj:198-227)."""

    def __init__(self, db, p=None):
        self.db = db
        self.p = p or n.partitioner(lambda nodes: None)

    def setup(self, test):
        return PartitionNemesis(self.db, self.p.setup(test))

    def invoke(self, test, op):
        if op.f == "start-partition":
            g = grudge(test, self.db, op.value)
            out = self.p.invoke(test, op.copy(f="start", value=g))
        elif op.f == "stop-partition":
            out = self.p.invoke(test, op.copy(f="stop", value=None))
        else:
            raise ValueError(f"unknown f {op.f!r}")
        return out.copy(f=op.f)

    def teardown(self, test):
        self.p.teardown(test)

    def fs(self):
        return {"start-partition", "stop-partition"}

    def fault_kinds(self):
        return {"start-partition": ("partition", "begin"),
                "stop-partition": ("partition", "end")}


def partition_package(opts: dict) -> dict:
    """Network partition package (combined.clj:229-249)."""
    needed = "partition" in opts["faults"]
    db = opts["db"]
    targets = (opts.get("partition") or {}).get(
        "targets", partition_specs(db))

    def start(test, ctx):
        return {"type": "info", "f": "start-partition",
                "value": random.choice(targets)}

    stop = {"type": "info", "f": "stop-partition", "value": None}
    g = gen.stagger(opts.get("interval", DEFAULT_INTERVAL),
                    gen.flip_flop(start, gen.repeat(stop)))
    return {
        "generator": g if needed else None,
        "final_generator": stop if needed else None,
        "nemesis": PartitionNemesis(db),
        "perf": {("partition", frozenset({"start-partition"}),
                  frozenset({"stop-partition"}), "#E9DCA0")},
    }


class PacketNemesis(n.Nemesis):
    """tc-netem packet disruption on spec-selected nodes
    (combined.clj:251-287). Ops:
    {'f': 'start-packet', 'value': [node-spec, behaviors]} /
    {'f': 'stop-packet'}."""

    def __init__(self, db):
        self.db = db

    def setup(self, test):
        test["net"].shape(test, None, None)
        return self

    def invoke(self, test, op):
        net = test["net"]
        if op.f == "start-packet":
            spec, behaviors = op.value
            targets = db_nodes(test, self.db, spec)
            res = net.shape(test, targets, behaviors)
        elif op.f == "stop-packet":
            res = net.shape(test, None, None)
        else:
            raise ValueError(f"unknown f {op.f!r}")
        return op.copy(value=res)

    def teardown(self, test):
        test["net"].shape(test, None, None)

    def fs(self):
        return {"start-packet", "stop-packet"}

    def fault_kinds(self):
        return {"start-packet": ("packet", "begin"),
                "stop-packet": ("packet", "end")}


def packet_package(opts: dict) -> dict:
    """Packet-behavior package (combined.clj:289-328). opts['packet']:
    {'targets': [spec...], 'behaviors': [{'delay': {}}, ...]}.
    The default behaviors list is [{}] — a no-disruption behavior —
    matching the reference; configure 'behaviors' to actually disturb
    packets."""
    needed = "packet" in opts["faults"]
    db = opts["db"]
    popts = opts.get("packet") or {}
    targets = popts.get("targets", node_specs(db))
    behaviors = popts.get("behaviors", [{}])

    def start(test, ctx):
        return {"type": "info", "f": "start-packet",
                "value": [random.choice(targets),
                          random.choice(behaviors)]}

    stop = {"type": "info", "f": "stop-packet", "value": None}
    g = gen.stagger(opts.get("interval", DEFAULT_INTERVAL),
                    gen.flip_flop(start, gen.repeat(stop)))
    return {
        "generator": g if needed else None,
        "final_generator": stop if needed else None,
        "nemesis": PacketNemesis(db),
        "perf": {("packet", frozenset({"start-packet"}),
                  frozenset({"stop-packet"}), "#D1E8A0")},
    }


def clock_package(opts: dict) -> dict:
    """Clock-skew package (combined.clj:330-362)."""
    needed = "clock" in opts["faults"]
    db = opts["db"]
    nemesis = n.compose([({"reset-clock": "reset",
                           "check-clock-offsets": "check-offsets",
                           "strobe-clock": "strobe",
                           "bump-clock": "bump"},
                          nt.clock_nemesis())])
    target_specs = (opts.get("clock") or {}).get("targets",
                                                 node_specs(db))

    def targets(test):
        spec = random.choice(target_specs) if target_specs else None
        return db_nodes(test, db, spec)

    clock_gen = gen.phases(
        {"type": "info", "f": "check-offsets"},
        gen.mix([nt.reset_gen_select(targets),
                 nt.bump_gen_select(targets),
                 nt.strobe_gen_select(targets)]))
    g = gen.stagger(
        opts.get("interval", DEFAULT_INTERVAL),
        gen.f_map({"reset": "reset-clock",
                   "check-offsets": "check-clock-offsets",
                   "strobe": "strobe-clock",
                   "bump": "bump-clock"}, clock_gen))
    return {
        "generator": g if needed else None,
        "final_generator": ({"type": "info", "f": "reset-clock"}
                            if needed else None),
        "nemesis": nemesis,
        "perf": {("clock", frozenset({"bump-clock"}),
                  frozenset({"reset-clock"}), "#A0E9E3")},
    }


class FileCorruptionNemesis(n.Nemesis):
    """bitflip/truncate on spec-selected nodes (combined.clj:364-399),
    plus lazyfs lose-unfsynced-writes when a lazyfs map is supplied
    (jepsen/src/jepsen/lazyfs.clj:246-295).
    Ops: {'f': 'bitflip'|'truncate'|'lose-unfsynced-writes',
          'value': [node-spec, corruption-map]}."""

    def __init__(self, db, bf=None, trunc=None, lazyfs_map=None):
        self.db = db
        self.bf = bf or n.bitflip()
        self.trunc = trunc or n.truncate_file()
        if lazyfs_map is not None:
            from .. import lazyfs as lazyfs_mod

            # accept a bare dir or partial map like every other
            # lazyfs entry point
            lazyfs_map = lazyfs_mod.lazyfs(lazyfs_map)
        self.lazyfs_map = lazyfs_map

    def setup(self, test):
        return FileCorruptionNemesis(self.db, self.bf.setup(test),
                                     self.trunc.setup(test),
                                     self.lazyfs_map)

    def invoke(self, test, op):
        spec, corruption = op.value
        targets = db_nodes(test, self.db, spec) or []
        if op.f == "lose-unfsynced-writes":
            from .. import control, lazyfs

            got = control.on_nodes(
                test,
                lambda t, node: lazyfs.lose_unfsynced_writes(
                    self.lazyfs_map),
                targets)
            return op.copy(value=got)
        plan = {node: corruption for node in targets}
        op2 = op.copy(value=plan)
        if op.f == "bitflip":
            return self.bf.invoke(test, op2)
        if op.f == "truncate":
            return self.trunc.invoke(test, op2)
        raise ValueError(f"unknown f {op.f!r}")

    def teardown(self, test):
        self.bf.teardown(test)
        self.trunc.teardown(test)

    def fs(self):
        fs = {"bitflip", "truncate"}
        if self.lazyfs_map is not None:
            fs.add("lose-unfsynced-writes")
        return fs

    def fault_kinds(self):
        kinds = {"bitflip": ("file-bitflip", "pulse"),
                 "truncate": ("file-truncate", "pulse")}
        if self.lazyfs_map is not None:
            kinds["lose-unfsynced-writes"] = ("file-lost-writes",
                                              "pulse")
        return kinds


def file_corruption_package(opts: dict) -> dict:
    """File corruption package (combined.clj:401-460).
    opts['file_corruption']: {'targets': [spec...], 'lazyfs': map?,
    'corruptions':
    [{'type': 'bitflip', 'file': ..., 'probability': p-or-dist},
     {'type': 'truncate', 'file': ..., 'drop': n-or-dist},
     {'type': 'lose-unfsynced-writes'}  # needs 'lazyfs'
    ]}."""
    faults = opts["faults"]
    needed = "file-corruption" in faults
    fc = opts.get("file_corruption") or {}
    db = opts["db"]
    targets = fc.get("targets", node_specs(db))
    corruptions = fc.get("corruptions") or []
    lazyfs_map = fc.get("lazyfs")
    if lazyfs_map is None and any(
            c["type"] == "lose-unfsynced-writes" for c in corruptions):
        raise ValueError("lose-unfsynced-writes corruption needs "
                         "file_corruption['lazyfs'] (a lazyfs map)")

    def g_fn(test, ctx):
        target = random.choice(targets)
        c = random.choice(corruptions)
        if c["type"] == "lose-unfsynced-writes":
            return {"type": "info", "f": "lose-unfsynced-writes",
                    "value": [target, None]}
        corruption = {"file": c["file"]}
        if c["type"] == "bitflip":
            p = c.get("probability")
            p = util.rand_distribution(p) if isinstance(p, dict) else p
            if p is not None:
                corruption["probability"] = p
        else:
            d = c.get("drop")
            d = util.rand_distribution(d) if isinstance(d, dict) else d
            if d is not None:
                corruption["drop"] = d
        return {"type": "info", "f": c["type"],
                "value": [target, corruption]}

    g = (gen.stagger(opts.get("interval", DEFAULT_INTERVAL), g_fn)
         if corruptions else None)
    nem = FileCorruptionNemesis(db, lazyfs_map=lazyfs_map)
    return {
        "generator": g if needed else None,
        "final_generator": None,
        "nemesis": nem,
        "perf": {("file-corruption", frozenset(nem.fs()),
                  frozenset(), "#99F2E2")},
    }


def compose_packages(packages: Iterable[dict]) -> dict:
    """Combines packages: generators via any (soonest wins), final
    generators sequentially, nemeses by f routing
    (combined.clj:496-510)."""
    packages = list(packages)
    if not packages:
        return dict(NOOP_PACKAGE)
    if len(packages) == 1:
        return packages[0]
    gens = [p["generator"] for p in packages if p.get("generator")]
    finals = [p["final_generator"] for p in packages
              if p.get("final_generator")]
    perf = set()
    for p in packages:
        perf |= set(p.get("perf") or ())
    return {
        "generator": gen.any_gen(*gens) if gens else None,
        "final_generator": finals or None,
        "nemesis": n.compose([p["nemesis"] for p in packages
                              if p.get("nemesis")]),
        "perf": perf,
    }


DEFAULT_FAULTS = ["partition", "packet", "kill", "pause", "clock",
                  "file-corruption"]


def nemesis_packages(opts: dict) -> list:
    """The standard package list for an option map
    (combined.clj:512-522); membership joins when its fault is
    requested (nemesis/membership.clj package)."""
    from . import membership

    opts = dict(opts)
    opts["faults"] = set(opts.get("faults", DEFAULT_FAULTS))
    pkgs = [partition_package(opts), packet_package(opts),
            file_corruption_package(opts), clock_package(opts),
            db_package(opts)]
    mp = membership.package(opts)
    if mp is not None:
        pkgs.append(mp)
    return pkgs


def nemesis_package(opts: dict) -> dict:
    """One combined package: {nemesis, generator, final_generator,
    perf} (combined.clj:524-568). Mandatory opts: db. Optional:
    interval, faults, partition/packet/kill/pause/clock/
    file_corruption sub-options."""
    return compose_packages(nemesis_packages(opts))
