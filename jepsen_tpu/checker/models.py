"""Sequential data-type models for linearizability checking.

Capability reference: the knossos.model namespace as consumed by the
reference's checkers and suites (checker.clj:202-233 passes models into
knossos; suite usage e.g. cockroachdb/src/jepsen/cockroach/register.clj;
an in-repo mirror of the protocol shape is tests/causal.clj:10-29).

A model is an immutable value with step(op) -> next model, or an
Inconsistent value if the op can't be applied. Models also compile to
dense transition tables for the TPU checker (jepsen_tpu.tpu.encode).
"""

from __future__ import annotations

from typing import Any, Iterable

from ..history import Op


class Inconsistent:
    __slots__ = ("msg",)

    def __init__(self, msg):
        self.msg = msg

    def __repr__(self):
        return f"Inconsistent<{self.msg}>"


def inconsistent(msg) -> Inconsistent:
    return Inconsistent(msg)


def is_inconsistent(m) -> bool:
    return isinstance(m, Inconsistent)


def _hfreeze(v):
    """Hashable view of a model state value: ops may carry lists/dicts
    (e.g. a list written into a Register), and model states built from
    them must still hash for search-state dedup."""
    if isinstance(v, (list, tuple)):
        return tuple(_hfreeze(x) for x in v)
    if isinstance(v, (set, frozenset)):
        return frozenset(_hfreeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted(((k, _hfreeze(x)) for k, x in v.items()),
                            key=repr))
    return v


class Model:
    """A sequential datatype: step(op) -> next model | Inconsistent.

    Contract for the TPU checker: step() must depend ONLY on op.f and
    op.value — the transition tables (jepsen_tpu.tpu.encode) key distinct
    ops by (f, value). A model that consults op.process/op.ext must set
    `tabulable = False`, which routes checking to the object-model host
    search instead of the device kernels."""

    tabulable = True

    def step(self, op: Op):
        raise NotImplementedError

    def __eq__(self, other):
        return type(self) is type(other) and self.__dict__ == other.__dict__

    def __hash__(self):
        return hash((type(self).__name__, tuple(
            (k, _hfreeze(v))
            for k, v in sorted(self.__dict__.items()))))


class NoOp(Model):
    """Every op succeeds and does nothing."""

    def step(self, op):
        return self


class Register(Model):
    """A read/write register."""

    def __init__(self, value=None):
        self.value = value

    def step(self, op):
        if op.f == "write":
            return Register(op.value)
        if op.f == "read":
            if op.value is None or op.value == self.value:
                return self
            return inconsistent(
                f"read {op.value!r} but expected {self.value!r}")
        return inconsistent(f"unknown f {op.f!r}")

    def __repr__(self):
        return f"Register<{self.value!r}>"


class CASRegister(Model):
    """A register supporting read/write/cas, the canonical Jepsen
    linearizable-register model."""

    def __init__(self, value=None):
        self.value = value

    def step(self, op):
        f = op.f
        if f == "write":
            return CASRegister(op.value)
        if f == "cas":
            if op.value is None:
                return inconsistent("nil cas value")
            cur, new = op.value
            if cur == self.value:
                return CASRegister(new)
            return inconsistent(f"can't CAS {self.value!r} from {cur!r}")
        if f == "read":
            if op.value is None or op.value == self.value:
                return self
            return inconsistent(
                f"can't read {op.value!r} from register {self.value!r}")
        return inconsistent(f"unknown f {f!r}")

    def __repr__(self):
        return f"CASRegister<{self.value!r}>"


class Mutex(Model):
    """A lock: acquire/release."""

    def __init__(self, locked=False):
        self.locked = locked

    def step(self, op):
        if op.f == "acquire":
            if self.locked:
                return inconsistent("already held")
            return Mutex(True)
        if op.f == "release":
            if not self.locked:
                return inconsistent("not held")
            return Mutex(False)
        return inconsistent(f"unknown f {op.f!r}")


class UnorderedQueue(Model):
    """A queue where dequeue may return any enqueued element."""

    def __init__(self, pending: frozenset = frozenset()):
        self.pending = pending

    def step(self, op):
        if op.f == "enqueue":
            return UnorderedQueue(self.pending | {op.value})
        if op.f == "dequeue":
            if op.value in self.pending:
                return UnorderedQueue(self.pending - {op.value})
            return inconsistent(
                f"can't dequeue {op.value!r}: not in queue")
        return inconsistent(f"unknown f {op.f!r}")


class FIFOQueue(Model):
    """A strictly-ordered queue."""

    def __init__(self, pending: tuple = ()):
        self.pending = pending

    def step(self, op):
        if op.f == "enqueue":
            return FIFOQueue(self.pending + (op.value,))
        if op.f == "dequeue":
            if not self.pending:
                return inconsistent("can't dequeue from empty queue")
            if self.pending[0] != op.value:
                return inconsistent(
                    f"dequeued {op.value!r} but head was "
                    f"{self.pending[0]!r}")
            return FIFOQueue(self.pending[1:])
        return inconsistent(f"unknown f {op.f!r}")


class GSet(Model):
    """A grow-only set with add/read."""

    def __init__(self, elements: frozenset = frozenset()):
        self.elements = elements

    def step(self, op):
        if op.f == "add":
            return GSet(self.elements | {op.value})
        if op.f == "read":
            if op.value is None or set(op.value) == set(self.elements):
                return self
            return inconsistent(
                f"read {op.value!r} but expected {sorted(self.elements)!r}")
        return inconsistent(f"unknown f {op.f!r}")


def register(value=None) -> Register:
    return Register(value)


def cas_register(value=None) -> CASRegister:
    return CASRegister(value)


def mutex() -> Mutex:
    return Mutex(False)


def unordered_queue() -> UnorderedQueue:
    return UnorderedQueue()


def fifo_queue() -> FIFOQueue:
    return FIFOQueue()


def step(model, op):
    """Steps a model, passing Inconsistent through unchanged."""
    if is_inconsistent(model):
        return model
    return model.step(op)
