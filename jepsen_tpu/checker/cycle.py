"""Transactional cycle workload bundles: list-append and rw-register.

Capability reference: jepsen/src/jepsen/tests/cycle/append.clj (checker
11-27 wrapping elle.list-append/check, gen 29-46) and wr.clj (10-25
wrapping elle.rw-register/check). Generators emit txn ops whose values
are lists of micro-ops; clients fill in read results on completion.
"""

from __future__ import annotations

import random
from typing import Iterator

from . import Checker, _Fn
from ..tpu import elle


def _with_artifacts(test, result: dict) -> dict:
    """On an invalid result with a store directory, writes the elle/
    anomaly files + cycle plots (the reference passes :directory to
    elle so it drops the same artifacts, append.clj:17-27)."""
    store_dir = isinstance(test, dict) and test.get("store_dir")
    if store_dir and result.get("anomalies"):
        try:
            from ..reports import explain

            paths = explain.write_elle_artifacts(store_dir, result)
            # anomaly provenance: resolve each anomaly's op-indices
            # into trace excerpts when the run carried optrace.jsonl
            paths += explain.write_trace_excerpts(store_dir, result)
            if paths:
                result = dict(result)
                result["artifacts"] = paths
        except Exception:  # noqa: BLE001 — artifacts are best-effort
            import logging

            logging.getLogger(__name__).exception(
                "writing elle artifacts failed")
    return result


def append_checker(opts: dict | None = None) -> Checker:
    """Checks list-append histories via the elle-equivalent engine
    (append.clj:11-27). Checker-driven verdicts carry a verdict
    certificate by default (jepsen_tpu.tpu.certify; pass
    {'certify': False} to skip the proof)."""
    o = dict(opts or {})
    o.setdefault("certify", True)

    def run(test, hist, copts):
        return _with_artifacts(test, elle.check_list_append(hist, o))

    return _Fn(run)


def wr_checker(opts: dict | None = None) -> Checker:
    """Checks rw-register histories (wr.clj:10-25). Verdicts carry a
    certificate by default, like append_checker."""
    o = dict(opts or {})
    o.setdefault("certify", True)

    def run(test, hist, copts):
        return _with_artifacts(test, elle.check_rw_register(hist, o))

    return _Fn(run)


def append_gen(key_count: int = 3, min_txn_length: int = 1,
               max_txn_length: int = 4, max_writes_per_key: int = 32,
               seed: int | None = None) -> Iterator[dict]:
    """Infinite stream of list-append txn ops (append.clj:29-46 /
    elle.list-append/gen): each key sees monotonically increasing
    append values; keys rotate out once fully written."""
    rng = random.Random(seed)
    next_val: dict[int, int] = {}
    first_key = 0

    while True:
        # retire the lowest key once IT fills; the window always holds
        # key_count keys and no key exceeds its write budget
        while next_val.get(first_key, 0) >= max_writes_per_key:
            first_key += 1
        keys = list(range(first_key, first_key + key_count))
        txn = []
        for _ in range(rng.randint(min_txn_length, max_txn_length)):
            k = rng.choice(keys)
            if (rng.random() < 0.5
                    or next_val.get(k, 0) >= max_writes_per_key):
                txn.append(["r", k, None])
            else:
                v = next_val.get(k, 0) + 1
                next_val[k] = v
                txn.append(["append", k, v])
        yield {"f": "txn", "value": txn}


def wr_gen(key_count: int = 3, min_txn_length: int = 1,
           max_txn_length: int = 4, max_writes_per_key: int = 32,
           seed: int | None = None) -> Iterator[dict]:
    """Infinite stream of rw-register txn ops with globally distinct
    written values per key (elle.rw-register/gen)."""
    rng = random.Random(seed)
    next_val: dict[int, int] = {}
    first_key = 0
    while True:
        while next_val.get(first_key, 0) >= max_writes_per_key:
            first_key += 1
        keys = list(range(first_key, first_key + key_count))
        txn = []
        for _ in range(rng.randint(min_txn_length, max_txn_length)):
            k = rng.choice(keys)
            if (rng.random() < 0.5
                    or next_val.get(k, 0) >= max_writes_per_key):
                txn.append(["r", k, None])
            else:
                v = next_val.get(k, 0) + 1
                next_val[k] = v
                txn.append(["w", k, v])
        yield {"f": "txn", "value": txn}
