"""Checkers: verify that a history is consistent with a model.

Capability reference: jepsen/src/jepsen/checker.clj (Checker protocol
57-72, check-safe 79-90, compose 92-104, concurrency-limit 106-121,
unhandled-exceptions 129-157, stats 159-200, linearizable 202-233, queue
235-255, set 257-317, set-full 320-612, total-queue 648-708, unique-ids
710-747, counter 749-819, log-file-pattern 863-905). The reference runs
tesser fork-join folds over history chunks; the O(n) checkers here fold
directly (with numpy where it pays), and the search-heavy checkers
dispatch to the TPU kernels in jepsen_tpu.tpu.
"""

from __future__ import annotations

import logging
import re
import subprocess
import threading
import traceback
from collections import Counter
from typing import Any, Callable

from .. import history as h
from .. import telemetry
from .. import util
from ..history import History, Op
from . import models as model

logger = logging.getLogger(__name__)


class Checker:
    def check(self, test, history: History, opts: dict | None = None) -> dict:
        """Returns at least {'valid?': True|False|'unknown'}. opts may
        include 'subdirectory' for output files."""
        raise NotImplementedError


def _as_history(hist) -> History:
    if isinstance(hist, History):
        return hist
    return History(hist)


def check(checker: Checker, test, hist, opts=None) -> dict:
    return checker.check(test, _as_history(hist), opts or {})


_TIMED_OUT = object()


def checker_timeout_s(test, opts=None) -> float | None:
    """The per-checker wall-clock bound, from opts or the test map
    (test["checker_timeout_s"]); None = unbounded."""
    for src in (opts or {}, test if isinstance(test, dict) else {}):
        v = src.get("checker_timeout_s")
        if v:
            return float(v)
    return None


def check_safe(checker: Checker, test, hist, opts=None,
               timeout_s: float | None = None) -> dict:
    """check, but exceptions degrade to valid? 'unknown'
    (checker.clj:79-90). With timeout_s, a hung checker degrades the
    same way after that many wall-clock seconds — the worker thread is
    abandoned, not interrupted (util.timeout), so analysis proceeds to
    the remaining checkers instead of stalling the whole run."""
    def body():
        try:
            return check(checker, test, hist, opts)
        except Exception:  # noqa: BLE001
            logger.exception("Error while checking history:")
            return {"valid?": "unknown", "error": traceback.format_exc()}

    if not timeout_s:
        return body()
    res = util.timeout(timeout_s, body, default=_TIMED_OUT)
    if res is _TIMED_OUT:
        telemetry.count("checker.timeouts")
        logger.warning("checker %s timed out after %.1fs; degrading to "
                       "valid? unknown", type(checker).__name__,
                       timeout_s)
        return {"valid?": "unknown",
                "error": f"checker timed out after {timeout_s}s"}
    return res


def op_indices(hist: History | None, *ops) -> list[int]:
    """Participating op (invocation) indices for a group of ops —
    anomaly provenance, joining verdicts to the per-op trace
    (optrace.jsonl) and timeline anchors. Completion ops resolve to
    their invocation when the history is given."""
    idxs = set()
    for o in ops:
        if o is None:
            continue
        idx = getattr(o, "index", None)
        if idx is None and isinstance(o, dict):
            idx = o.get("index")
        if not isinstance(idx, int) or idx < 0:
            continue
        ty = getattr(o, "type", None) or (
            o.get("type") if isinstance(o, dict) else None)
        if hist is not None and ty is not None and ty != "invoke":
            try:
                inv = hist.invocation(o)
                if inv is not None:
                    idx = inv.index
            except (KeyError, TypeError, AttributeError):
                pass
        idxs.add(idx)
    return sorted(idxs)


def merge_valid(valids) -> Any:
    """false dominates, then unknown, else true."""
    out: Any = True
    for v in valids:
        if v is False:
            return False
        if v == "unknown":
            out = "unknown"
    return out


def anomaly_classes(result: dict, **classes) -> dict:
    """Attaches the coverage taxonomy tag to a checker result:
    `anomaly-classes` maps each class this checker CHECKED to
    'witnessed' (found), 'clean' (checked, none found — the explicit
    negative result the coverage atlas needs), or 'unknown' (the check
    was indeterminate). Values may be bools (witnessed?) — they are
    resolved against the result's valid? — or literal outcome strings
    (jepsen_tpu.coverage)."""
    resolved = {}
    indeterminate = result.get("valid?") == "unknown"
    for cls, v in classes.items():
        cls = cls.replace("_", "-")
        if isinstance(v, str):
            resolved[cls] = v
        elif v:
            resolved[cls] = "witnessed"
        else:
            resolved[cls] = "unknown" if indeterminate else "clean"
    result["anomaly-classes"] = resolved
    return result


class _Fn(Checker):
    def __init__(self, fn):
        self.fn = fn

    def check(self, test, hist, opts=None):
        return self.fn(test, hist, opts or {})


def checker(fn) -> Checker:
    """Wraps fn(test, history, opts) -> result as a Checker."""
    return _Fn(fn)


def noop() -> Checker:
    return _Fn(lambda test, hist, opts: None)


def unbridled_optimism() -> Checker:
    """Everything is awesome."""
    return _Fn(lambda test, hist, opts: {"valid?": True})


class Compose(Checker):
    """Runs named checkers in parallel; valid? is the merge of all
    (checker.clj:92-104)."""

    def __init__(self, checker_map: dict):
        self.checker_map = dict(checker_map)

    def check(self, test, hist, opts=None):
        opts = opts or {}
        partial = opts.get("partial_results")  # crash-surviving sink
        # per-checker wall-clock bound: one hung checker degrades to
        # valid? 'unknown' instead of stalling the whole analysis
        timeout_s = checker_timeout_s(test, opts)
        # results recovered from a crashed analysis's partial log
        # (analyze --resume): completed checkers are not re-run
        resumed = opts.get("resume_results") or {}
        # sub-checkers must NOT inherit the sink: a nested compose
        # would write its inner results flat with colliding keys (two
        # 'stats' entries, workload results hoisted to top level)
        sub_opts = {k: v for k, v in opts.items()
                    if k not in ("partial_results", "resume_results")}

        def one(kv):
            name, c = kv
            if name in resumed:
                telemetry.count("checker.resumed")
                r = resumed[name]
            else:
                # per-checker timing: the checker:<name> spans feed the
                # :telemetry summary core.analyze attaches to results
                with telemetry.span(f"checker:{name}"):
                    r = check_safe(c, test, hist, sub_opts,
                                   timeout_s=timeout_s)
            if partial is not None:
                try:
                    partial.put(name, r)
                except Exception:  # noqa: BLE001 — never sink the check
                    logger.exception("writing partial result failed")
            return name, r

        outs = util.bounded_pmap(one, list(self.checker_map.items()),
                                 limit=8)
        results = dict(outs)
        results["valid?"] = merge_valid(
            (r or {}).get("valid?") for r in results.values()
            if isinstance(r, dict))
        return results


def compose(checker_map: dict) -> Checker:
    return Compose(checker_map)


class ConcurrencyLimit(Checker):
    """Bounds concurrent executions of a checker (checker.clj:106-121)."""

    def __init__(self, limit: int, inner: Checker):
        self.sem = threading.Semaphore(limit)
        self.inner = inner

    def check(self, test, hist, opts=None):
        with self.sem:
            return self.inner.check(test, hist, opts)


def concurrency_limit(limit: int, inner: Checker) -> Checker:
    return ConcurrencyLimit(limit, inner)


# ---------------------------------------------------------------------------
# Stats + exceptions
# ---------------------------------------------------------------------------

def stats() -> Checker:
    """Success/failure rates, overall and by :f; valid only if every :f has
    some ok ops (checker.clj:159-200). Single counting pass — no
    per-f op lists (SURVEY P4: O(n) folds stay O(1) in memory)."""

    def run(test, hist, opts):
        by: dict = {}
        for o in hist:
            t = o.type
            if t == "invoke" or not h.is_client_op(o):
                continue
            d = by.get(o.f)
            if d is None:
                d = by[o.f] = [0, 0, 0]  # ok, info, fail
            if t == "ok":
                d[0] += 1
            elif t == "info":
                d[1] += 1
            elif t == "fail":
                d[2] += 1

        def fold(oks, infos, fails):
            return {"valid?": oks > 0, "count": oks + infos + fails,
                    "ok-count": oks, "fail-count": fails,
                    "info-count": infos}

        by_f = {f: fold(*c) for f, c in sorted(
            by.items(), key=lambda kv: str(kv[0]))}
        out = fold(sum(c[0] for c in by.values()),
                   sum(c[1] for c in by.values()),
                   sum(c[2] for c in by.values()))
        out["by-f"] = by_f
        out["valid?"] = merge_valid(r["valid?"] for r in by_f.values())
        return out

    return _Fn(run)


def unhandled_exceptions() -> Checker:
    """Frequency table of exceptions recorded in :info ops
    (checker.clj:129-157)."""

    def run(test, hist, opts):
        by_class: dict = {}
        for o in hist:
            if o.type == "info" and o.get("exception"):
                cls = str(o.get("exception")).strip().splitlines()[-1][:120]
                by_class.setdefault(cls, []).append(o)
        exes = [{"count": len(ops), "class": cls, "example": ops[0]}
                for cls, ops in sorted(by_class.items(),
                                       key=lambda kv: -len(kv[1]))]
        out = {"valid?": True}
        if exes:
            out["exceptions"] = exes
        return out

    return _Fn(run)


# ---------------------------------------------------------------------------
# Linearizability
# ---------------------------------------------------------------------------

class Linearizable(Checker):
    """Validates linearizability. opts: {'model': Model, 'algorithm':
    'tpu' (default) | 'wgl' | 'model'}. 'wgl' is the pure-host reference
    search; 'tpu' is the batched frontier kernel (checker.clj:202-233;
    the reference delegates to knossos competition/linear/wgl).

    check_batch checks many histories in one device launch — the
    independent checker uses it to make per-key histories the kernel's
    batch dimension."""

    def __init__(self, opts: dict):
        self.model = opts.get("model")
        assert self.model is not None, \
            "the linearizable checker requires a model"
        self.algorithm = opts.get("algorithm", "tpu")
        # checker-driven verdicts carry a machine-checkable proof by
        # default (jepsen_tpu.tpu.certify); raw wgl.analysis calls
        # (bench kernels) don't pay for extraction
        self.certify = bool(opts.get("certify", True))

    @staticmethod
    def _trim(a: dict) -> dict:
        a["final-paths"] = a.get("final-paths", [])[:10]
        a["configs"] = a.get("configs", [])[:10]
        return a

    def check(self, test, hist, opts=None):
        from ..tpu import wgl

        store_dir = test.get("store_dir") \
            if isinstance(test, dict) else None
        if store_dir and test.get("extend?") \
                and self.algorithm == "tpu":
            # checkpoint-and-extend (doc/robustness.md): re-checking
            # the grown run-dir reuses the persisted frontier, paying
            # O(suffix); a stale/absent record falls through to the
            # full check inside analysis_extend
            out = self._trim(wgl.analysis_extend(
                self.model, hist,
                store_path=self._extend_path(store_dir, hist),
                certify=self.certify))
            return self._explain(test, out)
        ckpt_dir = None
        if store_dir and test.get("checkpoint?"):
            from pathlib import Path

            # a DIRECTORY: each check derives a per-fingerprint file,
            # so concurrent per-key/composed checkers never collide
            ckpt_dir = Path(store_dir) / "checker-frontier"
        out = self._trim(wgl.analysis(self.model, hist,
                                      algorithm=self.algorithm,
                                      checkpoint_dir=ckpt_dir,
                                      certify=self.certify))
        return self._explain(test, out)

    def _extend_path(self, store_dir, hist):
        """Per-(model, history-identity) store file under the run
        dir's ckpt/: keyed by the model repr and the FIRST op (stable
        as the run grows by appending), so concurrent per-key checks
        never share — and never thrash — one record."""
        import hashlib

        from ..store import format as fmt
        from ..tpu import ckpt

        h = hashlib.sha256(repr(self.model).encode())
        first = next(iter(hist), None)
        if first is not None:
            h.update(fmt.encode_op(first))
        return ckpt.run_dir_path(store_dir,
                                 f"wgl-{h.hexdigest()[:16]}")

    @staticmethod
    def _explain(test, out: dict) -> dict:
        """Invalid + store dir: render the counterexample SVG (the
        reference's knossos render-analysis! hook, checker.clj:222-229).
        The filename carries a content fingerprint so concurrent
        per-key checks sharing one store dir never clobber or
        mis-attribute each other's renders."""
        # coverage taxonomy: the one class this checker decides, with
        # the explicit negative ("checked, linearizable") recorded
        anomaly_classes(out,
                        nonlinearizable=out.get("valid?") is False)
        store_dir = isinstance(test, dict) and test.get("store_dir")
        if store_dir and out.get("valid?") is False:
            try:
                from pathlib import Path

                from ..reports import explain

                fp = explain._fingerprint(
                    (repr(out.get("op")), repr(out.get("previous-ok")),
                     repr(out.get("configs"))))
                p = explain.render_linear_svg(
                    out, Path(store_dir)
                    / f"linear-counterexample-{fp}.svg")
                if p:
                    out["counterexample-svg"] = p
                # provenance: the counterexample's op-indices resolve
                # to per-op trace excerpts when the run was traced
                p2 = explain.write_linear_trace_excerpt(store_dir, out)
                if p2:
                    out["trace-excerpt"] = p2
            except Exception:  # noqa: BLE001 — rendering is best-effort
                import logging

                logging.getLogger(__name__).exception(
                    "rendering linear counterexample failed")
        return out

    def check_batch(self, test, hists, opts=None) -> list[dict]:
        from ..tpu import wgl

        if self.algorithm != "tpu":
            return [self._explain(test, self._trim(
                        wgl.analysis(self.model, hh,
                                     algorithm=self.algorithm,
                                     certify=self.certify)))
                    for hh in hists]
        return [self._explain(test, self._trim(a)) for a in
                wgl.analysis_batch(self.model, hists,
                                   certify=self.certify)]


def linearizable(opts: dict) -> Checker:
    return Linearizable(opts)


# ---------------------------------------------------------------------------
# Queue / set / counter families
# ---------------------------------------------------------------------------

def queue(m: model.Model) -> Checker:
    """Assume every non-failing enqueue succeeded and only ok dequeues
    happened; fold the model over that (checker.clj:235-255)."""

    def run(test, hist, opts):
        final = m
        for o in hist:
            if o.f == "enqueue" and o.type == "invoke":
                final = model.step(final, o)
            elif o.f == "dequeue" and o.type == "ok":
                final = model.step(final, o)
        if model.is_inconsistent(final):
            return {"valid?": False, "error": final.msg}
        return {"valid?": True, "final-queue": final}

    return _Fn(run)


def set_checker() -> Checker:
    """Adds followed by a final read: every ok add must be read; only
    attempted adds may appear (checker.clj:257-317)."""

    def run(test, hist, opts):
        attempts = {o.value for o in hist
                    if o.type == "invoke" and o.f == "add"}
        adds = {o.value for o in hist if o.type == "ok" and o.f == "add"}
        final_read = None
        for o in hist:
            if o.f == "read" and o.type == "ok":
                final_read = o.value
        if final_read is None:
            return anomaly_classes(
                {"valid?": "unknown", "error": "Set was never read"},
                set_lost=False, set_unexpected=False)
        final = set(final_read)
        ok = final & attempts
        unexpected = final - attempts
        lost = adds - final
        recovered = ok - adds
        return anomaly_classes({
            "valid?": not lost and not unexpected,
            "attempt-count": len(attempts),
            "acknowledged-count": len(adds),
            "ok-count": len(ok),
            "lost-count": len(lost),
            "recovered-count": len(recovered),
            "unexpected-count": len(unexpected),
            "ok": util.integer_interval_set_str(ok)
            if _all_ints(ok) else sorted(ok, key=str),
            "lost": util.integer_interval_set_str(lost)
            if _all_ints(lost) else sorted(lost, key=str),
            "unexpected": util.integer_interval_set_str(unexpected)
            if _all_ints(unexpected) else sorted(unexpected, key=str),
            "recovered": util.integer_interval_set_str(recovered)
            if _all_ints(recovered) else sorted(recovered, key=str),
        }, set_lost=bool(lost), set_unexpected=bool(unexpected))

    return _Fn(run)


def _all_ints(xs) -> bool:
    return all(isinstance(x, int) for x in xs)


class _SetFullElement:
    """Per-element lifecycle state (checker.clj SetFullElement,
    330-433)."""

    __slots__ = ("element", "known", "last_present", "last_absent")

    def __init__(self, element):
        self.element = element
        self.known = None          # completion op confirming existence
        self.last_present = None   # latest read invocation observing it
        self.last_absent = None    # latest read invocation missing it

    def add_ok(self, op):
        if self.known is None:
            self.known = op

    def read_present(self, inv, op):
        if self.known is None:
            self.known = op
        if self.last_present is None or self.last_present.index < inv.index:
            self.last_present = inv

    def read_absent(self, inv, op):
        if self.last_absent is None or self.last_absent.index < inv.index:
            self.last_absent = inv

    def results(self) -> dict:
        lp = self.last_present.index if self.last_present else -1
        la = self.last_absent.index if self.last_absent else -1
        stable = bool(self.last_present and la < lp)
        lost = bool(self.known and self.last_absent and lp < la
                    and self.known.index < la)
        stable_time = ((self.last_absent.time + 1 if self.last_absent else 0)
                       if stable else None)
        lost_time = ((self.last_present.time + 1 if self.last_present else 0)
                     if lost else None)
        known_time = self.known.time if self.known else 0
        stable_latency = (max(0, stable_time - known_time) // 1_000_000
                          if stable else None)
        lost_latency = (max(0, lost_time - known_time) // 1_000_000
                        if lost else None)
        return {"element": self.element,
                "outcome": ("stable" if stable
                            else "lost" if lost else "never-read"),
                "stable-latency": stable_latency,
                "lost-latency": lost_latency,
                "known": self.known,
                "last-absent": self.last_absent}


def _frequency_distribution(points, values):
    values = sorted(values)
    if not values:
        return None
    n = len(values)
    return {p: values[min(n - 1, int(n * p))] for p in points}


def _set_full_results_slow(hist) -> tuple[list, dict]:
    """Object-model per-element lifecycle fold (the correctness
    reference; O(reads x elements))."""
    elements: dict = {}
    dups: dict = {}
    for op in hist:
        if not h.is_client_op(op):
            continue
        if op.f == "add":
            if op.type == "invoke":
                elements[op.value] = _SetFullElement(op.value)
            elif op.type == "ok" and op.value in elements:
                elements[op.value].add_ok(op)
        elif op.f == "read" and op.type == "ok":
            inv = hist.invocation(op)
            if inv is None:
                continue
            vals = op.value or []
            for k, n in Counter(vals).items():
                if n > 1:
                    dups[k] = max(dups.get(k, 0), n)
            vset = set(vals)
            for element, state in elements.items():
                if element in vset:
                    state.read_present(inv, op)
                else:
                    state.read_absent(inv, op)
    rs = [e.results() for _k, e in sorted(elements.items(),
                                          key=lambda kv: str(kv[0]))]
    return rs, dups


def _set_full_results_fast(hist) -> tuple[list, dict] | None:
    """Array formulation of the same fold (SURVEY P4): per-element
    last-present/last-absent/known reduce to segment max/min over
    (element, read) membership pairs, so cost is O(total read volume)
    in C instead of O(reads x elements) in Python. Returns None when
    the history isn't int-valued (caller falls back).

    last_absent needs the highest read (in invocation order) NOT
    containing an element: with reads ranked 0..R-1, that is
    R-1-k where k is the element's trailing run of consecutive
    present ranks ending at R-1 (k=0 when absent from the last read).
    """
    import numpy as np

    seen_add: set = set()       # elements with an add invocation
    add_ok: dict = {}           # element -> first add-ok op
    reads: list = []            # (inv_index, inv_time, comp_index,
    #                              comp_time, comp_pos, values)
    for pos, op in enumerate(hist):
        f = op.f
        if f == "add":
            if not h.is_client_op(op):
                continue
            if type(op.value) is not int:
                return None
            ty = op.type
            if ty == "invoke":
                seen_add.add(op.value)
            elif ty == "ok" and op.value in seen_add:
                add_ok.setdefault(op.value, op)
        elif f == "read" and op.type == "ok":
            if not h.is_client_op(op):
                continue
            inv = hist.invocation(op)
            if inv is None:
                continue
            reads.append((inv.index, inv.time or 0, op.index,
                          op.time or 0, inv, op, op.value or []))
    elements = sorted(seen_add)  # numeric order for array ops
    E, R = len(elements), len(reads)
    elem_arr = np.asarray(elements, dtype=np.int64)
    reads.sort(key=lambda r: r[0])  # rank = invocation order
    inv_idx = np.asarray([r[0] for r in reads], dtype=np.int64)
    inv_time = np.asarray([r[1] for r in reads], dtype=np.int64)
    inv_ops = [r[4] for r in reads]   # invocation Op per rank
    comp_ops = [r[5] for r in reads]  # completion Op per rank

    # One vectorized pass per read, in invocation-rank order: updates
    # last-present ranks, first-present completion (for known), the
    # trailing consecutive-present run (for last_absent), and
    # duplicate counts — O(read volume + reads * E), no global sort.
    BIG = np.iinfo(np.int64).max
    comp_idx = np.asarray([r[2] for r in reads], dtype=np.int64)
    comp_time = np.asarray([r[3] for r in reads], dtype=np.int64)
    last_present = np.full(E, -1, dtype=np.int64)
    first_pres_comp = np.full(E, BIG, dtype=np.int64)
    first_pres_comp_time = np.zeros(E, dtype=np.int64)
    first_pres_rank = np.full(E, -1, dtype=np.int64)
    run = np.zeros(E, dtype=np.int64)
    dups: dict = {}
    for rank in range(R):
        try:
            vals = np.asarray(reads[rank][6], dtype=np.int64)
        except (TypeError, ValueError, OverflowError):
            return None
        if vals.size:
            uniq, counts = np.unique(vals, return_counts=True)
            for j in np.flatnonzero(counts > 1):
                k = int(uniq[j])
                dups[k] = max(dups.get(k, 0), int(counts[j]))
            # keep only elements that were actually added
            p = np.searchsorted(elem_arr, uniq)
            p = np.clip(p, 0, max(E - 1, 0))
            ok = (elem_arr[p] == uniq) if E else np.zeros(
                len(uniq), dtype=bool)
            ids = p[ok]
        else:
            ids = np.empty(0, dtype=np.int64)
        last_present[ids] = rank  # ranks ascend: assignment == max
        ci, ct = int(comp_idx[rank]), int(comp_time[rank])
        upd = ids[first_pres_comp[ids] > ci]
        first_pres_comp[upd] = ci
        first_pres_comp_time[upd] = ct
        first_pres_rank[upd] = rank
        # elements absent from this read restart their run at 0
        nrun = np.zeros(E, dtype=np.int64)
        nrun[ids] = run[ids] + 1
        run = nrun
    # last_absent = highest rank NOT containing the element: R-1 minus
    # the trailing consecutive-present run (-1 when no reads at all)
    last_absent = ((R - 1) - run if R
                   else np.full(E, -1, dtype=np.int64))

    # known = first confirming event in history order: add-ok or
    # present-read completion, whichever completes first
    add_ok_idx = np.full(E, BIG, dtype=np.int64)
    for i, e in enumerate(elements):
        o = add_ok.get(e)
        if o is not None:
            add_ok_idx[i] = o.index
    known_idx = np.minimum(add_ok_idx, first_pres_comp)
    has_known = known_idx < BIG

    lp, la = last_present, last_absent
    has_p = lp >= 0
    has_a = la >= 0
    if R:
        lp_idx = np.where(has_p, inv_idx[np.clip(lp, 0, None)], -1)
        la_idx = np.where(has_a, inv_idx[np.clip(la, 0, None)], -1)
        la_time = np.where(has_a, inv_time[np.clip(la, 0, None)], -1)
        lp_time = np.where(has_p, inv_time[np.clip(lp, 0, None)], -1)
    else:  # no successful reads at all: everything is never-read
        lp_idx = la_idx = np.full(E, -1, dtype=np.int64)
        la_time = lp_time = np.full(E, -1, dtype=np.int64)
    stable = has_p & (la < lp)
    lost = has_known & has_a & (lp < la) & (known_idx < la_idx)

    # times + latencies (checker.clj results, 435-470)
    add_ok_time = np.zeros(E, dtype=np.int64)
    for i, e in enumerate(elements):
        o = add_ok.get(e)
        if o is not None:
            add_ok_time[i] = o.time or 0
    by_add = add_ok_idx <= first_pres_comp
    known_time = np.where(by_add, add_ok_time, first_pres_comp_time)

    stable_time = np.where(has_a, la_time + 1, 0)
    lost_time = np.where(has_p, lp_time + 1, 0)
    stable_lat = np.maximum(0, stable_time - known_time) // 1_000_000
    lost_lat = np.maximum(0, lost_time - known_time) // 1_000_000

    # rows in str(element) order, matching the object path exactly;
    # plain-list views keep the row loop free of numpy scalar overhead
    stable_l = stable.tolist()
    lost_l = lost.tolist()
    sl_l = stable_lat.tolist()
    ll_l = lost_lat.tolist()
    hk_l = has_known.tolist()
    ha_l = has_a.tolist()
    la_l = la.tolist()
    by_add_l = by_add.tolist()
    fpr_l = first_pres_rank.tolist()
    idx_of = {e: i for i, e in enumerate(elements)}
    rs = []
    for e in sorted(elements, key=str):
        i = idx_of[e]
        outcome = ("stable" if stable_l[i]
                   else "lost" if lost_l[i] else "never-read")
        if not hk_l[i]:
            known = None
        elif by_add_l[i]:
            known = add_ok.get(e)
        else:  # existence proven by a read's completion (slow-path op)
            known = comp_ops[fpr_l[i]]
        rs.append({
            "element": e,
            "outcome": outcome,
            "stable-latency": sl_l[i] if stable_l[i] else None,
            "lost-latency": ll_l[i] if lost_l[i] else None,
            "known": known,
            "last-absent": (inv_ops[la_l[i]] if ha_l[i] else None),
        })
    return rs, dups


def set_full(checker_opts: dict | None = None) -> Checker:
    """Rigorous per-element set analysis: stable/lost/never-read outcomes
    with stable/lost latencies (checker.clj:320-612)."""
    copts = {"linearizable?": False}
    copts.update(checker_opts or {})

    def run(test, hist, opts):
        fast = _set_full_results_fast(hist)
        rs, dups = (fast if fast is not None
                    else _set_full_results_slow(hist))
        outcomes: dict = {}
        for r in rs:
            outcomes.setdefault(r["outcome"], []).append(r)
        stale = [r for r in outcomes.get("stable", [])
                 if r["stable-latency"] and r["stable-latency"] > 0]
        stable_lat = [r["stable-latency"] for r in rs
                      if r["stable-latency"] is not None]
        lost_lat = [r["lost-latency"] for r in rs
                    if r["lost-latency"] is not None]
        lost_n = len(outcomes.get("lost", []))
        stable_n = len(outcomes.get("stable", []))
        valid: Any = True
        if lost_n > 0:
            valid = False
        elif stable_n == 0:
            valid = "unknown"
        elif copts.get("linearizable?") and stale:
            valid = False
        out = {
            "valid?": (False if dups else valid),
            "attempt-count": len(rs),
            "stable-count": stable_n,
            "lost-count": lost_n,
            "lost": sorted((r["element"] for r in outcomes.get("lost", [])),
                           key=str),
            "never-read-count": len(outcomes.get("never-read", [])),
            "never-read": sorted((r["element"]
                                  for r in outcomes.get("never-read", [])),
                                 key=str),
            "stale-count": len(stale),
            "stale": sorted((r["element"] for r in stale), key=str),
            "worst-stale": sorted(stale, key=lambda r: -r["stable-latency"]
                                  )[:8],
            "duplicated-count": len(dups),
            "duplicated": dups,
        }
        if lost_n:
            # provenance for lost elements: the op indices proving
            # existence (known) and loss (last-absent), joinable to
            # the per-op trace and timeline
            out["lost-op-indices"] = {
                r["element"]: op_indices(hist, r["known"],
                                         r["last-absent"])
                for r in outcomes.get("lost", [])}
        points = [0, 0.5, 0.95, 0.99, 1]
        if stable_lat:
            out["stable-latencies"] = _frequency_distribution(
                points, stable_lat)
        if lost_lat:
            out["lost-latencies"] = _frequency_distribution(points, lost_lat)
        return anomaly_classes(out, set_lost=bool(lost_n),
                               set_stale=bool(stale),
                               set_duplicated=bool(dups))

    return _Fn(run)


def _expand_drains(hist: History) -> tuple:
    """Expands :drain ops into dequeue invoke/ok pairs
    (checker.clj:614-646). An :info drain (aborted mid-loop, e.g. the
    broker went away) still contributes its fetched values — ack'd
    messages are really gone — but is counted as aborted, so the
    conservation verdict can degrade to unknown instead of reporting
    still-enqueued messages as lost. Returns (ops, aborted_drains)."""
    out, aborted = [], 0
    for op in hist:
        if op.f != "drain":
            out.append(op)
        elif op.type in ("invoke", "fail"):
            continue
        else:
            if op.type == "info":
                aborted += 1
            for element in op.value or []:
                out.append(op.copy(index=-1, type="invoke", f="dequeue",
                                   value=None))
                out.append(op.copy(index=-1, type="ok", f="dequeue",
                                   value=element))
    return out, aborted


def total_queue() -> Checker:
    """What goes in must come out; requires a fully drained queue
    (checker.clj:648-708)."""

    def run(test, hist, opts):
        ops, aborted_drains = _expand_drains(hist)
        attempts = Counter(o.value for o in ops
                           if o.f == "enqueue" and o.type == "invoke")
        enqueues = Counter(o.value for o in ops
                           if o.f == "enqueue" and o.type == "ok")
        dequeues = Counter(o.value for o in ops
                           if o.f == "dequeue" and o.type == "ok")
        ok = dequeues & attempts
        unexpected = Counter({k: n for k, n in dequeues.items()
                              if k not in attempts})
        duplicated = dequeues - attempts - unexpected
        lost = enqueues - dequeues
        recovered = ok - enqueues
        if unexpected:
            valid = False
        elif lost:
            # if a drain aborted, "lost" messages may simply still sit
            # in the queue nobody finished draining: indeterminate
            valid = "unknown" if aborted_drains else False
        else:
            valid = True
        # a "lost" count under an aborted drain is indeterminate, not
        # a witness — the messages may still sit in the queue
        lost_outcome = ("clean" if not lost
                        else "unknown" if aborted_drains
                        else "witnessed")
        return anomaly_classes({
            "valid?": valid,
            "aborted-drain-count": aborted_drains,
            "attempt-count": sum(attempts.values()),
            "acknowledged-count": sum(enqueues.values()),
            "ok-count": sum(ok.values()),
            "unexpected-count": sum(unexpected.values()),
            "duplicated-count": sum(duplicated.values()),
            "lost-count": sum(lost.values()),
            "recovered-count": sum(recovered.values()),
            "lost": dict(lost),
            "unexpected": dict(unexpected),
            "duplicated": dict(duplicated),
            "recovered": dict(recovered),
        }, queue_lost=lost_outcome,
           queue_unexpected=bool(unexpected),
           queue_duplicated=bool(duplicated))

    return _Fn(run)


def unique_ids() -> Checker:
    """A unique-id generator must emit unique ids (checker.clj:710-747)."""

    def run(test, hist, opts):
        attempted = sum(1 for o in hist
                        if o.f == "generate" and o.type == "invoke")
        acks = [o.value for o in hist
                if o.f == "generate" and o.type == "ok"]
        freqs = Counter(acks)
        dups = {k: n for k, n in freqs.items() if n > 1}
        rng = [min(acks), max(acks)] if acks else None
        return anomaly_classes({
            "valid?": not dups,
            "attempted-count": attempted,
            "acknowledged-count": len(acks),
            "duplicated-count": len(dups),
            "duplicated": dict(sorted(dups.items(),
                                      key=lambda kv: -kv[1])[:48]),
            "range": rng,
        }, duplicate_ids=bool(dups))

    return _Fn(run)


def counter() -> Checker:
    """At each read, value must lie between the sum of ok increments and
    the sum of attempted increments (checker.clj:749-819)."""

    def run(test, hist, opts):
        lower = 0
        upper = 0
        pending_reads: dict = {}
        reads = []
        for op in hist:
            key = (op.type, op.f)
            if key == ("invoke", "read"):
                completion = hist.completion(op)
                if completion is not None and completion.type == "ok":
                    pending_reads[op.process] = [lower, completion.value]
            elif key == ("ok", "read"):
                r = pending_reads.pop(op.process, None)
                if r is not None:
                    reads.append([r[0], r[1], upper])
            elif key == ("invoke", "add"):
                assert op.value >= 0, "counter checker assumes increments"
                completion = hist.completion(op)
                if completion is None or completion.type != "fail":
                    upper += op.value
            elif key == ("ok", "add"):
                lower += op.value
        errors = [r for r in reads if not (r[0] <= r[1] <= r[2])]
        return anomaly_classes(
            {"valid?": not errors, "reads": reads, "errors": errors},
            counter_bounds=bool(errors))

    return _Fn(run)


def log_file_pattern(pattern: str, filename: str) -> Checker:
    """Greps downloaded node logs in the store dir for a pattern
    (checker.clj:863-905)."""

    def run(test, hist, opts):
        from .. import store as jstore

        matches = []
        for node in test.get("nodes") or []:
            path = jstore.path(test, str(node), filename)
            if not path.exists():
                continue
            try:
                text = path.read_text(errors="replace")
            except OSError:
                continue
            for line in text.splitlines():
                if re.search(pattern, line):
                    matches.append({"node": node, "line": line})
        return {"valid?": not matches, "count": len(matches),
                "matches": matches}

    return _Fn(run)


def perf(opts: dict | None = None) -> Checker:
    """Latency + rate + live-monitor graphs (checker/perf.clj plus the
    monitor time-series plot); see jepsen_tpu.reports.perf."""
    from ..reports.perf import latency_graph, monitor_graph, rate_graph

    return compose({"latency-graph": latency_graph(opts),
                    "rate-graph": rate_graph(opts),
                    "monitor-graph": monitor_graph(opts)})


def clock_plot() -> Checker:
    """Clock-skew plot (checker/clock.clj:14-49)."""
    from ..reports.clock import plot as clock_plot_fn

    return _Fn(lambda test, hist, opts:
               clock_plot_fn(test, hist, opts) or {"valid?": True})


def timeline() -> Checker:
    """HTML timeline (checker/timeline.clj)."""
    from ..reports.timeline import html as timeline_html

    return timeline_html()
