"""CharybdeFS: syscall-level fault injection (EIO and friends) through
a FUSE passthrough filesystem.

Capability reference: charybdefs/src/jepsen/charybdefs.clj — build
thrift + charybdefs from source (7-65), mount /faulty over /real
(55-65), and cookbook faults: every operation fails with EIO
(break-all, 72-75), 1% of operations fail (break-one-percent, 77-80),
clear (82-85). Plus a nemesis wiring those as ops.
"""

from __future__ import annotations

from . import control
from . import nemesis as jnemesis
from .control import util as cu
from .os_setup import debian

DIR = "/opt/charybdefs"
BIN = f"{DIR}/charybdefs"
FAULTY = "/faulty"
REAL = "/real"
THRIFT_URL = ("http://www-eu.apache.org/dist/thrift/0.10.0/"
              "thrift-0.10.0.tar.gz")
THRIFT_DIR = "/opt/thrift"


def install_thrift() -> None:
    """Builds thrift from source (charybdefs.clj:30-43); needs the
    build toolchain already installed."""
    cu.install_archive(THRIFT_URL, THRIFT_DIR)
    with control.cd(THRIFT_DIR):
        control.exec_("./configure", "--prefix=/usr")
        control.exec_("make", "-j4")
        with control.su():
            control.exec_("make", "install")
    with control.cd(f"{THRIFT_DIR}/lib/py"):
        with control.su():
            control.exec_("python", "setup.py", "install")


def install() -> None:
    """Builds charybdefs and mounts FAULTY over REAL
    (charybdefs.clj:45-65). Toolchain first: thrift's configure/make
    need a compiler on a fresh node."""
    if not cu.exists_p(BIN):
        with control.su():
            debian.install(["build-essential", "cmake", "libfuse-dev",
                            "fuse"])
            control.exec_("mkdir", "-p", DIR)
            control.exec_("chmod", "777", DIR)
        install_thrift()
        control.exec_("git", "clone", "--depth", "1",
                      "https://github.com/scylladb/charybdefs.git", DIR)
        with control.cd(DIR):
            control.exec_("thrift", "-r", "--gen", "cpp",
                          "server.thrift")
            control.exec_("cmake", "CMakeLists.txt")
            control.exec_("make")
    with control.su():
        control.exec_("modprobe", "fuse")
        control.exec_("sh", "-c", f"umount {FAULTY} || /bin/true")
        control.exec_("mkdir", "-p", REAL, FAULTY)
        control.exec_(BIN, FAULTY,
                      f"-oallow_other,modules=subdir,subdir={REAL}")
        control.exec_("chmod", "777", REAL, FAULTY)


def _cookbook(flag: str) -> None:
    with control.cd(f"{DIR}/cookbook"):
        control.exec_("./recipes", flag)


def break_all() -> None:
    """Every filesystem operation fails with EIO
    (charybdefs.clj:72-75)."""
    _cookbook("--io-error")


def break_one_percent() -> None:
    """1% of operations fail (charybdefs.clj:77-80)."""
    _cookbook("--probability")


def clear() -> None:
    """Removes the active fault injection (charybdefs.clj:82-85)."""
    _cookbook("--clear")


class CharybdeFSNemesis(jnemesis.Nemesis):
    """Ops: f='break-all'|'break-one-percent'|'clear-faults', value a
    node list (default: all)."""

    _FS = {"break-all": break_all,
           "break-one-percent": break_one_percent,
           "clear-faults": clear}

    def invoke(self, test, op):
        f = self._FS.get(op.f)
        if f is None:
            raise ValueError(f"unknown f {op.f!r}")
        nodes = op.value or test["nodes"]
        got = control.on_nodes(test, lambda t, n: f() or "done", nodes)
        return op.copy(value=got)

    def teardown(self, test):
        try:
            control.on_nodes(test, lambda t, n: clear() or None,
                             test.get("nodes"))
        except Exception:  # noqa: BLE001 — best-effort cleanup
            pass

    def fs(self):
        return set(self._FS)


def nemesis() -> CharybdeFSNemesis:
    return CharybdeFSNemesis()
