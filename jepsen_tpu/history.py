"""Operation records and histories.

The history is the central artifact of the framework: an append-only list
of invocations and completions. This module provides the Op record, the
History container with invocation/completion pairing, and the
structure-of-arrays (SoA) encoding that feeds the TPU checkers.

Capability reference: the external io.jepsen/history 0.1.3 library as used
throughout jepsen (Op construction at jepsen/src/jepsen/generator.clj:528-536;
pairing at jepsen/src/jepsen/checker.clj:782-804; parallel folds at
checker.clj:139-200). Where the reference pairs ops via per-process scans
over persistent vectors, we precompute dense int32 index columns so checkers
can operate on flat numpy/JAX arrays.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, Iterator, Sequence

import numpy as np

# Op types
INVOKE = "invoke"
OK = "ok"
FAIL = "fail"
INFO = "info"
SLEEP = "sleep"
LOG = "log"

_COMPLETION_TYPES = (OK, FAIL, INFO)


class Op:
    """A single operation event.

    Fields mirror jepsen.history.Op [index time type process f value]; any
    other keys (error, exception, ...) live in the `ext` dict. Ops are
    treated as immutable: use copy()/merge() to derive new ones.
    """

    __slots__ = ("index", "time", "type", "process", "f", "value", "ext")

    def __init__(self, index=-1, time=0, type=INVOKE, process=None, f=None,
                 value=None, ext=None):
        self.index = index
        self.time = time
        self.type = type
        self.process = process
        self.f = f
        self.value = value
        self.ext = ext

    # -- map-like access ----------------------------------------------------

    _CORE = ("index", "time", "type", "process", "f", "value")

    def get(self, k: str, default=None):
        if k in Op._CORE:
            return getattr(self, k)
        if self.ext:
            return self.ext.get(k, default)
        return default

    def __getitem__(self, k):
        v = self.get(k, _MISSING)
        if v is _MISSING:
            raise KeyError(k)
        return v

    def __contains__(self, k):
        return k in Op._CORE or bool(self.ext and k in self.ext)

    @property
    def error(self):
        return self.ext.get("error") if self.ext else None

    def keys(self):
        ks = list(Op._CORE)
        if self.ext:
            ks.extend(self.ext.keys())
        return ks

    def to_dict(self) -> dict:
        d = {k: getattr(self, k) for k in Op._CORE}
        if self.ext:
            d.update(self.ext)
        return d

    # -- derivation ---------------------------------------------------------

    def copy(self, **changes) -> "Op":
        """Returns a new Op with the given fields replaced; non-core keys go
        into ext."""
        core = {k: getattr(self, k) for k in Op._CORE}
        ext = dict(self.ext) if self.ext else {}
        for k, v in changes.items():
            if k in core:
                core[k] = v
            else:
                ext[k] = v
        return Op(ext=ext or None, **core)

    def without(self, *keys) -> "Op":
        ext = dict(self.ext) if self.ext else {}
        for k in keys:
            ext.pop(k, None)
        return Op(self.index, self.time, self.type, self.process, self.f,
                  self.value, ext or None)

    # -- dunder -------------------------------------------------------------

    def __eq__(self, other):
        if not isinstance(other, Op):
            return NotImplemented
        return (self.index == other.index and self.time == other.time
                and self.type == other.type and self.process == other.process
                and self.f == other.f and self.value == other.value
                and (self.ext or None) == (other.ext or None))

    def __hash__(self):
        return hash((self.index, self.time, self.type, self.process, self.f))

    def __repr__(self):
        parts = [f"{self.index}", f"{self.type}", f"p{self.process}",
                 f"{self.f}", f"{self.value!r}"]
        if self.ext:
            parts.append(repr(self.ext))
        return "Op<" + " ".join(parts) + ">"


_MISSING = object()


def op(**kwargs) -> Op:
    """Convenience Op constructor accepting arbitrary keys."""
    core = {k: kwargs.pop(k) for k in list(kwargs) if k in Op._CORE}
    return Op(ext=kwargs or None, **core)


# ---------------------------------------------------------------------------
# Predicates (jepsen.history: invoke?/ok?/fail?/info?/client-op?)
# ---------------------------------------------------------------------------

def is_invoke(o: Op) -> bool:
    return o.type == INVOKE


def is_ok(o: Op) -> bool:
    return o.type == OK


def is_fail(o: Op) -> bool:
    return o.type == FAIL


def is_info(o: Op) -> bool:
    return o.type == INFO


def is_completion(o: Op) -> bool:
    return o.type in _COMPLETION_TYPES


def is_client_op(o: Op) -> bool:
    return isinstance(o.process, int)


def has_f(f) -> Callable[[Op], bool]:
    fs = f if isinstance(f, (set, frozenset)) else {f}
    return lambda o: o.f in fs


# ---------------------------------------------------------------------------
# History
# ---------------------------------------------------------------------------

class History(Sequence):
    """An indexed sequence of Ops with invocation/completion pairing.

    Construction assigns dense indices if absent. Pairing: an invocation's
    completion is the next op by the same process; crashed invocations
    (whose process never completes) pair with nothing (mirrors
    jepsen.history pair-index semantics used at checker.clj:782-804).
    """

    def __init__(self, ops: Iterable, assign_indices: bool | None = None):
        lst = []
        for o in ops:
            if isinstance(o, dict):
                o = op(**o)
            lst.append(o)
        if assign_indices is None:
            assign_indices = any(o.index is None or o.index < 0 for o in lst)
        if assign_indices:
            lst = [o.copy(index=i) if o.index != i else o
                   for i, o in enumerate(lst)]
        self._ops: list[Op] = lst
        self._pair_index: np.ndarray | None = None
        self._pos_by_index: dict | None = None

    # -- Sequence protocol --------------------------------------------------

    def __len__(self):
        return len(self._ops)

    def __getitem__(self, i):
        if isinstance(i, slice):
            return History(self._ops[i], assign_indices=False)
        return self._ops[i]

    def __iter__(self) -> Iterator[Op]:
        return iter(self._ops)

    def __repr__(self):
        return f"History<{len(self._ops)} ops>"

    def __eq__(self, other):
        if isinstance(other, History):
            return self._ops == other._ops
        if isinstance(other, list):
            return self._ops == other
        return NotImplemented

    # -- filters ------------------------------------------------------------

    def filter(self, pred: Callable[[Op], bool]) -> "History":
        return History([o for o in self._ops if pred(o)], assign_indices=False)

    def invokes(self) -> "History":
        return self.filter(is_invoke)

    def oks(self) -> "History":
        return self.filter(is_ok)

    def fails(self) -> "History":
        return self.filter(is_fail)

    def infos(self) -> "History":
        return self.filter(is_info)

    def client_ops(self) -> "History":
        return self.filter(is_client_op)

    def nemesis_ops(self) -> "History":
        return self.filter(lambda o: not is_client_op(o))

    # -- pairing ------------------------------------------------------------

    def pair_index(self) -> np.ndarray:
        """int64 array mapping each op's *position in this history* to its
        pair's position (-1 when unpaired). Invocations point at their
        completion and vice versa."""
        if self._pair_index is None:
            n = len(self._ops)
            pairs = np.full(n, -1, dtype=np.int64)
            open_invokes: dict[Any, int] = {}
            for i, o in enumerate(self._ops):
                if o.type == INVOKE:
                    open_invokes[o.process] = i
                elif o.type in _COMPLETION_TYPES:
                    j = open_invokes.pop(o.process, None)
                    if j is not None:
                        pairs[i] = j
                        pairs[j] = i
            self._pair_index = pairs
        return self._pair_index

    def _position_of(self, o: Op) -> int:
        """Position of an op in this history. O(1) when indices are dense
        positions (the common, unfiltered case); falls back to an
        index->position map for filtered/sliced histories."""
        n = len(self._ops)
        i = o.index
        if 0 <= i < n and self._ops[i] is o:
            return i
        if self._pos_by_index is None:
            self._pos_by_index = {op.index: p
                                  for p, op in enumerate(self._ops)}
        p = self._pos_by_index.get(i)
        if p is None:
            raise KeyError(f"op with index {i} is not in this history")
        return p

    def completion(self, o: Op) -> Op | None:
        """The completion op for an invocation (or None if it never
        completed)."""
        j = self.pair_index()[self._position_of(o)]
        return self._ops[j] if j >= 0 else None

    def invocation(self, o: Op) -> Op | None:
        """The invocation op for a completion."""
        j = self.pair_index()[self._position_of(o)]
        return self._ops[j] if j >= 0 else None

    # -- folds --------------------------------------------------------------

    def fold(self, f: Callable[[Any, Op], Any], init: Any) -> Any:
        """Sequential fold; the reference's parallel h/fold collapses to
        this on the host — TPU checkers use the SoA encoding instead."""
        acc = init
        for o in self._ops:
            acc = f(acc, o)
        return acc

    # -- SoA encoding -------------------------------------------------------

    def to_soa(self, f_codes: dict | None = None) -> "SoaHistory":
        return SoaHistory.from_history(self, f_codes=f_codes)


class SoaHistory:
    """Structure-of-arrays view of a history: dense int columns ready to be
    packed onto a device.

    Columns (all length n):
      time      int64  nanoseconds
      type      int8   0=invoke 1=ok 2=fail 3=info
      process   int32  dense process ids (nemesis & named → negative)
      f         int32  interned op function code
      pair      int64  index of pair op, -1 if none

    Values are history-specific and encoded by each checker's own packer
    (see jepsen_tpu.tpu.encode)."""

    TYPE_CODES = {INVOKE: 0, OK: 1, FAIL: 2, INFO: 3}

    def __init__(self, time, type_, process, f, pair, f_codes, process_codes,
                 ops):
        self.time = time
        self.type = type_
        self.process = process
        self.f = f
        self.pair = pair
        self.f_codes = f_codes
        self.process_codes = process_codes
        self.ops = ops

    @classmethod
    def from_history(cls, h: History, f_codes: dict | None = None):
        n = len(h)
        time = np.zeros(n, dtype=np.int64)
        type_ = np.zeros(n, dtype=np.int8)
        process = np.zeros(n, dtype=np.int32)
        f_col = np.full(n, -1, dtype=np.int32)
        f_codes = dict(f_codes) if f_codes else {}
        process_codes: dict[Any, int] = {}
        next_named = -1
        for i, o in enumerate(h):
            time[i] = o.time or 0
            type_[i] = cls.TYPE_CODES.get(o.type, 3)
            p = o.process
            if isinstance(p, int):
                process[i] = p
            else:
                if p not in process_codes:
                    process_codes[p] = next_named
                    next_named -= 1
                process[i] = process_codes[p]
            if o.f is not None:
                if o.f not in f_codes:
                    f_codes[o.f] = len(f_codes)
                f_col[i] = f_codes[o.f]
        return cls(time, type_, process, f_col, h.pair_index(), f_codes,
                   process_codes, h)


def history(ops: Iterable) -> History:
    """Builds a History from Ops or dicts, assigning indices as needed."""
    return History(ops)
