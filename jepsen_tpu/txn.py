"""Transaction micro-op algebra.

A transaction is a list of micro-ops ("mops") [f, k, v]:
  ["r", k, v-or-None]   read key k, observing v
  ["w", k, v]           write v to k
  ["append", k, v]      append v to the list at k

Capability reference: txn/src/jepsen/txn.clj (reduce-mops 6-28,
ext-reads 48-63, ext-writes 65-80) — the external read/write sets feed
elle-style dependency inference.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable


def reduce_mops(f: Callable, init: Any, txn: Iterable) -> Any:
    """Fold f(acc, [fk, k, v]) over the mops of a transaction
    (txn.clj:6-28)."""
    acc = init
    for mop in txn:
        acc = f(acc, mop)
    return acc


def ext_reads(txn: Iterable) -> dict:
    """Externally visible reads: the first read of each key *before any
    write to it* in this txn observes external state (txn.clj:48-63)."""
    ignore: set = set()
    reads: dict = {}
    for fk, k, v in txn:
        if fk == "r":
            if k not in ignore and k not in reads:
                reads[k] = v
        else:  # any write form masks later reads of k
            ignore.add(k)
    return reads


def ext_writes(txn: Iterable) -> dict:
    """Externally visible writes: the last write of each key
    (txn.clj:65-80)."""
    writes: dict = {}
    for fk, k, v in txn:
        if fk != "r":
            writes[k] = v
    return writes


def writes(txn: Iterable) -> dict:
    """All written values per key, in order (list-append txns make every
    append externally visible)."""
    out: dict = {}
    for fk, k, v in txn:
        if fk != "r":
            out.setdefault(k, []).append(v)
    return out


def keys(txn: Iterable) -> set:
    return {k for _f, k, _v in txn}
