"""libfaketime wrappers: per-process clock-rate skew.

Capability reference: jepsen/src/jepsen/faketime.clj — build the
pinned libfaketime fork (8-23), generate a wrapper script running a
binary under `faketime -m -f "+OFFSETs xRATE"` (25-36), atomically
swap a binary for its wrapper / restore it (38-56), and `rand-factor`
for choosing clock rates spread around 1 (58-65).
"""

from __future__ import annotations

import random

from . import control
from .control import util as cu

REPO_URL = "https://github.com/jepsen-io/libfaketime.git"
BRANCH = "0.9.6-jepsen1"
BUILD_DIR = "/tmp/jepsen/libfaketime-jepsen"


def install() -> None:
    """Builds + installs the patched libfaketime (faketime.clj:8-23)."""
    with control.su():
        control.exec_("mkdir", "-p", "/tmp/jepsen")
        if not cu.exists_p(BUILD_DIR):
            control.exec_("git", "clone", REPO_URL, BUILD_DIR)
        with control.cd(BUILD_DIR):
            control.exec_("git", "checkout", BRANCH)
            control.exec_("make")
            control.exec_("make", "install")


def script(cmd: str, init_offset: float, rate: float) -> str:
    """A wrapper script body invoking cmd under faketime
    (faketime.clj:25-36)."""
    off = int(init_offset)
    sign = "-" if off < 0 else "+"
    return ("#!/bin/bash\n"
            f'faketime -m -f "{sign}{abs(off)}s x{float(rate)}" '
            f'{cmd} "$@"\n')


def wrap(cmd: str, init_offset: float, rate: float) -> None:
    """Replaces the executable at cmd with a faketime wrapper calling
    the original (moved to cmd.no-faketime). Idempotent
    (faketime.clj:38-48)."""
    orig = f"{cmd}.no-faketime"
    body = script(orig, init_offset, rate)
    if not cu.exists_p(orig):
        control.exec_("mv", cmd, orig)
    cu.write_file(body, cmd)
    control.exec_("chmod", "a+x", cmd)


def unwrap(cmd: str) -> None:
    """Restores the original binary if a wrapper is installed
    (faketime.clj:50-56)."""
    orig = f"{cmd}.no-faketime"
    if cu.exists_p(orig):
        control.exec_("mv", orig, cmd)


def rand_factor(factor: float, rng=None) -> float:
    """A clock rate near 1 such that across calls, max_rate <= factor
    * min_rate (faketime.clj:58-65)."""
    rng = rng or random
    hi = 2.0 / (1.0 + 1.0 / factor)
    lo = hi / factor
    return lo + rng.random() * (hi - lo)
