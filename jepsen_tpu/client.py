"""Client protocol: applies operations to the database under test.

Capability reference: jepsen/src/jepsen/client.clj (Client protocol 9-27,
Reusable 29-44, Validate 64-114, timeout wrapper 116-148, noop client).
"""

from __future__ import annotations

from typing import Any, Callable

from . import util
from .history import Op


class Client:
    """A client opens a connection to one node and applies ops.

    Lifecycle: open(test, node) -> setup(test) -> invoke(test, op)* ->
    teardown(test) -> close(test). open/close must not affect the logical
    state of the test."""

    def open(self, test, node) -> "Client":
        return self

    def setup(self, test) -> "Client":
        return self

    def invoke(self, test, op: Op) -> Op:
        raise NotImplementedError

    def teardown(self, test) -> None:
        pass

    def close(self, test) -> None:
        pass

    def reusable(self, test) -> bool:
        """If True, this client survives a crashed invocation and can be
        reused by the replacement process (Reusable protocol,
        client.clj:29-44)."""
        return False


def is_reusable(client, test) -> bool:
    try:
        return bool(client.reusable(test))
    except Exception:  # noqa: BLE001 - parity with is-reusable? fallback
        return False


class NoopClient(Client):
    """Completes every op :ok without talking to anything."""

    def invoke(self, test, op):
        return op.copy(type="ok")


noop = NoopClient()


class InvalidCompletion(Exception):
    def __init__(self, op, op2, problems):
        self.op = op
        self.op2 = op2
        self.problems = problems
        super().__init__(f"invalid completion for {op!r}: {op2!r} ({problems})")


class Validate(Client):
    """Asserts invoke returns a completion with legal type and unchanged
    process/f (client.clj:64-114)."""

    def __init__(self, client: Client):
        self.client = client

    def open(self, test, node):
        res = self.client.open(test, node)
        if not isinstance(res, Client):
            raise TypeError(f"open should return a Client, got {res!r}")
        return Validate(res)

    def setup(self, test):
        return Validate(self.client.setup(test))

    def invoke(self, test, op):
        op2 = self.client.invoke(test, op)
        problems = []
        if not isinstance(op2, Op):
            problems.append("should be an Op")
        else:
            if op2.type not in ("ok", "info", "fail"):
                problems.append("type should be ok, info, or fail")
            if op2.process != op.process:
                problems.append("process should be the same")
            if op2.f != op.f:
                problems.append("f should be the same")
        if problems:
            raise InvalidCompletion(op, op2, problems)
        return op2

    def teardown(self, test):
        self.client.teardown(test)

    def close(self, test):
        self.client.close(test)

    def reusable(self, test):
        return is_reusable(self.client, test)


def validate(client: Client) -> Validate:
    return Validate(client)


class Timeout(Client):
    """Times out invocations after timeout_ms (or (f op) -> ms), completing
    them :info with error 'timeout' (client.clj:116-148)."""

    def __init__(self, timeout_fn: Callable[[Op], float], client: Client):
        self.timeout_fn = timeout_fn
        self.client = client

    def open(self, test, node):
        return Timeout(self.timeout_fn, self.client.open(test, node))

    def setup(self, test):
        return Timeout(self.timeout_fn, self.client.setup(test))

    def invoke(self, test, op):
        ms = self.timeout_fn(op)
        return util.timeout(ms / 1000.0,
                            lambda: self.client.invoke(test, op),
                            default=op.copy(type="info", error="timeout"))

    def teardown(self, test):
        self.client.teardown(test)

    def close(self, test):
        self.client.close(test)

    def reusable(self, test):
        return is_reusable(self.client, test)


def timeout(timeout_or_fn, client: Client) -> Timeout:
    if callable(timeout_or_fn):
        return Timeout(timeout_or_fn, client)
    return Timeout(lambda _op: timeout_or_fn, client)


class Traced(Client):
    """Per-call client tracing (the dgraph/src/jepsen/dgraph/trace.clj
    analog): wraps invoke — and reopen-during-invoke — in a 'client'
    child span of the ambient op trace, so the client round-trip is
    visible as its own slice under the op's lifetime. A no-op when
    tracing is disabled (jepsen_tpu.tracing gates every record on one
    enabled check)."""

    def __init__(self, client: Client):
        self.client = client

    def open(self, test, node):
        from . import tracing

        with tracing.span("client", "client.open",
                          node=util.name_str(node)
                          if node is not None else None):
            return Traced(self.client.open(test, node))

    def setup(self, test):
        return Traced(self.client.setup(test))

    def invoke(self, test, op):
        from . import tracing

        with tracing.span("client", f"client.{op.f}") as rec:
            op2 = self.client.invoke(test, op)
            if rec is not None:
                rec.setdefault("attrs", {})["type"] = op2.type
            return op2

    def teardown(self, test):
        self.client.teardown(test)

    def close(self, test):
        from . import tracing

        with tracing.span("client", "client.close"):
            self.client.close(test)

    def reusable(self, test):
        return is_reusable(self.client, test)


def traced(client: Client) -> Traced:
    return Traced(client)


def should_trace(test) -> bool:
    """Whether the interpreter should wrap this test's client in
    Traced: tracing must be on for the run (test['trace?'], wired by
    core.run), and suites opt out of per-call client spans with
    test['trace_clients?'] = False (or force the wrapper on a client
    they build themselves via traced())."""
    from . import tracing

    return (tracing.get().enabled
            and test.get("trace_clients?", True) is not False)


def definite_http_failure(e: Exception) -> bool:
    """True when an HTTP request certainly never executed — a refused
    connection — so the op is a safe definite :fail. Timeouts, resets
    and 5xx are indeterminate (:info): the server may have applied the
    write before the reply was lost. Shared by the HTTP-driven suites
    (the reference's suites each carry a with-errors macro making the
    same split, e.g. consul/client.clj with-errors)."""
    import urllib.error

    if isinstance(e, urllib.error.URLError):
        reason = getattr(e, "reason", None)
        return isinstance(reason, ConnectionRefusedError)
    return isinstance(e, ConnectionRefusedError)
