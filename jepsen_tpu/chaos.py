"""Harness chaos rig: seeded fault injection for the harness ITSELF.

Jepsen injects faults into the system under test; this module injects
faults into *jepsen's own plumbing* — flaky control transports, client
calls that time out, duplicate, or blow up — to prove the pipeline
keeps its crash-safety promises under the same abuse it dishes out.
The invariants a chaotic run must keep (tests/test_chaos.py asserts
all of them):

  1. the run TERMINATES — no fault wedges the interpreter;
  2. the history stays WELL-FORMED (validate_history below);
  3. teardown HEALS — the final heal fires even when the nemesis died;
  4. the STORE VALIDATES — history.jlog is fully readable, results
     land;
  5. analysis SUCCEEDS OR DEGRADES CLEANLY — valid? is True, False, or
     'unknown', never an exception.

Faults are driven by util.seeded_rng, so a failing combination replays
from its seed. Rates are per-call probabilities:

  drop-connection  control: TransportError BEFORE the command runs
                   client:  definite :fail (the op never executed)
  command-timeout  control: the command RUNS, then TransportError (the
                   classic indeterminate window — a retry double-
                   applies, exactly the hazard retries create)
                   client:  the op RUNS, then completes :info
  duplicate        the command/op is applied twice (an internally
                   retrying client), completion reports the second run
  exception        client only: the invoke raises — the interpreter
                   must crash the worker to :info and reincarnate the
                   process

See doc/robustness.md.
"""

from __future__ import annotations

import threading
from collections import Counter
from typing import Optional

from . import client as jclient
from . import coverage, telemetry, util
from .nemesis import core as _jnemesis_core
from .control.core import Action, Remote, Result, Session, TransportError
from .history import History

DEFAULT_REMOTE_RATES = {
    "drop-connection": 0.05,
    "command-timeout": 0.05,
    "duplicate": 0.02,
}

DEFAULT_CLIENT_RATES = {
    "drop-connection": 0.05,
    "command-timeout": 0.05,
    "duplicate": 0.03,
    "exception": 0.03,
}


class ChaosError(RuntimeError):
    """The injected client-side failure (a 'bug' in the client/worker
    the interpreter must absorb as a crash-to-:info)."""


class _Injector:
    """Shared seeded dice + tally. One per wrapped session/client so
    call sequences stay deterministic per (seed, scope)."""

    def __init__(self, seed, scope: tuple, rates: dict,
                 tally: Counter):
        self.rng = util.seeded_rng(seed, *scope)
        self.rates = rates
        self.tally = tally

    def roll(self) -> Optional[str]:
        """At most one fault per call: the rates partition [0, 1)."""
        r = self.rng.random()
        acc = 0.0
        for kind, p in self.rates.items():
            acc += p
            if r < acc:
                self.tally[kind] += 1
                telemetry.count(f"chaos.{kind}")
                # harness faults are coverage cells too (`harness-*`
                # kinds): a run abused by the chaos rig exercises the
                # pipeline's crash-safety column in the atlas
                coverage.record_harness(kind)
                return kind
        return None


class ChaosSession(Session):
    def __init__(self, inner: Session, inj: _Injector, node):
        self.inner = inner
        self.inj = inj
        self.node = node

    def execute(self, action: Action) -> Result:
        kind = self.inj.roll()
        if kind == "drop-connection":
            raise TransportError("chaos: connection dropped",
                                 node=self.node, cmd=action.cmd)
        res = self.inner.execute(action)
        if kind == "duplicate":
            res = self.inner.execute(action)
        elif kind == "command-timeout":
            # the command RAN; the caller only sees a dead transport
            raise TransportError("chaos: command timed out after "
                                 "completing", node=self.node,
                                 cmd=action.cmd)
        return res

    def upload(self, local_paths, remote_path) -> None:
        if self.inj.roll() == "drop-connection":
            raise TransportError("chaos: connection dropped",
                                 node=self.node)
        return self.inner.upload(local_paths, remote_path)

    def download(self, remote_paths, local_path) -> None:
        if self.inj.roll() == "drop-connection":
            raise TransportError("chaos: connection dropped",
                                 node=self.node)
        return self.inner.download(remote_paths, local_path)

    def disconnect(self) -> None:
        self.inner.disconnect()


class ChaosRemote(Remote):
    """Wraps a Remote so every session misbehaves with seeded
    probabilities. `tally` (a Counter) records what was injected."""

    _guarded_by_lock = {"_lock": ("_n_conns",)}

    def __init__(self, inner: Remote, seed=0, rates: dict | None = None,
                 connect_rate: float = 0.0):
        self.inner = inner
        self.seed = seed
        self.rates = dict(DEFAULT_REMOTE_RATES if rates is None
                          else rates)
        self.connect_rate = connect_rate
        self.tally: Counter = Counter()
        self._lock = threading.Lock()
        self._n_conns: Counter = Counter()

    def connect(self, conn_spec: dict) -> Session:
        host = conn_spec.get("host")
        with self._lock:
            self._n_conns[host] += 1
            nth = self._n_conns[host]
        inj = _Injector(self.seed, ("remote", str(host), nth),
                        self.rates, self.tally)
        if self.connect_rate and inj.rng.random() < self.connect_rate:
            self.tally["connect-refused"] += 1
            telemetry.count("chaos.connect-refused")
            raise TransportError("chaos: connect refused", node=host)
        return ChaosSession(self.inner.connect(conn_spec), inj, host)


class ChaosClient(jclient.Client):
    """Wraps a Client so invocations misbehave with seeded
    probabilities. Each fault maps to an HONEST completion — a dropped
    op (never ran) is a definite :fail, a timed-out op (ran!) is
    :info, an injected exception crashes the worker — so a correct
    checker over a chaotic history still reaches a sound verdict."""

    def __init__(self, inner: jclient.Client, seed=0,
                 rates: dict | None = None, tally: Counter | None = None,
                 _inj: _Injector | None = None,
                 _shared=None):
        self.inner = inner
        self.seed = seed
        self.rates = dict(DEFAULT_CLIENT_RATES if rates is None
                          else rates)
        self.tally = tally if tally is not None else Counter()
        self._inj = _inj
        # open-counter shared across the open tree (workers reopen
        # clients on process reincarnation: each reopen needs a fresh
        # deterministic stream)
        self._shared = _shared if _shared is not None else \
            {"lock": threading.Lock(), "opens": Counter()}

    def open(self, test, node):
        with self._shared["lock"]:
            self._shared["opens"][node] += 1
            nth = self._shared["opens"][node]
        inj = _Injector(self.seed, ("client", str(node), nth),
                        self.rates, self.tally)
        return ChaosClient(self.inner.open(test, node), self.seed,
                           self.rates, self.tally, _inj=inj,
                           _shared=self._shared)

    def setup(self, test):
        self.inner.setup(test)
        return self

    def invoke(self, test, op):
        kind = self._inj.roll() if self._inj is not None else None
        if kind == "exception":
            raise ChaosError("chaos: injected worker exception")
        if kind == "drop-connection":
            # never reached the database: definite fail
            return op.copy(type="fail", error="chaos: connection "
                                              "refused")
        op2 = self.inner.invoke(test, op)
        if kind == "duplicate":
            op2 = self.inner.invoke(test, op)
        elif kind == "command-timeout":
            # the op took effect but the reply was lost: indeterminate
            return op.copy(type="info", error="chaos: timeout")
        return op2

    def teardown(self, test):
        self.inner.teardown(test)

    def close(self, test):
        self.inner.close(test)

    def reusable(self, test):
        return jclient.is_reusable(self.inner, test)


class CrashingNemesis(_jnemesis_core.Nemesis):
    """A nemesis whose teardown dies — the case final heal must still
    fire (core.final_heal). Wraps any nemesis; setup/invoke delegate."""

    def __init__(self, inner, crash_teardown: bool = True):
        self.inner = inner
        self.crash_teardown = crash_teardown

    def setup(self, test):
        return CrashingNemesis(self.inner.setup(test),
                               self.crash_teardown)

    def invoke(self, test, op):
        return self.inner.invoke(test, op)

    def teardown(self, test):
        if self.crash_teardown:
            telemetry.count("chaos.nemesis-teardown-crashes")
            coverage.record_harness("nemesis-teardown-crash")
            raise ChaosError("chaos: nemesis teardown crashed")
        self.inner.teardown(test)

    def fs(self):
        return self.inner.fs()

    def fault_kinds(self):
        return self.inner.fault_kinds()


# ---------------------------------------------------------------------------
# Fleet transport chaos
# ---------------------------------------------------------------------------

DEFAULT_FLEET_RATES = {
    "drop-frame": 0.05,       # the frame vanishes (ack never comes)
    "duplicate-frame": 0.04,  # sent twice (server dedups by seq)
    "reorder-frame": 0.04,    # held back; rides behind the next frame
    "truncate-frame": 0.03,   # half a frame, then the socket dies
}


class ChaosFleetTransport:
    """Seeded chaos on the fleet client's frame stream
    (jepsen_tpu.fleet.client.Transport seam): frames are dropped,
    duplicated, reordered, or torn mid-frame with per-send
    probabilities, driving the exact recovery machinery a hostile
    network exercises — ack timeouts, seq-dedup, resync rewinds, and
    torn-frame reconnects. The invariant (tests/test_fleet.py): a
    chaos-wrapped stream still journals the identical op sequence and
    yields the identical verdict + certificate as a clean one.

    One injector per connection attempt keeps the fault schedule
    deterministic per (seed, connection ordinal) even as retries
    reconnect. `hello` frames are exempt from drop/reorder (a client
    whose every hello is eaten isn't a transport test, it's a timeout
    test) but NOT from truncate — a torn hello must also recover."""

    _guarded_by_lock = {"_lock": ("_conns", "_inj", "_inj_sock",
                                  "_held")}

    def __init__(self, seed=0, rates: dict | None = None,
                 tally: Counter | None = None):
        self.seed = seed
        self.rates = dict(DEFAULT_FLEET_RATES if rates is None
                          else rates)
        self.tally = tally if tally is not None else Counter()
        self._lock = threading.Lock()
        self._conns = 0
        self._inj: Optional[_Injector] = None
        self._inj_sock = None
        self._held: Optional[bytes] = None  # reordered frame in limbo

    def _injector(self, sock) -> _Injector:
        # a new socket object = a new connection: fresh seeded stream
        with self._lock:
            if self._inj is None or self._inj_sock is not sock:
                self._conns += 1
                self._inj = _Injector(
                    self.seed, ("fleet", self._conns),
                    self.rates, self.tally)
                self._inj_sock = sock
                self._held = None
            return self._inj

    def send(self, sock, msg: dict) -> None:
        import socket as _socket

        from .fleet import wire

        inj = self._injector(sock)
        kind = inj.roll()
        buf = wire.frame_msg(msg)
        is_hello = msg.get("type") == "hello"
        if kind == "truncate-frame" and len(buf) > 8:
            # half a frame on the wire, then a dead socket: the
            # receiver sees a torn tail and both sides resync
            try:
                sock.sendall(buf[:len(buf) // 2])
                sock.shutdown(_socket.SHUT_RDWR)
            except OSError:
                pass
            raise wire.FrameError("chaos: frame torn mid-send")
        if kind == "drop-frame" and not is_hello:
            return  # vanished: the ack timeout will notice
        with self._lock:
            held, self._held = self._held, None
            if kind == "reorder-frame" and not is_hello \
                    and held is None:
                self._held = buf  # rides behind the NEXT frame
                return
        try:
            if held is not None:
                sock.sendall(buf + held)  # swapped order
            else:
                sock.sendall(buf)
                if kind == "duplicate-frame":
                    sock.sendall(buf)
        except OSError as e:
            raise wire.FrameError(f"send failed: {e}") from e

    def recv(self, sock) -> dict:
        from .fleet import wire

        return wire.recv_msg(sock)


# ---------------------------------------------------------------------------
# Durability chaos (checkpoint-and-extend, doc/robustness.md)
# ---------------------------------------------------------------------------

DEFAULT_WAL_FAULT_RATES = {
    "enospc": 0.05,
    "eio": 0.05,
}

DEFAULT_CKPT_FAULT_RATES = {
    "enospc": 0.05,
    "eio": 0.05,
    "torn-ckpt": 0.05,
    "stale-ckpt": 0.03,
}


class DurabilityChaos:
    """Seeded durability faults on the fleet's two write-behind paths
    — WAL appends (fleet.wal.set_fault_hook) and checkpoint writes
    (tpu.ckpt.set_fault_hook). The invariants (tests/test_fleet.py):
    the server SHEDS un-journalable chunks with retry-after and an
    honest degraded stamp (never crashes, never acks bytes it didn't
    journal), torn/stale checkpoints are detected-and-discarded on
    read, and the verdict every stream eventually reaches is
    byte-identical to a solo run's.

      enospc / eio   OSError raised from the write call itself
      torn-ckpt      the checkpoint lands truncated mid-frame (the
                     atomic-rename discipline normally prevents this;
                     the injection simulates a broken filesystem)
      stale-ckpt     the PREVIOUS checkpoint's bytes land instead of
                     the new ones (a frozen cache) — valid framing,
                     wrong frontier: the digest screen must catch it

    Use as a context manager; hooks are process-global, so one rig at
    a time."""

    _guarded_by_lock = {"_lock": ("_last_ckpt",)}

    def __init__(self, seed=0, wal_rates: dict | None = None,
                 ckpt_rates: dict | None = None,
                 tally: Counter | None = None):
        self.tally = tally if tally is not None else Counter()
        self._wal_inj = _Injector(
            seed, ("durability", "wal"),
            dict(DEFAULT_WAL_FAULT_RATES if wal_rates is None
                 else wal_rates), self.tally)
        self._ckpt_inj = _Injector(
            seed, ("durability", "ckpt"),
            dict(DEFAULT_CKPT_FAULT_RATES if ckpt_rates is None
                 else ckpt_rates), self.tally)
        self._lock = threading.Lock()
        self._last_ckpt: dict[str, bytes] = {}

    def __enter__(self) -> "DurabilityChaos":
        from .fleet import wal as fwal
        from .tpu import ckpt as tckpt

        fwal.set_fault_hook(self._wal_hook)
        tckpt.set_fault_hook(self._ckpt_hook)
        return self

    def __exit__(self, *exc) -> None:
        from .fleet import wal as fwal
        from .tpu import ckpt as tckpt

        fwal.set_fault_hook(None)
        tckpt.set_fault_hook(None)

    @staticmethod
    def _oserror(kind: str) -> OSError:
        import errno

        code = errno.ENOSPC if kind == "enospc" else errno.EIO
        return OSError(code, f"chaos: injected {kind}")

    def _wal_hook(self, path, rec) -> None:
        kind = self._wal_inj.roll()
        if kind in ("enospc", "eio"):
            raise self._oserror(kind)

    def _ckpt_hook(self, path, data: bytes) -> bytes:
        kind = self._ckpt_inj.roll()
        if kind in ("enospc", "eio"):
            raise self._oserror(kind)
        with self._lock:
            prev = self._last_ckpt.get(str(path))
            self._last_ckpt[str(path)] = data
        if kind == "torn-ckpt":
            return data[:max(len(data) // 2, 1)]
        if kind == "stale-ckpt" and prev is not None:
            return prev
        return data


def corrupt_checkpoint(path, mode: str = "torn") -> None:
    """Damages an on-disk checkpoint file in exactly the ways
    `tpu.ckpt.read` must detect and discard:

      torn      truncated mid-frame (short payload)
      garbage   one payload byte flipped (CRC mismatch)
      magic     the magic scribbled (not a checkpoint at all)
    """
    from pathlib import Path

    p = Path(path)
    buf = bytearray(p.read_bytes())
    if mode == "torn":
        buf = buf[:max(len(buf) // 2, 9)]
    elif mode == "garbage":
        buf[-1] ^= 0xFF
    elif mode == "magic":
        buf[0] ^= 0xFF
    else:
        raise ValueError(f"unknown corruption mode {mode!r}")
    p.write_bytes(bytes(buf))


# ---------------------------------------------------------------------------
# Invariant checks
# ---------------------------------------------------------------------------

_COMPLETIONS = ("ok", "fail", "info")


def validate_history(hist) -> list[str]:
    """Well-formedness problems in a history ([] = well-formed):
    contiguous indices, each client completion pairs the process's open
    invocation with the same :f, completion types legal, no client op
    on a process with an already-open invocation."""
    problems: list[str] = []
    if not isinstance(hist, History):
        hist = History(hist)
    open_inv: dict = {}
    for i, op in enumerate(hist):
        if op.index != i:
            problems.append(
                f"op {i} has index {op.index} (not contiguous)")
        if not isinstance(op.process, int):
            continue  # nemesis ops pair loosely (info/info)
        if op.type == "invoke":
            if op.process in open_inv:
                problems.append(
                    f"op {op.index}: process {op.process} invoked "
                    "while already in flight")
            open_inv[op.process] = op
        elif op.type in _COMPLETIONS:
            inv = open_inv.pop(op.process, None)
            if inv is None:
                problems.append(
                    f"op {op.index}: completion without invocation "
                    f"(process {op.process})")
            elif inv.f != op.f:
                problems.append(
                    f"op {op.index}: completion f={op.f!r} != "
                    f"invocation f={inv.f!r}")
        else:
            problems.append(
                f"op {op.index}: illegal type {op.type!r}")
    return problems
