"""Live run monitor: streaming time-series for an in-progress test.

PR 2's telemetry layer made runs explainable after the fact; this
module makes them observable *while they execute* — the gap SURVEY §5
notes between Jepsen's post-hoc perf plots and a serving stack's live
dashboards. A background sampler thread snapshots the run's vitals on
a fixed cadence and appends one JSON point per tick to a
`timeseries.jsonl` artifact next to `telemetry.jsonl`:

  - ops/s and generator-stall rate (deltas between ticks)
  - in-flight ops per worker thread, with their current ages
  - streaming latency quantiles from a mergeable log-bucket histogram
    (LogHistogram — constant memory, merge-associative across workers)
  - the active nemesis set (tracked from nemesis op completions)
  - wgl/elle/scc kernel gauges from the telemetry recorder (so device
    occupancy is visible mid-analysis, not only at exit)
  - watchdog violation counts and open telemetry spans

The interpreter feeds the monitor from its main loop (on_dispatch /
on_complete / on_stall); all hooks are a few dict updates under one
uncontended lock, cheap enough that the interpreter throughput-floor
test passes with the monitor enabled (bench.py records the overhead
delta as a BENCH line).

Because points are appended and flushed incrementally, a *different
process* (web.py's `/live/` SSE endpoint) can tail the file and stream
the run live; read_points() tolerates a torn trailing line the same
way telemetry.read_events does.
"""

from __future__ import annotations

import json
import logging
import math
import threading
from pathlib import Path
from typing import Any, Callable, Iterator

from . import telemetry, util

logger = logging.getLogger(__name__)

TIMESERIES_FILE = "timeseries.jsonl"

# Gauge prefixes worth streaming live (device-kernel health).
_LIVE_GAUGE_PREFIXES = ("wgl.", "elle.", "scc.")


# ---------------------------------------------------------------------------
# Streaming histogram
# ---------------------------------------------------------------------------

class LogHistogram:
    """A log-bucketed streaming histogram: constant memory, mergeable.

    Bucket b covers [GROWTH**b, GROWTH**(b+1)); GROWTH = 2**(1/8) puts
    every estimate within ~9% of the true value (one bucket). merge()
    is a counter add, so per-worker histograms combine associatively
    and commutatively — the property the live sampler leans on and the
    test suite checks against numpy.quantile.
    """

    GROWTH = 2 ** 0.125
    _LOG_G = math.log(GROWTH)

    def __init__(self):
        self.counts: dict[int, int] = {}
        self.zeros = 0  # values <= 0 (clock tie or skew); rank 0
        self.n = 0

    @classmethod
    def bucket_of(cls, value: float) -> int:
        return int(math.floor(math.log(value) / cls._LOG_G))

    def add(self, value: float, n: int = 1) -> None:
        if value <= 0:
            self.zeros += n
        else:
            b = self.bucket_of(value)
            self.counts[b] = self.counts.get(b, 0) + n
        self.n += n

    def merge(self, other: "LogHistogram") -> "LogHistogram":
        """A new histogram holding both datasets."""
        out = LogHistogram()
        for src in (self, other):
            for b, c in src.counts.items():
                out.counts[b] = out.counts.get(b, 0) + c
            out.zeros += src.zeros
            out.n += src.n
        return out

    def quantile(self, q: float) -> float | None:
        """Value at quantile q — the geometric midpoint of the bucket
        holding the rank-q sample; None on an empty histogram."""
        if self.n == 0:
            return None
        rank = min(self.n - 1, int(math.floor(self.n * q)))
        if rank < self.zeros:
            return 0.0
        seen = self.zeros
        for b in sorted(self.counts):
            seen += self.counts[b]
            if rank < seen:
                return self.GROWTH ** (b + 0.5)
        return self.GROWTH ** (max(self.counts) + 0.5)

    def quantiles(self, qs) -> dict:
        return {q: self.quantile(q) for q in qs}

    def to_dict(self) -> dict:
        return {"n": self.n, "zeros": self.zeros,
                "counts": {str(b): c for b, c in self.counts.items()}}

    @classmethod
    def from_dict(cls, d: dict) -> "LogHistogram":
        """Inverse of to_dict, tolerant of junk (a torn snapshot
        folds as empty rather than poisoning the observer). With
        merge() this is the cross-process path: the fleet flight
        recorder persists histograms per verdict and a restarted
        server — or an external observer — folds them back in."""
        out = cls()
        if not isinstance(d, dict):
            return out
        try:
            counts = {int(b): int(c)
                      for b, c in (d.get("counts") or {}).items()
                      if int(c) > 0}
            zeros = max(int(d.get("zeros") or 0), 0)
        except (AttributeError, TypeError, ValueError):
            return out
        out.counts = counts
        out.zeros = zeros
        # n is derived, not trusted: merge associativity needs the
        # invariant n == zeros + sum(counts) to survive round trips
        out.n = zeros + sum(counts.values())
        return out

    @classmethod
    def merge_dicts(cls, dicts) -> "LogHistogram":
        """Folds many serialized histograms (merge is associative and
        commutative, so observer-side folding order is irrelevant)."""
        out = cls()
        for d in dicts:
            out = out.merge(cls.from_dict(d))
        return out


# ---------------------------------------------------------------------------
# Nemesis activity tracking
# ---------------------------------------------------------------------------

def _nemesis_specs(test) -> list[dict]:
    """The test's nemesis activity specs, normalized by the single
    authority (reports/perf._nemesis_specs — what the graphs shade),
    with the monitor's defaults: unnamed specs display as 'nemesis'
    and no specs at all means the plain start/stop pair."""
    from .reports.perf import _nemesis_specs as perf_specs

    out = [{"name": s.get("name") or "nemesis",
            "start": s["start"], "stop": s["stop"]}
           for s in perf_specs(test or {})]
    return out or [{"name": "nemesis", "start": {"start"},
                    "stop": {"stop"}}]


# ---------------------------------------------------------------------------
# The monitor
# ---------------------------------------------------------------------------

class Monitor:
    """Collects per-op signals from the interpreter and samples them
    periodically into a time-series.

    Lifecycle (driven by core.run): Monitor(test) -> start(path) ->
    [interpreter feeds hooks] -> stop(). Tests may also drive hooks and
    sample() directly, without the thread.
    """

    DEFAULT_INTERVAL_S = 1.0

    # concurrency-lint contract (jepsen_tpu.analysis.concurrency,
    # doc/static-analysis.md): the interpreter hooks and the sampler
    # thread race on these; writes happen under _lock only. The
    # lifecycle attrs (_out/_thread/_stopped) are driven from the
    # controlling thread and deliberately not listed.
    _guarded_by_lock = {"_lock": (
        "_hist", "_completed", "_dispatched", "_stalls", "_inflight",
        "_nemesis_active", "_probe_gauges", "_points",
        "_last_t", "_last_completed", "_last_stalls")}

    def __init__(self, test: dict | None = None,
                 interval_s: float | None = None):
        test = test or {}
        self.interval_s = float(
            interval_s if interval_s is not None
            else test.get("monitor_interval_s", self.DEFAULT_INTERVAL_S))
        self._lock = threading.RLock()
        self._hist = LogHistogram()
        self._completed = 0
        self._dispatched = 0
        self._stalls = 0
        self._inflight: dict[Any, int] = {}     # thread -> invoke t (ns)
        self._nemesis_specs = _nemesis_specs(test)
        self._nemesis_active: set = set()
        self._probe_gauges: dict[str, Any] = {}
        self._probes: list[Callable] = [
            factory() for factory in (test.get("monitor_probes") or [])]
        self._points: list[dict] = []
        self._out = None
        self._stop = threading.Event()
        self._stopped = False
        self._thread: threading.Thread | None = None
        # previous tick, for rate deltas; the construction time anchors
        # the FIRST tick, so a run short enough to see only the final
        # stop() sample still gets a real ops_s (rate since start)
        # instead of a point the post-hoc graphs must drop
        self._t0 = util.relative_time_nanos()
        self._last_t: int | None = None
        self._last_completed = 0
        self._last_stalls = 0

    # -- interpreter hooks (main-loop thread) ------------------------------

    def on_dispatch(self, op, thread, now: int) -> None:
        with self._lock:
            if op.process != "nemesis":
                self._dispatched += 1
            # in-flight tracks every worker (a stuck fault activation
            # is worth seeing), but only client ops count as ops
            self._inflight[thread] = now

    def on_complete(self, op, thread, now: int) -> None:
        with self._lock:
            t0 = self._inflight.pop(thread, None)
            if op.process == "nemesis":
                # fault activations track as nemesis state, never as
                # client latency/throughput — a multi-second partition
                # start would otherwise dominate the p99
                if op.type == "info":
                    for spec in self._nemesis_specs:
                        if op.f in spec["start"]:
                            self._nemesis_active.add(spec["name"])
                        elif op.f in spec["stop"]:
                            self._nemesis_active.discard(spec["name"])
            else:
                if t0 is not None:
                    self._hist.add(now - t0)
                self._completed += 1
            for probe in self._probes:
                try:
                    probe(op, self)
                except Exception:  # noqa: BLE001 — probes are best-effort
                    logger.exception("monitor probe failed")

    def on_stall(self) -> None:
        with self._lock:
            self._stalls += 1

    def probe_gauge(self, name: str, value) -> None:
        """Record a workload-specific live gauge (e.g. kafka consumer
        lag); included in every subsequent sample point."""
        with self._lock:
            self._probe_gauges[name] = value

    # -- sampling ----------------------------------------------------------

    def histogram(self) -> LogHistogram:
        """A snapshot copy of the cumulative latency histogram."""
        with self._lock:
            return LogHistogram().merge(self._hist)

    def sample(self) -> dict:
        """One time-series point. Rates are deltas since the previous
        sample; quantiles are cumulative (the histogram streams)."""
        now = util.relative_time_nanos()
        tel = telemetry.get()
        with self._lock:
            base_t = self._last_t if self._last_t is not None \
                else self._t0
            dt_s = (now - base_t) / 1e9
            dt_s = dt_s if dt_s > 0 else None
            d_completed = self._completed - self._last_completed
            d_stalls = self._stalls - self._last_stalls
            self._last_t = now
            self._last_completed = self._completed
            self._last_stalls = self._stalls
            qs = self._hist.quantiles((0.5, 0.95, 0.99))
            point = {
                "t": now,
                "ops_s": (round(d_completed / dt_s, 2)
                          if dt_s else None),
                "stall_rate": (round(d_stalls / dt_s, 2)
                               if dt_s else None),
                "completed": self._completed,
                "dispatched": self._dispatched,
                "inflight": {util.name_str(th): now - t0
                             for th, t0 in self._inflight.items()},
                "latency_ms": {f"p{int(q * 100)}":
                               (round(v / 1e6, 3) if v is not None
                                else None)
                               for q, v in qs.items()},
                "nemesis": sorted(self._nemesis_active),
            }
            if self._probe_gauges:
                point["probes"] = dict(self._probe_gauges)
        gauges = {k: v for k, v in tel.gauges().items()
                  if k.startswith(_LIVE_GAUGE_PREFIXES)}
        if gauges:
            point["gauges"] = gauges
        wd = tel.counters().get("watchdog.violations", 0)
        if wd:
            point["watchdog"] = wd
        open_spans = [s.get("name") for s in tel.open_spans()]
        if open_spans:
            point["open_spans"] = open_spans
        return point

    def _emit(self) -> None:
        point = self.sample()
        with self._lock:
            self._points.append(point)
            if self._out is not None:
                try:
                    self._out.write(json.dumps(point, default=repr))
                    self._out.write("\n")
                    self._out.flush()  # live tailers read mid-run
                except OSError:
                    logger.exception("monitor point write failed")
                    self._out = None

    def flush_point(self) -> None:
        """Emit one point now, outside the sampler's cadence. core.run
        calls this at the case→analyze boundary so the post-hoc graphs
        always have at least one real-rate sample of the case, even
        when the run finished inside the first sampler interval."""
        try:
            self._emit()
        except Exception:  # noqa: BLE001 — observability must not sink
            logger.exception("monitor flush failed")

    # -- lifecycle ---------------------------------------------------------

    def start(self, out_path=None) -> "Monitor":
        if out_path is not None:
            try:
                p = Path(out_path)
                p.parent.mkdir(parents=True, exist_ok=True)
                self._out = open(p, "w")
            except OSError:  # observability must never sink the run;
                logger.exception("monitor artifact unavailable")
                self._out = None  # points still accumulate in memory
        with self._lock:
            if self._last_t is None:
                # re-anchor: core.run resets the relative clock between
                # Monitor construction and start
                self._t0 = util.relative_time_nanos()
        self._stop.clear()

        def run():
            while not self._stop.wait(self.interval_s):
                try:
                    self._emit()
                except Exception:  # noqa: BLE001 — sampler must not die
                    logger.exception("monitor sample failed")

        self._thread = threading.Thread(
            target=run, name="jepsen-monitor", daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        """Stops the sampler, emitting one final point so short runs
        always leave at least one behind. Idempotent: core.run stops
        the monitor before publishing results.json (so /live/ tailers
        see the final point before the end-of-run marker) and again in
        its crash-tolerant finally block."""
        if self._stopped:
            return
        self._stopped = True
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        try:
            self._emit()
        finally:
            if self._out is not None:
                self._out.close()
                self._out = None

    def points(self) -> list[dict]:
        with self._lock:
            return list(self._points)


# ---------------------------------------------------------------------------
# Reading stored artifacts
# ---------------------------------------------------------------------------

def read_points(path) -> Iterator[dict]:
    """Points from a timeseries.jsonl; a torn trailing line (the
    sampler died, or the run is still writing) is dropped rather than
    raised (telemetry.read_jsonl, the shared parser)."""
    return telemetry.read_jsonl(path)
