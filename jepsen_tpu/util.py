"""Kitchen-sink utilities: relative-time clock, retries, parallel maps.

Mirrors the roles of jepsen/src/jepsen/util.clj (relative-time-nanos
util.clj:388-407, real-pmap util.clj:71-83, timeout util.clj:430,
await-fn util.clj:443-486, with-retry util.clj:487-529,
nemesis-intervals util.clj:780).
"""

from __future__ import annotations

import math
import random
import threading
import time as _time
from concurrent.futures import ThreadPoolExecutor
from contextlib import contextmanager
from typing import Any, Callable, Iterable, Sequence

# ---------------------------------------------------------------------------
# Relative time
# ---------------------------------------------------------------------------

_relative_time_origin: int | None = None
_origin_lock = threading.Lock()


def init_relative_time(origin: int | None = None) -> int:
    """Fixes the origin of the test's linear clock (monotonic nanoseconds).

    Mirrors jepsen.util/with-relative-time (util.clj:397-407): all op
    times in a history are nanoseconds since this origin.
    """
    global _relative_time_origin
    with _origin_lock:
        _relative_time_origin = _time.monotonic_ns() if origin is None else origin
    return _relative_time_origin


def relative_time_nanos() -> int:
    """Nanoseconds since the origin fixed by init_relative_time."""
    origin = _relative_time_origin
    if origin is None:
        origin = init_relative_time()
    return _time.monotonic_ns() - origin


@contextmanager
def with_relative_time():
    """Scopes a fresh relative-time origin, restoring the old one after."""
    global _relative_time_origin
    old = _relative_time_origin
    init_relative_time()
    try:
        yield
    finally:
        with _origin_lock:
            _relative_time_origin = old


def secs_to_nanos(secs: float) -> int:
    return int(secs * 1_000_000_000)


def nanos_to_secs(nanos: float) -> float:
    return nanos / 1_000_000_000


def ms_to_nanos(ms: float) -> int:
    return int(ms * 1_000_000)


# ---------------------------------------------------------------------------
# Parallelism helpers (host-side control plane)
# ---------------------------------------------------------------------------


class RealPmapError(Exception):
    """One or more real_pmap tasks failed; carries all underlying errors."""

    def __init__(self, errors):
        self.errors = errors
        super().__init__(f"{len(errors)} parallel task(s) failed: {errors[0]!r}")


def real_pmap(f: Callable[[Any], Any], xs: Iterable[Any]) -> list:
    """Failure-propagating parallel map over a thread per element.

    Mirrors jepsen.util/real-pmap (util.clj:71-83): unlike lazy pmap, runs
    every element eagerly on its own thread and raises if any task raised.
    """
    xs = list(xs)
    if not xs:
        return []
    if len(xs) == 1:
        return [f(xs[0])]
    with ThreadPoolExecutor(max_workers=len(xs)) as pool:
        futures = [pool.submit(f, x) for x in xs]
        results, errors = [], []
        for fut in futures:
            try:
                results.append(fut.result())
            except Exception as e:  # noqa: BLE001 - propagate all task failures
                errors.append(e)
        if errors:
            raise RealPmapError(errors)
        return results


def bounded_pmap(f: Callable[[Any], Any], xs: Iterable[Any], limit: int = 16) -> list:
    """Parallel map with at most `limit` concurrent tasks."""
    xs = list(xs)
    if not xs:
        return []
    with ThreadPoolExecutor(max_workers=min(limit, len(xs))) as pool:
        return list(pool.map(f, xs))


class Timeout(Exception):
    pass


def timeout(seconds: float, f: Callable[[], Any], default: Any = Timeout) -> Any:
    """Runs f on a worker thread; if it exceeds the deadline, returns
    `default` (or raises Timeout when no default is given). The worker is
    abandoned, not interrupted — mirrors the advisory nature of
    jepsen.util/timeout (util.clj:430-442)."""
    result: list = []
    error: list = []

    def run():
        try:
            result.append(f())
        except Exception as e:  # noqa: BLE001
            error.append(e)

    t = threading.Thread(target=run, daemon=True)
    t.start()
    t.join(seconds)
    if t.is_alive():
        if default is Timeout:
            raise Timeout(f"timed out after {seconds}s")
        return default
    if error:
        raise error[0]
    return result[0]


def await_fn(
    f: Callable[[], Any],
    *,
    retry_interval: float = 1.0,
    log_interval: float = 10.0,
    log_message: str | None = None,
    timeout_secs: float = 60.0,
) -> Any:
    """Calls f repeatedly until it returns without raising; raises Timeout
    after timeout_secs. Mirrors jepsen.util/await-fn (util.clj:443-486)."""
    deadline = _time.monotonic() + timeout_secs
    last_log = _time.monotonic()
    while True:
        try:
            return f()
        except Exception as e:  # noqa: BLE001
            now = _time.monotonic()
            if now > deadline:
                raise Timeout(
                    f"await_fn timed out after {timeout_secs}s: {e!r}"
                ) from e
            if log_message and now - last_log >= log_interval:
                import logging

                logging.getLogger(__name__).info("%s (%r)", log_message, e)
                last_log = now
            _time.sleep(retry_interval)


def with_retry(
    f: Callable[[], Any],
    *,
    retries: int = 5,
    backoff: float = 1.0,
    exceptions: tuple = (Exception,),
) -> Any:
    """Calls f, retrying up to `retries` times on the given exceptions with
    linear backoff. Mirrors the common jepsen.util/with-retry idiom
    (util.clj:487-529)."""
    attempt = 0
    while True:
        try:
            return f()
        except exceptions:
            attempt += 1
            if attempt > retries:
                raise
            _time.sleep(backoff * attempt)


# ---------------------------------------------------------------------------
# Misc
# ---------------------------------------------------------------------------


class NamedLocks:
    """A family of locks keyed by name (jepsen.util/named-locks,
    util.clj:855-943): with_named_lock serializes bodies per key."""

    def __init__(self):
        self._guard = threading.Lock()
        self._locks: dict = {}

    @contextmanager
    def hold(self, name):
        with self._guard:
            lock = self._locks.setdefault(name, threading.Lock())
        with lock:
            yield


def named_locks() -> NamedLocks:
    return NamedLocks()


def meh(f: Callable[[], Any]) -> Any:
    """Calls f, returning its value, or the exception it raised instead
    of propagating it (jepsen.util/meh)."""
    try:
        return f()
    except Exception as e:  # noqa: BLE001
        return e


def name_str(x: Any) -> str:
    """Printable name for a thread/process id (int or str like 'nemesis')."""
    return str(x)


def seeded_rng(seed, *key):
    """A `random.Random` deterministically derived from (seed, *key).

    Uses a string seed (CPython seeds str via SHA-512) so replays are
    stable across processes regardless of PYTHONHASHSEED; a None seed
    yields a nondeterministic generator, matching every workload's
    "no seed = fresh randomness" convention.
    """
    return random.Random(None if seed is None else repr((seed,) + key))


def majority(n: int) -> int:
    """Smallest majority of n nodes (util.clj)."""
    return n // 2 + 1


def minority_third(n: int) -> int:
    """Largest integer strictly less than n/3 (util.clj:95-99), for
    byzantine-fault thresholds."""
    return (n - 1) // 3


def random_nonempty_subset(coll, rng: Any = None) -> list | None:
    """A randomly selected, randomly ordered, non-empty subset; None for
    an empty collection (util.clj:51-56)."""

    rng = rng or random
    coll = list(coll)
    if not coll:
        return None
    rng.shuffle(coll)
    return coll[:1 + rng.randrange(len(coll))]


def rand_distribution(dist_map: dict | None = None, rng: Any = None):
    """Random value from a distribution spec (util.clj:140-184):
    {'distribution': 'uniform', 'min': 0, 'max': 1024} |
    {'distribution': 'geometric', 'p': 1e-3} |
    {'distribution': 'one-of', 'values': [...]} |
    {'distribution': 'weighted', 'weights': {value: weight, ...}}"""

    rng = rng or random
    d = dict(dist_map or {})
    kind = d.get("distribution", "uniform")
    if kind == "uniform":
        lo = d.get("min", 0)
        hi = d.get("max", 2 ** 63 - 1)
        assert lo < hi, f"invalid distribution-map: {d}"
        return int(math.floor(lo + rng.random() * (hi - lo)))
    if kind == "geometric":
        p = d["p"]
        return int(math.ceil(math.log(rng.random()) / math.log(1.0 - p)))
    if kind == "one-of":
        values = list(d["values"])
        assert values, f"invalid distribution-map: {d}"
        return rng.choice(values)
    if kind == "weighted":
        weights = d["weights"]
        values = list(weights.keys())
        return rng.choices(values, weights=[weights[v] for v in values])[0]
    raise AssertionError(f"invalid distribution-map: {d}")


def integer_interval_set_str(xs: Iterable[int]) -> str:
    """Compact string for a set of ints, e.g. '#{1..3 5 7..9}'.

    Mirrors jepsen.util/integer-interval-set-str (util.clj:691)."""
    xs = sorted(set(xs))
    if not xs:
        return "#{}"
    parts = []
    lo = hi = xs[0]
    for x in xs[1:]:
        if x == hi + 1:
            hi = x
        else:
            parts.append(f"{lo}..{hi}" if lo != hi else f"{lo}")
            lo = hi = x
    parts.append(f"{lo}..{hi}" if lo != hi else f"{lo}")
    return "#{" + " ".join(parts) + "}"


def nemesis_intervals(history, specs=None) -> list:
    """Pairs up nemesis start/stop ops into [start, stop] intervals.

    Mirrors jepsen.util/nemesis-intervals (util.clj:780-827). `specs` is a
    list of {'start': set_of_fs, 'stop': set_of_fs} maps; defaults to
    {:start}/{:stop}.
    """
    specs = specs or [{"start": {"start"}, "stop": {"stop"}}]
    nemesis_ops = [op for op in history if op.process == "nemesis"]
    intervals = []
    for spec in specs:
        starts, stops = spec["start"], spec["stop"]
        open_start = None
        for op in nemesis_ops:
            if op.f in starts and op.type == "info":
                if open_start is None:
                    open_start = op
            elif op.f in stops and op.type == "info" and open_start is not None:
                intervals.append([open_start, op])
                open_start = None
        if open_start is not None:
            intervals.append([open_start, None])
    return intervals


def coll_scaled(n_str: str, n_nodes: int) -> int:
    """Parses a concurrency spec like '10' or '3n' (n = node count).

    Mirrors the CLI's '2n' concurrency syntax (cli.clj:64-206)."""
    s = str(n_str)
    if s.endswith("n"):
        return int(float(s[:-1] or 1) * n_nodes)
    return int(s)


def fraction_of(frac: float | str, n: int) -> int:
    if isinstance(frac, str) and frac.endswith("%"):
        return max(1, math.floor(n * float(frac[:-1]) / 100))
    return int(frac)


@contextmanager
def profile_trace(trace_dir=None):
    """Captures a JAX/XLA profiler trace (xplane protobufs viewable in
    TensorBoard/xprof) around the body when trace_dir is set; no-op
    otherwise. The kernel-level profiling hook SURVEY §5 calls for on
    top of the op-level trace combinator and perf plots."""
    if not trace_dir:
        yield
        return
    try:
        import jax
    except ImportError:
        yield
        return
    with jax.profiler.trace(str(trace_dir)):
        yield
