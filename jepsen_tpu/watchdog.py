"""Online safety watchdog: incremental checkers that watch the history
as it is produced.

"Faster linearizability checking via P-compositionality" (PAPERS.md)
observes that per-key decomposition makes incremental checking cheap;
this module applies the idea *online*: lightweight adapters subscribe
to the interpreter's completion stream and flag violations the moment
they are observed — minutes before the post-hoc checkers run — without
replacing them. Every adapter check is sound under concurrency (no
false positives from interleaving): each one only flags states no
correct system could produce given the operations *attempted* so far.

Adapters:

  register   per-key CAS-register order: an ok read (or the expected
             side of an ok cas) must be the initial value or a value
             some write/cas attempt could have installed
  counter    bounds: an ok read must lie within [sum of attempted
             negative deltas, sum of attempted positive deltas]
  set        dirty/phantom reads: an ok read may not contain an
             element whose every add attempt failed (none in flight,
             none indeterminate), or one never attempted at all

A violation raises a `watchdog` telemetry span + counter (so the live
monitor streams it) and is attached to the final results under
`watchdog` by core.analyze — which never changes the post-hoc checker
verdicts. With the opt-in `early_abort` test flag the interpreter
additionally stops the run at the first violation, so a multi-minute
test doesn't keep burning time after safety is already lost.

Configuration (test map keys):

  test["watchdog"] = True                      # all adapters
  test["watchdog"] = ["register", "counter"]   # specific adapters
  test["watchdog"] = {"adapters": ["set"], "early_abort": True}
  test["early_abort"] = True                   # flag rides separately
"""

from __future__ import annotations

import logging
from typing import Any

from . import telemetry
from .history import FAIL, INFO, INVOKE, OK, Op

logger = logging.getLogger(__name__)

MAX_VIOLATIONS = 64  # kept in full; beyond this only the count grows


def _split_key(value) -> tuple[Any, Any]:
    """(key, payload) for an op value: independent-workload ops carry
    (key, payload) *tuples* (independent.ktuple); plain ops (scalar
    values, cas [from, to] lists) live on the single default key."""
    if isinstance(value, tuple) and len(value) == 2:
        return value[0], value[1]
    return None, value


class Adapter:
    """One incremental safety check. observe() sees every journaled op
    (invocations and completions) in history order, on the interpreter
    thread; returns a violation dict or None."""

    name = "?"

    def observe(self, op: Op) -> dict | None:
        raise NotImplementedError


class RegisterAdapter(Adapter):
    """Per-key CAS-register order. Values a register can possibly hold
    are the initial value plus everything any write or cas *attempted*
    to install; an ok read outside that set — or an ok cas whose
    expected value is outside it — is impossible under any
    interleaving."""

    name = "register"

    def __init__(self, initial=None):
        self.initial = initial
        self.possible: dict = {}  # key -> set of attempted values
        self.armed = False        # first write/cas arms the checks

    def _possible(self, k) -> set:
        s = self.possible.get(k)
        if s is None:
            s = self.possible[k] = set()
        return s

    def observe(self, op):
        if op.f not in ("read", "write", "cas"):
            return None
        k, v = _split_key(op.value)
        if op.type == INVOKE:
            if op.f == "write":
                self.armed = True
                self._possible(k).add(v)
            elif op.f == "cas" and isinstance(v, (list, tuple)) \
                    and len(v) == 2:
                self.armed = True
                self._possible(k).add(v[1])
            return None
        # reads are ambiguous across workloads (counter reads are
        # numbers too): stay silent until this workload's signature
        # write appears, so co-enabled adapters never cross-flag
        if op.type != OK or not self.armed:
            return None
        if op.f == "read":
            if v is not None and v != self.initial \
                    and v not in self._possible(k):
                return {"type": "impossible-read", "key": k,
                        "value": v}
        elif op.f == "cas" and isinstance(v, (list, tuple)) \
                and len(v) == 2:
            frm = v[0]
            if frm is not None and frm != self.initial \
                    and frm not in self._possible(k):
                return {"type": "impossible-cas-from", "key": k,
                        "value": frm}
        return None


class CounterAdapter(Adapter):
    """Counter bounds. The counter starts at 0; at any instant its
    value lies within [sum of attempted negative deltas, sum of
    attempted positive deltas] — an ok read outside that envelope is
    impossible no matter which attempts actually landed."""

    name = "counter"

    def __init__(self):
        self.lo = 0
        self.hi = 0
        self.armed = False  # first add arms the read check

    def observe(self, op):
        if op.f in ("add", "increment", "inc"):
            if op.type == INVOKE and isinstance(op.value, (int, float)):
                self.armed = True
                if op.value >= 0:
                    self.hi += op.value
                else:
                    self.lo += op.value
            return None
        # arm only once the workload's signature write appears: reads
        # are ambiguous across workloads (a register read is not a
        # counter read), and an adapter enabled alongside others must
        # never flag ops that aren't its own
        if self.armed and op.f == "read" and op.type == OK \
                and isinstance(op.value, (int, float)):
            if not (self.lo <= op.value <= self.hi):
                return {"type": "counter-out-of-bounds",
                        "value": op.value,
                        "bounds": [self.lo, self.hi]}
        return None


class SetAdapter(Adapter):
    """Set dirty/phantom reads. An ok read may not contain an element
    nobody ever attempted to add (a phantom), or one where every add
    attempt is known to have failed AND none is still in flight (a
    dirty read — the failed add's effects leaked). The in-flight count
    keeps retries sound: while any attempt is outstanding the element
    may legitimately appear."""

    name = "set"

    def __init__(self):
        # (key, element) -> [outstanding attempts, possibly applied?]
        self.state: dict = {}
        self.armed_keys: set = set()  # keys with at least one add

    @staticmethod
    def _track(k, e):
        """The tracking key for element e of set k; None when the
        element isn't hashable (e.g. a workload whose reads return
        row lists) — such elements are simply not checked."""
        try:
            hash(e)
        except TypeError:
            return None
        return (k, e)

    def observe(self, op):
        if op.f == "add":
            k, e = _split_key(op.value)
            tk = self._track(k, e)
            if tk is None:
                return None
            st = self.state.get(tk)
            if st is None:
                st = self.state[tk] = [0, False]
            self.armed_keys.add(k)
            if op.type == INVOKE:
                st[0] += 1
            elif op.type in (OK, INFO):
                # INFO is indeterminate: the add may have applied
                st[0] = max(st[0] - 1, 0)
                st[1] = True
            elif op.type == FAIL:
                st[0] = max(st[0] - 1, 0)
            return None
        # arming is per key (no adds seen on a key, no claims on it —
        # same rule as CounterAdapter, sharpened for independent keys)
        if self.state and op.f == "read" and op.type == OK:
            k, elems = _split_key(op.value)
            if k not in self.armed_keys \
                    or not isinstance(elems, (list, set, tuple)):
                return None
            for e in elems:
                tk = self._track(k, e)
                if tk is None:
                    continue
                st = self.state.get(tk)
                if st is None:
                    return {"type": "phantom-read", "key": k,
                            "element": e}
                if not st[1] and st[0] == 0:
                    return {"type": "dirty-read", "key": k,
                            "element": e}
        return None


ADAPTERS = {"register": RegisterAdapter, "counter": CounterAdapter,
            "set": SetAdapter}


class Watchdog:
    """Fans completions out to adapters, records violations, and
    decides whether the interpreter should abort early. Called only
    from the interpreter's main loop — no locking needed; readers
    (sampler, web) see its state through the telemetry counter."""

    def __init__(self, adapters, early_abort: bool = False):
        self.adapters = list(adapters)
        self.early_abort = bool(early_abort)
        self.violations: list[dict] = []
        self.count = 0
        self.tripped = False

    def observe(self, op: Op) -> None:
        if op.process == "nemesis":
            return
        for a in self.adapters:
            try:
                v = a.observe(op)
            except Exception:  # noqa: BLE001 — a broken adapter must
                logger.exception("watchdog adapter %s failed", a.name)
                continue      # not take down the run
            if v is not None:
                self._record(a, v, op)

    def _record(self, adapter: Adapter, violation: dict, op: Op) -> None:
        self.count += 1
        self.tripped = True
        # the counter is what the live monitor streams (visible the
        # tick after it happens, not at exit) and counts everything;
        # the stored list, the spans, and the log lines all cap at
        # MAX_VIOLATIONS so a thoroughly-broken long run can't grow
        # memory or flood telemetry.jsonl without bound
        telemetry.count("watchdog.violations")
        if len(self.violations) >= MAX_VIOLATIONS:
            return
        v = dict(violation)
        v["adapter"] = adapter.name
        v["op-index"] = op.index
        v["time"] = op.time
        v["process"] = op.process
        self.violations.append(v)
        with telemetry.span("watchdog", adapter=adapter.name,
                            type=violation.get("type"),
                            op_index=op.index):
            pass
        logger.warning("watchdog: %s violation at op %s: %s",
                       adapter.name, op.index, violation)

    def results(self) -> dict:
        """The `watchdog` entry core.analyze attaches to the final
        results — informational: it rides NEXT to the checker verdict
        and never changes it."""
        return {"valid?": self.count == 0,
                "count": self.count,
                "early_abort": self.early_abort,
                "tripped": self.tripped,
                "violations": list(self.violations)}


def from_test(test: dict) -> Watchdog | None:
    """Builds the watchdog a test asked for; None when unconfigured."""
    spec = test.get("watchdog")
    if not spec or isinstance(spec, Watchdog):
        return spec or None
    early_abort = bool(test.get("early_abort"))
    if spec is True:
        names = list(ADAPTERS)
    elif isinstance(spec, dict):
        names = list(spec.get("adapters") or ADAPTERS)
        early_abort = bool(spec.get("early_abort", early_abort))
    else:
        names = list(spec)
    adapters = []
    for n in names:
        if isinstance(n, Adapter):
            adapters.append(n)
        elif n in ADAPTERS:
            kwargs = {}
            if n == "register" and test.get("initial") is not None:
                kwargs["initial"] = test["initial"]
            adapters.append(ADAPTERS[n](**kwargs))
        else:
            raise ValueError(
                f"unknown watchdog adapter {n!r}; "
                f"must be one of {sorted(ADAPTERS)}")
    return Watchdog(adapters, early_abort=early_abort)
