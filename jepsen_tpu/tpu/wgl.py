"""Wing & Gong / Lowe linearizability checking, host + TPU.

The reference delegates this to knossos (`checker.clj:202-233`:
`knossos.competition/analysis`, `linear/analysis`, `wgl/analysis`). Both
of knossos's searches explore *configurations* — (set of linearized ops,
model state) pairs — memoizing visited configurations. We keep that
algorithm but re-shape it for SIMD:

- A configuration is `(p, window-bitmask, state)`: every entry below `p`
  (in invocation order) is linearized; the uint32 mask covers entries
  `[p, p+W)`; `state` indexes the model's pre-tabulated state space
  (encode.py). This fixed-width encoding is exact as long as no candidate
  entry falls `>= W` past the first unlinearized entry; when that happens
  the kernel flags the history and the caller falls back to the unbounded
  host search — the kernel is sound, never wrong.
- One BFS step linearizes exactly one entry in every live configuration,
  so the search is a `lax.while_loop` of at most `m` steps over a
  fixed-size frontier `[B, F]`, batched over `B` histories (vmap over
  keys/histories is the TPU win: jepsen shards its keyspace precisely so
  histories stay short — independent.clj:2-7).
- Candidate entries: `j` may linearize next iff
  `inv_t[j] < min(ret_t[unlinearized])` — the standard minimal-op rule.
  Crashed (`:info`) entries never block (`ret_t = INF`) and may either
  take effect (a normal transition) or never happen (a "discard" action:
  mark linearized, keep the state).
- Deduplication is a sort + unique-compaction on packed config keys each
  step (the memo set of the sequential algorithm becomes per-step
  frontier dedup; BFS levels never revisit earlier levels because every
  config at level k has exactly k entries linearized).
"""

from __future__ import annotations

import functools
import logging
import os as _os
import threading as _threading
import time as _time
import warnings as _warnings
from typing import Any, Sequence

import numpy as np

from .. import history as h
from .. import telemetry
from ..checker import models as model_mod
from ..history import History
from . import profiler
from .encode import INF, Encoded, EncodingError, encode

BIG = int(INF)

logger = logging.getLogger(__name__)


# ---------------------------------------------------------------------------
# Host search (unbounded window; correctness reference and fallback)
# ---------------------------------------------------------------------------

def _min_ret(p: int, wmask: int, m, sufmin, ret_t) -> int:
    """Earliest completion among unlinearized entries at config (p,
    wmask): the candidate cutoff — only entries invoked before it may
    linearize next. Shared by the searches and witness extraction."""
    span = wmask.bit_length()
    mr = int(sufmin[min(p + span, m)])
    for i in range(span):
        if not (wmask >> i) & 1 and p + i < m:
            r = int(ret_t[p + i])
            if r < mr:
                mr = r
    return mr


def search_host(enc: Encoded, witness: bool = False) -> dict:
    """Exhaustive WGL over an Encoded history. Returns {'valid?': bool}
    plus witness info (furthest entry reached, pending ops, states) when
    witness=True and the history is invalid."""
    m = enc.m
    if m == 0:
        return {"valid?": True}
    inv_t = enc.inv_t
    ret_t = enc.ret_t
    crashed = enc.crashed
    trans = enc.trans
    sufmin = enc.suffix_min_ret()

    # config = (p, wmask, state); wmask bit i == entry p+i linearized;
    # bit 0 always clear (p is the first unlinearized entry).
    s0 = enc.init_state
    seen: set[tuple[int, int, int]] = set()
    stack: list[tuple[int, int, int]] = [(0, 0, s0)]
    seen.add((0, 0, s0))
    best_p = 0
    best_cfgs: list[tuple[int, int, int]] = [(0, 0, s0)]

    while stack:
        p, wmask, st = stack.pop()
        if p >= m:
            return {"valid?": True}
        if p > best_p:
            best_p, best_cfgs = p, []
        if p == best_p and len(best_cfgs) < 8:
            best_cfgs.append((p, wmask, st))
        min_ret = _min_ret(p, wmask, m, sufmin, ret_t)
        # candidates: unlinearized j with inv_t[j] < min_ret (inv_t sorted)
        i = 0
        while p + i < m and int(inv_t[p + i]) < min_ret:
            if not (wmask >> i) & 1:
                e = p + i
                nmask = wmask | (1 << i)
                # advance past the linearized prefix
                t = _trailing_ones(nmask)
                np_, nmask_ = p + t, nmask >> t
                s2 = int(trans[e, st])
                if s2 >= 0:
                    cfg = (np_, nmask_, s2)
                    if cfg not in seen:
                        seen.add(cfg)
                        stack.append(cfg)
                if crashed[e]:
                    cfg = (np_, nmask_, st)
                    if cfg not in seen:
                        seen.add(cfg)
                        stack.append(cfg)
            i += 1

    out: dict = {"valid?": False}
    if witness:
        out["op"] = enc.entry_ops[best_p] if best_p < m else None
        # search-dynamics telemetry: where in the history the search
        # got stuck — the witness-position percentile feeding the
        # coverage atlas and ROADMAP-3's early-exit tuning
        out["witness-entry"] = int(best_p)
        out["entry-count"] = int(m)
        cfgs = []
        for p, wmask, st in best_cfgs:
            # pending = every unlinearized entry in flight at the stuck
            # point: invoked before the earliest completion among
            # unlinearized entries (can lie well past the mask span).
            min_ret = _min_ret(p, wmask, m, sufmin, ret_t)
            pending = []
            i = 0
            while p + i < m and int(inv_t[p + i]) < min_ret:
                if not (wmask >> i) & 1:
                    pending.append(enc.entry_ops[p + i])
                    if len(pending) >= 4:
                        break
                i += 1
            cfgs.append({"model": enc.states[st], "pending": pending})
        out["configs"] = cfgs
        out["previous-ok"] = enc.entry_ops[best_p - 1] if best_p else None
    return out


def search_host_reach(enc: Encoded) -> int:
    """Exhaustive host search returning the bitmask of model states the
    history can end in (0 = not linearizable). Host analog of the
    kernel's reach mode, for per-segment fallback."""
    m = enc.m
    if m == 0:
        return 1 << enc.init_state
    inv_t, ret_t, crashed, trans = (enc.inv_t, enc.ret_t, enc.crashed,
                                    enc.trans)
    sufmin = enc.suffix_min_ret()
    seen = {(0, 0, enc.init_state)}
    stack = [(0, 0, enc.init_state)]
    out = 0
    while stack:
        p, wmask, st = stack.pop()
        if p >= m:
            out |= 1 << st
            continue
        min_ret = _min_ret(p, wmask, m, sufmin, ret_t)
        i = 0
        while p + i < m and int(inv_t[p + i]) < min_ret:
            if not (wmask >> i) & 1:
                e = p + i
                nmask = wmask | (1 << i)
                t = _trailing_ones(nmask)
                np_, nmask_ = p + t, nmask >> t
                s2 = int(trans[e, st])
                nexts = [s2] if s2 >= 0 else []
                if crashed[e]:
                    nexts.append(st)
                for s_next in nexts:
                    cfg = (np_, nmask_, s_next)
                    if cfg not in seen:
                        seen.add(cfg)
                        stack.append(cfg)
            i += 1
    return out


def _trailing_ones(x: int) -> int:
    t = 0
    while x & 1:
        x >>= 1
        t += 1
    return t


def search_host_model(model, hist: History, witness: bool = False) -> dict:
    """Object-model WGL for models whose state space can't be tabulated
    (mirrors knossos stepping model values directly)."""
    from .encode import entries as entries_fn

    ents = entries_fn(hist)
    m = len(ents)
    if m == 0:
        return {"valid?": True}
    inv_t = [e[0] for e in ents]
    ret_t = [e[1] for e in ents]
    crashed = [e[2] for e in ents]
    ops = [e[3] for e in ents]
    sufmin = [BIG] * (m + 1)
    for i in range(m - 1, -1, -1):
        sufmin[i] = min(sufmin[i + 1], ret_t[i])

    seen: set = set()
    start = (0, 0, model)
    stack = [start]
    seen.add((0, 0, model))
    best_p = 0
    best: list = [start]
    while stack:
        p, wmask, st = stack.pop()
        if p >= m:
            return {"valid?": True}
        if p > best_p:
            best_p, best = p, []
        if p == best_p and len(best) < 8:
            best.append((p, wmask, st))
        span = wmask.bit_length()
        min_ret = sufmin[min(p + span, m)]
        for i in range(span):
            if not (wmask >> i) & 1 and p + i < m:
                min_ret = min(min_ret, ret_t[p + i])
        i = 0
        while p + i < m and inv_t[p + i] < min_ret:
            if not (wmask >> i) & 1:
                e = p + i
                nmask = wmask | (1 << i)
                t = _trailing_ones(nmask)
                np_, nmask_ = p + t, nmask >> t
                st2 = st.step(ops[e])
                if not model_mod.is_inconsistent(st2):
                    cfg = (np_, nmask_, st2)
                    if cfg not in seen:
                        seen.add(cfg)
                        stack.append(cfg)
                if crashed[e]:
                    cfg = (np_, nmask_, st)
                    if cfg not in seen:
                        seen.add(cfg)
                        stack.append(cfg)
            i += 1
    out: dict = {"valid?": False}
    if witness:
        out["op"] = ops[best_p] if best_p < m else None
        out["witness-entry"] = int(best_p)
        out["entry-count"] = int(m)
        out["configs"] = [{"model": st, "pending":
                           [ops[p + i] for i in range(wmask.bit_length() + 1)
                            if p + i < m and not (wmask >> i) & 1][:4]}
                          for p, wmask, st in best]
    return out


# ---------------------------------------------------------------------------
# Batched device kernel
# ---------------------------------------------------------------------------

VALID = 1
INVALID = 0
UNKNOWN = -1
RUNNING = -2


def _next_pow2(n: int) -> int:
    return 1 << max(0, (n - 1)).bit_length()


class RangeError(Exception):
    """History too large for the kernel's f32-exact position range;
    callers fall back to the host search."""


# ---------------------------------------------------------------------------
# Device-failure degradation ladder
# ---------------------------------------------------------------------------
#
# The host searches are exact, so a device that can't run the kernel is
# a performance problem, never a correctness one: analysis steps DOWN —
# halve the batch (smaller launches fit where one big one OOMed), then
# halve the search width, then the host search as the floor — instead
# of dying. "Faster linearizability checking via P-compositionality"
# (PAPERS.md) is what makes the intermediate rungs sound: decomposed
# searches answer the same question. Every rung is counted in telemetry
# (wgl.ladder.*) and the verdict carries the path taken.

def device_error_kind(e: BaseException) -> str | None:
    """Classifies an exception from a kernel launch: 'oom' (XLA
    RESOURCE_EXHAUSTED / allocator failure — retry smaller), 'compile'
    (compilation failure — this shape is unrunnable, don't re-attempt
    it per sub-batch), 'device' (any other XLA/jax runtime failure —
    INTERNAL, device lost; degradable, the host floor is exact), or
    None (not a device failure: re-raise, it's a bug)."""
    if isinstance(e, (RangeError, EncodingError)):
        return None
    s = str(e)
    if ("RESOURCE_EXHAUSTED" in s or "Out of memory" in s
            or "out of memory" in s or "OOM" in s):
        return "oom"
    if ("error during compilation" in s or "Compilation failure" in s
            or "UNIMPLEMENTED" in s or "FAILED_PRECONDITION" in s):
        return "compile"
    if type(e).__name__ in ("XlaRuntimeError", "JaxRuntimeError"):
        # XlaRuntimeError is jax's RUNTIME error type too, not only
        # compilation: a kernel regression lands here, so this kind is
        # logged loudly (error, not warning) — verdicts stay correct
        # via the host floor, but a silently dead device path would
        # cost 10-100x per analysis
        return "device"
    return None


_ladder_local = _threading.local()


def _ladder_steps() -> list | None:
    return getattr(_ladder_local, "steps", None)


def _ladder_note(step: str) -> None:
    """Counts a degradation rung and records it on the ambient ladder
    scope (analysis attaches the path to its verdict)."""
    telemetry.count(f"wgl.ladder.{step}")
    steps = _ladder_steps()
    if steps is not None and (not steps or steps[-1] != step):
        steps.append(step)


class _ladder_scope:
    """Collects the degradation rungs walked during one analysis call
    (thread-local; nested scopes share the outermost list)."""

    def __enter__(self):
        self.own = _ladder_steps() is None
        if self.own:
            _ladder_local.steps = []
        return _ladder_local.steps

    def __exit__(self, *exc):
        if self.own:
            _ladder_local.steps = None
        return False


class _ladder_fork:
    """Records rungs on a fresh list — one result's OWN provenance —
    then merges them back onto the enclosing scope. Slicing the shared
    scope list instead would let its consecutive-dedup suppress a rung
    that belongs to a different result (e.g. chunk B's OOM after chunk
    A's in analysis_batch_streamed). Telemetry counts happen inside
    _ladder_note, so the merge only appends, never re-counts."""

    def __enter__(self):
        self.outer = _ladder_steps()
        _ladder_local.steps = []
        return _ladder_local.steps

    def __exit__(self, *exc):
        forked = _ladder_local.steps
        _ladder_local.steps = self.outer
        if self.outer is not None:
            for s in forked:
                if not self.outer or self.outer[-1] != s:
                    self.outer.append(s)
        return False


def _ladder_classify(e: BaseException, what: str) -> str:
    """One device failure becomes one counted, logged ladder rung; a
    non-device exception re-raises (a bug, not a rung)."""
    kind = device_error_kind(e)
    if kind is None:
        raise e
    _ladder_note(kind)
    log = logger.error if kind == "device" else logger.warning
    log("%s failed on device (%s: %s); degrading",
        what, kind, str(e)[:200])
    return kind


class PackedBatch:
    """A bucket of Encoded histories padded to common (M, S).

    Positions are rank-compressed per history: the kernel only compares
    invocation/completion positions, so each history's finite positions
    are remapped to their dense rank. Ranks stay < 2m < 2^22, keeping
    them exact through the kernel's f32 one-hot contractions for any
    history up to 2^21 entries (RangeError beyond)."""

    __slots__ = ("inv_t", "ret_t", "trans", "m", "sufmin",
                 "st0", "M", "S", "B", "has_crashed")

    def __init__(self, encs: Sequence[Encoded]):
        _pack_t0 = _time.monotonic_ns()
        B = len(encs)
        self.has_crashed = any(bool(e.crashed.any()) for e in encs)
        M = max((e.m for e in encs), default=0)
        # Bucket to powers of two so the jitted kernel compiles once per
        # bucket rather than once per history length. Generous floors keep
        # the number of compiled variants small; padding compute is cheap.
        M = _next_pow2(max(M, 64))
        S = _next_pow2(max((e.n_states for e in encs), default=1) or 1)
        S = max(S, 8)
        # One packed row per distinct history/segment, plus a sentinel
        # empty row at index K that batch-padding rows point at. Search
        # rows reference these via the kernel's row->segment indirection,
        # so checking the same segment from S start states shares one
        # copy of its tensors.
        K = B + 1
        self.B, self.M, self.S = B, M, S
        self.inv_t = np.full((K, M), BIG, dtype=np.int32)
        self.ret_t = np.full((K, M), BIG, dtype=np.int32)
        self.trans = np.full((K, M, S), -1, dtype=np.int32)
        self.m = np.zeros(K, dtype=np.int32)
        self.sufmin = np.full((K, M + 1), BIG, dtype=np.int32)
        for b, e in enumerate(encs):
            mm = e.m
            self.m[b] = mm
            if not mm:
                continue
            if 2 * mm >= (1 << 21):
                raise RangeError(
                    f"history with {mm} entries exceeds the kernel's "
                    "f32-exact rank range")
            fin = e.ret_t < INF
            order = np.unique(np.concatenate([e.inv_t, e.ret_t[fin]]))
            inv_r = np.searchsorted(order, e.inv_t).astype(np.int32)
            ret_r = np.full(mm, BIG, dtype=np.int32)
            ret_r[fin] = np.searchsorted(order, e.ret_t[fin])
            self.inv_t[b, :mm] = inv_r
            self.ret_t[b, :mm] = ret_r
            self.trans[b, :mm, :e.n_states] = e.trans
            self.sufmin[b, mm] = BIG
            self.sufmin[b, :mm] = np.minimum.accumulate(ret_r[::-1])[::-1]
        # batch shape profile: real entries vs padded slots — the
        # bucketing waste a tuning loop needs to see
        used = int(self.m.sum())
        slots = int(B * M)
        tel = telemetry.get()
        tel.count("wgl.batch.histories", B)
        tel.count("wgl.batch.entries", used)
        tel.count("wgl.batch.slots", slots)
        if slots:
            tel.gauge("wgl.batch.occupancy", round(used / slots, 4))
            tel.gauge("wgl.batch.padding-waste",
                      round(1 - used / slots, 4))
        # host-side packing is part of the launch pipeline's "encode"
        # wall time; aggregate-only (ensembles pack hundreds of times)
        profiler.get().record_host(
            "pack", _time.monotonic_ns() - _pack_t0, entries=used)

    def rows(self, rows: Sequence[tuple[int, int]]):
        """(row_seg, st0) int32 arrays for (segment, start-state) search
        rows, padded to a power of two with sentinel rows."""
        B = len(rows)
        Bp = _next_pow2(max(B, 1))
        row_seg = np.full(Bp, self.B, dtype=np.int32)  # sentinel = empty
        st0 = np.zeros(Bp, dtype=np.int32)
        for i, (k, s) in enumerate(rows):
            row_seg[i] = k
            st0[i] = s
        return row_seg, st0


# The packed segment tensors (inv_t/ret_t/trans/mseg/sufmin — the big
# per-launch H2D payload) are donated: every launch site converts its
# numpy PackedBatch fields to fresh device arrays per call, so nothing
# reads them after dispatch, and donation hands XLA the buffers as
# scratch instead of keeping them live across the whole search
# (graftlint R3; jepsen_tpu.analysis). Backends that can't alias them
# (CPU) just ignore the donation, with an advisory warning per
# compile — quieted by quiet_unusable_donation() below.
DONATE_ARGNUMS = (0, 1, 2, 3, 4)


def quiet_unusable_donation() -> None:
    """Narrow filter for jax's 'Some donated buffers were not usable'
    advisory, registered by the jit FACTORIES (not at import: merely
    importing this library must not mutate global warning filters).
    The filter is still process-global once a donated kernel is
    built — a per-dispatch catch_warnings would race across the
    checker thread pools — but it only fires for processes that
    actually launch these kernels, and only for this one message.
    (pytest resets filters per test; tests/conftest.py re-asserts
    it.)"""
    _warnings.filterwarnings(
        "ignore", message="Some donated buffers were not usable")


@functools.lru_cache(maxsize=None)
def _jitted_kernel():
    import jax

    from . import spmd

    spmd.enable_compile_cache()
    quiet_unusable_donation()
    return jax.jit(_kernel, static_argnames=("W", "F", "max_iters",
                                             "reach", "debug",
                                             "crash_free"),
                   donate_argnums=DONATE_ARGNUMS)


def _kernel(inv_t, ret_t, trans, mseg, sufmin, row_seg, st0,
            W: int, F: int, max_iters: int, reach: bool = False,
            debug: bool = False, crash_free: bool = False):
    """The batched WGL frontier search.

    Packed data is per-*segment* ([K, M] / [K, M, S]); search rows are
    (row_seg[b], st0[b]) pairs so many rows (e.g. S start states) share
    one segment's tensors.

    reach=False: returns int8 results [B]: 1 valid / 0 invalid /
    -1 unknown (fall back to host); stops each history at first success.

    reach=True: exhausts each history's search and returns
    (out_mask uint32 [B] — bit s set iff final state s is reachable —
    and unknown bool [B]); used by the segment-parallel long-history
    path, which composes per-segment reachability. Requires S <= 32.

    Search-dynamics telemetry: both modes also return three int32
    [max_iters] level-series — live frontier configs entering each BFS
    level, unique successor states produced by it, and dedup hits
    (generated minus unique) — summed over the batch. _drain folds
    them into profiler records and wgl.search.* telemetry; cost is
    three scalar reductions + dynamic_update_slice per level."""
    import jax
    import jax.numpy as jnp

    B = row_seg.shape[0]
    K, M = inv_t.shape
    INFi = jnp.int32(BIG)
    u1 = jnp.uint32(1)
    m = mseg[row_seg]                                          # [B]
    S = trans.shape[2]

    # TPU gathers cost ~8ns/element; a naive window gather dominates the
    # whole search. Instead exploit the BFS invariant: at iteration `it`
    # every live config has linearized exactly `it` entries, so its
    # prefix pointer p lies in [it-W, it]. All values any config needs
    # this step live in the contiguous entry slab [it-W, it+2W): fetch
    # it with one dynamic_slice per table and extract per-config windows
    # with one-hot einsum contractions on the MXU — no gathers at all.
    # Positions are clamped to KINF = 2^22 so f32 accumulation is exact.
    L = 3 * W + 8
    KINF = jnp.int32(1 << 22)
    kinf = jnp.float32(1 << 22)
    pad_lo, pad_hi = W, max_iters + L
    inv_p = jnp.pad(jnp.minimum(inv_t, KINF), ((0, 0), (pad_lo, pad_hi)),
                    constant_values=1 << 22).astype(jnp.float32)
    ret_p = jnp.pad(jnp.minimum(ret_t, KINF), ((0, 0), (pad_lo, pad_hi)),
                    constant_values=1 << 22).astype(jnp.float32)
    suf_p = jnp.pad(jnp.minimum(sufmin, KINF), ((0, 0), (pad_lo, pad_hi)),
                    constant_values=1 << 22).astype(jnp.float32)
    trans_p = jnp.pad(trans, ((0, 0), (pad_lo, pad_hi), (0, 0)),
                      constant_values=-1).astype(jnp.float32)
    rows_oh = (row_seg[:, None] ==
               jnp.arange(K)[None, :]).astype(jnp.float32)     # [B,K]
    # Positions/state codes ride through these one-hot contractions as
    # f32 integers up to 2^22; default TPU matmul precision is bf16
    # (8 mantissa bits) which silently rounds them. HIGHEST keeps the
    # products exact.
    PREC = jax.lax.Precision.HIGHEST
    ein = functools.partial(jnp.einsum, precision=PREC)
    iota_w = jnp.arange(W, dtype=jnp.int32)
    iota_l = jnp.arange(L, dtype=jnp.int32)

    def body(carry):
        (p, mask, st, result, out_mask, ovf, it,
         lvl_live, lvl_new, lvl_dup) = carry
        live = p < INFi                                       # [B, F]
        # slab absolute entry range [it-W, it+2W+8)
        slab_iv = jax.lax.dynamic_slice(inv_p, (0, it), (K, L))
        slab_rt = jax.lax.dynamic_slice(ret_p, (0, it), (K, L))
        slab_sf = jax.lax.dynamic_slice(suf_p, (0, it), (K, L))
        slab_tr = jax.lax.dynamic_slice(trans_p, (0, it, 0), (K, L, S))
        row_iv = ein("bk,kl->bl", rows_oh, slab_iv)           # [B, L]
        row_rt = ein("bk,kl->bl", rows_oh, slab_rt)
        row_sf = ein("bk,kl->bl", rows_oh, slab_sf)
        row_tr = ein("bk,kls->bls", rows_oh, slab_tr)         # [B,L,S]

        rel = p - (it - W)                                    # [B,F]
        oh_w = ((rel[:, :, None, None] + iota_w[None, None, :, None])
                == iota_l).astype(jnp.float32)                # [B,F,W,L]
        inv_w = ein("bfwl,bl->bfw", oh_w, row_iv)
        ret_w = ein("bfwl,bl->bfw", oh_w, row_rt)
        cra_w = ret_w >= kinf
        bit = (mask[:, :, None] >> jnp.arange(W, dtype=jnp.uint32)) & u1
        unlin = (bit == 0) & (inv_w < kinf)
        minret_w = jnp.min(jnp.where(unlin, ret_w, kinf), axis=2)  # [B,F]
        oh_t = ((rel[:, :, None] + W) == iota_l).astype(
            jnp.float32)                                      # [B,F,L]
        tail_min = ein("bfl,bl->bf", oh_t, row_sf)
        minret = jnp.minimum(minret_w, tail_min)
        cand = unlin & (inv_w < minret[:, :, None])           # [B,F,W]
        # window overflow: entry p+W would itself be a candidate
        tail_inv = ein("bfl,bl->bf", oh_t, row_iv)
        cfg_ovf = live & (tail_inv < minret)                  # [B,F]

        # next state per candidate: trans[seg, e, st] via two one-hot
        # contractions (window, then current state)
        st_w = ein("bfwl,bls->bfws", oh_w, row_tr)            # [B,F,W,S]
        st_oh = (st[:, :, None] == jnp.arange(S)[None, None, :]
                 ).astype(jnp.float32)                        # [B,F,S]
        st_nxt = ein("bfws,bfs->bfw", st_w, st_oh
                     ).astype(jnp.int32)                      # [B,F,W]
        apply_ok = cand & (st_nxt >= 0)
        disc_ok = cand & cra_w

        # successors [B, F, W, 2]: action 0 = apply, 1 = discard
        nmask = mask[:, :, None] | (u1 << jnp.arange(W, dtype=jnp.uint32))
        invm = ~nmask
        t_ones = jnp.where(
            invm == 0, jnp.uint32(W),
            jax.lax.population_count((invm & (jnp.uint32(0) - invm))
                                     - u1)).astype(jnp.int32)  # [B,F,W]
        s_p = p[:, :, None] + t_ones
        s_mask = jnp.where(t_ones >= W, jnp.uint32(0),
                           nmask >> t_ones.astype(jnp.uint32))
        running = (result == RUNNING)[:, None, None]
        ok0 = apply_ok & live[:, :, None] & ~cfg_ovf[:, :, None] & running
        gen_n = jnp.sum(ok0)
        if crash_free:
            # no crashed entries anywhere in the batch: the discard
            # action never fires, so successors are half as wide and
            # the dedup sorts process half the candidates
            N = F * W
            sp = jnp.where(ok0, s_p, INFi).reshape(B, N)
            sm = jnp.where(ok0, s_mask, 0).reshape(B, N)
            ss = jnp.where(ok0, st_nxt, 0).reshape(B, N)
        else:
            ok1 = disc_ok & live[:, :, None] & ~cfg_ovf[:, :, None] & running
            gen_n = gen_n + jnp.sum(ok1)
            sp = jnp.stack([jnp.where(ok0, s_p, INFi),
                            jnp.where(ok1, s_p, INFi)], axis=3)
            sm = jnp.stack([jnp.where(ok0, s_mask, 0),
                            jnp.where(ok1, s_mask, 0)], axis=3)
            ss = jnp.stack([jnp.where(ok0, st_nxt, 0),
                            jnp.where(ok1, st[:, :, None], 0)], axis=3)
            N = F * W * 2
            sp = sp.reshape(B, N)
            sm = sm.reshape(B, N)
            ss = ss.reshape(B, N)

        # sort + dedup + compact to F slots: two fused multi-key sorts
        # (lax.sort with num_keys compares tuples in ONE pass — far
        # cheaper on TPU than lexsort's per-key stable passes).
        sp, sm, ss = jax.lax.sort((sp, sm, ss), dimension=-1, num_keys=3)
        prev_ne = ((sp != jnp.roll(sp, 1, axis=1))
                   | (sm != jnp.roll(sm, 1, axis=1))
                   | (ss != jnp.roll(ss, 1, axis=1)))
        first = jnp.zeros_like(prev_ne).at[:, 0].set(True)
        uniq = (prev_ne | first) & (sp < INFi)
        n_uniq = jnp.sum(uniq, axis=1)                        # [B]
        sp, sm, ss = jax.lax.sort(
            (jnp.where(uniq, sp, INFi), jnp.where(uniq, sm, 0),
             jnp.where(uniq, ss, 0)), dimension=-1, num_keys=3)
        sp, sm, ss = sp[:, :F], sm[:, :F], ss[:, :F]
        kept = sp < INFi

        # resolution
        done_cfg = kept & (sp >= m[:, None]) & (sp < INFi)    # [B,F]
        succeeded = jnp.any(done_cfg, axis=1)
        new_ovf = ovf | jnp.any(cfg_ovf & live, axis=1) | (n_uniq > F)
        was_running = result == RUNNING
        if reach:
            # accumulate reachable final states; retire success configs
            reached = jnp.any(
                done_cfg[:, :, None]
                & (ss[:, :, None] == jnp.arange(S)[None, None, :]),
                axis=1)                                        # [B,S]
            bits = jnp.sum(
                jnp.where(reached,
                          u1 << jnp.arange(min(S, 32), dtype=jnp.uint32)
                          [None, :S],
                          jnp.uint32(0)), axis=1, dtype=jnp.uint32)
            out_mask = jnp.where(was_running, out_mask | bits, out_mask)
            sp = jnp.where(done_cfg, INFi, sp)
            empty = ~jnp.any(sp < INFi, axis=1)
            result = jnp.where(
                was_running & empty,
                jnp.where(new_ovf, UNKNOWN, INVALID).astype(result.dtype),
                result)
        else:
            empty = n_uniq == 0
            result = jnp.where(was_running & succeeded, VALID, result)
            result = jnp.where(
                was_running & ~succeeded & empty,
                jnp.where(new_ovf, UNKNOWN, INVALID).astype(result.dtype),
                result)
        # freeze resolved histories
        frozen = (result != RUNNING)[:, None]
        sp = jnp.where(frozen, INFi, sp)
        # per-level search-shape samples (batch-summed)
        new_n = jnp.sum(n_uniq).astype(jnp.int32)
        lvl_live = lvl_live.at[it].set(jnp.sum(live).astype(jnp.int32))
        lvl_new = lvl_new.at[it].set(new_n)
        lvl_dup = lvl_dup.at[it].set(
            jnp.maximum(gen_n.astype(jnp.int32) - new_n, 0))
        return (sp, sm, ss, result, out_mask, new_ovf, it + 1,
                lvl_live, lvl_new, lvl_dup)

    def cond(carry):
        result, it = carry[3], carry[6]
        return jnp.any(result == RUNNING) & (it < max_iters)

    p0 = jnp.full((B, F), BIG, dtype=jnp.int32).at[:, 0].set(0)
    mask0 = jnp.zeros((B, F), dtype=jnp.uint32)
    sts0 = jnp.zeros((B, F), dtype=jnp.int32).at[:, 0].set(st0)
    res0 = jnp.where(m == 0, VALID, RUNNING).astype(jnp.int8)
    ovf0 = jnp.zeros(B, dtype=bool)
    out0 = jnp.where(m == 0, u1 << jnp.minimum(
        st0.astype(jnp.uint32), 31), jnp.uint32(0))
    p0 = jnp.where((res0 != RUNNING)[:, None], jnp.int32(BIG), p0)
    lvl0 = jnp.zeros(max_iters, dtype=jnp.int32)
    carry = (p0, mask0, sts0, res0, out0, ovf0, jnp.int32(0),
             lvl0, lvl0, lvl0)
    carry = jax.lax.while_loop(cond, body, carry)
    (p, mask, st, result, out_mask, ovf, it,
     lvl_live, lvl_new, lvl_dup) = carry
    if debug:
        return p, mask, st, result, out_mask, ovf, it
    result = jnp.where(result == RUNNING, UNKNOWN, result)
    # `it` + the level series ride along so callers can account
    # while-loop iterations and search shape without a debug launch
    # (see _drain)
    if reach:
        unknown = (result == UNKNOWN) | ovf
        return out_mask, unknown, it, lvl_live, lvl_new, lvl_dup
    return result, it, lvl_live, lvl_new, lvl_dup


# kernel shape buckets this process has already compiled: first launch
# of a bucket is timed synchronously as compile (trace + XLA compile +
# first execute); later launches stay async and cost only dispatch here
_compiled_buckets: set = set()
_buckets_lock = _threading.Lock()


def _timed_launch(bucket, dispatch, kernel: str = "wgl", lower=None,
                  meta: dict | None = None):
    """Runs a kernel-dispatch thunk with first-launch-per-bucket
    compile accounting. Shared by the single-device path below and the
    mesh-sharded path (tpu/ensemble.py); their bucket tuples differ in
    shape so one seen-set serves both. The bucket is CLAIMED under a
    lock before measuring: concurrent checkers (compose fans out over
    a thread pool) racing on the same bucket must record one compile,
    not two — the loser's wait lands in execute time, where it
    belongs.

    Profiling: opens a per-launch profiler record (kernel/bucket/meta,
    dispatch + compile phases, per-bucket cost analysis via `lower` —
    a zero-arg thunk returning the jax Lowered) and parks it against
    the dispatched output; _drain closes it with the device-wait and
    readback phases."""
    import jax

    with _buckets_lock:
        fresh = bucket not in _compiled_buckets
        if fresh:
            _compiled_buckets.add(bucket)
        n_buckets = len(_compiled_buckets)
    tel = telemetry.get()
    prof = profiler.get()
    rec = prof.begin(kernel, bucket=bucket, **(meta or {}))
    prof.cache_event(kernel, fresh)
    if fresh:
        # distinct-bucket cardinality (set size, not the miss count:
        # a failed first launch unclaims and retries without growing
        # it) — graftlint R5's runtime cross-check. The wgl launch
        # family (wgl/wgl-reach/wgl-sharded) shares one claim set, so
        # each kernel's gauge reads the family total.
        tel.gauge(f"profiler.{kernel}.bucket_cardinality", n_buckets)
    t0 = _time.monotonic_ns()
    try:
        out = dispatch()
    except BaseException:
        # the claimed bucket never compiled: release it, or the real
        # first compile on retry would be misrecorded as a plain launch
        if fresh:
            with _buckets_lock:
                _compiled_buckets.discard(bucket)
        prof.finish(rec)
        raise
    rec["dispatch_ns"] = _time.monotonic_ns() - t0
    if fresh:
        jax.block_until_ready(out)
        compile_ns = _time.monotonic_ns() - t0
        tel.count("wgl.kernel.compiles")
        tel.count("wgl.kernel.compile_ns", compile_ns)
        rec["compile_ns"] = compile_ns
    # cost analysis: computed once right after the bucket's compile
    # (the executable cache is warm), replayed from cache for hits
    rec.update(prof.bucket_cost(bucket, lower, fresh))
    tel.count("wgl.kernel.launches")
    return prof.attach(out, rec)


def _launch(pb: PackedBatch, rows: Sequence[tuple[int, int]], W: int,
            F: int, reach: bool):
    """Dispatches one batched search. On a multi-device process the
    rows (and their segment tensors, blocked per device — nothing
    replicated) shard over the mesh via the SPMD program in
    tpu/ensemble.py; single-device processes (and JEPSEN_TPU_SPMD=0)
    take the plain jit path below. Every wgl entry point —
    check_batch / check_batch_reach / check_segmented / check_slices,
    and through the last one the fleet scheduler's cross-tenant
    launches — funnels through here, so they all scale with the mesh
    (and the chaos tests' monkeypatch seam stays this one function)."""
    from . import spmd

    rows = list(rows)
    if spmd.spmd_devices() > 1 and len(rows) >= spmd.MIN_ROWS:
        from . import ensemble

        return ensemble.sharded_launch(
            pb, rows, W, F, reach=reach,
            kernel="wgl-reach" if reach else "wgl")
    import jax.numpy as jnp

    prof = profiler.get()
    row_seg, st0 = pb.rows(rows)
    t0 = _time.monotonic_ns()
    args = (jnp.asarray(pb.inv_t), jnp.asarray(pb.ret_t),
            jnp.asarray(pb.trans), jnp.asarray(pb.m),
            jnp.asarray(pb.sufmin), jnp.asarray(row_seg),
            jnp.asarray(st0))
    h2d_ns = _time.monotonic_ns() - t0
    bucket = (pb.inv_t.shape, pb.trans.shape[2], len(row_seg), W, F,
              pb.M + 4, reach, pb.has_crashed)
    telemetry.count("wgl.kernel.rows", len(row_seg))
    kw = dict(W=W, F=F, max_iters=pb.M + 4, reach=reach,
              crash_free=not pb.has_crashed)
    meta = {"h2d_ns": h2d_ns, "rows": len(row_seg), "batch": pb.B,
            "m": pb.M, "states": pb.S}
    return _timed_launch(
        bucket, lambda: _jitted_kernel()(*args, **kw),
        kernel="wgl-reach" if reach else "wgl",
        lower=lambda: _jitted_kernel().lower(*args, **kw), meta=meta)


def _downsample(xs, n: int = 32) -> list[int]:
    """At most n evenly-spaced samples of a level series (profiler
    span attrs carry the curve; a segment search can run 8k levels)."""
    xs = list(xs)
    if len(xs) <= n:
        return [int(x) for x in xs]
    step = len(xs) / n
    return [int(xs[int(i * step)]) for i in range(n)]


def _drain(out, reach: bool):
    """Materializes a launch's outputs (blocking on the device),
    recording the host wait as execute time plus the kernel's
    while-loop iteration count and search-shape series (frontier
    occupancy / states explored / dedup hits per BFS level), and
    closing the launch's profiler record (device-compute wait, D2H
    readback). Returns result [B] (reach=False) or (out_mask, unknown)
    arrays (reach=True)."""
    tel = telemetry.get()
    prof = profiler.get()
    rec = prof.take(out)
    t0 = _time.monotonic_ns()
    try:
        import jax

        jax.block_until_ready(out)
    except Exception:  # noqa: BLE001 — numpy outs (mocked launches)
        pass
    t_ready = _time.monotonic_ns()
    if reach:
        mask, unk, it, lvl_live, lvl_new, lvl_dup = out
        res = (np.asarray(mask), np.asarray(unk))
    else:
        r, it, lvl_live, lvl_new, lvl_dup = out
        res = np.asarray(r)
    n_it = int(it)
    live = np.asarray(lvl_live)[:n_it]
    new = np.asarray(lvl_new)[:n_it]
    dup = np.asarray(lvl_dup)[:n_it]
    peak = int(live.max()) if live.size else 0
    states = int(new.sum())
    dedup = int(dup.sum())
    t1 = _time.monotonic_ns()
    tel.count("wgl.kernel.execute_ns", t1 - t0)
    tel.count("wgl.kernel.iterations", n_it)
    # the search explorer's aggregate counters (per-launch series ride
    # in the profiler record / kernel:<k> span attrs)
    tel.count("wgl.search.levels", n_it)
    tel.count("wgl.search.states", states)
    tel.count("wgl.search.dedup-hits", dedup)
    if peak:
        tel.gauge_max("wgl.search.frontier-peak", peak)
    if rec is not None:
        rec["compute_ns"] = t_ready - t0
        rec["d2h_ns"] = t1 - t_ready
        rec["iterations"] = n_it
        rec["frontier_peak"] = peak
        rec["states_explored"] = states
        rec["dedup_hits"] = dedup
        rec["frontier_curve"] = _downsample(live)
        prof.finish(rec)
    return res


"""Floor of the width-halving rung: below this window the kernel mostly
answers UNKNOWN anyway, so the ladder goes straight to host."""
MIN_LADDER_W = 8


def check_batch(encs: Sequence[Encoded], W: int = 32,
                F: int = 64) -> np.ndarray:
    """Checks a batch of encoded histories on device. Returns int8 [B]
    (VALID/INVALID/UNKNOWN). UNKNOWN means the fixed-width search couldn't
    decide (window or frontier overflow) — fall back to search_host.

    Device failures (OOM, compile) walk the degradation ladder instead
    of raising: halve the batch, then halve the search width, then
    report UNKNOWN for the affected histories so callers take the host
    floor. The result is therefore never *wrong* on device failure,
    only less decisive."""
    try:
        pb = PackedBatch(encs)
        rows = [(i, e.init_state) for i, e in enumerate(encs)]
        res = _drain(_launch(pb, rows, W, F, reach=False), reach=False)
        return res[:pb.B]
    except Exception as e:  # noqa: BLE001 — device ladder
        kind = _ladder_classify(e, "batched kernel")
    # a compile failure is deterministic for the shape: re-attempting
    # compilation on every halved sub-batch would just fail B more
    # times, so that rung is skipped (width-halving below DOES change
    # the compiled shape and still applies)
    if kind != "compile" and len(encs) > 1:
        # smaller launches fit where one big one OOMed (and isolate a
        # poisoned shape bucket to half the batch)
        _ladder_note("batch-halved")
        mid = len(encs) // 2
        return np.concatenate([check_batch(encs[:mid], W, F),
                               check_batch(encs[mid:], W, F)])
    if W > MIN_LADDER_W:
        # a narrower window/frontier shrinks every per-step tensor;
        # histories needing the wider window come back UNKNOWN, which
        # is sound (host fallback decides)
        _ladder_note("width-halved")
        return check_batch(encs, max(W // 2, MIN_LADDER_W),
                           max(F // 2, 2 * MIN_LADDER_W))
    _ladder_note("host-floor")
    return np.full(len(encs), UNKNOWN, dtype=np.int8)


def check_batch_reach(encs: Sequence[Encoded], W: int = 32,
                      F: int = 32) -> tuple[np.ndarray, np.ndarray]:
    """Exhaustive reachability over a batch: returns (out_mask uint32 [B]
    — bit s set iff the whole history can linearize ending in state s —
    and unknown bool [B]). Requires every n_states <= 32. Device
    failures degrade like check_batch: smaller launches, then all-
    unknown (callers host-search unknown rows)."""
    assert max((e.n_states for e in encs), default=1) <= 32, \
        "reach mode packs states into a uint32"
    try:
        pb = PackedBatch(encs)
        rows = [(i, e.init_state) for i, e in enumerate(encs)]
        out, unk = _drain(_launch(pb, rows, W, F, reach=True),
                          reach=True)
        return out[:pb.B], unk[:pb.B]
    except Exception as e:  # noqa: BLE001 — device ladder
        kind = _ladder_classify(e, "batched reach kernel")
    if kind != "compile" and len(encs) > 1:  # see check_batch
        _ladder_note("batch-halved")
        mid = len(encs) // 2
        a_out, a_unk = check_batch_reach(encs[:mid], W, F)
        b_out, b_unk = check_batch_reach(encs[mid:], W, F)
        return (np.concatenate([a_out, b_out]),
                np.concatenate([a_unk, b_unk]))
    _ladder_note("host-floor")
    return (np.zeros(len(encs), dtype=np.uint32),
            np.ones(len(encs), dtype=bool))


def check_slices(slices: Sequence[tuple[Encoded, int]],
                 W: int = 24, F: int = 48
                 ) -> tuple[np.ndarray, np.ndarray]:
    """The fleet's cross-run batching entry point: packs (encoded
    slice, start state) rows from MANY tenants' streams into ONE reach
    launch (jepsen_tpu.fleet.scheduler — continuous batching, as in
    inference serving). Distinct rows may share an Encoded (one
    segment searched from several live start states costs one packed
    history, several rows), so slices dedupe by identity before
    packing. Returns (out_mask uint32 [len(slices)], unknown bool
    [len(slices)]), row i answering slices[i]. Requires every
    n_states <= 32 (reach packs states into a uint32).

    Device failures walk the same ladder as check_batch_reach —
    smaller launches, then the HOST floor, which here computes real
    per-row masks (search_host_reach) instead of all-unknown: a fleet
    under device pressure gets slower, never less decisive."""
    slices = list(slices)
    if not slices:
        return (np.empty(0, dtype=np.uint32),
                np.empty(0, dtype=bool))
    assert max(e.n_states for e, _s in slices) <= 32, \
        "reach mode packs states into a uint32"
    encs: list[Encoded] = []
    idx: dict[int, int] = {}
    rows: list[tuple[int, int]] = []
    for enc, s in slices:
        j = idx.get(id(enc))
        if j is None:
            j = idx[id(enc)] = len(encs)
            encs.append(enc)
        rows.append((j, int(s)))
    try:
        pb = PackedBatch(encs)
        out, unk = _drain(_launch(pb, rows, W, F, reach=True),
                          reach=True)
        return (np.asarray(out[:len(rows)], dtype=np.uint32),
                np.asarray(unk[:len(rows)], dtype=bool))
    except Exception as e:  # noqa: BLE001 — device ladder
        kind = _ladder_classify(e, "slices kernel")
    if kind != "compile" and len(slices) > 1:  # see check_batch
        _ladder_note("batch-halved")
        mid = len(slices) // 2
        a_out, a_unk = check_slices(slices[:mid], W, F)
        b_out, b_unk = check_slices(slices[mid:], W, F)
        return (np.concatenate([a_out, b_out]),
                np.concatenate([a_unk, b_unk]))
    _ladder_note("host-floor")
    out = np.fromiter(
        (search_host_reach(e.with_init(s)) for e, s in slices),
        dtype=np.uint32, count=len(slices))
    return out, np.zeros(len(slices), dtype=bool)


# ---------------------------------------------------------------------------
# Segment-parallel checking of long histories
# ---------------------------------------------------------------------------

def valid_cut_points(enc: Encoded) -> np.ndarray:
    """Entry indices where a compositional cut is sound: every earlier
    entry completed before this entry invoked (zero ops span the cut),
    so real-time order forces all pre-cut ops before all post-cut ops
    in ANY linearization. Crashed entries (ret=INF) forbid all later
    cuts."""
    m = enc.m
    if m == 0:
        return np.empty(0, dtype=np.int32)
    prefix_max = np.maximum.accumulate(enc.ret_t)
    valid = np.zeros(m, dtype=bool)
    valid[1:] = prefix_max[:-1] < enc.inv_t[1:]
    # int32: entry indices stay < 2^21 (the kernel's rank range), so
    # the 8-byte default index type just doubles the memory traffic
    return np.flatnonzero(valid).astype(np.int32)


def segment_cuts(enc: Encoded, target_len: int = 2048,
                 vcuts: np.ndarray | None = None) -> list[int]:
    """Cut points for compositional checking (see valid_cut_points);
    segments come out a little over target_len, degrading gracefully to
    bigger trailing segments when few cuts exist. Pass vcuts to reuse
    an already-computed valid_cut_points array."""
    m = enc.m
    if m == 0:
        return [0, 0]
    idx = valid_cut_points(enc) if vcuts is None else vcuts
    cuts = [0]
    want = target_len
    while want < m:
        j = np.searchsorted(idx, want)
        if j >= len(idx):
            break
        e = int(idx[j])
        cuts.append(e)
        want = e + target_len
    cuts.append(m)
    return cuts


class _SegmentCheckpoint:
    """CRC-framed (k, s) -> mask log keyed by a fingerprint of the
    history + transition tables + cut layout, so a checkpoint written
    for different data OR a different model never poisons a check."""

    def __init__(self, path, enc: Encoded, cuts):
        import zlib as _z
        from pathlib import Path as _P

        self.path = _P(path)
        h = _z.crc32(enc.inv_t.tobytes())
        h = _z.crc32(enc.ret_t.tobytes(), h)
        h = _z.crc32(enc.trans.tobytes(), h)  # model semantics
        h = _z.crc32(np.asarray(cuts, dtype=np.int64).tobytes(), h)
        self.fingerprint = int(h)
        self._known: set = set()
        self._reset_needed = False
        self._opened = False

    def load(self) -> dict:
        import json as _json

        from ..store import format as sformat

        out: dict = {}
        if not self.path.exists():
            return out
        try:
            for payload, _end in sformat._scan_path(self.path):
                d = _json.loads(payload)
                if d.get("fp") != self.fingerprint:
                    # different history/model/cuts: restart the file
                    # on the next write, or mixed-fingerprint records
                    # would poison every later load
                    self._reset_needed = True
                    self._known = set()
                    return {}
                out[(d["k"], d["s"])] = d["m"]
        except (OSError, ValueError):
            self._reset_needed = True
            return {}
        self._known = set(out)
        telemetry.count("wgl.checkpoint.loaded", len(out))
        return out

    def _prepare(self):
        """First write: restart a stale/corrupt file, or truncate a
        torn tail so appends stay reachable (the HistoryWriter reopen
        rule — appending after a torn record hides everything later)."""
        from ..store import format as sformat

        self.path.parent.mkdir(parents=True, exist_ok=True)
        if self._reset_needed or not self.path.exists():
            with open(self.path, "wb") as f:
                f.write(sformat.MAGIC)
            self._reset_needed = False
        else:
            end = sformat._valid_prefix_end(self.path)
            if end == 0:
                with open(self.path, "wb") as f:
                    f.write(sformat.MAGIC)
            elif end < self.path.stat().st_size:
                with open(self.path, "r+b") as f:
                    f.truncate(end)
        self._opened = True

    def save_one(self, k: int, s: int, mask: int) -> None:
        import json as _json
        import struct as _struct
        import zlib as _z

        if (k, s) in self._known:
            return
        if not self._opened:
            self._prepare()
        with open(self.path, "ab") as f:
            payload = _json.dumps(
                {"fp": self.fingerprint, "k": k, "s": s,
                 "m": int(mask)}).encode()
            f.write(_struct.pack("<II", len(payload),
                                 _z.crc32(payload)))
            f.write(payload)
        self._known.add((k, s))
        telemetry.count("wgl.checkpoint.saved")

    def save(self, resolved: dict) -> None:
        for (k, s), m in resolved.items():
            if m is not None:
                self.save_one(k, s, m)


def _wave_bounds(K: int, early: bool) -> list[tuple[int, int]]:
    """Segment-index waves for early-exit composition: geometric
    doubling from 4, so a witness at fraction p of the history costs
    O(p) launches + one wave of overshoot, while a valid history pays
    only ~log2(K/4) extra dispatches over the single-launch path.
    Without early exit (or for small K) everything is one wave."""
    if not early or K < 8:
        return [(0, K)]
    out = []
    lo, w = 0, 4
    while lo < K:
        out.append((lo, min(K, lo + w)))
        lo += w
        w *= 2
    return out


def _resolve_wave(enc: Encoded, segs, cuts, vcuts, lo: int, hi: int,
                  S: int, W: int, F: int, prefix_screen: int,
                  resolved: dict, pad_to: int | None = None) -> None:
    """Resolves every unresolved (segment, start-state) reach mask for
    segments [lo, hi): the device prefix screen first (rows whose
    time-complete prefix proves mask 0 never reach the main launch),
    then ONE batched reach launch over the survivors. Device failures
    leave rows at None for the caller's lazy host floor. pad_to pads
    the wave's packed batch with empty segments so wave launches
    bucket to a fixed set of compile shapes."""
    rows: list[tuple[int, int]] = []
    if prefix_screen:
        # Screening runs ON DEVICE: all (segment, start-state) prefix
        # rows go up in one small batched reach launch (the prefixes
        # bucket to one tiny kernel shape), replacing K x S sequential
        # host searches. Rare UNKNOWN prefix rows fall back to the
        # exact host search.
        screen_rows: list[tuple[int, int]] = []
        screen_segs: dict[int, tuple] = {}  # k -> (pre_enc, exact)
        for k in range(lo, hi):
            klo, khi = cuts[k], cuts[k + 1]
            j = np.searchsorted(vcuts, klo + prefix_screen)
            pre_end = int(vcuts[j]) if (j < len(vcuts)
                                        and vcuts[j] < khi) else khi
            if (pre_end - klo > 2 * prefix_screen
                    or enc.crashed[klo:pre_end].any()):
                # No NEARBY interior cut (one such "prefix" would pad
                # the whole screen batch up to its length), or crashed
                # entries in the prefix: screening can't shrink the
                # work cheaply — leave every state to the main launch
                # (minus checkpoint-restored entries).
                rows.extend((k, s) for s in range(S)
                            if resolved.get((k, s)) is None)
                continue
            exact = pre_end == khi
            pre = segs[k] if exact else enc.segment(klo, pre_end)
            screen_segs[k] = (pre, exact)
            screen_rows.extend((k, s) for s in range(S)
                               if resolved.get((k, s)) is None)
        if screen_rows:
            ks = sorted(screen_segs)
            kidx = {k: i for i, k in enumerate(ks)}
            launch_rows = [(kidx[k], s) for k, s in screen_rows]
            try:
                pre_pb = PackedBatch([screen_segs[k][0] for k in ks])
                p_out, p_unk = _drain(
                    _launch(pre_pb, launch_rows, W, F, reach=True),
                    reach=True)
                p_out = p_out[:len(launch_rows)]
                p_unk = p_unk[:len(launch_rows)]
            except Exception as e:  # noqa: BLE001 — ladder rung
                # screen launch failed: every screened row resolves on
                # host (the exact search — sound, just slower)
                _ladder_classify(e, "segmented prefix screen")
                _ladder_note("segment-host-screen")
                p_out = np.zeros(len(launch_rows), dtype=np.uint32)
                p_unk = np.ones(len(launch_rows), dtype=bool)
            for i, (k, s) in enumerate(screen_rows):
                pre, exact = screen_segs[k]
                mask = (search_host_reach(pre.with_init(s))
                        if p_unk[i] else int(p_out[i]))
                if exact:
                    resolved[(k, s)] = mask
                elif mask == 0:
                    resolved[(k, s)] = 0
                else:
                    rows.append((k, s))
    else:
        rows = [(k, s) for k in range(lo, hi) for s in range(S)
                if resolved.get((k, s)) is None]
    if not rows:
        return
    # One packed copy per segment; rows share it via the kernel's
    # row->segment indirection. Device failure marks every row
    # unresolved: the composition host-searches ONLY the states it
    # actually reaches (the lazy floor), and each result still
    # checkpoints, so a retry resumes instead of re-searching.
    wave_segs = list(segs[lo:hi])
    if pad_to and len(wave_segs) < pad_to:
        empty = enc.segment(cuts[lo], cuts[lo])
        wave_segs += [empty] * (pad_to - len(wave_segs))
    try:
        pb = PackedBatch(wave_segs)
        launch_rows = [(k - lo, s) for k, s in rows]
        out, unk = _drain(_launch(pb, launch_rows, W, F, reach=True),
                          reach=True)
        out = out[:len(launch_rows)]
        unk = unk[:len(launch_rows)]
        for i, (k, s) in enumerate(rows):
            resolved[(k, s)] = None if unk[i] else int(out[i])
    except Exception as e:  # noqa: BLE001 — ladder rung
        _ladder_classify(e, "segmented main launch")
        _ladder_note("segment-host-floor")
        for k, s in rows:
            resolved.setdefault((k, s), None)


def check_segmented(enc: Encoded, target_len: int | None = None,
                    W: int = 24,
                    F: int = 48, witness: bool = False,
                    prefix_screen: int = 96,
                    checkpoint_path=None,
                    checkpoint_dir=None,
                    early_exit: bool | None = None) -> dict | None:
    """Checks one long history by cutting it into segments, computing
    per-(segment, start-state) final-state reachability in ONE batched
    device launch, and composing reachability masks across segments.
    Returns None when the history doesn't segment usefully (caller uses
    the plain kernel).

    checkpoint_path / checkpoint_dir: persists every resolved
    (segment, start-state)
    reachability mask to a CRC-framed log as it lands, and reloads it
    on entry — a crashed or interrupted long check resumes without
    re-searching finished segments (SURVEY §5: long-running checker
    jobs checkpoint search state; the history itself checkpoints the
    same way in the store). Entries are keyed by history fingerprint
    so a stale checkpoint for different data is ignored.
    checkpoint_path names one exact file (single-check usage);
    checkpoint_dir derives a per-fingerprint filename, so concurrent
    checkers (per-key independent checks, composed checkers) sharing a
    store directory never fight over one file.

    prefix_screen: before the main launch, each (segment, start-state)
    row is screened over the segment's first ~prefix_screen entries
    ENDING AT A VALID CUT — a time-complete sub-history, so
    reach(prefix) == 0 soundly proves reach(segment) == 0 (an
    arbitrary entry-prefix would NOT be: a pending read may observe a
    later write). The screen itself is one batched device reach
    launch over all prefix rows (a tiny kernel bucket); rare UNKNOWN
    rows fall back to the exact host search. Wrong start states die
    in the prefix, so the main launch runs ~half the rows.

    early_exit (default on; JEPSEN_TPU_EARLY_EXIT=0 disables):
    segments resolve in geometric waves composed as they land, so an
    invalid history witnessed at fraction p of the search costs ~p of
    the check (PR 9's `search.witness-position` proves where the
    anomaly localizes; doc/spmd.md documents the semantics). Verdicts,
    masks, witnesses and certificates are identical either way — a
    wave resolves exactly the masks the single launch would have, and
    composition stops at the same failed segment."""
    if enc.n_states > 32:
        # the per-(segment, state) reach masks are uint32 bitmasks; a
        # bigger state space silently fell back to the whole-history
        # path before — make the bail observable (telemetry counter +
        # a warning naming the model), since the fallback forfeits
        # segment-level checkpointing and anomaly localization
        telemetry.count("wgl.segmented.fallback-states")
        model_name = (type(enc.states[enc.init_state]).__name__
                      if len(enc.states) else "?")
        logger.warning(
            "check_segmented: %s model has %d states (> 32, the "
            "reach-mask width); falling back to the whole-history "
            "search path", model_name, enc.n_states)
        return None
    if target_len is None:
        # Adaptive: long segments amortize kernel latency best (the
        # sweep puts ~8192 at the single-chip sweet spot), but small
        # histories still need >= ~8 segments for the batch dimension
        # (and for checkpointing) to exist at all
        target_len = min(8192, max(256, enc.m // 8))
    vcuts = valid_cut_points(enc)
    cuts = segment_cuts(enc, target_len, vcuts=vcuts)
    K = len(cuts) - 1
    if K < 2:
        return None
    if 2 * max(cuts[k + 1] - cuts[k] for k in range(K)) >= (1 << 21):
        return None  # a segment alone exceeds the kernel range
    S = enc.n_states
    segs = [enc.segment(cuts[k], cuts[k + 1]) for k in range(K)]
    # resolved mask per (segment, start-state); None = device said
    # UNKNOWN, resolve lazily on host ONLY if the composition actually
    # reaches that state (unknown rows are the hardest searches).
    resolved: dict[tuple[int, int], int | None] = {}
    ckpt = None
    if checkpoint_path is not None:
        ckpt = _SegmentCheckpoint(checkpoint_path, enc, cuts)
    elif checkpoint_dir is not None:
        probe = _SegmentCheckpoint("/dev/null", enc, cuts)
        from pathlib import Path as _P

        ckpt = _SegmentCheckpoint(
            _P(checkpoint_dir)
            / f"frontier-{probe.fingerprint & 0xffffffff:08x}.jlog",
            enc, cuts)
    if ckpt is not None:
        resolved.update(ckpt.load())
    early = early_exit if early_exit is not None else \
        _os.environ.get("JEPSEN_TPU_EARLY_EXIT", "1") != "0"
    waves = _wave_bounds(K, early)
    reach = 1 << enc.init_state
    reaches = [reach]  # reachable-state mask entering each segment
    wstate = 0
    failed_k = None
    for lo, hi in waves:
        _resolve_wave(enc, segs, cuts, vcuts, lo, hi, S, W, F,
                      prefix_screen, resolved,
                      pad_to=(_next_pow2(hi - lo)
                              if len(waves) > 1 else None))
        if ckpt is not None:
            ckpt.save(resolved)
        for k in range(lo, hi):
            nreach = 0
            for s in range(S):
                if (reach >> s) & 1:
                    mask = resolved.get((k, s))
                    if mask is None:
                        mask = search_host_reach(segs[k].with_init(s))
                        resolved[(k, s)] = mask
                        if ckpt is not None:
                            ckpt.save_one(k, s, mask)
                    nreach |= mask
            if nreach == 0:
                failed_k = k
                wstate = next(s for s in range(S) if (reach >> s) & 1)
                break
            reach = nreach
            reaches.append(reach)
        if failed_k is not None:
            if hi < K:
                # the early-exit payoff: segments past the witness's
                # wave were never launched — an anomaly at 12% of the
                # history cost ~12% of the search
                telemetry.count("wgl.segmented.early-exit")
                telemetry.gauge(
                    "wgl.segmented.early-exit-frac",
                    round(cuts[hi] / max(enc.m, 1), 4))
            break
    if failed_k is not None:
        k = failed_k
        res: dict = {"valid?": False, "failed-segment": k,
                     "segment-range": [cuts[k], cuts[k + 1]]}
        chain = _reach_chain(resolved, reaches, k, wstate)
        if chain is not None:
            # the reach/choice data a certificate re-derives the
            # pre-witness linearization from (jepsen_tpu.tpu
            # .certify); also where the witness sits in the
            # history — the early-exit signal (ROADMAP item 3)
            res["search-chain"] = {"cuts": [int(c) for c in cuts],
                                   "chain": chain}
        if witness:
            w = search_host(segs[k].with_init(wstate),
                            witness=True)
            res.update({kk: v for kk, v in w.items()
                        if kk != "valid?"})
            if "witness-entry" in res:
                # globalize the segment-local stuck entry
                res["witness-entry"] = int(
                    cuts[k] + res["witness-entry"])
                res["entry-count"] = int(enc.m)
        return res
    final_state = next(s for s in range(S) if (reach >> s) & 1)
    chain = _reach_chain(resolved, reaches, K, final_state)
    res = {"valid?": True, "segments": K}
    if chain is not None:
        res["search-chain"] = {"cuts": [int(c) for c in cuts],
                               "chain": chain}
    return res


def _reach_chain(resolved: dict, reaches: list[int], upto: int,
                 final_state: int) -> list[int] | None:
    """A concrete per-segment start-state chain out of the resolved
    reach masks: chain[j] is segment j's start state, chain[upto] =
    final_state, and resolved[(j, chain[j])] contains chain[j+1] for
    every j — the choice data certificates compose per-segment
    linearization orders along. Backward reconstruction; None when a
    mask is missing (shouldn't happen after composition resolved
    them)."""
    chain = [0] * (upto + 1)
    chain[upto] = int(final_state)
    for j in range(upto - 1, -1, -1):
        nxt = chain[j + 1]
        for s in range(32):
            if (reaches[j] >> s) & 1:
                mask = resolved.get((j, s))
                if mask is not None and (mask >> nxt) & 1:
                    chain[j] = s
                    break
        else:
            return None
    return chain


# ---------------------------------------------------------------------------
# Checkpoint-and-extend: incremental re-checking of grown histories
# ---------------------------------------------------------------------------

# The extend path's FIXED cut stride. check_segmented's adaptive
# target_len (m//8-ish) moves the cut layout whenever the history
# grows, which would orphan every checkpointed mask; a fixed stride
# makes the greedy cut schedule prefix-stable (entries below a valid
# cut are frozen by real time, so the same cuts — and the same reach
# masks — fall out of the grown history), which is the whole game.
EXTEND_STRIDE = 512


def _extend_fingerprint(enc: Encoded) -> int:
    """Model-semantics fingerprint for wgl-extend records: the model
    class and initial state (via the models' value-based reprs). Entry
    digests key the HISTORY prefix; this keys the MODEL, so a
    checkpoint written for a different model (or initial value) never
    poisons a resume. Deliberately NOT the transition-table bytes:
    those depend on the whole history's distinct-op set, which grows
    with the suffix — state identity is carried per-state by the
    record's "states" reprs instead."""
    import zlib as _z

    init = enc.states[enc.init_state]
    return int(_z.crc32(
        f"{type(init).__name__}:{init!r}".encode()))


def _remap_record_masks(record: dict, enc: Encoded,
                        reused_segments: int
                        ) -> dict[tuple[int, int], int] | None:
    """Translates a record's (segment, state) -> mask entries into
    THIS encoding's state indices. A grown history can discover new
    distinct ops, which reorders state discovery — indices move, but
    the states themselves (value-carrying model objects with stable
    reprs) do not, and a reach mask is semantically a SET of model
    states. Returns None when any recorded state is unknown to this
    encoding (not a superset — stale record)."""
    new_idx = {repr(s): i for i, s in enumerate(enc.states)}
    old_keys = record["states"]
    mapping = []
    for key in old_keys:
        i = new_idx.get(key)
        if i is None:
            return None
        mapping.append(i)
    out: dict[tuple[int, int], int] = {}
    for key, mask in record["masks"].items():
        k_str, s_str = key.split(":")
        k, s = int(k_str), int(s_str)
        if k >= reused_segments or s >= len(mapping):
            continue
        new_mask = 0
        m = int(mask)
        for j in range(len(mapping)):
            if (m >> j) & 1:
                new_mask |= 1 << mapping[j]
        out[(k, mapping[s])] = new_mask
    return out


def check_extend(enc: Encoded, record: dict | None = None,
                 stride: int = EXTEND_STRIDE, W: int = 24,
                 F: int = 48) -> tuple[dict | None, dict | None]:
    """Segment-composed check with a prefix-stable cut schedule and a
    reusable (segment, state) -> reach-mask frontier. Returns
    (result, new_record); (None, None) when the history doesn't
    segment (caller falls back to the plain paths).

    `record` is a ckpt.py "wgl-extend" record from a previous check of
    a PREFIX of this history. Reuse is earned, never assumed: the
    record's cuts must match this history's greedy schedule position by
    position AND digest by digest (sha256 over the encoded entries
    below each cut) — so a torn, stale, or wrong-history record
    degrades to a full re-check, with `ckpt.stale` counted when a
    record was offered and nothing matched. Masks for the matched
    prefix segments are reused verbatim; only suffix segments launch.
    Fresh and resumed runs compose the SAME exact masks through the
    SAME deterministic composition, so verdicts, search chains — and
    therefore certificates (certify.attach_wgl derives them from the
    search chain alone) — are identical by construction."""
    from . import ckpt as ckpt_mod

    if enc.n_states > 32:
        return None, None
    vcuts = valid_cut_points(enc)
    cuts = segment_cuts(enc, stride, vcuts=vcuts)
    K = len(cuts) - 1
    if K < 2:
        return None, None
    if 2 * max(cuts[k + 1] - cuts[k] for k in range(K)) >= (1 << 21):
        return None, None  # a segment alone exceeds the kernel range
    S = enc.n_states
    digests = ckpt_mod.entry_digest_chain(enc, cuts)
    fp = _extend_fingerprint(enc)

    resolved: dict[tuple[int, int], int] = {}
    reused_segments = 0
    if record is not None:
        ok = (record.get("stride") == stride
              and record.get("model_fp") == fp)
        matched = 0
        if ok:
            rcuts = record["cuts"]
            rdigs = record["digests"]
            limit = min(len(rcuts), len(cuts))
            while matched < limit and rcuts[matched] == cuts[matched] \
                    and rdigs[matched] == digests[matched]:
                matched += 1
        # a segment is reusable when BOTH its cut endpoints matched
        reused_segments = max(0, matched - 1)
        remapped = (_remap_record_masks(record, enc, reused_segments)
                    if reused_segments else None)
        if remapped:
            resolved.update(remapped)
            telemetry.count("ckpt.extend.reused-masks",
                            len(resolved))
            telemetry.count("ckpt.extend.resumed")
        else:
            reused_segments = 0
            telemetry.count("ckpt.stale")

    segs = [enc.segment(cuts[k], cuts[k + 1]) for k in range(K)]
    need = [(k, s) for k in range(K) for s in range(S)
            if (k, s) not in resolved]
    if need:
        out, unk = check_slices([(segs[k], s) for k, s in need],
                                W, F)
        for i, (k, s) in enumerate(need):
            # UNKNOWN rows get the exact host search — resolved masks
            # are always exact, so resumed composition is bit-stable
            resolved[(k, s)] = (search_host_reach(
                segs[k].with_init(s)) if unk[i] else int(out[i]))
    telemetry.count("ckpt.extend.computed-masks", len(need))

    reach = 1 << enc.init_state
    reaches = [reach]
    failed_k = None
    wstate = 0
    for k in range(K):
        nreach = 0
        for s in range(S):
            if (reach >> s) & 1:
                mask = resolved.get((k, s))
                if mask is None:
                    # a reused segment can miss a state the old
                    # encoding never had; the exact host search fills
                    # it deterministically
                    mask = int(search_host_reach(
                        segs[k].with_init(s)))
                    resolved[(k, s)] = mask
                nreach |= mask
        if nreach == 0:
            failed_k = k
            wstate = next(s for s in range(S) if (reach >> s) & 1)
            break
        reach = nreach
        reaches.append(reach)

    new_record = {
        "v": ckpt_mod.VERSION, "kind": "wgl-extend",
        "stride": int(stride), "model_fp": fp,
        "cuts": [int(c) for c in cuts], "digests": digests,
        "states": [repr(s) for s in enc.states],
        "masks": {f"{k}:{s}": int(m)
                  for (k, s), m in sorted(resolved.items())},
        "n_ops": int(enc.m), "digest": digests[-1],
    }
    if failed_k is not None:
        k = failed_k
        res: dict = {"valid?": False, "failed-segment": k,
                     "segment-range": [cuts[k], cuts[k + 1]]}
        chain = _reach_chain(resolved, reaches, k, wstate)
        if chain is not None:
            res["search-chain"] = {"cuts": [int(c) for c in cuts],
                                   "chain": chain}
        w = search_host(segs[k].with_init(wstate), witness=True)
        res.update({kk: v for kk, v in w.items() if kk != "valid?"})
        if "witness-entry" in res:
            res["witness-entry"] = int(cuts[k] + res["witness-entry"])
            res["entry-count"] = int(enc.m)
        return res, new_record
    final_state = next(s for s in range(S) if (reach >> s) & 1)
    chain = _reach_chain(resolved, reaches, K, final_state)
    res = {"valid?": True, "segments": K}
    if chain is not None:
        res["search-chain"] = {"cuts": [int(c) for c in cuts],
                               "chain": chain}
    return res, new_record


# ---------------------------------------------------------------------------
# Public analysis API (knossos-analysis-shaped results)
# ---------------------------------------------------------------------------

# Below this many entries the whole-history kernel/host search is cheap
# enough that segment-localized witness extraction isn't worth a launch.
SEGMENT_MIN_M = 4096


def _witness_op_indices(out: dict) -> dict:
    """Attaches the participating op (invocation) indices to an
    invalid analysis as out['op-indices'] — anomaly provenance: the
    stuck op, its predecessor, and every pending op in the surviving
    configs. entry_ops are merged invocations, so the indices join the
    per-op trace (optrace.jsonl) and timeline anchors directly."""
    if out.get("valid?") is not False or "op-indices" in out:
        return out
    idxs = set()

    def add(o):
        i = getattr(o, "index", None)
        if i is None and isinstance(o, dict):
            i = o.get("index")
        if isinstance(i, int) and i >= 0:
            idxs.add(i)

    add(out.get("op"))
    add(out.get("previous-ok"))
    for cfg in out.get("configs") or []:
        for o in (cfg.get("pending") or []) if isinstance(cfg, dict) \
                else []:
            add(o)
    out["op-indices"] = sorted(idxs)
    return out


def _seg_kwargs(W: int | None, F: int | None, **extra) -> dict:
    """check_segmented kwargs: only overrides the leaner segmented
    defaults (W=24/F=48) when the caller tuned W/F explicitly."""
    kw = dict(extra)
    if W is not None:
        kw["W"] = W
    if F is not None:
        kw["F"] = F
    return kw


def extract_witness(enc: Encoded, W: int | None = None,
                    F: int | None = None) -> dict:
    """Bounded witness extraction for a history the device kernel
    flagged INVALID or UNKNOWN.

    For long histories, localizes the FIRST failing segment by
    reach-mask composition (one batched device launch over
    segment x start-state rows) and host-searches only that segment —
    replacing the whole-history `search_host` fallback whose cost is
    unbounded at 1M-op scale (the anomaly path the reference pays hours
    for, jepsen/src/jepsen/checker.clj:202-233). Small or unsegmentable
    histories fall through to the exact whole-history host search.

    Sets result["witness-extraction"] to 'segmented' or 'host' so
    callers (and tests) can see which path ran."""
    if enc.m >= SEGMENT_MIN_M:
        seg = check_segmented(enc, witness=True, **_seg_kwargs(W, F))
        if seg is not None:
            seg["witness-extraction"] = "segmented"
            return _witness_op_indices(seg)
    out = search_host(enc, witness=True)
    out["witness-extraction"] = "host"
    return _witness_op_indices(out)


def _search_stats(out: dict) -> dict:
    """Attaches out['search'] — the witness-position percentile
    ("nonlinearizable witnessed at 12% of the history") for invalid
    verdicts: the direct input for segment-level early-exit (ROADMAP
    item 3) and the coverage atlas's anomaly-localization ranking."""
    if out.get("valid?") is not False:
        return out
    we = out.get("witness-entry")
    m = out.get("entry-count")
    if we is None and "segment-range" in out:
        we = out["segment-range"][0]
    if we is not None and m:
        out["search"] = {"witness-entry": int(we),
                         "entries": int(m),
                         "witness-position": round(int(we) / int(m),
                                                   4)}
    return out


def analysis(model, hist, algorithm: str = "tpu", W: int | None = None,
             F: int | None = None, checkpoint_path=None,
             checkpoint_dir=None, certify: bool = False) -> dict:
    """Checks a single history against a model.

    algorithm: 'tpu'  — device kernel, host fallback on UNKNOWN
               'wgl'  — host search over encoded tables
               'model' — host search stepping model objects
    Result mirrors knossos analysis maps: {'valid?': bool, 'op': ...,
    'configs': [...], 'analyzer': ...}. When the device kernel failed
    (OOM / compile) and analysis stepped down the degradation ladder,
    the verdict carries the rungs walked as result['degradation'].

    certify=True (the checker entry points pass it; raw bench paths
    don't) additionally attaches a machine-checkable proof of the
    verdict as result['certificate'] (jepsen_tpu.tpu.certify) — for
    valid, a per-segment linearization order re-derived from the reach
    chain; for invalid, the replayable blocked-frontier witness."""
    with _ladder_scope() as steps:
        enc_box: list = [None]
        out = _analysis(model, hist, algorithm, W, F, checkpoint_path,
                        checkpoint_dir, enc_box)
        if steps:
            out["degradation"] = list(steps)
        _search_stats(out)
        if certify:
            from . import certify as certify_mod

            certify_mod.attach_wgl(model, hist, enc_box[0], out)
        return out


def analysis_extend(model, hist, store_path=None,
                    stride: int = EXTEND_STRIDE, W: int | None = None,
                    F: int | None = None,
                    certify: bool = False) -> dict:
    """analysis(), resumable: checks via check_extend's prefix-stable
    segmentation, loading the previous frontier from the ckpt.py store
    at `store_path` and persisting the grown frontier back after the
    verdict. Re-checking a grown history costs O(suffix); a missing,
    torn, stale, or wrong-model record costs a full re-check — never a
    wrong verdict. Histories that don't segment (too short, > 32
    states, unencodable) fall through to plain analysis(), so this is
    always safe to call where analysis() was."""
    from . import ckpt as ckpt_mod

    with _ladder_scope() as steps:
        if not isinstance(hist, History):
            hist = History(hist)
        enc = None
        try:
            enc = encode(model, hist)
        except EncodingError:
            pass
        out = None
        new_rec = None
        if enc is not None:
            record = None
            if store_path is not None:
                record = ckpt_mod.load(store_path, "wgl-extend")
            out, new_rec = check_extend(
                enc, record=record, stride=stride,
                **_seg_kwargs(W, F))
        if out is None:
            telemetry.count("ckpt.extend.fallback")
            return analysis(model, hist, algorithm="tpu", W=W, F=F,
                            certify=certify)
        out["analyzer"] = "tpu-extend"
        _witness_op_indices(out)
        if steps:
            out["degradation"] = list(steps)
        _search_stats(out)
        if store_path is not None and new_rec is not None:
            # best-effort durability: a failed write (ENOSPC/EIO)
            # leaves the previous record in place — degraded, not
            # wrong — and the verdict still stands
            ckpt_mod.try_write(store_path, new_rec)
        if certify:
            from . import certify as certify_mod

            certify_mod.attach_wgl(model, hist, enc, out)
        return out


def _analysis(model, hist, algorithm, W, F, checkpoint_path,
              checkpoint_dir, enc_box: list | None = None) -> dict:
    if not isinstance(hist, History):
        hist = History(hist)
    try:
        enc = encode(model, hist)
    except EncodingError:
        out = search_host_model(model, hist, witness=True)
        out["analyzer"] = "model"
        return _witness_op_indices(out)
    if enc_box is not None:
        enc_box[0] = enc  # certificate extraction reuses the encode

    if algorithm == "model":
        out = search_host_model(model, hist, witness=True)
        out["analyzer"] = "model"
        return _witness_op_indices(out)
    if algorithm == "wgl":
        out = search_host(enc, witness=True)
        out["analyzer"] = "wgl"
        return _witness_op_indices(out)

    # Long histories: segment-parallel path (one batched launch over
    # segments x start-states instead of m sequential frontier steps).
    # W/F default per path: the prefix-screened segmented search runs
    # leaner (24/48, unknowns fall back soundly) than the whole-history
    # kernel (32/64).
    if enc.m >= SEGMENT_MIN_M:
        seg_kw = _seg_kwargs(W, F)
        if checkpoint_path is not None:
            seg_kw["checkpoint_path"] = checkpoint_path
        if checkpoint_dir is not None:
            seg_kw["checkpoint_dir"] = checkpoint_dir
        seg = check_segmented(enc, witness=True, **seg_kw)
        if seg is not None:
            seg["analyzer"] = "tpu-segmented"
            return _witness_op_indices(seg)

    try:
        res = int(check_batch([enc],
                              W=W if W is not None else 32,
                              F=F if F is not None else 64)[0])
    except RangeError:
        out = search_host(enc, witness=True)
        out["analyzer"] = "wgl"
        return _witness_op_indices(out)
    if res == VALID:
        return {"valid?": True, "analyzer": "tpu"}
    if res == INVALID:
        out = search_host(enc, witness=True)  # witness extraction
        out["analyzer"] = "tpu"
        return _witness_op_indices(out)
    out = search_host(enc, witness=True)
    out["analyzer"] = "tpu+host-fallback"
    if _ladder_steps():
        _ladder_note("host-fallback")
    return _witness_op_indices(out)


def analysis_batch_streamed(model, hists: Sequence, chunk: int = 256,
                            W: int | None = None,
                            F: int | None = None,
                            certify: bool = False) -> list[dict]:
    """analysis_batch with host->HBM pipelining (SURVEY P7): histories
    are encoded and launched chunk by chunk, and because JAX dispatch
    is asynchronous, chunk i+1's host-side encoding overlaps chunk i's
    device search. A one-chunk drain lag keeps at most two chunks of
    packed tensors live on the host while preserving the overlap.
    certify=True attaches a per-result verdict certificate (the
    checker batch path passes it; the raw bench path doesn't)."""
    hists = list(hists)
    results: list[dict] = [None] * len(hists)  # type: ignore

    def launch(group, start):
        encs = []
        idx_map = []
        for off, hh in enumerate(group):
            i = start + off
            if not isinstance(hh, History):
                hh = History(hh)
            try:
                encs.append(encode(model, hh))
                idx_map.append(i)
            except EncodingError:
                out = search_host_model(model, hh, witness=True)
                out["analyzer"] = "model"
                results[i] = _witness_op_indices(_search_stats(out))
                if certify_mod is not None:
                    certify_mod.attach_wgl(model, hh, None,
                                           results[i])
        if not encs:
            return None
        try:
            pb = PackedBatch(encs)
            rows = [(j, e.init_state) for j, e in enumerate(encs)]
            return (_launch(pb, rows,
                            W if W is not None else 32,
                            F if F is not None else 64,
                            reach=False),
                    encs, idx_map, [])
        except RangeError:
            return None, encs, idx_map, []
        except Exception as e:  # noqa: BLE001 — device ladder
            return (None, encs, idx_map,
                    [_ladder_classify(e, "streamed launch")])

    certify_mod = None
    if certify:
        from . import certify as certify_mod  # noqa: PLC0415

    def drain(entry):
        dev, encs, idx_map, rungs = entry
        if dev is not None:
            try:
                res = _drain(dev, reach=False)[:len(encs)]
            except Exception as e:  # noqa: BLE001 — async dispatch
                # defers device failure to the blocking drain
                rungs = rungs + [_ladder_classify(e, "streamed drain")]
                res = [UNKNOWN] * len(encs)
        else:
            res = [UNKNOWN] * len(encs)
        for j, i in enumerate(idx_map):
            r = int(res[j])
            own = list(rungs)
            if r == VALID:
                results[i] = {"valid?": True, "analyzer": "tpu"}
            else:
                # Bounded: long invalid/unknown members are localized
                # segment-wise instead of re-searched whole on host,
                # keeping the caller's W/F tuning.
                with _ladder_fork() as sub:
                    # rungs the witness extraction itself walked (e.g.
                    # a segmented-search device failure) belong to
                    # THIS result too
                    out = extract_witness(encs[j], W=W, F=F)
                own += sub
                out["analyzer"] = ("tpu" if r == INVALID
                                   else "tpu+host-fallback")
                results[i] = out
            if own:
                # only this chunk's own failures, not the cumulative
                # call-wide list: the pipelining means other chunks'
                # rungs may already be on the ladder scope
                own = [s for k, s in enumerate(own)
                       if k == 0 or own[k - 1] != s]
                results[i].setdefault("degradation", own)
            _search_stats(results[i])
            if certify_mod is not None:
                certify_mod.attach_wgl(model, hists[i], encs[j],
                                       results[i])

    with _ladder_scope():
        pending = None
        for start in range(0, len(hists), chunk):
            entry = launch(hists[start:start + chunk], start)
            # drain the PREVIOUS chunk now: the current one is already
            # dispatched, so the device keeps working while we decode
            if pending is not None:
                drain(pending)
            pending = entry
        if pending is not None:
            drain(pending)
    return results


def analysis_batch(model, hists: Sequence, W: int | None = None,
                   F: int | None = None,
                   certify: bool = False) -> list[dict]:
    """Checks many histories at once (the ensemble path: one device
    launch for the whole batch, host fallback only for UNKNOWNs)."""
    hists = list(hists)
    return analysis_batch_streamed(model, hists,
                                   chunk=max(len(hists), 1), W=W, F=F,
                                   certify=certify)