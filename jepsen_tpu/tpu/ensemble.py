"""Device-mesh sharding of the WGL search: many histories (or many
segments of one long history) checked concurrently across chips.

The reference's scaling story for checking is host-side only: bounded
pmap over per-key subhistories (jepsen/src/jepsen/independent.clj:271-377)
and fork-join folds over history chunks (checker.clj:139-200). Here the
batch dimension of the WGL kernel — independent keys, ensemble histories,
or segments x start-states of one long history — is laid out over a 1-D
`jax.sharding.Mesh` as a TRUE SPMD program:

  - `shard_layout` blocks the packed segment tensors into per-device
    groups (LPT-balanced by search work, encode.balanced_groups), so
    each chip holds ONLY the segments its search rows reference —
    nothing big is replicated (graftlint R4 prices exactly this; the
    pre-SPMD path shipped 22 MiB of replicated tables per launch).
  - `shard_map` (SNIPPETS.md [1]-[3]; partition rules in
    tpu/spmd.py) runs one frontier search per chip over its local
    rows. Each shard's `lax.while_loop` exits as soon as ITS rows
    resolve — there is no per-BFS-level cross-chip sync at all; the
    only collectives are the end-of-search psum/pmax of the (tiny)
    search-shape stats and the gather that restores caller row order.
  - The blocked segment tensors are donated (wgl.DONATE_ARGNUMS):
    launch sites build them fresh per call, so XLA reuses the shards
    as scratch.

Per-row results are bit-identical to the single-device kernel for any
mesh size: a search row never reads another row's state, so blocking
and padding change nothing but the wall clock (tests/test_spmd.py
pins verdicts AND certificates across mesh 1/2/4/8).
"""

from __future__ import annotations

import time as _time
from functools import lru_cache, partial
from typing import Sequence

import numpy as np

from .. import telemetry
from . import profiler, spmd
from . import wgl as wgl_mod
from .encode import Encoded, balanced_groups
from .wgl import PackedBatch, _drain, _kernel, _next_pow2, _timed_launch

# The sharded program's argument names, in signature order (the
# partition-rule table in tpu/spmd.py keys off these; the lint
# registry traces the same layout).
SHARD_ARGS = ("inv_t", "ret_t", "trans", "mseg", "sufmin",
              "row_seg", "st0", "inv_perm")


@lru_cache(maxsize=None)
def _jitted_sharded(mesh, W: int, F: int, max_iters: int, reach: bool,
                    crash_free: bool = False):
    """One jitted shard_map program per (mesh, static config); jax.jit
    then caches compiled executables per array shape bucket."""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding

    spmd.enable_compile_cache()
    wgl_mod.quiet_unusable_donation()
    kern = partial(_kernel, W=W, F=F, max_iters=max_iters, reach=reach,
                   crash_free=crash_free)
    n_res = 2 if reach else 1

    def local(inv_t, ret_t, trans, mseg, sufmin, row_seg, st0):
        # one per-chip frontier search over the chip's row shard; the
        # while_loop stops when the LOCAL rows resolve (no cross-chip
        # level sync). Only the search-shape stats cross the mesh.
        outs = kern(inv_t, ret_t, trans, mseg, sufmin, row_seg, st0)
        res, (it, *lvls) = outs[:n_res], outs[n_res:]
        it = jax.lax.pmax(it, spmd.AXIS)
        lvls = tuple(jax.lax.psum(lv, spmd.AXIS) for lv in lvls)
        return res + (it,) + lvls

    data_specs = spmd.match_partition_rules(spmd.WGL_RULES,
                                            SHARD_ARGS[:7])
    from jax.sharding import PartitionSpec as P

    out_specs = (P(spmd.AXIS),) * n_res + (P(),) * 4
    mapped = shard_map(local, mesh=mesh, in_specs=data_specs,
                       out_specs=out_specs, check_rep=False)

    def run(inv_t, ret_t, trans, mseg, sufmin, row_seg, st0, inv_perm):
        outs = mapped(inv_t, ret_t, trans, mseg, sufmin, row_seg, st0)
        # restore caller row order: a gather over the per-row result
        # vector (1-4 bytes/row) — the only all-gather in the program
        res = tuple(o[inv_perm] for o in outs[:n_res])
        return res + outs[n_res:]

    shardings = tuple(
        NamedSharding(mesh, s) for s in
        spmd.match_partition_rules(spmd.WGL_RULES, SHARD_ARGS))
    return jax.jit(run, in_shardings=shardings,
                   donate_argnums=wgl_mod.DONATE_ARGNUMS)


def default_mesh(n_devices: int | None = None):
    """A 1-D mesh over the first n (default: all) devices; with the
    multi-host env set (tpu/dist.py), 'all' spans every host's chips
    and the batch axis shards across DCN."""
    import jax

    from . import dist

    dist.ensure_initialized()
    if n_devices is None:
        # honor the SPMD knobs (JEPSEN_TPU_SPMD / _SPMD_DEVICES): with
        # sharding disabled or capped, the explicitly-sharded entry
        # points degrade to a smaller mesh instead of silently running
        # shard_map over every device — JEPSEN_TPU_SPMD=0 really does
        # give the differential reference everywhere
        n_devices = max(1, spmd.spmd_devices())
    # clamp like the old devs[:n] slice: asking for more devices than
    # the process has yields the full mesh, not an error
    return spmd.mesh_for(min(n_devices, len(jax.devices())))


class _ShardLayout:
    """The per-device blocking of one launch: segment tensors gathered
    into [n_dev * (K_loc + 1), ...] blocks (each device's K_loc
    segments + its own sentinel empty row), rows rebased to local
    segment indices, and the inverse permutation that restores caller
    row order."""

    __slots__ = ("inv_t", "ret_t", "trans", "mseg", "sufmin",
                 "row_seg", "st0", "inv_perm", "n_dev", "n_rows",
                 "device_entries")


def shard_layout(pb: PackedBatch, rows: Sequence[tuple[int, int]],
                 n_dev: int) -> _ShardLayout:
    """Blocks a PackedBatch + its search rows onto n_dev devices.

    Segments are grouped by LPT over estimated search work
    (entries x rows referencing the segment); each device's block
    holds only its own segments, so H2D traffic and HBM footprint
    ship every byte ONCE across the mesh instead of once per chip.
    Segments no row references don't ship at all."""
    t0 = _time.monotonic_ns()
    rows = list(rows)
    B = pb.B
    n_rows_seg = np.zeros(B + 1, dtype=np.int32)
    for k, _s in rows:
        n_rows_seg[k] += 1
    used = [k for k in range(B) if n_rows_seg[k]]
    weights = [(int(pb.m[k]) + 1) * int(n_rows_seg[k]) for k in used]
    groups = [[used[i] for i in g]
              for g in balanced_groups(weights, n_dev)]
    K_loc = _next_pow2(max((len(g) for g in groups), default=1))
    # device-major gather map; unfilled slots and each device's local
    # sentinel (index K_loc) point at pb's empty row B
    gmap = np.full((n_dev, K_loc + 1), B, dtype=np.int32)
    loc: dict[int, tuple[int, int]] = {}
    for d, g in enumerate(groups):
        for j, k in enumerate(g):
            gmap[d, j] = k
            loc[k] = (d, j)
    flat = gmap.reshape(-1)
    lay = _ShardLayout()
    lay.inv_t = pb.inv_t[flat]
    lay.ret_t = pb.ret_t[flat]
    lay.trans = pb.trans[flat]
    lay.mseg = pb.m[flat]
    lay.sufmin = pb.sufmin[flat]
    # rows per device, caller order preserved within each device
    per: list[list[tuple[int, int]]] = [[] for _ in range(n_dev)]
    where: list[tuple[int, int]] = []
    for k, s in rows:
        d, j = loc[k]
        where.append((d, len(per[d])))
        per[d].append((j, int(s)))
    B_loc = _next_pow2(max((len(p) for p in per), default=1))
    row_seg = np.full(n_dev * B_loc, K_loc, dtype=np.int32)
    st0 = np.zeros(n_dev * B_loc, dtype=np.int32)
    for d, p in enumerate(per):
        for slot, (j, s) in enumerate(p):
            row_seg[d * B_loc + slot] = j
            st0[d * B_loc + slot] = s
    inv_perm = np.zeros(_next_pow2(max(len(rows), 1)), dtype=np.int32)
    for i, (d, slot) in enumerate(where):
        inv_perm[i] = d * B_loc + slot
    lay.row_seg, lay.st0, lay.inv_perm = row_seg, st0, inv_perm
    lay.n_dev, lay.n_rows = n_dev, len(rows)
    lay.device_entries = [
        int(sum(int(pb.m[k]) * int(n_rows_seg[k]) for k in g))
        for g in groups]
    profiler.get().record_host("shard-layout",
                               _time.monotonic_ns() - t0)
    return lay


def sharded_launch(pb: PackedBatch, rows: Sequence[tuple[int, int]],
                   W: int, F: int, reach: bool, mesh=None,
                   kernel: str = "wgl-sharded"):
    """Dispatches one SPMD launch (async; drain with wgl._drain).
    Outputs answer rows in CALLER order — trim to len(rows).

    Profiling meta carries the per-device work attribution
    (`device_entries`, entries of search work per chip) and the
    mean/max `balance` figure — with zero replicated bytes, an uneven
    `balance` is what's left to explain a flat device sweep."""
    import jax
    from jax.sharding import NamedSharding

    prof = profiler.get()
    if mesh is None:
        n = max(1, min(spmd.spmd_devices(), len(rows)))
        # pow2 mesh sizes only: _jitted_sharded caches per mesh, so a
        # stray 3-row launch must not mint a mesh3 compile family
        mesh = spmd.mesh_for(1 << (n.bit_length() - 1))
    n_dev = mesh.devices.size
    lay = shard_layout(pb, rows, n_dev)
    fn = _jitted_sharded(mesh, W, F, pb.M + 4, reach,
                         not pb.has_crashed)
    host_args = (lay.inv_t, lay.ret_t, lay.trans, lay.mseg,
                 lay.sufmin, lay.row_seg, lay.st0, lay.inv_perm)
    specs = spmd.match_partition_rules(spmd.WGL_RULES, SHARD_ARGS)
    t0 = _time.monotonic_ns()
    args = tuple(jax.device_put(a, NamedSharding(mesh, s))
                 for a, s in zip(host_args, specs))
    h2d_ns = _time.monotonic_ns() - t0
    bucket = (mesh, lay.inv_t.shape, lay.trans.shape[2],
              len(lay.row_seg), len(lay.inv_perm), W, F, pb.M + 4,
              reach, pb.has_crashed)
    telemetry.count("wgl.kernel.rows", len(lay.row_seg))
    telemetry.count("wgl.spmd.launches")
    telemetry.gauge_max("wgl.spmd.devices", n_dev)
    balance = profiler.work_balance(lay.device_entries)
    meta = {"h2d_ns": h2d_ns, "rows": len(lay.row_seg),
            "batch": pb.B, "m": pb.M, "states": pb.S,
            "devices": n_dev, "device_entries": lay.device_entries,
            "balance": balance}
    return _timed_launch(bucket, lambda: fn(*args), kernel=kernel,
                         lower=lambda: fn.lower(*args), meta=meta)


def check_batch_sharded(encs: Sequence[Encoded], mesh=None, W: int = 32,
                        F: int = 64, reach: bool = False, rows=None):
    """check_batch/check_batch_reach across a device mesh. Search rows
    — (segment, start-state) pairs, default one per history — AND the
    packed segment tensors both shard over the mesh's 'b' axis via the
    blocked layout (see module docstring)."""
    if mesh is None:
        mesh = default_mesh()
    pb = PackedBatch(encs)
    if rows is None:
        rows = [(i, e.init_state) for i, e in enumerate(encs)]
    n_rows = len(rows)
    telemetry.count("wgl.ensemble.launches")
    out = sharded_launch(pb, rows, W, F, reach=reach, mesh=mesh,
                         kernel="wgl-sharded")
    if reach:
        mask, unk = _drain(out, reach=True)
        return mask[:n_rows], unk[:n_rows]
    return _drain(out, reach=False)[:n_rows]


def analysis_batch_sharded(model, hists, mesh=None, W: int | None = None,
                           F: int | None = None) -> list[dict]:
    """analysis_batch across a mesh: the ensemble benchmark path
    (BASELINE config 5: 1024 generated histories checked concurrently)."""
    from . import wgl as wgl_mod
    from ..history import History
    from .encode import EncodingError, encode

    encs, idx_map, results = [], [], [None] * len(hists)
    for i, hh in enumerate(hists):
        if not isinstance(hh, History):
            hh = History(hh)
        try:
            encs.append(encode(model, hh))
            idx_map.append(i)
        except EncodingError:
            out = wgl_mod.search_host_model(model, hh, witness=True)
            out["analyzer"] = "model"
            results[i] = out
    if encs:
        from .wgl import RangeError
        try:
            res = check_batch_sharded(encs, mesh=mesh,
                                      W=W if W is not None else 32,
                                      F=F if F is not None else 64)
        except RangeError:
            res = [wgl_mod.UNKNOWN] * len(encs)
        for j, i in enumerate(idx_map):
            r = int(res[j])
            if r == wgl_mod.VALID:
                results[i] = {"valid?": True, "analyzer": "tpu-sharded"}
            else:
                # Bounded anomaly path: localize the failing segment on
                # device instead of re-searching the whole history on
                # host (unbounded at 1M-op scale).
                out = wgl_mod.extract_witness(encs[j], W=W, F=F)
                out["analyzer"] = ("tpu-sharded" if r == wgl_mod.INVALID
                                   else "tpu+host-fallback")
                results[i] = wgl_mod._search_stats(out)
    return results
