"""Device-mesh sharding of the WGL search: many histories (or many
segments of one long history) checked concurrently across chips.

The reference's scaling story for checking is host-side only: bounded
pmap over per-key subhistories (jepsen/src/jepsen/independent.clj:271-377)
and fork-join folds over history chunks (checker.clj:139-200). Here the
batch dimension of the WGL kernel — independent keys, ensemble histories,
or segments x start-states of one long history — is laid out over a 1-D
`jax.sharding.Mesh`, so each chip runs its frontier shard and the only
cross-chip traffic is the while_loop's any(running) reduction riding ICI.
"""

from __future__ import annotations

from functools import partial
from typing import Sequence

import numpy as np

from functools import lru_cache

from .. import telemetry
from . import profiler
from . import wgl as wgl_mod
from .encode import Encoded
from .wgl import PackedBatch, _drain, _kernel, _next_pow2, _timed_launch


@lru_cache(maxsize=None)
def _jitted_sharded(mesh, W: int, F: int, max_iters: int, reach: bool):
    """One jitted+sharded kernel per (mesh, shape bucket); jax.jit then
    caches compiled executables per array shape."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    repl = NamedSharding(mesh, P())
    shard = NamedSharding(mesh, P("b"))
    # trailing outputs: the scalar iteration count plus the three
    # batch-summed search-shape level series (all replicated — XLA
    # all-reduces the per-shard partial sums)
    stats = (repl, repl, repl, repl)
    # segment tensors are donated like the single-device path's
    # (wgl.DONATE_ARGNUMS): launch sites re-create device arrays per
    # call, so XLA may reuse the replicated slabs as scratch
    wgl_mod.quiet_unusable_donation()
    return jax.jit(
        partial(_kernel, W=W, F=F, max_iters=max_iters, reach=reach),
        in_shardings=(repl, repl, repl, repl, repl, shard, shard),
        out_shardings=((shard, shard) + stats if reach
                       else (shard,) + stats),
        donate_argnums=wgl_mod.DONATE_ARGNUMS)


def default_mesh(n_devices: int | None = None):
    """A 1-D mesh over the first n (default: all) devices; with the
    multi-host env set (tpu/dist.py), 'all' spans every host's chips
    and the batch axis shards across DCN."""
    import jax

    from . import dist

    dist.ensure_initialized()
    devs = jax.devices()
    if n_devices is not None:
        devs = devs[:n_devices]
    return jax.sharding.Mesh(np.array(devs), ("b",))


def _pad_rows(rows: list, multiple: int) -> list:
    n = _next_pow2(max(len(rows), 1))
    n = max(n, multiple)
    if n % multiple:
        n = ((n // multiple) + 1) * multiple
    return rows + [None] * (n - len(rows))


def check_batch_sharded(encs: Sequence[Encoded], mesh=None, W: int = 32,
                        F: int = 64, reach: bool = False, rows=None):
    """check_batch/check_batch_reach across a device mesh. Segment data
    is replicated; search rows — (segment, start-state) pairs, default
    one per history — are sharded over the mesh's 'b' axis."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    if mesh is None:
        mesh = default_mesh()
    n_dev = mesh.devices.size
    pb = PackedBatch(encs)
    if rows is None:
        rows = [(i, e.init_state) for i, e in enumerate(encs)]
    n_rows = len(rows)
    padded = _pad_rows(list(rows), n_dev)
    row_seg = np.full(len(padded), pb.B, dtype=np.int32)
    st0 = np.zeros(len(padded), dtype=np.int32)
    for i, r in enumerate(padded):
        if r is not None:
            row_seg[i], st0[i] = r

    fn = _jitted_sharded(mesh, W, F, pb.M + 4, reach)
    args = (pb.inv_t, pb.ret_t, pb.trans, pb.m, pb.sufmin,
            row_seg, st0)
    # the (mesh, ...) bucket is disjoint from wgl._launch's by shape
    bucket = (mesh, pb.inv_t.shape, pb.trans.shape[2], len(padded),
              W, F, pb.M + 4, reach)
    telemetry.count("wgl.ensemble.launches")
    telemetry.count("wgl.kernel.rows", len(padded))
    # per-device work attribution: entries of search work landing on
    # each chip's row shard, plus a load-balance ratio (mean/max work
    # — 1.0 means a perfectly even mesh; the figure that, with the
    # replicated-segment H2D cost, explains a flat device sweep)
    work = profiler.device_work(row_seg, pb.m[:pb.B], n_dev)
    balance = (round(float(np.mean(work)) / max(work), 4)
               if work and max(work) else None)
    meta = {"rows": len(padded), "batch": pb.B, "m": pb.M,
            "states": pb.S, "devices": n_dev,
            "device_entries": work, "balance": balance}
    out = _timed_launch(bucket, lambda: fn(*args),
                        kernel="wgl-sharded",
                        lower=lambda: fn.lower(*args), meta=meta)
    if reach:
        mask, unk = _drain(out, reach=True)
        return mask[:n_rows], unk[:n_rows]
    return _drain(out, reach=False)[:n_rows]


def analysis_batch_sharded(model, hists, mesh=None, W: int | None = None,
                           F: int | None = None) -> list[dict]:
    """analysis_batch across a mesh: the ensemble benchmark path
    (BASELINE config 5: 1024 generated histories checked concurrently)."""
    from . import wgl as wgl_mod
    from ..history import History
    from .encode import EncodingError, encode

    encs, idx_map, results = [], [], [None] * len(hists)
    for i, hh in enumerate(hists):
        if not isinstance(hh, History):
            hh = History(hh)
        try:
            encs.append(encode(model, hh))
            idx_map.append(i)
        except EncodingError:
            out = wgl_mod.search_host_model(model, hh, witness=True)
            out["analyzer"] = "model"
            results[i] = out
    if encs:
        from .wgl import RangeError
        try:
            res = check_batch_sharded(encs, mesh=mesh,
                                      W=W if W is not None else 32,
                                      F=F if F is not None else 64)
        except RangeError:
            res = [wgl_mod.UNKNOWN] * len(encs)
        for j, i in enumerate(idx_map):
            r = int(res[j])
            if r == wgl_mod.VALID:
                results[i] = {"valid?": True, "analyzer": "tpu-sharded"}
            else:
                # Bounded anomaly path: localize the failing segment on
                # device instead of re-searching the whole history on
                # host (unbounded at 1M-op scale).
                out = wgl_mod.extract_witness(encs[j], W=W, F=F)
                out["analyzer"] = ("tpu-sharded" if r == wgl_mod.INVALID
                                   else "tpu+host-fallback")
                results[i] = wgl_mod._search_stats(out)
    return results
