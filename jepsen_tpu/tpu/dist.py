"""Multi-host device meshes: the checking plane's distributed
communication backend.

Capability reference: SURVEY §2.5 — the reference's control plane
talks to nodes over SSH; its analysis plane is single-JVM. Here the
analysis plane is a JAX program: within one host, history shards ride
ICI via the mesh in jepsen_tpu.tpu.ensemble; across hosts, JAX's
distributed runtime (jax.distributed.initialize) brings every
process's devices into one global mesh, with collectives crossing DCN.

Environment contract (standard JAX multi-process variables):
  JAX_COORDINATOR_ADDRESS  host:port of process 0
  JAX_NUM_PROCESSES        world size
  JAX_PROCESS_ID           this process's rank
On TPU pods these can all be inferred by the runtime, so
ensure_initialized() also honors a bare JEPSEN_TPU_MULTIHOST=1.
Without any of them it is a no-op: single-host behavior unchanged.
"""

from __future__ import annotations

import logging
import os
import threading

logger = logging.getLogger(__name__)

_lock = threading.Lock()
_initialized = False


def multihost_requested() -> bool:
    return bool(os.environ.get("JAX_COORDINATOR_ADDRESS")
                or os.environ.get("JEPSEN_TPU_MULTIHOST"))


def ensure_initialized() -> bool:
    """Initializes jax.distributed once, iff multi-host env is set.
    Returns True when running multi-host."""
    global _initialized
    if _initialized:
        return True
    if not multihost_requested():
        return False
    with _lock:
        if _initialized:
            return True
        import jax

        kwargs = {}
        coord = os.environ.get("JAX_COORDINATOR_ADDRESS")
        if coord:
            kwargs["coordinator_address"] = coord
        n = os.environ.get("JAX_NUM_PROCESSES")
        if n:
            kwargs["num_processes"] = int(n)
        pid = os.environ.get("JAX_PROCESS_ID")
        if pid:
            kwargs["process_id"] = int(pid)
        logger.info("initializing jax.distributed (%s)", kwargs)
        try:
            jax.distributed.initialize(**kwargs)
        except RuntimeError as e:
            # initialize() must precede the first JAX computation;
            # call ensure_initialized() at program entry (core.run,
            # bench.main) — reaching here later degrades to
            # single-host rather than crashing the check
            logger.warning("jax.distributed.initialize failed "
                           "(call earlier in the program): %s", e)
            return False
        _initialized = True
        return True


def process_info() -> dict:
    """Rank/size for logging and sharded store paths."""
    import jax

    return {"process_index": jax.process_index(),
            "process_count": jax.process_count(),
            "local_devices": len(jax.local_devices()),
            "global_devices": len(jax.devices())}
