"""Elle-style transactional anomaly checking (list-append, rw-register).

Capability reference: the reference wraps the external elle 0.2.1
library (jepsen/src/jepsen/tests/cycle/append.clj:6-27, wr.clj:5-25):
infer ww/wr/rw dependency edges from each transaction's external reads
and writes (txn/src/jepsen/txn.clj:48-80), build the dependency graph,
find strongly-connected components, extract and classify cycle
witnesses (G0, G1a, G1b, G1c, G-single, G2-item), plus non-cycle
anomalies (aborted read, intermediate read, internal inconsistency,
incompatible version orders, duplicate appends).

This module is the HOST REFERENCE engine: plain-Python edge inference
and scipy SCC, kept simple as the correctness baseline. Large
histories dispatch (engine="auto") to the device engine —
jepsen_tpu.tpu.elle_device interns txns/keys/values into int arrays,
infers edges with numpy segment ops, and runs cycle detection through
the batched label-propagation SCC kernel (jepsen_tpu.tpu.scc);
differential tests pin the two engines to identical anomaly results.

Pipeline here:
  1. collect committed/aborted/indeterminate txns from the history;
  2. per-key version orders: for list-append, the longest observed read
     is the spine and every read must be one of its prefixes;
  3. ww/wr/rw edge inference from external reads/writes against the
     spine;
  4. exact SCC via scipy.sparse.csgraph (compiled Tarjan-equivalent:
     the graph step the reference runs on the JVM), cycle witness
     extraction host-side, classified by edge composition.

Realtime edges implement the FULL interval order (A precedes B iff A
completed before B invoked), reduced by a covering-frontier sweep to
O(n * concurrency) edges; per-process chains carry session order.
"""

from __future__ import annotations

import threading
from collections import defaultdict
from typing import Any

import numpy as np

from .. import history as h
from .. import telemetry
from ..history import History
from .. import txn as txnlib

WW, WR, RW, RT, PROC = 0, 1, 2, 3, 4
EDGE_NAMES = {WW: "ww", WR: "wr", RW: "rw", RT: "realtime",
              PROC: "process"}


class Txn:
    __slots__ = ("i", "op", "type", "process", "invoke_pos",
                 "complete_pos", "mops")

    def __init__(self, i, op, type_, process, invoke_pos, complete_pos,
                 mops):
        self.i = i
        self.op = op
        self.type = type_
        self.process = process
        self.invoke_pos = invoke_pos
        self.complete_pos = complete_pos
        self.mops = mops


def collect(hist: History) -> list[Txn]:
    """Pairs txn invocations with completions. Committed (:ok) txns use
    the completion's mops (which carry read results); :fail txns are
    aborted; :info indeterminate."""
    txns: list[Txn] = []
    open_inv: dict[Any, tuple[int, Any]] = {}
    for pos, op in enumerate(hist):
        if not h.is_client_op(op):
            continue
        if op.type == h.INVOKE:
            open_inv[op.process] = (pos, op)
        elif op.type in (h.OK, h.FAIL, h.INFO):
            pair = open_inv.pop(op.process, None)
            if pair is None:
                continue
            inv_pos, inv = pair
            mops = op.value if (op.type == h.OK and op.value is not None
                                ) else inv.value
            txns.append(Txn(len(txns), op, op.type, op.process, inv_pos,
                            pos, mops or []))
    for inv_pos, inv in open_inv.values():
        txns.append(Txn(len(txns), inv, h.INFO, inv.process, inv_pos,
                        1 << 60, inv.value or []))
    return txns


# ---------------------------------------------------------------------------
# list-append analysis
# ---------------------------------------------------------------------------

def _freeze(v):
    return tuple(v) if isinstance(v, list) else v


class AppendAnalysis:
    def __init__(self, hist: History):
        self.txns = collect(hist)
        self.anomalies: dict[str, list] = defaultdict(list)
        # writer[(k, v)] = (txn, position among txn's appends to k,
        #                   total appends by txn to k)
        self.writer: dict = {}
        self._index_appends()
        self.spine: dict = {}      # k -> [v...] observed version order
        self._version_orders()
        self._read_anomalies()
        self.edges = self._edges()

    def _index_appends(self):
        # Writers that may have committed (:ok, or :info indeterminate —
        # a cycle through an unexecuted :info writer can't close, since
        # its outgoing edges all require its values to be observed).
        self.writers_by_key: dict = defaultdict(dict)
        for t in self.txns:
            per_key: dict = defaultdict(list)
            for mop in t.mops:
                f, k, v = mop[0], mop[1], mop[2]
                if f == "append":
                    per_key[k].append(v)
            for k, vs in per_key.items():
                if t.type != h.FAIL:
                    self.writers_by_key[k][t.i] = t
                for j, v in enumerate(vs):
                    key = (k, _freeze(v))
                    prev = self.writer.get(key)
                    if (prev is not None and t.type != h.FAIL
                            and prev[0].type != h.FAIL):
                        self.anomalies["duplicate-appends"].append(
                            {"key": k, "value": v, "op": t.op})
                    if t.type != h.FAIL or prev is None:
                        self.writer[key] = (t, j, len(vs))

    def _reads(self):
        for t in self.txns:
            if t.type != h.OK:
                continue
            for mop in t.mops:
                if mop[0] == "r" and mop[2] is not None:
                    yield t, mop[1], list(mop[2])

    def _version_orders(self):
        longest: dict = {}
        for _t, k, vs in self._reads():
            if len(vs) > len(longest.get(k, [])):
                longest[k] = vs
        self.spine = longest
        for t, k, vs in self._reads():
            sp = self.spine.get(k, [])
            if vs != sp[:len(vs)]:
                self.anomalies["incompatible-order"].append(
                    {"key": k, "read": vs, "spine": sp, "op": t.op})

    def _read_anomalies(self):
        for t, k, vs in self._reads():
            for v in vs:
                w = self.writer.get((k, _freeze(v)))
                if w is None:
                    self.anomalies["unobservable-read"].append(
                        {"key": k, "value": v, "op": t.op})
                    continue
                wt, j, total = w
                if wt.type == h.FAIL:
                    self.anomalies["G1a"].append(
                        {"key": k, "value": v, "op": t.op,
                         "writer": wt.op})
            if vs:
                w = self.writer.get((k, _freeze(vs[-1])))
                if w is not None:
                    wt, j, total = w
                    if j != total - 1 and wt.i != t.i:
                        self.anomalies["G1b"].append(
                            {"key": k, "value": vs[-1], "op": t.op,
                             "writer": wt.op})
            # internal: own appends so far must be a suffix of the read
            pre = []
            for mop in t.mops:
                if mop[1] != k:
                    continue
                if mop[0] == "append":
                    pre.append(mop[2])
                elif mop[0] == "r" and mop[2] is not None:
                    got = list(mop[2])
                    if pre and got[-len(pre):] != pre:
                        self.anomalies["internal"].append(
                            {"key": k, "expected-suffix": pre,
                             "read": got, "op": t.op})
                        break

    def _edges(self) -> list[tuple[int, int, int]]:
        """(src txn idx, dst txn idx, edge type). Per-key data-edge
        counts accumulate in self.key_edges — the search explorer's
        per-key cost attribution."""
        edges: list[tuple[int, int, int]] = []
        self.key_edges: dict = defaultdict(int)
        committed = [t for t in self.txns if t.type == h.OK]
        # ww along each spine; wr/rw from each read's last element
        for k, sp in self.spine.items():
            prev = None
            for v in sp:
                w = self.writer.get((k, _freeze(v)))
                if w is None or w[0].type == h.FAIL:
                    continue  # aborted writers are G1a, not graph nodes
                if prev is not None and prev.i != w[0].i:
                    edges.append((prev.i, w[0].i, WW))
                    self.key_edges[k] += 1
                prev = w[0]
        nxt: dict = {}
        for k, sp in self.spine.items():
            for a, b in zip(sp, sp[1:]):
                nxt[(k, _freeze(a))] = b
        # Targets for empty-read anti-dependencies, one set per key:
        # the first spine writer (the rest of the spine is reachable
        # from it via the ww chain) plus every possibly-committed
        # writer none of whose appends made the observed spine.
        empty_targets: dict = {}

        def _targets(k):
            ts = empty_targets.get(k)
            if ts is None:
                ts = {}
                spine_writers = set()
                for v in self.spine.get(k) or []:
                    w = self.writer.get((k, _freeze(v)))
                    if w is not None and w[0].type != h.FAIL:
                        if not spine_writers:
                            ts[w[0].i] = w[0]
                        spine_writers.add(w[0].i)
                for wt in self.writers_by_key.get(k, {}).values():
                    if wt.i not in spine_writers:
                        ts[wt.i] = wt
                empty_targets[k] = ts
            return ts

        for t, k, vs in self._reads():
            if vs:
                last = _freeze(vs[-1])
                w = self.writer.get((k, last))
                if (w is not None and w[0].i != t.i
                        and w[0].type != h.FAIL):
                    edges.append((w[0].i, t.i, WR))
                    self.key_edges[k] += 1
                # anti-dependency: reader -> writer of the next version
                nv = nxt.get((k, last))
                if nv is not None:
                    w = self.writer.get((k, _freeze(nv)))
                    if (w is not None and w[0].i != t.i
                            and w[0].type != h.FAIL):
                        edges.append((t.i, w[0].i, RW))
                        self.key_edges[k] += 1
            else:
                # An external read of [] precedes EVERY install on this
                # key: in any serial order consistent with it, t runs
                # before each committed appender (else t would observe
                # its value). This also covers keys no read ever
                # observed, which the spine-based path used to miss
                # (round-2 advisor finding).
                for wt in _targets(k).values():
                    if wt.i != t.i:
                        edges.append((t.i, wt.i, RW))
                        self.key_edges[k] += 1
        edges.extend(_order_edges(committed))
        return list(dict.fromkeys(edges))


def order_edge_arrays(committed: list[Txn]):
    """Process chains (session order per process) plus the FULL
    realtime interval order, reduced: a time sweep keeps a covering
    frontier of completed txns, so A reaches B by realtime edges iff
    A completed before B invoked — exactly elle's realtime relation,
    with O(n * concurrency) edges instead of O(n^2). Returns int
    (src, dst, type) arrays; the single implementation behind both the
    host and device engines. Process chains are a lexsort; the sweep
    runs in C (native/order.c) with a Python loop as fallback."""
    n = len(committed)
    if n == 0:
        e = np.empty(0, dtype=np.int64)
        return e, e.copy(), e.copy()
    ids = np.fromiter((t.i for t in committed), dtype=np.int64,
                      count=n)
    inv = np.fromiter((t.invoke_pos for t in committed),
                      dtype=np.int64, count=n)
    comp = np.fromiter((t.complete_pos for t in committed),
                       dtype=np.int64, count=n)
    proc_ids: dict = {}
    procid = np.fromiter(
        (proc_ids.setdefault(t.process, len(proc_ids))
         for t in committed), dtype=np.int64, count=n)
    return order_edges_from_arrays(ids, inv, comp, procid)


def order_edges_from_arrays(ids, inv, comp, procid):
    """Array-native core of order_edge_arrays: txn ids, invoke and
    complete history positions, and per-txn process codes (any ints
    that equal iff the process is the same)."""
    n = len(ids)
    if n == 0:
        e = np.empty(0, dtype=np.int64)
        return e, e.copy(), e.copy()
    # session order: adjacent pairs within each process
    order = np.lexsort((inv, procid))
    same = procid[order][1:] == procid[order][:-1]
    p_src = ids[order[:-1][same]]
    p_dst = ids[order[1:][same]]
    # realtime order: completion-frontier sweep
    try:
        from .. import native

        r_src_i, r_dst_i = native.realtime_edges(inv, comp)
    except RuntimeError:
        r_src_i, r_dst_i = _realtime_edges_arrays_py(inv, comp)
    r_src, r_dst = ids[r_src_i], ids[r_dst_i]
    src = np.concatenate([p_src, r_src])
    dst = np.concatenate([p_dst, r_dst])
    ty = np.concatenate([np.full(len(p_src), PROC, dtype=np.int64),
                         np.full(len(r_src), RT, dtype=np.int64)])
    return src, dst, ty


def _realtime_edges_arrays_py(inv, comp):
    """Pure-Python frontier sweep (the C path's reference semantics),
    over dense row indices. On a completion, drop frontier members the
    completing txn already covers; on an invocation, link every
    frontier member in."""
    src: list[int] = []
    dst: list[int] = []
    events = []
    for i in range(len(inv)):
        events.append((int(inv[i]), 1, i))
        events.append((int(comp[i]), 0, i))
    events.sort()
    frontier: list[int] = []
    for _pos, is_inv, i in events:
        if is_inv:
            for a in frontier:
                if a != i:
                    src.append(a)
                    dst.append(i)
        else:
            frontier[:] = [y for y in frontier
                           if int(comp[y]) >= int(inv[i])]
            frontier.append(i)
    return (np.asarray(src, dtype=np.int64),
            np.asarray(dst, dtype=np.int64))


def _order_edges(committed: list[Txn]) -> list[tuple[int, int, int]]:
    src, dst, ty = order_edge_arrays(committed)
    return [(int(a), int(b), int(c)) for a, b, c in zip(src, dst, ty)]


# ---------------------------------------------------------------------------
# Cycle search + classification
# ---------------------------------------------------------------------------

def _sccs(n: int, edges) -> list[list[int]]:
    """Nontrivial SCCs via scipy's compiled graph kernels."""
    if not edges or n == 0:
        return []
    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import connected_components

    src = np.array([e[0] for e in edges])
    dst = np.array([e[1] for e in edges])
    g = coo_matrix((np.ones(len(src), dtype=np.int8), (src, dst)),
                   shape=(n, n))
    ncomp, labels = connected_components(g, directed=True,
                                         connection="strong")
    groups: dict = defaultdict(list)
    for v, lbl in enumerate(labels):
        groups[lbl].append(v)
    return [vs for vs in groups.values() if len(vs) > 1]


def _find_cycle(scc: list[int], edges) -> list[tuple[int, int, int]]:
    """A short cycle within an SCC: BFS from the first node back to
    itself, restricted to SCC members. Returns edge list."""
    members = set(scc)
    adj: dict = defaultdict(list)
    for s, d, ty in edges:
        if s in members and d in members:
            adj[s].append((d, ty))
    start = scc[0]
    prev: dict = {}
    frontier = [start]
    seen = {start}
    while frontier:
        nf = []
        for u in frontier:
            for v, ty in adj[u]:
                if v == start:
                    path = [(u, v, ty)]
                    while u != start:
                        pu, pty = prev[u]
                        path.append((pu, u, pty))
                        u = pu
                    return list(reversed(path))
                if v not in seen:
                    seen.add(v)
                    prev[v] = (u, ty)
                    nf.append(v)
        frontier = nf
    return []


def _classify(cycle) -> str:
    """Adya class from edge composition. Cycles that only close through
    realtime/process edges get a -realtime/-process suffix (elle naming:
    they violate strict/session variants, not serializability itself)."""
    types = {ty for _s, _d, ty in cycle}
    data = types & {WW, WR, RW}
    n_rw = sum(1 for _s, _d, ty in cycle if ty == RW)
    if data <= {WW}:
        name = "G0"
    elif RW not in data:
        name = "G1c"
    elif n_rw == 1:
        name = "G-single"
    else:
        name = "G2-item"
    if RT in types:
        name += "-realtime"
    elif PROC in types:
        name += "-process"
    return name


_SERIALIZABILITY = {"G0", "G1c", "G-single", "G2-item"}

# The anomaly classes each engine CHECKS — the coverage taxonomy's
# negative-result declaration: a valid verdict still reports every one
# of these as explicitly checked-and-clean (jepsen_tpu.coverage).
CHECKED_APPEND = ("G0", "G1a", "G1b", "G1c", "G-single", "G2-item",
                  "internal", "unobservable-read", "duplicate-appends",
                  "incompatible-order")
CHECKED_WR = ("G0", "G1a", "G1b", "G1c", "G-single", "G2-item",
              "internal", "unobservable-read", "duplicate-writes")


def _with_classes(result: dict, checked) -> dict:
    """Attaches `anomaly-classes` — one outcome per checked class —
    to an elle check result. A -realtime/-process suffixed cycle
    witnesses its base class (it is a stronger-model violation of the
    same Adya phenomenon)."""
    found = set()
    for name in (result.get("anomalies") or {}):
        base = name
        for suffix in ("-realtime", "-process"):
            if base.endswith(suffix):
                base = base[:-len(suffix)]
        found.add(base)
        found.add(name)
    result["anomaly-classes"] = {
        cls: ("witnessed" if cls in found else "clean")
        for cls in checked}
    return result


def cycle_anomalies(n: int, edges, txns) -> dict[str, list]:
    """SCC search over increasingly strong edge subsets, so each cycle
    is reported at the weakest level it violates (mirrors elle's
    cycle-search strategy)."""
    out: dict[str, list] = defaultdict(list)
    subsets = [
        [e for e in edges if e[2] == WW],
        [e for e in edges if e[2] in (WW, WR)],
        [e for e in edges if e[2] in (WW, WR, RW)],
        [e for e in edges if e[2] in (WW, WR, RW, PROC)],
        list(edges),
    ]
    seen_sccs: set = set()
    for sub in subsets:
        for scc in _sccs(n, sub):
            key = frozenset(scc)
            if key in seen_sccs:
                continue
            seen_sccs.add(key)
            cycle = _find_cycle(scc, sub)
            if not cycle:
                continue
            name = _classify(cycle)
            out[name].append({
                "cycle": [txns[s].op for s, _d, _ty in cycle],
                "steps": [{"from": s, "to": d, "type": EDGE_NAMES[ty]}
                          for s, d, ty in cycle]})
    return out


# ---------------------------------------------------------------------------
# Anomaly provenance
# ---------------------------------------------------------------------------

def annotate_op_indices(result: dict, hist) -> dict:
    """Attaches the participating op (invocation) indices to every
    anomaly record as rec['op-indices'] — the provenance link from a
    verdict back to its traced ops (anomaly records usually carry
    completion ops; checker.op_indices resolves them to the
    invocation indices that trace records and timeline anchors join
    on). reports/explain resolves these into per-anomaly trace
    excerpts; web.py links them to pre-filtered Perfetto/timeline
    views. Shared by both the host and device engines so the
    differential tests stay engine-agnostic."""
    from ..checker import op_indices

    if not isinstance(hist, History):
        hist = History(hist)
    for recs in (result.get("anomalies") or {}).values():
        for rec in recs:
            if not isinstance(rec, dict) or "op-indices" in rec:
                continue
            ops = [rec.get(k) for k in ("op", "writer", "previous-ok")]
            ops.extend(rec.get("cycle") or [])
            rec["op-indices"] = op_indices(hist, *ops)
    return result


# ---------------------------------------------------------------------------
# Public checks
# ---------------------------------------------------------------------------

# Histories at least this many ops take the interned-array device
# engine (elle_device) under engine="auto"; below it, flat-Python
# wins on constant factors.
_DEVICE_MIN_OPS = 4000


def _with_search(result: dict, key_edges: dict | None = None) -> dict:
    """Attaches result['search'] — the search explorer's elle half:
    edge volume, witnessing-cycle count, and (host engine) the per-key
    edge cost attribution. Mirrored into elle.search.* telemetry so
    the profile CLI and ledger see search-shape drift."""
    s: dict = {"edges": int(result.get("edge-count") or 0),
               "txns": int(result.get("txn-count") or 0)}
    cycles = sum(1 for recs in (result.get("anomalies") or {}).values()
                 for rec in recs
                 if isinstance(rec, dict) and rec.get("steps"))
    s["cycles"] = cycles
    if key_edges:
        top = sorted(key_edges.items(), key=lambda kv: (-kv[1],
                                                        str(kv[0])))
        s["keys"] = len(key_edges)
        s["per-key-edges"] = {str(k): int(v) for k, v in top[:8]}
    telemetry.count("elle.search.edges", s["edges"])
    if cycles:
        telemetry.count("elle.search.cycles", cycles)
    result["search"] = s
    return result


def _finish(result: dict, hist, family: str,
            opts: dict | None, key_edges: dict | None = None) -> dict:
    """Shared tail of both public checks: search stats always, a
    verdict certificate when the caller opted in (checker wrappers
    pass opts['certify']; raw bench calls don't pay for proofs)."""
    _with_search(result, key_edges)
    if (opts or {}).get("certify"):
        from . import certify as certify_mod

        certify_mod.attach_elle(hist, result, family)
    return result


def _degrade_to_host(which: str, e: Exception) -> list[str]:
    """Device-engine failure (XLA OOM / compile): count the ladder
    rung and fall back to the host reference engine, which computes
    the identical anomaly set (the differential tests pin this). A
    non-device exception is a real bug and re-raises."""
    import logging

    from .wgl import device_error_kind

    kind = device_error_kind(e)
    if kind is None:
        raise e
    from .. import telemetry

    telemetry.count(f"elle.ladder.{kind}")
    telemetry.count("elle.ladder.host-fallback")
    logging.getLogger(__name__).warning(
        "elle %s device engine failed (%s: %s); falling back to the "
        "host engine", which, kind, str(e)[:200])
    return [kind, "host-fallback"]


def check_list_append(hist, opts: dict | None = None) -> dict:
    """elle.list-append/check equivalent: infers the dependency graph
    from append/read txns and reports anomalies.

    opts["engine"]: "host" (this module's reference implementation),
    "device" (interned arrays + batched SCC, jepsen_tpu.tpu.elle_device),
    or "auto" (default: device for large histories, host otherwise;
    non-internable histories always fall back to host)."""
    if not isinstance(hist, History):
        hist = History(hist)
    engine = (opts or {}).get("engine", "auto")
    degraded = None
    if engine == "device" or (engine == "auto"
                              and len(hist) >= _DEVICE_MIN_OPS):
        from . import elle_device
        try:
            return _finish(_with_classes(annotate_op_indices(
                elle_device.check_list_append_device(hist), hist),
                CHECKED_APPEND), hist, "list-append", opts)
        except elle_device.Unvectorizable:
            if engine == "device":
                raise
        except Exception as e:  # noqa: BLE001 — device ladder
            degraded = _degrade_to_host("list-append", e)
    a = AppendAnalysis(hist)
    anomalies = dict(a.anomalies)
    for name, ws in cycle_anomalies(len(a.txns), a.edges,
                                    a.txns).items():
        anomalies[name] = ws
    types = sorted(anomalies.keys())
    out = {
        "valid?": not anomalies,
        "anomaly-types": types,
        "anomalies": {k: v[:8] for k, v in anomalies.items()},
        "edge-count": len(a.edges),
        "txn-count": len(a.txns),
    }
    if degraded:
        out["degradation"] = degraded
    return _finish(_with_classes(annotate_op_indices(out, hist),
                                 CHECKED_APPEND),
                   hist, "list-append", opts, a.key_edges)


def check_rw_register(hist, opts: dict | None = None) -> dict:
    """elle.rw-register/check equivalent over write/read registers,
    assuming distinct written values per key (the generator's
    guarantee). Proven edges only: wr (read-from), ww via
    write-follows-read within a txn, rw against the successor in the
    proven version chain, plus process/realtime order.

    opts["engine"]: "host" (scipy SCC per graded subset), "device"
    (the fully interned array path in elle_device: vectorized edge
    inference + batched SCC), or "auto" (default: device for large
    histories). Histories the device path can't intern fall back to
    this host implementation, which stays the correctness reference."""
    if not isinstance(hist, History):
        hist = History(hist)
    engine = (opts or {}).get("engine", "auto")
    want_device = (engine == "device"
                   or (engine == "auto"
                       and len(hist) >= _DEVICE_MIN_OPS))
    degraded = None
    if want_device:
        from . import elle_device

        try:
            return _finish(_with_classes(annotate_op_indices(
                elle_device.check_rw_register_device(hist), hist),
                CHECKED_WR), hist, "rw-register", opts)
        except elle_device.Unvectorizable:
            pass  # host edge inference below; SCC still on device
        except Exception as e:  # noqa: BLE001 — device ladder
            degraded = _degrade_to_host("rw-register", e)
            want_device = False  # host SCC too: the device just failed
    txns = collect(hist)
    anomalies: dict[str, list] = defaultdict(list)
    writer: dict = {}
    intermediate: dict = {}  # (k, v) -> txn, for non-final writes
    for t in txns:
        per_key_writes: dict = defaultdict(list)
        for mop in t.mops:
            f, k, v = mop[0], mop[1], mop[2]
            if f == "w":
                key = (k, _freeze(v))
                prev = writer.get(key)
                if (prev is not None and t.type != h.FAIL
                        and prev.type != h.FAIL):
                    anomalies["duplicate-writes"].append(
                        {"key": k, "value": v, "op": t.op})
                if t.type != h.FAIL or prev is None:
                    writer[key] = t
                per_key_writes[k].append(v)
        if t.type != h.FAIL:
            for k, vs in per_key_writes.items():
                for v in vs[:-1]:
                    intermediate[(k, _freeze(v))] = t

    # internal consistency: each mop must agree with the txn's own
    # prior reads/writes of that key (elle.rw-register internal)
    for t in txns:
        if t.type != h.OK:
            continue
        expected: dict = {}
        for mop in t.mops:
            f, k, v = mop[0], mop[1], mop[2]
            if f == "w":
                expected[k] = v
            elif f == "r" and v is not None:
                if k in expected and expected[k] != v:
                    anomalies["internal"].append(
                        {"key": k, "expected": expected[k],
                         "read": v, "op": t.op})
                expected[k] = v

    edges: list[tuple[int, int, int]] = []
    key_edges: dict = defaultdict(int)
    succ: dict = {}  # (k, v) -> next written value, when proven
    for t in txns:
        if t.type != h.OK:
            continue
        last_read: dict = {}
        for mop in t.mops:
            f, k, v = mop[0], mop[1], mop[2]
            if f == "r" and v is not None:
                w = writer.get((k, _freeze(v)))
                if w is None:
                    anomalies["unobservable-read"].append(
                        {"key": k, "value": v, "op": t.op})
                else:
                    if w.type == h.FAIL:
                        anomalies["G1a"].append(
                            {"key": k, "value": v, "op": t.op,
                             "writer": w.op})
                    elif w.i != t.i:
                        iw = intermediate.get((k, _freeze(v)))
                        if iw is not None and iw.i != t.i:
                            anomalies["G1b"].append(
                                {"key": k, "value": v, "op": t.op,
                                 "writer": iw.op})
                        edges.append((w.i, t.i, WR))
                        key_edges[k] += 1
                last_read[k] = v
            elif f == "w":
                # write-follows-read: proven ww + version succession
                pv = last_read.pop(k, None)
                if pv is not None:
                    pw = writer.get((k, _freeze(pv)))
                    if pw is not None and pw.i != t.i:
                        edges.append((pw.i, t.i, WW))
                        key_edges[k] += 1
                    succ[(k, _freeze(pv))] = v
    for t in txns:
        if t.type != h.OK:
            continue
        for k, v in txnlib.ext_reads(t.mops).items():
            if v is None:
                continue
            nv = succ.get((k, _freeze(v)))
            if nv is not None:
                w = writer.get((k, _freeze(nv)))
                if w is not None and w.i != t.i and w.type == h.OK:
                    edges.append((t.i, w.i, RW))
                    key_edges[k] += 1
    committed = [t for t in txns if t.type == h.OK]
    cyc = None
    if want_device:
        # unvectorizable values (e.g. strings): edge inference stayed
        # host-side above, but cycle detection still rides the batched
        # device SCC over plain int txn-index edges
        from . import elle_device

        try:
            e = (np.asarray(edges, dtype=np.int64).reshape(-1, 3)
                 if edges else np.empty((0, 3), dtype=np.int64))
            o_src, o_dst, o_ty = order_edge_arrays(committed)
            src = np.concatenate([e[:, 0], o_src])
            dst = np.concatenate([e[:, 1], o_dst])
            ty = np.concatenate([e[:, 2], o_ty])
            n_edges = int(len(src))
            cyc = elle_device.cycle_anomalies_arrays(
                len(txns), src, dst, ty, txns)
        except Exception as de:  # noqa: BLE001 — device ladder
            degraded = _degrade_to_host("rw-register-scc", de)
    if cyc is None:
        edges.extend(_order_edges(committed))
        n_edges = len(edges)
        cyc = cycle_anomalies(len(txns), edges, txns)
    for name, ws in cyc.items():
        anomalies[name] = ws
    out = {
        "valid?": not anomalies,
        "anomaly-types": sorted(anomalies.keys()),
        "anomalies": {k: v[:8] for k, v in anomalies.items()},
        "edge-count": n_edges,
        "txn-count": len(txns),
    }
    if degraded:
        out["degradation"] = degraded
    return _finish(_with_classes(annotate_op_indices(out, hist),
                                 CHECKED_WR),
                   hist, "rw-register", opts, key_edges)



# ---------------------------------------------------------------------------
# Streaming elle (checkpoint-and-extend, doc/robustness.md)
# ---------------------------------------------------------------------------

_INF_POS = 1 << 60


class StreamingElle:
    """Incremental committed-txn consumer: the streaming-wgl contract
    (fleet.scheduler.StreamingRun) for the elle families. As chunks
    arrive, the CLOSED txn frontier — txns whose completion is already
    streamed, which is append-stable under growth — extends the
    dependency graph, and cycle re-search is scoped to SCCs touching
    the suffix: new txns, or endpoints of edges the previous step had
    not seen.

    Honesty rules (mirroring streaming wgl's):
      * a cycle or a monotone read anomaly (G1a/G1b/internal/
        duplicate-appends/incompatible-order — none can un-happen as
        the history grows, given spine prefix-stability) tightens the
        verdict to `tentative-invalid` mid-stream;
      * a retroactive spine reorder (a longer read that REWRITES an
        already-consumed version-order prefix) means earlier graph
        extensions were built on a version order the full history
        contradicts: the stream reports `unknown` and stops
        tightening — the final check stays authoritative;
      * `unobservable-read` alone never tightens: the writer may
        simply not have streamed yet (indecision, not anomaly).

    Only list-append streams (its spine IS the observed version
    order); other families report `unsupported` and rely on the final
    check — exactly how streaming wgl treats >32-state models.

    Checkpoints: after each consumed frontier the `elle` record
    (family, n_closed, per-key versions, SCC condensation frontier)
    goes to `ckpt_sink`; `seed()` resumes from a digest-verified
    record so a restarted server re-searches only the suffix.
    """

    _guarded_by_lock = {"_lock": ("_ops", "_since", "_n_closed",
                                  "_versions", "_edges_seen", "_state",
                                  "_inflight", "_frac")}

    STREAM_EVERY = 128

    def __init__(self, family: str, tenant: str = "", run: str = ""):
        self.family = family
        self.tenant = tenant
        self.run = run
        self._ops: list = []
        self._since = 0
        self._lock = threading.Lock()
        self._n_closed = 0
        self._versions: dict[str, list] = {}
        self._edges_seen: set = set()
        self._frac = 0.0
        self._state = "streaming" if family == "list-append" \
            else "unsupported"
        self._inflight = False
        self.ckpt_sink = None  # set at attach time, before streaming

    # -- the StreamingRun duck-typed surface ----------------------------

    def add_ops(self, ops: list) -> None:
        with self._lock:
            self._ops.extend(ops)
            self._since += len(ops)
            due = self._since >= self.STREAM_EVERY
        if due:
            self.step()

    def status(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "checked-frac": round(self._frac, 4),
                    "ops": len(self._ops)}

    def seed(self, ops: list, rec: dict | None) -> bool:
        """Restart recovery: adopt the replayed ops and — when the
        record digest-matches their prefix — the consumed frontier, so
        the first post-restart step re-searches only the suffix. A
        stale/mismatched record is counted and ignored (full
        re-consume, never a wrong tightening)."""
        from . import ckpt

        resumed = False
        if rec is not None and self._state == "streaming":
            ok = (rec.get("kind") == "elle"
                  and rec.get("family") == self.family
                  and rec.get("n_ops", 0) <= len(ops)
                  and ckpt.ops_digest(ops, rec["n_ops"])
                  == rec.get("digest"))
            if ok:
                resumed = True
                telemetry.count("ckpt.resumed")
            else:
                telemetry.count("ckpt.stale")
        with self._lock:
            self._ops = list(ops)
            if resumed:
                self._n_closed = int(rec["n_closed"])
                self._versions = {str(k): list(v) for k, v
                                  in rec["versions"].items()}
                fr = rec.get("frontier") or {}
                if fr.get("state") in ("tentative-invalid", "unknown"):
                    self._state = fr["state"]
            self._since = max(len(ops), self.STREAM_EVERY)
        return resumed

    def step(self) -> None:
        with self._lock:
            if self._state != "streaming" or self._inflight:
                return
            self._inflight = True
            self._since = 0
        threading.Thread(
            target=self._step_work,
            name=f"elle-stream-{self.tenant}-{self.run}",
            daemon=True).start()

    # -- the consuming step ---------------------------------------------

    @staticmethod
    def _vjson(v):
        from ..store import format as fmt

        return fmt.jsonable(_freeze(v))

    def _settle(self, state: str | None = None) -> None:
        with self._lock:
            self._inflight = False
            if state is not None:
                self._state = state
            elif self._since < self.STREAM_EVERY:
                self._since = self.STREAM_EVERY

    def _step_work(self) -> None:
        try:
            with self._lock:
                snapshot = list(self._ops)
                lo = self._n_closed
                old_versions = {k: list(v) for k, v
                                in self._versions.items()}
                edges_seen = set(self._edges_seen)
            a = AppendAnalysis(History(snapshot))
            closed = sum(1 for t in a.txns
                         if t.complete_pos < _INF_POS)
            if closed <= lo:
                return self._settle()
            # retroactive spine reorder: an already-consumed version-
            # order prefix was rewritten by a longer read -> the graph
            # extensions consumed so far may be wrong. Honest unknown.
            new_versions = {str(k): [self._vjson(v) for v in sp]
                            for k, sp in a.spine.items()}
            for k, old in old_versions.items():
                if new_versions.get(k, [])[:len(old)] != old:
                    telemetry.count("elle.stream.reordered")
                    return self._settle("unknown")
            # monotone read anomalies tighten immediately;
            # unobservable-read is indecision (writer may stream later)
            monotone = {name: recs for name, recs
                        in a.anomalies.items()
                        if name != "unobservable-read" and recs}
            # suffix-scoped cycle re-search: only SCCs touching a new
            # txn or a new edge can contain a new cycle
            new_edges = [e for e in a.edges if e not in edges_seen]
            touched = {e[0] for e in new_edges} \
                | {e[1] for e in new_edges}
            cyclic = False
            for scc in _sccs(len(a.txns), a.edges):
                if not (touched & set(scc)
                        or any(i >= lo for i in scc)):
                    continue
                if _find_cycle(scc, a.edges):
                    cyclic = True
                    break
            with self._lock:
                self._inflight = False
                if self._state != "streaming":
                    return
                self._n_closed = closed
                self._versions = new_versions
                self._edges_seen = set(a.edges)
                self._frac = closed / max(len(a.txns), 1)
                if monotone or cyclic:
                    self._state = "tentative-invalid"
                    telemetry.count("elle.stream.tentative-invalid")
            telemetry.count("elle.stream.segments")
            self._checkpoint(snapshot, closed, new_versions,
                             len(a.edges))
        except Exception:  # noqa: BLE001 — streaming is advisory
            import logging

            logging.getLogger(__name__).exception(
                "streaming elle step failed")
            return self._settle("unknown")
        with self._lock:
            pending = (self._state == "streaming"
                       and self._since >= self.STREAM_EVERY)
        if pending:
            self.step()

    def _checkpoint(self, snapshot, closed, versions, n_edges) -> None:
        sink = self.ckpt_sink
        if sink is None:
            return
        from . import ckpt

        with self._lock:
            state = self._state
        try:
            sink({"v": ckpt.VERSION, "kind": "elle",
                  "family": self.family, "n_closed": closed,
                  "versions": versions,
                  "frontier": {"state": state, "edges": n_edges},
                  "n_ops": len(snapshot),
                  "digest": ckpt.ops_digest(snapshot)})
        except Exception:  # noqa: BLE001 — checkpoints are advisory
            import logging

            logging.getLogger(__name__).exception(
                "elle stream checkpoint sink failed")
