"""Device-performance profiling for compiled-kernel launches.

telemetry.py answers "where did the run spend its time?"; this module
answers "what did the device DO with it?". Every compiled-kernel launch
site (wgl batched search, the mesh-sharded ensemble path, the SCC
coloring kernel, the elle device engines, plus the host-side encode and
pack stages that feed them) reports a per-launch record:

  - lowered-HLO cost analysis: FLOPs, bytes accessed (from
    jax.stages.Lowered.cost_analysis()) and peak device memory
    (argument + output + temp sizes from Compiled.memory_analysis()),
    computed ONCE per compile bucket and attached to every launch of
    that bucket;
  - the wall vs device split: host encode / H2D transfer / dispatch /
    device compute / D2H readback, each phase timed separately;
  - compile-cache accounting per shape bucket (hooked into the
    existing wgl._compiled_buckets claim), so the profile shows hit
    rates, not just compile totals;
  - for mesh-sharded launches: per-device work attribution (entries of
    search work landing on each chip) and a load-balance figure — the
    data that explains a flat device-count sweep.

Records flow through the existing observability fabric: each finished
launch mirrors into telemetry as a `kernel:<name>` span (so it lands in
telemetry.jsonl and the Perfetto export's device track) and as
`profiler.<kernel>.*` counters/gauges (so metrics.json carries the
aggregate the `profile` CLI and web section render). The recorder is
always on and adds two dict updates per launch; cost analysis costs one
lowering per NEW bucket only (JEPSEN_TPU_PROFILE_COST=0 disables it).

Cross-run trending lives in jepsen_tpu.ledger (the bench perf ledger);
parallel_efficiency() below is the shared scaling metric both the
multichip dry run and bench report.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from contextlib import contextmanager
from typing import Any, Callable

from .. import telemetry, util

logger = logging.getLogger(__name__)

# Launch-phase keys, in pipeline order. Every *_ns field of a record
# that isn't one of these is still additive host time (compile_ns).
PHASES = ("encode_ns", "h2d_ns", "dispatch_ns", "compute_ns", "d2h_ns")

# Cost fields attached per bucket (None when the backend can't say).
COST_FIELDS = ("flops", "bytes_accessed", "peak_memory_bytes")

_COST_ENABLED = os.environ.get("JEPSEN_TPU_PROFILE_COST", "1") != "0"


def _memory_analysis_enabled() -> bool:
    """Whether peak-memory stats are worth their price. flops/bytes
    come from Lowered.cost_analysis() (no compile), but
    memory_analysis needs a Compiled — and Lowered.compile() does NOT
    reuse the jit dispatch path's executable, so it's a second full
    XLA compile per fresh bucket unless something makes it cheap:
    the CPU backend (sub-second compiles) or a persistent compilation
    cache serving it from disk (bench enables one; a ~35s TPU kernel
    compile must not be paid twice). JEPSEN_TPU_PROFILE_MEMORY
    overrides in either direction."""
    env = os.environ.get("JEPSEN_TPU_PROFILE_MEMORY")
    if env is not None:
        return env != "0"
    try:
        import jax

        if jax.default_backend() == "cpu":
            return True
        return bool(jax.config.jax_compilation_cache_dir)
    except Exception:  # noqa: BLE001 — no jax, no memory stats
        return False

# Per-launch records are mirrored into telemetry individually only up
# to this many launches per kernel per run; past it, only aggregates
# accumulate (a 1024-history ensemble must not write 1024 span lines).
# The cap is configurable (JEPSEN_TPU_PROFILE_MAX_SPANS) and NEVER
# silent: every launch past it counts `profiler.<k>.spans_dropped` in
# metrics.json, so a truncated telemetry mirror is visible instead of
# reading as "that's all the launches there were".
MAX_MIRRORED_LAUNCHES = 64


def max_mirrored_launches() -> int:
    try:
        return int(os.environ.get("JEPSEN_TPU_PROFILE_MAX_SPANS",
                                  MAX_MIRRORED_LAUNCHES))
    except ValueError:
        return MAX_MIRRORED_LAUNCHES


def _fresh_bucket_cost(lower: Callable, bucket_key) -> dict:
    """FLOPs / bytes / peak memory for a newly-compiled bucket.

    `lower` is a zero-arg thunk returning the jax.stages.Lowered for
    the same (args, static) the launch used. flops/bytes read off the
    Lowered alone (no compile); peak memory needs Lowered.compile(),
    which is a second XLA compile of the bucket, so it only runs when
    _memory_analysis_enabled() says that's cheap. Any failure (backend
    without cost analysis, jax API drift) degrades to None fields —
    profiling must never break a launch."""
    cost: dict = {k: None for k in COST_FIELDS}
    if not _COST_ENABLED or lower is None:
        return cost
    try:
        lowered = lower()
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            if ca.get("flops") is not None:
                cost["flops"] = float(ca["flops"])
            if ca.get("bytes accessed") is not None:
                cost["bytes_accessed"] = float(ca["bytes accessed"])
        ma = None
        if _memory_analysis_enabled():
            try:
                ma = lowered.compile().memory_analysis()
            except Exception:  # noqa: BLE001 — memory is optional
                ma = None
        if ma is not None:
            peak = sum(
                int(getattr(ma, f, 0) or 0)
                for f in ("argument_size_in_bytes",
                          "output_size_in_bytes",
                          "temp_size_in_bytes"))
            if peak:
                cost["peak_memory_bytes"] = peak
    except Exception as e:  # noqa: BLE001 — profiling is best-effort
        logger.debug("cost analysis failed for bucket %r: %r",
                     bucket_key, e)
    return cost


class Profiler:
    """Per-launch device-profile recorder. Thread-safe; one global
    instance (get()) serves the process, tests may make their own.
    `enabled=False` makes it a no-op recorder: records still open and
    park (call sites mutate them unconditionally) but nothing is
    aggregated, mirrored to telemetry, or cost-analyzed."""

    # concurrency-lint contract (jepsen_tpu.analysis.concurrency,
    # doc/static-analysis.md): these attrs are written under _lock
    # only (or in *_locked methods whose callers hold it)
    _guarded_by_lock = {"_lock": ("_records", "_pending",
                                  "_bucket_cost", "_seen_buckets",
                                  "cache_stats")}

    def __init__(self, enabled: bool = True):
        self.enabled = enabled
        self._lock = threading.Lock()
        self._records: list[dict] = []
        self._pending: dict[int, dict] = {}   # id(device out) -> record
        self._bucket_cost: dict[Any, dict] = {}
        self._seen_buckets: dict[str, set] = {}
        self.cache_stats: dict[str, dict] = {}

    # -- launch records ----------------------------------------------------

    def begin(self, kernel: str, bucket=None, **attrs) -> dict:
        """Opens a launch record. `kernel` is a dot-free site name
        ('wgl', 'scc', ...); `bucket` the compile-shape key."""
        rec: dict = {"kernel": kernel.replace(".", "-"),
                     "t0": util.relative_time_nanos(),
                     # straggler guard: a telemetry.reset() (next run
                     # starting) before this record finishes means its
                     # clock origin is stale — _finish_locked drops it
                     "_epoch": telemetry.get().epoch}
        if bucket is not None:
            rec["bucket"] = repr(bucket)
        for k, v in attrs.items():
            if v is not None:
                rec[k] = v
        return rec

    @contextmanager
    def phase(self, rec: dict | None, name: str):
        """Times one pipeline phase (see PHASES) into the record."""
        if rec is None:
            yield
            return
        t0 = time.monotonic_ns()
        try:
            yield
        finally:
            rec[name] = rec.get(name, 0) + (time.monotonic_ns() - t0)

    def cache_event(self, kernel: str, fresh: bool) -> None:
        """One compile-cache lookup: miss (fresh bucket, compiling) or
        hit (bucket already compiled this process)."""
        if not self.enabled:
            return
        kernel = kernel.replace(".", "-")
        with self._lock:
            st = self.cache_stats.setdefault(kernel,
                                             {"hits": 0, "misses": 0})
            st["misses" if fresh else "hits"] += 1
        telemetry.count(f"profiler.{kernel}.compile."
                        + ("miss" if fresh else "hit"))

    def bucket_fresh(self, site: str, bucket) -> bool:
        """First-sighting test for launch sites without their own
        compiled-bucket set (scc); counts the cache event too, and
        gauges the site's distinct-bucket cardinality (set size, NOT
        the miss counter: a failed first launch is unclaimed and
        retried, and its second miss must not inflate the gauge) —
        graftlint R5's runtime cross-check."""
        with self._lock:
            seen = self._seen_buckets.setdefault(site, set())
            fresh = bucket not in seen
            if fresh:
                seen.add(bucket)
            n = len(seen)
        self.cache_event(site, fresh)
        if fresh:
            telemetry.gauge(f"profiler.{site}.bucket_cardinality", n)
        return fresh

    def bucket_unclaim(self, site: str, bucket) -> None:
        """Un-claims a bucket whose first launch failed (the analog of
        wgl._timed_launch discarding its _compiled_buckets claim): the
        next attempt really recompiles and must record a miss + fresh
        compile_ns, not a phantom cache hit."""
        with self._lock:
            self._seen_buckets.get(site, set()).discard(bucket)

    def bucket_cost(self, bucket, lower: Callable | None,
                    fresh: bool) -> dict:
        """The bucket's cost analysis: computed on first sight (when
        `fresh`, right after its compile), served from cache after."""
        if not self.enabled:
            return {k: None for k in COST_FIELDS}
        with self._lock:
            cached = self._bucket_cost.get(bucket)
        if cached is not None:
            return cached
        if not fresh and lower is None:
            return {k: None for k in COST_FIELDS}
        cost = _fresh_bucket_cost(lower, bucket)
        with self._lock:
            self._bucket_cost.setdefault(bucket, cost)
        return cost

    def attach(self, out, rec: dict) -> Any:
        """Parks an open record until the launch's output is drained
        (the async-dispatch handoff: _launch returns, _drain blocks).
        Keyed by the output object's id — the caller holds the output
        alive until drain, so the id can't be recycled underneath."""
        if rec is None:
            return out
        with self._lock:
            if len(self._pending) > 256:
                # exception paths may abandon records; cap the parking
                # lot, finalizing EVERY stray so all of them still
                # aggregate (an in-flight launch loses its parked
                # record to the sweep — its _drain finds None — but
                # its dispatch-side phases are preserved here)
                for stray in list(self._pending.values()):
                    self._finish_locked(stray)
                self._pending.clear()
            self._pending[id(out)] = rec
        return out

    def take(self, out) -> dict | None:
        with self._lock:
            return self._pending.pop(id(out), None)

    def finish(self, rec: dict | None) -> dict | None:
        """Closes a record: stamps t1, mirrors it into telemetry (span
        + per-kernel aggregate counters)."""
        if rec is None:
            return None
        with self._lock:
            self._finish_locked(rec)
        return rec

    def _finish_locked(self, rec: dict) -> None:
        if "t1" in rec:
            return
        rec["t1"] = util.relative_time_nanos()
        epoch = rec.pop("_epoch", None)
        tel = telemetry.get()
        if not self.enabled or (epoch is not None
                                and epoch != tel.epoch):
            # disabled recorder, or a straggler finishing after the
            # next run began: its t0 was measured against the previous
            # run's clock origin — dropping beats misfiling
            return
        self._records.append(rec)
        k = rec["kernel"]
        tel.count(f"profiler.{k}.launches")
        wall = max(rec["t1"] - rec["t0"], 0)
        tel.count(f"profiler.{k}.wall_ns", wall)
        for ph in PHASES:
            if rec.get(ph):
                tel.count(f"profiler.{k}.{ph}", int(rec[ph]))
        if rec.get("compile_ns"):
            tel.count(f"profiler.{k}.compile_ns", int(rec["compile_ns"]))
        if rec.get("iterations"):
            tel.count(f"profiler.{k}.iterations", int(rec["iterations"]))
        if rec.get("rows"):
            tel.count(f"profiler.{k}.rows", int(rec["rows"]))
        # search-explorer aggregates (wgl _drain attaches the series)
        if rec.get("states_explored"):
            tel.count(f"profiler.{k}.states",
                      int(rec["states_explored"]))
        if rec.get("dedup_hits"):
            tel.count(f"profiler.{k}.dedup_hits",
                      int(rec["dedup_hits"]))
        if rec.get("frontier_peak"):
            tel.gauge_max(f"profiler.{k}.frontier_peak",
                          int(rec["frontier_peak"]))
        if rec.get("flops"):
            tel.count(f"profiler.{k}.flops", int(rec["flops"]))
        if rec.get("bytes_accessed"):
            tel.count(f"profiler.{k}.bytes", int(rec["bytes_accessed"]))
        if rec.get("peak_memory_bytes"):
            tel.gauge_max(f"profiler.{k}.peak_memory_bytes",
                          int(rec["peak_memory_bytes"]))
        if rec.get("devices"):
            tel.gauge_max(f"profiler.{k}.devices", int(rec["devices"]))
        if rec.get("balance") is not None:
            tel.gauge(f"profiler.{k}.balance", rec["balance"])
        n_k = sum(1 for r in self._records if r["kernel"] == k)
        if n_k <= max_mirrored_launches():
            attrs = {kk: v for kk, v in rec.items()
                     if kk not in ("kernel", "t0", "t1")
                     and v is not None}
            tel.record_span(f"kernel:{k}", rec["t0"], rec["t1"], attrs,
                            epoch=epoch)
        else:
            # no silent caps: truncation of the telemetry mirror is
            # itself a metric (aggregates above still saw the launch)
            tel.count(f"profiler.{k}.spans_dropped")

    # -- simple sites ------------------------------------------------------

    def record_host(self, kernel: str, ns: int, **attrs) -> None:
        """Aggregate-only accounting for cheap host stages (encode,
        pack) that run thousands of times per analysis: counters only,
        no per-call record."""
        if not self.enabled:
            return
        k = kernel.replace(".", "-")
        tel = telemetry.get()
        tel.count(f"profiler.{k}.launches")
        tel.count(f"profiler.{k}.wall_ns", int(ns))
        tel.count(f"profiler.{k}.encode_ns", int(ns))
        for name, v in attrs.items():
            if isinstance(v, (int, float)) and v:
                tel.count(f"profiler.{k}.{name}", int(v))

    # -- views / lifecycle -------------------------------------------------

    def records(self) -> list[dict]:
        with self._lock:
            return list(self._records)

    def reset(self) -> None:
        """Clears per-run state. Bucket cost/seen caches persist: they
        mirror the process-level XLA compile cache, which a new run
        still hits."""
        with self._lock:
            self._records = []
            self._pending = {}
            self.cache_stats = {}

    # -- shape buckets -----------------------------------------------------

    def shape_buckets(self) -> dict[str, set]:
        """Every compiled shape bucket this process has seen, per
        launch site: this recorder's own seen-sets (scc et al) merged
        with the wgl kernel's _compiled_buckets claim set (which the
        single-device and mesh-sharded launch paths share). The
        lint's trace-shape source (graftlint R5 cross-checks the
        cardinality; the registry re-traces the real wgl shapes)."""
        with self._lock:
            out = {site: set(s)
                   for site, s in self._seen_buckets.items()}
        try:
            from . import wgl as _wgl  # lazy: wgl imports this module

            with _wgl._buckets_lock:  # snapshot vs concurrent claims
                claimed = set(_wgl._compiled_buckets)
            out.setdefault("wgl", set()).update(claimed)
        except Exception:  # noqa: BLE001 — accessor is best-effort
            logger.debug("wgl bucket set unavailable", exc_info=True)
        return out


_global = Profiler()


def get() -> Profiler:
    return _global


def reset() -> None:
    _global.reset()


def shape_buckets() -> dict[str, set]:
    """Module-level façade over Profiler.shape_buckets()."""
    return _global.shape_buckets()


# ---------------------------------------------------------------------------
# Scaling attribution
# ---------------------------------------------------------------------------

def parallel_efficiency(times: dict[int, float]) -> dict[int, float]:
    """Per-mesh-size parallel efficiency from a {n_devices: seconds}
    sweep: eff(N) = T(1) / (T(N) * N). 1.0 = perfect linear scaling;
    a flat sweep shows ~1/N — the MULTICHIP failure signature this
    metric machine-checks (ROADMAP item 1)."""
    t1 = times.get(1)
    if not t1:
        return {}
    return {int(n): round(t1 / (t * n), 4)
            for n, t in sorted(times.items()) if n >= 1 and t > 0}


# Mesh sizes at least this big with efficiency below this floor get a
# loud warning (bench + the multichip dry run both check it).
EFFICIENCY_WARN_N = 4
EFFICIENCY_WARN_FLOOR = 0.5


def check_efficiency(eff: dict[int, float],
                     log: Callable[[str], None] | None = None) -> list:
    """Returns [(n, eff)] for every mesh size >= EFFICIENCY_WARN_N
    scaling under the floor, logging each (the flat-sweep tripwire)."""
    bad = [(n, e) for n, e in sorted(eff.items())
           if n >= EFFICIENCY_WARN_N and e < EFFICIENCY_WARN_FLOOR]
    emit = log or logger.warning
    for n, e in bad:
        emit(f"parallel efficiency at {n} devices is {e:.2f} "
             f"(< {EFFICIENCY_WARN_FLOOR}): the mesh adds devices "
             "without adding throughput")
    return bad


def work_balance(work) -> float | None:
    """Load-balance figure for a sharded launch's per-device work
    attribution: mean/max — 1.0 is a perfectly even mesh, and with
    zero replicated bytes (graftlint R4) an uneven balance is what's
    left to explain a flat device sweep. None when no work landed."""
    import numpy as np

    work = [int(w) for w in work]
    if not work or max(work) == 0:
        return None
    return round(float(np.mean(work)) / max(work), 4)
