"""Verdict certificates: self-proving checker results.

A device-kernel verdict is only as trustworthy as the kernel — and the
kernel surface keeps growing (degradation ladder, int packing, the
coming SPMD sharding). This module makes wgl/elle verdicts carry a
machine-checkable proof, so any kernel regression is caught by *proof
failure* on the very run it corrupts, instead of by host-differential
luck in a test suite:

  valid wgl     a per-segment linearization order, re-derived host-side
                from the device result's reach/choice data (the
                per-(segment, start-state) reach-mask chain
                check_segmented resolves) and composed across segments —
                P-compositionality (arXiv:1504.00204) is what makes the
                concatenated per-segment orders one whole-history proof.
  invalid wgl   the blocked-frontier witness normalized into the same
                schema: a replayable prefix reaching a concrete stuck
                configuration plus the pending op that cannot take
                effect there.
  valid elle    a serialization order over the committed txns, checked
                against the independently-derivable constraint set
                (session order, realtime order, read-from precedence).
  invalid elle  the witnessing cycle's edges, each justified by the
                concrete mops that induce the ww/wr/rw dependency (or a
                justified non-cycle anomaly: aborted read, duplicate
                write).

The *validator* (`validate`, `stamp_results`) shares no code with the
kernels or the checker engines: it re-pairs invocations with
completions from the raw history itself, replays model semantics
through its own tiny step functions, and checks each certificate in one
pass — O(n) in history size. Tampered orders, forged cycle edges, and
certificates replayed against an edited history all fail loudly
(tests/test_certify.py pins the rejection matrix). Results whose proofs
can't be extracted say so honestly (`{"absent": reason}`) — an absent
certificate is allowed, a validating-but-wrong one never is.

Extraction cost is bounded (a node budget on the order search) and
priced by bench.py's certificate-overhead line; JEPSEN_TPU_CERTIFY=0
disables extraction entirely (verdicts then carry an honest absent
marker rather than nothing, so downstream walks stay uniform).
"""

from __future__ import annotations

import json
import logging
import os
import zlib
from typing import Any, Iterable

from .. import history as h
from .. import telemetry
from ..history import History

logger = logging.getLogger(__name__)

VERSION = 1

# extraction search budget: configs visited before giving up with an
# honest absent("search-budget") — a certificate extractor must never
# turn a bounded device check into an unbounded host search
SEARCH_BUDGET = 500_000

BIG = 1 << 60


class CertificateError(Exception):
    """The certificate does not prove its verdict against this
    history."""


def enabled() -> bool:
    return os.environ.get("JEPSEN_TPU_CERTIFY", "1") != "0"


def absent(reason: str) -> dict:
    """An honest no-proof marker (host floors, non-tabulable models,
    exhausted search budgets). Never claims anything; stamp_results
    counts it separately from validation failures."""
    return {"v": VERSION, "absent": str(reason)[:200]}


def _jv(v):
    """JSON-shape normalization: tuples become lists (certificates
    round-trip through results.json, where a (cur, new) cas pair comes
    back as a list), sets become sorted lists."""
    if isinstance(v, (list, tuple)):
        return [_jv(x) for x in v]
    if isinstance(v, (set, frozenset)):
        return sorted((_jv(x) for x in v), key=repr)
    if isinstance(v, dict):
        return {str(k): _jv(x) for k, x in v.items()}
    return v


def _jsonable(v) -> bool:
    try:
        json.dumps(v)
        return True
    except (TypeError, ValueError):
        return False


# ---------------------------------------------------------------------------
# Validator-side model semantics
# ---------------------------------------------------------------------------
#
# Deliberately re-implemented from the datatype definitions (a CAS
# register is five lines), NOT imported from checker.models or the
# encode tabulation: the whole point is that a bug anywhere in the
# model->table->kernel pipeline cannot also be in the replay that
# checks its proofs.

_INCONSISTENT = object()


def _step_register(state, f, value, cas=False):
    if f == "write":
        return value
    if f == "read":
        if value is None or value == state:
            return state
        return _INCONSISTENT
    if cas and f == "cas":
        if not isinstance(value, list) or len(value) != 2:
            return _INCONSISTENT
        cur, new = value
        return new if cur == state else _INCONSISTENT
    return _INCONSISTENT


def _step_cas_register(state, f, value):
    return _step_register(state, f, value, cas=True)


def _step_mutex(state, f, value):
    if f == "acquire":
        return True if not state else _INCONSISTENT
    if f == "release":
        return False if state else _INCONSISTENT
    return _INCONSISTENT


def _step_fifo_queue(state, f, value):
    if f == "enqueue":
        return state + [value]
    if f == "dequeue":
        if state and state[0] == value:
            return state[1:]
        return _INCONSISTENT
    return _INCONSISTENT


def _step_unordered_queue(state, f, value):
    if f == "enqueue":
        return state + [value]
    if f == "dequeue":
        if value in state:
            out = list(state)
            out.remove(value)
            return out
        return _INCONSISTENT
    return _INCONSISTENT


def _step_g_set(state, f, value):
    if f == "add":
        return state if value in state else state + [value]
    if f == "read":
        if value is None:
            return state
        if not isinstance(value, list):
            return _INCONSISTENT
        want = sorted(state, key=repr)
        got = sorted(value, key=repr)
        return state if want == got else _INCONSISTENT
    return _INCONSISTENT


def _step_noop(state, f, value):
    return state


# model name -> (step fn, canonicalizer for state comparison)
_MODELS = {
    "register": (_step_register, lambda s: s),
    "cas-register": (_step_cas_register, lambda s: s),
    "mutex": (_step_mutex, bool),
    "fifo-queue": (_step_fifo_queue, lambda s: list(s)),
    "unordered-queue": (_step_unordered_queue,
                        lambda s: sorted(s, key=repr)),
    "g-set": (_step_g_set, lambda s: sorted(s, key=repr)),
    "noop": (_step_noop, lambda s: None),
}

# checker.models class name -> certificate model name + initial state
_MODEL_CLASSES = {
    "Register": ("register", lambda m: m.value),
    "CASRegister": ("cas-register", lambda m: m.value),
    "Mutex": ("mutex", lambda m: bool(m.locked)),
    "FIFOQueue": ("fifo-queue", lambda m: list(m.pending)),
    "UnorderedQueue": ("unordered-queue",
                       lambda m: sorted(m.pending, key=repr)),
    "GSet": ("g-set", lambda m: sorted(m.elements, key=repr)),
    "NoOp": ("noop", lambda m: None),
}


def describe_model(model) -> dict | None:
    """{"name", "init"} for a model the validator can replay; None for
    models outside the registry (object models, suite-specific types) —
    those verdicts carry an honest absent certificate."""
    entry = _MODEL_CLASSES.get(type(model).__name__)
    if entry is None:
        return None
    name, init_fn = entry
    try:
        init = _jv(init_fn(model))
    except Exception:  # noqa: BLE001 — unexpected model shape
        return None
    if not _jsonable(init):
        return None
    return {"name": name, "init": init}


def _state_json(model_name: str, model_obj):
    """A model *object* (enc.states entry) projected to the JSON state
    the validator's step functions operate on."""
    for cls, (name, init_fn) in _MODEL_CLASSES.items():
        if name == model_name and type(model_obj).__name__ == cls:
            return _jv(init_fn(model_obj))
    raise CertificateError(f"can't project state of "
                           f"{type(model_obj).__name__}")


# ---------------------------------------------------------------------------
# History digest + effective-op pairing (validator side)
# ---------------------------------------------------------------------------

def history_digest(hist) -> dict:
    """A structural fingerprint of the history: op count plus a crc
    over (index, type, process, f) per op. Values are deliberately NOT
    digested — every value a certificate relies on is re-read from the
    live history during replay, so value tampering fails the replay
    itself; the digest catches reordered / swapped / truncated
    histories where a replay might accidentally still pass."""
    crc = 0
    n = 0
    buf: list[str] = []
    for o in hist:
        buf.append(f"{o.index}|{o.type}|{o.process}|{o.f}")
        n += 1
        if len(buf) >= 8192:
            crc = zlib.crc32("\n".join(buf).encode(), crc)
            buf = []
    if buf:
        crc = zlib.crc32("\n".join(buf).encode(), crc)
    return {"ops": n, "crc": crc}


def effective_ops(hist, key=None) -> dict[int, dict]:
    """invocation-index -> effective-op entry, re-paired from the raw
    history in one pass (the validator's own pairing — nothing shared
    with encode): {"inv_pos", "ret_pos", "crashed", "f", "value"}.
    :fail invocations never appear (they never took effect). With
    `key`, only ops whose value is the independent checker's (key, v)
    tuple for that key count, and values are unwrapped."""
    if not isinstance(hist, History):
        hist = History(hist)
    out: dict[int, dict] = {}
    open_inv: dict[Any, tuple[int, Any]] = {}

    def unwrap(v):
        if key is None:
            return v
        if (isinstance(v, (list, tuple)) and len(v) == 2
                and _jv(v[0]) == key):
            return v[1]
        return _NOT_THIS_KEY

    for pos, o in enumerate(hist):
        if not h.is_client_op(o):
            continue
        if o.type == h.INVOKE:
            v = unwrap(o.value)
            if v is _NOT_THIS_KEY:
                open_inv.pop(o.process, None)
                continue
            open_inv[o.process] = (pos, o, v)
        elif o.type in (h.OK, h.FAIL, h.INFO):
            got = open_inv.pop(o.process, None)
            if got is None:
                continue
            inv_pos, inv, inv_v = got
            if o.type == h.FAIL:
                continue
            crashed = o.type != h.OK
            value = inv_v
            if not crashed and o.value is not None:
                cv = unwrap(o.value)
                if cv is not _NOT_THIS_KEY and cv is not None:
                    value = cv
            out[inv.index] = {
                "inv_pos": inv_pos,
                "ret_pos": BIG if crashed else pos,
                "crashed": crashed,
                "f": inv.f,
                "value": value,
            }
    for inv_pos, inv, inv_v in open_inv.values():
        out[inv.index] = {"inv_pos": inv_pos, "ret_pos": BIG,
                          "crashed": True, "f": inv.f, "value": inv_v}
    return out


_NOT_THIS_KEY = object()


def _check_digest(hist, cert, digest: dict | None = None) -> None:
    d = cert.get("history")
    if not isinstance(d, dict):
        raise CertificateError("certificate carries no history digest")
    got = digest if digest is not None else history_digest(hist)
    if got != d:
        raise CertificateError(
            f"stale certificate: history digest {got} != certified "
            f"{d} (the history changed since this proof was made)")


# ---------------------------------------------------------------------------
# wgl validation: replay a linearization order
# ---------------------------------------------------------------------------

def _replay_order(order, entries: dict, model_spec: dict,
                  what: str) -> tuple[Any, set]:
    """Replays one composed linearization order: every step must
    respect real-time precedence (an op that completed before another
    invoked must come first — checked with a running max over placed
    invocation positions) and the model's sequential semantics.
    Returns (final state, set of placed op indices)."""
    name = model_spec.get("name")
    if name not in _MODELS:
        raise CertificateError(f"unknown model {name!r}")
    step, _canon = _MODELS[name]
    state = _jv(model_spec.get("init"))
    seen: set[int] = set()
    max_inv = -1
    for j, item in enumerate(order):
        if (not isinstance(item, (list, tuple)) or len(item) != 2
                or item[1] not in ("apply", "discard")):
            raise CertificateError(f"{what}[{j}]: malformed step "
                                   f"{item!r}")
        idx, action = item
        e = entries.get(idx)
        if e is None:
            raise CertificateError(
                f"{what}[{j}]: op {idx} is not an effective client "
                "op of this history")
        if idx in seen:
            raise CertificateError(f"{what}[{j}]: op {idx} linearized "
                                   "twice")
        seen.add(idx)
        if e["ret_pos"] < max_inv:
            raise CertificateError(
                f"{what}[{j}]: op {idx} completed before an "
                "earlier-linearized op invoked (real-time order "
                "violated)")
        max_inv = max(max_inv, e["inv_pos"])
        if action == "discard":
            if not e["crashed"]:
                raise CertificateError(
                    f"{what}[{j}]: op {idx} completed ok but the "
                    "order discards it")
            continue
        state = step(state, e["f"], _jv(e["value"]))
        if state is _INCONSISTENT:
            raise CertificateError(
                f"{what}[{j}]: op {idx} ({e['f']} {e['value']!r}) is "
                "inconsistent at this point in the claimed order")
    return state, seen


def _wgl_order(cert) -> list:
    out = []
    for seg in cert.get("segments") or []:
        if not isinstance(seg, dict) or not isinstance(
                seg.get("order"), list):
            raise CertificateError("malformed segment in certificate")
        out.extend(seg["order"])
    return out


def _validate_wgl(hist, cert) -> None:
    entries = effective_ops(hist, cert.get("key"))
    model_spec = cert.get("model") or {}
    verdict = cert.get("verdict")
    if verdict == "valid":
        order = _wgl_order(cert)
        _state, seen = _replay_order(order, entries, model_spec,
                                     "order")
        missing = [i for i, e in entries.items()
                   if not e["crashed"] and i not in seen]
        if missing:
            raise CertificateError(
                f"linearization omits completed op(s) "
                f"{sorted(missing)[:8]} — not a whole-history proof")
        return
    if verdict == "invalid":
        w = cert.get("witness")
        if not isinstance(w, dict):
            raise CertificateError("invalid verdict without a witness")
        prefix = _wgl_order(cert) + list(w.get("prefix") or [])
        state, seen = _replay_order(prefix, entries, model_spec,
                                    "witness prefix")
        name = model_spec.get("name")
        step, canon = _MODELS[name]
        if "state" in w and canon(state) != canon(_jv(w["state"])):
            raise CertificateError(
                f"witness prefix replays to {state!r}, certificate "
                f"claims {w['state']!r}")
        stuck = w.get("op-index")
        e = entries.get(stuck)
        if e is None:
            raise CertificateError(f"stuck op {stuck!r} is not an "
                                   "effective client op")
        if stuck in seen:
            raise CertificateError(f"stuck op {stuck} is already in "
                                   "the witness prefix")
        if e["crashed"]:
            raise CertificateError(
                f"stuck op {stuck} crashed — a crashed op can always "
                "be discarded and is no blocking evidence")
        if step(state, e["f"], _jv(e["value"])) is not _INCONSISTENT:
            raise CertificateError(
                f"claimed stuck op {stuck} ({e['f']} {e['value']!r}) "
                "actually applies at the witness state — the witness "
                "proves nothing")
        for p in w.get("pending") or []:
            if p not in entries:
                raise CertificateError(f"pending op {p!r} is not an "
                                       "effective client op")
        return
    raise CertificateError(f"unknown wgl verdict {verdict!r}")


# ---------------------------------------------------------------------------
# elle validation: txn tables + per-edge justification
# ---------------------------------------------------------------------------

def _collect_txns(hist) -> dict[int, dict]:
    """invocation-index -> txn entry, paired in one pass:
    {"inv_pos", "ret_pos", "type", "process", "mops"} — :ok txns carry
    the completion's mops (read results), everything else the
    invocation's."""
    if not isinstance(hist, History):
        hist = History(hist)
    out: dict[int, dict] = {}
    open_inv: dict[Any, tuple[int, Any]] = {}
    for pos, o in enumerate(hist):
        if not h.is_client_op(o):
            continue
        if o.type == h.INVOKE:
            open_inv[o.process] = (pos, o)
        elif o.type in (h.OK, h.FAIL, h.INFO):
            got = open_inv.pop(o.process, None)
            if got is None:
                continue
            inv_pos, inv = got
            mops = o.value if (o.type == h.OK and o.value is not None
                               ) else inv.value
            out[inv.index] = {"inv_pos": inv_pos, "ret_pos": pos,
                              "type": o.type, "process": inv.process,
                              "mops": _jv(mops or [])}
    for inv_pos, inv in open_inv.values():
        out[inv.index] = {"inv_pos": inv_pos, "ret_pos": BIG,
                          "type": h.INFO, "process": inv.process,
                          "mops": _jv(inv.value or [])}
    return out


def _writes(t: dict, family: str) -> list[tuple]:
    wf = "append" if family == "list-append" else "w"
    return [(m[1], m[2]) for m in t["mops"]
            if isinstance(m, list) and len(m) >= 3 and m[0] == wf]


def _reads(t: dict) -> list[tuple]:
    return [(m[1], m[2]) for m in t["mops"]
            if isinstance(m, list) and len(m) >= 3 and m[0] == "r"
            and m[2] is not None]


def _fkey(k, v):
    return (json.dumps(k, sort_keys=True, default=repr),
            json.dumps(v, sort_keys=True, default=repr))


def _writer_map(txns: dict, family: str) -> dict:
    """(key, value) -> [writer inv indices] over non-:fail txns."""
    out: dict = {}
    for i, t in txns.items():
        if t["type"] == h.FAIL:
            continue
        for k, v in _writes(t, family):
            out.setdefault(_fkey(k, v), []).append(i)
    return out


def _observed(t: dict, k, v, family: str) -> bool:
    """Did committed txn t read value v on key k?"""
    for rk, rv in _reads(t):
        if rk != k:
            continue
        if family == "list-append":
            if isinstance(rv, list) and v in rv:
                return True
        elif rv == v:
            return True
    return False


def _adjacent_in_read(t: dict, k, u, v) -> bool:
    for rk, rv in _reads(t):
        if rk != k or not isinstance(rv, list):
            continue
        for a, b in zip(rv, rv[1:]):
            if a == u and b == v:
                return True
    return False


def _read_then_wrote(t: dict, k, u, v) -> bool:
    """Register succession proof: t read u on k, then wrote v on k."""
    saw = False
    for m in t["mops"]:
        if not isinstance(m, list) or len(m) < 3 or m[1] != k:
            continue
        if m[0] == "r" and m[2] == u:
            saw = True
        elif m[0] == "w" and m[2] == v and saw:
            return True
    return False


def _justify_edge(edge: dict, txns: dict, family: str,
                  where: str) -> None:
    ty = edge.get("type")
    a = txns.get(edge.get("from"))
    b = txns.get(edge.get("to"))
    if a is None or b is None:
        raise CertificateError(f"{where}: edge references unknown "
                               f"txn(s) {edge.get('from')!r} -> "
                               f"{edge.get('to')!r}")
    k, v, u = edge.get("key"), edge.get("value"), edge.get("prev-value")
    if ty == "realtime":
        if not (a["ret_pos"] < b["inv_pos"]):
            raise CertificateError(
                f"{where}: realtime edge forged — txn "
                f"{edge['from']} did not complete before "
                f"{edge['to']} invoked")
        return
    if ty == "process":
        if not (a["process"] == b["process"]
                and a["inv_pos"] < b["inv_pos"]):
            raise CertificateError(f"{where}: process edge forged")
        return
    if ty == "wr":
        if not any(wk == k and wv == v for wk, wv in
                   _writes(a, family)):
            raise CertificateError(
                f"{where}: wr edge forged — txn {edge['from']} never "
                f"wrote {v!r} to {k!r}")
        if b["type"] != h.OK or not _observed(b, k, v, family):
            raise CertificateError(
                f"{where}: wr edge forged — txn {edge['to']} never "
                f"observed {v!r} on {k!r}")
        return
    if ty == "ww":
        if not any(wk == k and wv == u for wk, wv in
                   _writes(a, family)):
            raise CertificateError(f"{where}: ww edge forged — "
                                   f"{edge['from']} never wrote "
                                   f"{u!r} to {k!r}")
        if not any(wk == k and wv == v for wk, wv in
                   _writes(b, family)):
            raise CertificateError(f"{where}: ww edge forged — "
                                   f"{edge['to']} never wrote "
                                   f"{v!r} to {k!r}")
        if family == "list-append":
            via = txns.get(edge.get("via-read"))
            if via is None or via["type"] != h.OK or \
                    not _adjacent_in_read(via, k, u, v):
                raise CertificateError(
                    f"{where}: ww edge unjustified — no committed "
                    f"read observes {u!r} immediately before {v!r} "
                    f"on {k!r}")
        elif not _read_then_wrote(b, k, u, v):
            raise CertificateError(
                f"{where}: ww edge unjustified — {edge['to']} did "
                f"not read {u!r} then write {v!r} on {k!r}")
        return
    if ty == "rw":
        if b["type"] == h.FAIL or not any(
                wk == k and wv == v for wk, wv in _writes(b, family)):
            raise CertificateError(f"{where}: rw edge forged — "
                                   f"{edge['to']} never wrote "
                                   f"{v!r} to {k!r}")
        if a["type"] != h.OK:
            raise CertificateError(f"{where}: rw edge forged — reader "
                                   f"{edge['from']} did not commit")
        if family == "list-append":
            if u is None:
                # empty-read anti-dependency: the reader observed []
                if not any(rk == k and rv == [] for rk, rv in
                           _reads(a)):
                    raise CertificateError(
                        f"{where}: rw empty-read edge forged — "
                        f"{edge['from']} never read [] on {k!r}")
                return
            if not any(rk == k and isinstance(rv, list) and rv
                       and rv[-1] == u for rk, rv in _reads(a)):
                raise CertificateError(
                    f"{where}: rw edge forged — {edge['from']} never "
                    f"read {u!r} as the last element of {k!r}")
            via = txns.get(edge.get("via-read"))
            if via is None or via["type"] != h.OK or \
                    not _adjacent_in_read(via, k, u, v):
                raise CertificateError(
                    f"{where}: rw edge unjustified — no committed "
                    f"read proves {v!r} directly follows {u!r} on "
                    f"{k!r}")
        else:
            if not any(rk == k and rv == u for rk, rv in _reads(a)):
                raise CertificateError(
                    f"{where}: rw edge forged — {edge['from']} never "
                    f"read {u!r} on {k!r}")
            if not _read_then_wrote(b, k, u, v):
                raise CertificateError(
                    f"{where}: rw edge unjustified — {edge['to']} "
                    f"did not read {u!r} then write {v!r} on {k!r}")
        return
    raise CertificateError(f"{where}: unknown edge type {ty!r}")


def _validate_elle(hist, cert) -> None:
    family = cert.get("family")
    if family not in ("list-append", "rw-register"):
        raise CertificateError(f"unknown elle family {family!r}")
    txns = _collect_txns(hist)
    verdict = cert.get("verdict")
    if verdict == "invalid":
        cycle = cert.get("cycle")
        if cycle:
            if len(cycle) < 2:
                raise CertificateError("cycle shorter than two edges")
            for j, edge in enumerate(cycle):
                nxt = cycle[(j + 1) % len(cycle)]
                if edge.get("to") != nxt.get("from"):
                    raise CertificateError(
                        f"cycle edge {j} does not chain: {edge!r} -> "
                        f"{nxt!r}")
                _justify_edge(edge, txns, family, f"cycle edge {j}")
            return
        anom = cert.get("anomaly")
        if isinstance(anom, dict):
            _validate_elle_anomaly(anom, txns, family)
            return
        raise CertificateError("invalid verdict with neither cycle "
                               "nor anomaly evidence")
    if verdict == "valid":
        order = cert.get("topo-order")
        if not isinstance(order, list):
            raise CertificateError("valid verdict without a "
                                   "topo-order")
        committed = {i for i, t in txns.items() if t["type"] == h.OK}
        if set(order) != committed or len(order) != len(committed):
            raise CertificateError(
                "topo-order is not a permutation of the committed "
                f"txns ({len(order)} vs {len(committed)})")
        pos = {i: j for j, i in enumerate(order)}
        # realtime: running max over invocation positions
        max_inv = -1
        last_by_proc: dict = {}
        for i in order:
            t = txns[i]
            if t["ret_pos"] < max_inv:
                raise CertificateError(
                    f"topo-order violates realtime order at txn {i}")
            max_inv = max(max_inv, t["inv_pos"])
            prev = last_by_proc.get(t["process"])
            if prev is not None and t["inv_pos"] < prev:
                raise CertificateError(
                    f"topo-order violates session order at txn {i}")
            last_by_proc[t["process"]] = t["inv_pos"]
        # read-from precedence: a committed read of v must follow v's
        # committed writer (writers re-derived in one pass)
        writers = _writer_map(txns, family)
        for i in order:
            for k, rv in _reads(txns[i]):
                vals = (rv if family == "list-append"
                        and isinstance(rv, list) else [rv])
                for v in vals:
                    ws = writers.get(_fkey(k, v), [])
                    ws = [w for w in ws if w in pos and w != i]
                    if len(ws) == 1 and pos[ws[0]] > pos[i]:
                        raise CertificateError(
                            f"topo-order violates read-from: txn {i} "
                            f"reads {v!r} on {k!r} before its writer "
                            f"{ws[0]}")
        return
    raise CertificateError(f"unknown elle verdict {verdict!r}")


def _validate_elle_anomaly(anom: dict, txns: dict, family: str
                           ) -> None:
    cls = anom.get("class")
    k, v = anom.get("key"), anom.get("value")
    if cls == "G1a":
        w = txns.get(anom.get("writer"))
        r = txns.get(anom.get("reader"))
        if w is None or w["type"] != h.FAIL or not any(
                wk == k and wv == v for wk, wv in _writes(w, family)):
            raise CertificateError(
                f"G1a forged — txn {anom.get('writer')!r} is not an "
                f"aborted writer of {v!r} on {k!r}")
        if r is None or r["type"] != h.OK or not _observed(
                r, k, v, family):
            raise CertificateError(
                f"G1a forged — txn {anom.get('reader')!r} never "
                f"observed {v!r} on {k!r}")
        return
    if cls == "duplicate":
        ws = anom.get("writers") or []
        if len(set(ws)) < 2:
            raise CertificateError("duplicate anomaly needs two "
                                   "distinct writers")
        for wi in ws:
            w = txns.get(wi)
            if w is None or w["type"] == h.FAIL or not any(
                    wk == k and wv == v
                    for wk, wv in _writes(w, family)):
                raise CertificateError(
                    f"duplicate forged — txn {wi!r} is not a "
                    f"surviving writer of {v!r} on {k!r}")
        return
    raise CertificateError(f"unjustifiable anomaly class {cls!r}")


# ---------------------------------------------------------------------------
# Public validation API
# ---------------------------------------------------------------------------

def validate_schema(cert) -> None:
    """Structural check (no history needed): run by tier-1 on every
    stored certificate alongside the other artifact validators."""
    if not isinstance(cert, dict):
        raise CertificateError("certificate must be a dict")
    if cert.get("v") != VERSION:
        raise CertificateError(f"unknown certificate version "
                               f"{cert.get('v')!r}")
    if "absent" in cert:
        if not isinstance(cert["absent"], str) or not cert["absent"]:
            raise CertificateError("absent marker must carry a reason")
        return
    kind = cert.get("kind")
    if kind not in ("wgl", "elle"):
        raise CertificateError(f"unknown certificate kind {kind!r}")
    if cert.get("verdict") not in ("valid", "invalid"):
        raise CertificateError(f"bad verdict {cert.get('verdict')!r}")
    if not isinstance(cert.get("history"), dict):
        raise CertificateError("missing history digest")
    if not _jsonable(cert):
        raise CertificateError("certificate is not JSON-serializable")
    if kind == "wgl":
        if not isinstance(cert.get("model"), dict):
            raise CertificateError("wgl certificate without a model")
        if cert["verdict"] == "valid" and not isinstance(
                cert.get("segments"), list):
            raise CertificateError("valid wgl certificate without "
                                   "segments")
        if cert["verdict"] == "invalid" and not isinstance(
                cert.get("witness"), dict):
            raise CertificateError("invalid wgl certificate without "
                                   "a witness")
    else:
        if cert.get("family") not in ("list-append", "rw-register"):
            raise CertificateError("elle certificate without a family")
        if cert["verdict"] == "valid" and not isinstance(
                cert.get("topo-order"), list):
            raise CertificateError("valid elle certificate without a "
                                   "topo-order")


def validate(hist, cert, digest: dict | None = None) -> None:
    """Replays one certificate against the raw history; raises
    CertificateError unless the certificate proves its verdict. Absent
    certificates raise too — callers decide whether absence is
    acceptable (stamp_results counts them separately). `digest`: a
    precomputed history_digest(hist), so callers validating many
    certificates against one history (per-key independent results)
    pay the O(n) digest pass once, not per certificate."""
    validate_schema(cert)
    if "absent" in cert:
        raise CertificateError(f"no proof: {cert['absent']}")
    _check_digest(hist, cert, digest)
    if cert["kind"] == "wgl":
        _validate_wgl(hist, cert)
    else:
        _validate_elle(hist, cert)


def iter_certificates(results, path: str = "", depth: int = 0
                      ) -> Iterable[tuple[str, dict]]:
    """Yields (path, result dict) for every result in the tree that
    carries a certificate — including the independent checker's
    per-key sub-results."""
    if not isinstance(results, dict) or depth > 6:
        return
    if isinstance(results.get("certificate"), dict):
        yield path or "result", results
    for k, v in sorted(results.items(), key=lambda kv: str(kv[0])):
        if isinstance(v, dict) and k not in ("certificate",
                                             "anomalies"):
            sub = f"{path}/{k}" if path else str(k)
            yield from iter_certificates(v, sub, depth + 1)


def stamp_results(results, hist) -> dict:
    """Validates every certificate in a results tree against the
    history, stamping each carrying result with `certified: True` or
    `certificate-error: reason`. Returns {"certified", "errors",
    "absent"} counts. Live in core.analyze; offline via `analyze
    --resume`; loud in telemetry (certify.* counters) either way."""
    out = {"certified": 0, "errors": 0, "absent": 0}
    digest = None
    for path, res in iter_certificates(results):
        cert = res["certificate"]
        if "absent" in cert:
            out["absent"] += 1
            telemetry.count("certify.absent")
            continue
        if digest is None:
            digest = history_digest(hist)
        try:
            validate(hist, cert, digest=digest)
        except CertificateError as e:
            res["certificate-error"] = str(e)[:300]
            out["errors"] += 1
            telemetry.count("certify.errors")
            logger.error("certificate at %s failed validation: %s",
                         path, e)
        except Exception as e:  # noqa: BLE001 — validator bug: loud,
            # but it must never sink the analysis that carries it
            res["certificate-error"] = f"validator crashed: {e!r}"[:300]
            out["errors"] += 1
            telemetry.count("certify.errors")
            logger.exception("certificate validator crashed at %s",
                             path)
        else:
            res["certified"] = True
            out["certified"] += 1
            telemetry.count("certify.validated")
    return out


# ---------------------------------------------------------------------------
# wgl extraction: order search over an Encoded history
# ---------------------------------------------------------------------------

def _trailing_ones(x: int) -> int:
    t = 0
    while x & 1:
        x >>= 1
        t += 1
    return t


class _Budget(Exception):
    """Order-search node budget exhausted."""


def _order_search(enc, targets=None, witness: bool = False,
                  budget: int = SEARCH_BUDGET):
    """DFS over WGL configurations recording the linearization path —
    the prover-side search that turns the kernel's yes/no (plus
    reach-mask choice data) back into checkable steps. Returns
    (True, actions) on success (`actions` = [(entry, 'apply'|'discard')]
    reaching the end, in a target state when `targets` given), or
    (False, best) where best = (actions, p, wmask, state) is the
    deepest configuration reached (the witness stub). Raises _Budget
    past the node budget."""
    m = enc.m
    if m == 0:
        return True, []
    inv_t, ret_t, crashed, trans = (enc.inv_t, enc.ret_t, enc.crashed,
                                    enc.trans)
    sufmin = enc.suffix_min_ret()

    def min_ret(p: int, wmask: int) -> int:
        span = wmask.bit_length()
        mr = int(sufmin[min(p + span, m)])
        for i in range(span):
            if not (wmask >> i) & 1 and p + i < m:
                r = int(ret_t[p + i])
                if r < mr:
                    mr = r
        return mr

    def moves(p: int, wmask: int, st: int):
        mr = min_ret(p, wmask)
        i = 0
        while p + i < m and int(inv_t[p + i]) < mr:
            if not (wmask >> i) & 1:
                e = p + i
                nmask = wmask | (1 << i)
                t = _trailing_ones(nmask)
                np_, nm = p + t, nmask >> t
                s2 = int(trans[e, st])
                if s2 >= 0:
                    yield e, "apply", np_, nm, s2
                if crashed[e]:
                    yield e, "discard", np_, nm, st
            i += 1

    start = (0, 0, enc.init_state)
    seen = {start}
    frames = [(start, moves(*start))]
    path: list[tuple[int, str]] = []
    best = ([], 0, 0, enc.init_state)
    visited = 1
    while frames:
        (p, wmask, st), it = frames[-1]
        advanced = False
        for e, action, np_, nm, s2 in it:
            cfg = (np_, nm, s2)
            if cfg in seen:
                continue
            seen.add(cfg)
            visited += 1
            if visited > budget:
                raise _Budget()
            path.append((e, action))
            if np_ >= m:
                if targets is None or s2 in targets:
                    return True, list(path)
                path.pop()
                continue
            if witness and np_ > best[1]:
                best = (list(path), np_, nm, s2)
            frames.append((cfg, moves(*cfg)))
            advanced = True
            break
        if not advanced:
            frames.pop()
            if path:
                path.pop()
    return False, best


def _entry_order_json(enc, actions) -> list:
    return [[int(enc.entry_ops[e].index), a] for e, a in actions]


def _witness_from_best(enc, best, model_name: str) -> dict:
    """A stuck-configuration witness from the deepest config the
    search reached: the prefix, the state, and a non-crashed candidate
    whose transition is inconsistent there."""
    actions, p, wmask, st = best
    inv_t, ret_t, crashed, trans = (enc.inv_t, enc.ret_t, enc.crashed,
                                    enc.trans)
    sufmin = enc.suffix_min_ret()
    m = enc.m
    span = wmask.bit_length()
    mr = int(sufmin[min(p + span, m)])
    for i in range(span):
        if not (wmask >> i) & 1 and p + i < m:
            mr = min(mr, int(ret_t[p + i]))
    stuck = None
    pending = []
    i = 0
    while p + i < m and int(inv_t[p + i]) < mr:
        if not (wmask >> i) & 1:
            e = p + i
            pending.append(int(enc.entry_ops[e].index))
            if (stuck is None and not crashed[e]
                    and int(trans[e, st]) < 0):
                stuck = e
        i += 1
    if stuck is None:
        raise CertificateError("no blocked non-crashed candidate at "
                               "the witness configuration")
    return {
        "op-index": int(enc.entry_ops[stuck].index),
        "state": _state_json(model_name, enc.states[st]),
        "prefix": _entry_order_json(enc, actions),
        "pending": pending[:8],
    }


def wgl_certificate(model, hist, enc, result) -> dict:
    """Builds the certificate for one wgl analysis result. Valid
    verdicts get a per-segment linearization order guided by the
    result's reach/choice chain (`search-chain`, recorded by
    check_segmented) when present; invalid verdicts a replayable
    blocked-frontier witness. Failure to extract returns an honest
    absent marker, never raises."""
    try:
        return _wgl_certificate(model, hist, enc, result)
    except _Budget:
        return absent("search-budget-exceeded")
    except CertificateError as e:
        return absent(str(e))
    except Exception as e:  # noqa: BLE001 — extraction is best-effort
        logger.exception("wgl certificate extraction failed")
        return absent(f"extraction-failed: {e!r}")


def _wgl_certificate(model, hist, enc, result) -> dict:
    verdict = result.get("valid?")
    if verdict not in (True, False):
        return absent("verdict is indeterminate")
    spec = describe_model(model)
    if spec is None:
        return absent(f"model {type(model).__name__} is outside the "
                      "validator's replay registry")
    if enc is None:
        return absent("history was not encodable (object-model "
                      "search)")
    cert: dict = {"v": VERSION, "kind": "wgl",
                  "verdict": "valid" if verdict else "invalid",
                  "model": spec, "history": history_digest(hist),
                  "segments": []}
    chain_info = result.get("search-chain")
    model_name = spec["name"]
    if verdict:
        if chain_info:
            cuts = chain_info["cuts"]
            chain = chain_info["chain"]
            for j in range(len(cuts) - 1):
                seg = enc.segment(cuts[j], cuts[j + 1],
                                  init_state=chain[j])
                ok, actions = _order_search(seg,
                                            targets={chain[j + 1]})
                if not ok:
                    raise CertificateError(
                        f"no linearization of segment {j} from state "
                        f"{chain[j]} to {chain[j + 1]} — the reach "
                        "chain lies")
                cert["segments"].append({
                    "range": [int(cuts[j]), int(cuts[j + 1])],
                    "order": _entry_order_json(seg, actions)})
        else:
            ok, actions = _order_search(enc)
            if not ok:
                raise CertificateError(
                    "no whole-history linearization found for a "
                    "valid verdict")
            cert["segments"].append({"range": [0, int(enc.m)],
                                     "order": _entry_order_json(
                                         enc, actions)})
        return cert
    # invalid: a replayable prefix (certified segments up to the
    # failing one) + the stuck-configuration witness inside it
    if chain_info and "failed-segment" in result:
        cuts = chain_info["cuts"]
        chain = chain_info["chain"]
        k = int(result["failed-segment"])
        for j in range(k):
            seg = enc.segment(cuts[j], cuts[j + 1],
                              init_state=chain[j])
            ok, actions = _order_search(seg, targets={chain[j + 1]})
            if not ok:
                raise CertificateError(
                    f"no linearization of pre-witness segment {j}")
            cert["segments"].append({
                "range": [int(cuts[j]), int(cuts[j + 1])],
                "order": _entry_order_json(seg, actions)})
        wseg = enc.segment(cuts[k], cuts[k + 1], init_state=chain[k])
        found, best = _order_search(wseg, witness=True)
        if found:
            raise CertificateError(
                "witness segment linearizes — the invalid verdict's "
                "choice data is wrong")
        cert["witness"] = _witness_from_best(wseg, best, model_name)
    else:
        found, best = _order_search(enc, witness=True)
        if found:
            raise CertificateError("history linearizes — invalid "
                                   "verdict is wrong")
        cert["witness"] = _witness_from_best(enc, best, model_name)
    return cert


def attach_wgl(model, hist, enc, result) -> dict:
    """Attaches a certificate to a wgl analysis result (checker entry
    points call this; raw bench/kernel paths don't). Disabled
    extraction still leaves an honest absent marker so result walks
    stay uniform."""
    if not isinstance(result, dict):
        return result
    if not enabled():
        result["certificate"] = absent("extraction disabled "
                                       "(JEPSEN_TPU_CERTIFY=0)")
        return result
    # spanned so the fleet flight recorder can price certification
    # separately from device compute (flightrec.kernel_phases joins
    # this span against the launch window)
    with telemetry.span("certify.attach"):
        cert = wgl_certificate(model, hist, enc, result)
    result["certificate"] = cert
    telemetry.count("certify.absent" if "absent" in cert
                    else "certify.extracted")
    return result


# ---------------------------------------------------------------------------
# elle extraction
# ---------------------------------------------------------------------------

def _resolve_op_index(hist: History, o) -> int | None:
    idx = getattr(o, "index", None)
    if idx is None and isinstance(o, dict):
        idx = o.get("index")
    if not isinstance(idx, int) or idx < 0:
        return None
    ty = getattr(o, "type", None) or (o.get("type")
                                      if isinstance(o, dict) else None)
    if ty is not None and ty != h.INVOKE:
        try:
            inv = hist.invocation(o)
            if inv is not None:
                idx = inv.index
        except (KeyError, TypeError, AttributeError):
            pass
    return idx


def _adjacency_index(txns: dict, family: str) -> dict:
    """(key, u, v) -> committed read txn observing u immediately
    before v — the via-read justification for list-append ww/rw
    edges. One pass over read volume."""
    out: dict = {}
    if family != "list-append":
        return out
    for i, t in txns.items():
        if t["type"] != h.OK:
            continue
        for k, rv in _reads(t):
            if not isinstance(rv, list):
                continue
            for a, b in zip(rv, rv[1:]):
                out.setdefault(_fkey(k, (a, b)), i)
    return out


def _justification(a_i, b_i, ty, txns, family, adj) -> dict | None:
    """Edge fields proving dependency a -> b, derived from the raw
    mops; None when no justification exists (extraction then goes
    absent rather than emitting an unprovable edge)."""
    edge = {"from": a_i, "to": b_i, "type": ty}
    a, b = txns[a_i], txns[b_i]
    if ty in ("realtime", "process"):
        return edge
    if ty == "wr":
        for k, v in _writes(a, family):
            if _observed(b, k, v, family):
                edge.update(key=k, value=v)
                return edge
        return None
    if ty == "ww":
        for k, u in _writes(a, family):
            for k2, v in _writes(b, family):
                if k2 != k:
                    continue
                if family == "list-append":
                    via = adj.get(_fkey(k, (u, v)))
                    if via is not None:
                        edge.update(key=k, value=v, **{
                            "prev-value": u, "via-read": via})
                        return edge
                elif _read_then_wrote(b, k, u, v):
                    edge.update(key=k, value=v, **{"prev-value": u})
                    return edge
        return None
    if ty == "rw":
        if family == "list-append":
            for k, rv in _reads(a):
                if not isinstance(rv, list):
                    continue
                if not rv:
                    for k2, v in _writes(b, family):
                        if k2 == k:
                            edge.update(key=k, value=v,
                                        **{"prev-value": None})
                            return edge
                    continue
                u = rv[-1]
                for k2, v in _writes(b, family):
                    if k2 != k:
                        continue
                    via = adj.get(_fkey(k, (u, v)))
                    if via is not None:
                        edge.update(key=k, value=v, **{
                            "prev-value": u, "via-read": via})
                        return edge
            return None
        for k, u in _reads(a):
            for k2, v in _writes(b, family):
                if k2 == k and _read_then_wrote(b, k, u, v):
                    edge.update(key=k, value=v, **{"prev-value": u})
                    return edge
        return None
    return None


def _first_cycle(result: dict):
    for name in sorted(result.get("anomalies") or {}):
        for rec in result["anomalies"][name] or []:
            if isinstance(rec, dict) and rec.get("steps") \
                    and rec.get("cycle"):
                return rec
    return None


def _realtime_order_ok(order: list[int], txns: dict) -> bool:
    max_inv = -1
    for i in order:
        if txns[i]["ret_pos"] < max_inv:
            return False
        max_inv = max(max_inv, txns[i]["inv_pos"])
    return True


def _topo_order(txns: dict, family: str) -> list[int] | None:
    """A committed-txn order consistent with session, realtime, and
    read-from constraints — derived directly from the raw history (the
    independently-checkable edge subset), so it never depends on the
    engine's ww/rw version-order inference. Completion order satisfies
    session + realtime by construction; read-from violations are
    repaired by a Kahn pass over the wr edges when needed."""
    committed = sorted((i for i, t in txns.items()
                        if t["type"] == h.OK),
                       key=lambda i: txns[i]["ret_pos"])
    pos = {i: j for j, i in enumerate(committed)}
    writers = _writer_map(txns, family)
    wr_edges: list[tuple[int, int]] = []
    bad = False
    for i in committed:
        for k, rv in _reads(txns[i]):
            vals = (rv if family == "list-append"
                    and isinstance(rv, list) else [rv])
            for v in vals:
                ws = [w for w in writers.get(_fkey(k, v), [])
                      if w in pos and w != i]
                if len(ws) == 1:
                    wr_edges.append((ws[0], i))
                    if pos[ws[0]] > pos[i]:
                        bad = True
    if not bad:
        return committed
    # Kahn over wr + session + realtime-as-tiebreak: realtime and
    # session constraints are kept by ordering the ready set by
    # completion position; a genuine conflict (cycle) yields None.
    import heapq

    adj: dict[int, list[int]] = {}
    indeg = {i: 0 for i in committed}
    last_by_proc: dict = {}
    for i in sorted(committed, key=lambda x: txns[x]["inv_pos"]):
        p = txns[i]["process"]
        prev = last_by_proc.get(p)
        if prev is not None:
            adj.setdefault(prev, []).append(i)
            indeg[i] += 1
        last_by_proc[p] = i
    for a, b in wr_edges:
        adj.setdefault(a, []).append(b)
        indeg[b] += 1
    ready = [(txns[i]["ret_pos"], i) for i in committed
             if indeg[i] == 0]
    heapq.heapify(ready)
    out: list[int] = []
    while ready:
        _r, i = heapq.heappop(ready)
        out.append(i)
        for j in adj.get(i, []):
            indeg[j] -= 1
            if indeg[j] == 0:
                heapq.heappush(ready, (txns[j]["ret_pos"], j))
    if len(out) != len(committed) or not _realtime_order_ok(out, txns):
        return None
    return out


def elle_certificate(hist, result, family: str) -> dict:
    """Builds the certificate for an elle check result (either
    engine): a justified cycle (or G1a/duplicate evidence) for invalid
    verdicts, a constraint-checked serialization order for valid ones.
    Never raises — unprovable results go absent."""
    try:
        return _elle_certificate(hist, result, family)
    except CertificateError as e:
        return absent(str(e))
    except Exception as e:  # noqa: BLE001 — extraction is best-effort
        logger.exception("elle certificate extraction failed")
        return absent(f"extraction-failed: {e!r}")


def _elle_certificate(hist, result, family: str) -> dict:
    if not isinstance(hist, History):
        hist = History(hist)
    verdict = result.get("valid?")
    if verdict not in (True, False):
        return absent("verdict is indeterminate")
    cert: dict = {"v": VERSION, "kind": "elle", "family": family,
                  "verdict": "valid" if verdict else "invalid",
                  "history": history_digest(hist)}
    txns = _collect_txns(hist)
    if verdict:
        order = _topo_order(txns, family)
        if order is None:
            return absent("no session/realtime/read-from-consistent "
                          "serialization order found")
        cert["topo-order"] = order
        return cert
    cyc = _first_cycle(result)
    if cyc is not None:
        adj = _adjacency_index(txns, family)
        ops = cyc["cycle"]
        idxs = [_resolve_op_index(hist, o) for o in ops]
        if any(i is None or i not in txns for i in idxs):
            return absent("cycle ops do not resolve to txns")
        edges = []
        for j, step in enumerate(cyc["steps"]):
            a_i = idxs[j]
            b_i = idxs[(j + 1) % len(idxs)]
            edge = _justification(a_i, b_i, step.get("type"), txns,
                                  family, adj)
            if edge is None:
                return absent(
                    f"no mop justification for {step.get('type')} "
                    f"edge {a_i} -> {b_i}")
            edges.append(edge)
        cert["cycle"] = edges
        return cert
    # non-cycle anomalies: the justifiable classes
    anomalies = result.get("anomalies") or {}
    for rec in anomalies.get("G1a") or []:
        w_i = _resolve_op_index(hist, rec.get("writer"))
        r_i = _resolve_op_index(hist, rec.get("op"))
        if w_i in txns and r_i in txns:
            cert["anomaly"] = {"class": "G1a",
                               "key": _jv(rec.get("key")),
                               "value": _jv(rec.get("value")),
                               "writer": w_i, "reader": r_i}
            return cert
    dup_cls = ("duplicate-appends" if family == "list-append"
               else "duplicate-writes")
    for rec in anomalies.get(dup_cls) or []:
        k, v = _jv(rec.get("key")), _jv(rec.get("value"))
        ws = [i for i, t in txns.items() if t["type"] != h.FAIL
              and any(wk == k and wv == v
                      for wk, wv in _writes(t, family))]
        if len(ws) >= 2:
            cert["anomaly"] = {"class": "duplicate", "key": k,
                               "value": v, "writers": ws[:2]}
            return cert
    return absent("no justifiable cycle or anomaly evidence in the "
                  f"result (classes: {sorted(anomalies)})")


def attach_elle(hist, result, family: str) -> dict:
    """Attaches a certificate to an elle check result (the checker
    wrappers opt in via opts['certify']; raw bench calls don't)."""
    if not isinstance(result, dict):
        return result
    if not enabled():
        result["certificate"] = absent("extraction disabled "
                                       "(JEPSEN_TPU_CERTIFY=0)")
        return result
    cert = elle_certificate(hist, result, family)
    result["certificate"] = cert
    telemetry.count("certify.absent" if "absent" in cert
                    else "certify.extracted")
    return result
