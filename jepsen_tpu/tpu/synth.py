"""Synthetic histories for benchmarks and compile checks.

Valid-by-construction concurrent register histories: each op's effect is
applied to the true register at a random instant inside its
invoke/complete window, so the resulting history is linearizable by
construction (the application order is a witness). This mirrors how the
reference generates its perf-regression history fixture
(jepsen/test/jepsen/perf_test.clj) but at arbitrary scale.
"""

from __future__ import annotations

import random

from ..history import History, op


def register_history(n_ops: int, n_procs: int = 5, seed: int = 0,
                     crash_p: float = 0.0, n_values: int = 5,
                     cas_p: float = 0.25, read_p: float = 0.4) -> History:
    """A valid CAS-register history with `n_ops` invocations (history
    length is ~2*n_ops events). Initial register value is None."""
    rng = random.Random(seed)
    value = None
    events: list = []
    # process -> [f, v, applied?, result]
    open_ops: dict[int, list] = {}
    budget = n_ops
    append = events.append
    while budget > 0 or open_ops:
        idle = n_procs - len(open_ops)
        unapplied = [p for p, o in open_ops.items() if not o[2]]
        applied = [p for p, o in open_ops.items() if o[2]]
        r = rng.random()
        # Prefer invoking while idle processes remain, then applying,
        # then completing — weights keep several ops in flight.
        if budget > 0 and idle and (r < 0.45 or not open_ops):
            p = rng.choice([q for q in range(n_procs)
                            if q not in open_ops])
            r2 = rng.random()
            if r2 < read_p:
                f, v = "read", None
            elif r2 < read_p + cas_p:
                f = "cas"
                v = [rng.randrange(n_values), rng.randrange(n_values)]
            else:
                f, v = "write", rng.randrange(n_values)
            open_ops[p] = [f, v, False, None]
            append(("invoke", p, f, v))
            budget -= 1
        elif unapplied and (r < 0.75 or not applied):
            p = rng.choice(unapplied)
            o = open_ops[p]
            f, v = o[0], o[1]
            if f == "read":
                o[3] = value
            elif f == "write":
                value = v
            else:
                cur, new = v
                if cur == value:
                    value = new
                    o[3] = "ok"
                else:
                    o[3] = "fail"
            o[2] = True
        elif applied:
            p = rng.choice(applied)
            f, v, _, result = open_ops.pop(p)
            if crash_p and rng.random() < crash_p:
                append(("info", p, f, v))
            elif f == "read":
                append(("ok", p, f, result))
            elif f == "write":
                append(("ok", p, f, v))
            else:
                append(("ok" if result == "ok" else "fail", p, f, v))
    ops = [op(index=i, time=i, type=t, process=p, f=f, value=v)
           for i, (t, p, f, v) in enumerate(events)]
    return History(ops, assign_indices=False)
