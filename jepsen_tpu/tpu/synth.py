"""Synthetic histories for benchmarks and compile checks.

Valid-by-construction concurrent register histories: each op's effect is
applied to the true register at a random instant inside its
invoke/complete window, so the resulting history is linearizable by
construction (the application order is a witness). This mirrors how the
reference generates its perf-regression history fixture
(jepsen/test/jepsen/perf_test.clj) but at arbitrary scale.
"""

from __future__ import annotations

import random

from ..history import History, op


def register_history(n_ops: int, n_procs: int = 5, seed: int = 0,
                     crash_p: float = 0.0, n_values: int = 5,
                     cas_p: float = 0.25, read_p: float = 0.4) -> History:
    """A valid CAS-register history with `n_ops` invocations (history
    length is ~2*n_ops events). Initial register value is None."""
    rng = random.Random(seed)
    value = None
    events: list = []
    # process -> [f, v, applied?, result]
    open_ops: dict[int, list] = {}
    budget = n_ops
    append = events.append
    while budget > 0 or open_ops:
        idle = n_procs - len(open_ops)
        unapplied = [p for p, o in open_ops.items() if not o[2]]
        applied = [p for p, o in open_ops.items() if o[2]]
        r = rng.random()
        # Prefer invoking while idle processes remain, then applying,
        # then completing — weights keep several ops in flight.
        if budget > 0 and idle and (r < 0.45 or not open_ops):
            p = rng.choice([q for q in range(n_procs)
                            if q not in open_ops])
            r2 = rng.random()
            if r2 < read_p:
                f, v = "read", None
            elif r2 < read_p + cas_p:
                f = "cas"
                v = [rng.randrange(n_values), rng.randrange(n_values)]
            else:
                f, v = "write", rng.randrange(n_values)
            open_ops[p] = [f, v, False, None]
            append(("invoke", p, f, v))
            budget -= 1
        elif unapplied and (r < 0.75 or not applied):
            p = rng.choice(unapplied)
            o = open_ops[p]
            f, v = o[0], o[1]
            if f == "read":
                o[3] = value
            elif f == "write":
                value = v
            else:
                cur, new = v
                if cur == value:
                    value = new
                    o[3] = "ok"
                else:
                    o[3] = "fail"
            o[2] = True
        elif applied:
            p = rng.choice(applied)
            f, v, _, result = open_ops.pop(p)
            if crash_p and rng.random() < crash_p:
                append(("info", p, f, v))
            elif f == "read":
                append(("ok", p, f, result))
            elif f == "write":
                append(("ok", p, f, v))
            else:
                append(("ok" if result == "ok" else "fail", p, f, v))
    ops = [op(index=i, time=i, type=t, process=p, f=f, value=v)
           for i, (t, p, f, v) in enumerate(events)]
    return History(ops, assign_indices=False)


def corrupt_register_history(hist: History, at_frac: float = 0.85,
                             bogus: int | None = None) -> tuple[History, int]:
    """Seeds ONE linearizability anomaly into a valid register history:
    the first ok read at/after `at_frac` of the history starts returning
    `bogus` (default: one past the largest int value seen anywhere in
    the history, so it is provably outside the write domain), making the
    read impossible to linearize. Returns (corrupted history, event
    index of the bad read).

    Drives the time-to-first-anomaly benchmark (BASELINE.md names the
    metric; the reference's knossos surfaces its counterexample through
    the same invalid-read shape, knossos.model/cas-register)."""
    events = list(hist)
    if bogus is None:
        seen = [e.value for e in events if isinstance(e.value, int)]
        seen += [v for e in events if isinstance(e.value, (list, tuple))
                 for v in e.value if isinstance(v, int)]
        bogus = max(seen, default=98) + 1
    start = int(len(events) * at_frac)
    for i in range(start, len(events)):
        e = events[i]
        if e.type == "ok" and e.f == "read":
            events[i] = e.copy(value=bogus)
            return History(events, assign_indices=False), i
    raise ValueError("no ok read at/after at_frac to corrupt")


def list_append_history(n_txns: int, n_procs: int = 5, n_keys: int = 6,
                        max_len: int = 4, rotate: int = 40,
                        seed: int = 0) -> History:
    """A valid concurrent list-append history: appends apply to a true
    store at completion, reads return its current state; keys rotate
    every `rotate` txns so read lists stay bounded (as elle's generator
    does). BASELINE config 3 fodder."""
    rng = random.Random(seed)
    store: dict = {}
    epoch = 0
    events: list = []
    open_t: dict[int, list] = {}
    t_count = 0
    nv = 1
    while t_count < n_txns or open_t:
        idle = n_procs - len(open_t)
        if t_count < n_txns and idle and (rng.random() < 0.6
                                          or not open_t):
            p = rng.choice([q for q in range(n_procs)
                            if q not in open_t])
            txn = []
            for _ in range(rng.randint(1, max_len)):
                k = f"k{rng.randrange(n_keys)}e{epoch}"
                if rng.random() < 0.5:
                    txn.append(["append", k, nv])
                    nv += 1
                else:
                    txn.append(["r", k, None])
            events.append(("invoke", p, txn))
            open_t[p] = txn
            t_count += 1
            if t_count % rotate == 0:
                epoch += 1
        else:
            p = rng.choice(list(open_t))
            txn = open_t.pop(p)
            res = []
            for f, k, v in txn:
                if f == "append":
                    store.setdefault(k, []).append(v)
                    res.append(["append", k, v])
                else:
                    res.append(["r", k, list(store.get(k, []))])
            events.append(("ok", p, res))
    ops = [op(index=i, time=i, type=t, process=p, f="txn", value=m)
           for i, (t, p, m) in enumerate(events)]
    return History(ops, assign_indices=False)


def bank_history(n_txns: int, n_procs: int = 5, n_accounts: int = 8,
                 initial: int = 10, max_transfer: int = 5,
                 read_p: float = 0.5, seed: int = 0) -> History:
    """A valid concurrent bank history: transfers apply atomically to
    true balances at completion, reads snapshot them. Total balance is
    conserved by construction. BASELINE config 4 fodder."""
    rng = random.Random(seed)
    balances = {a: initial for a in range(n_accounts)}
    events: list = []
    open_t: dict[int, tuple] = {}
    t_count = 0
    while t_count < n_txns or open_t:
        idle = n_procs - len(open_t)
        if t_count < n_txns and idle and (rng.random() < 0.6
                                          or not open_t):
            p = rng.choice([q for q in range(n_procs)
                            if q not in open_t])
            if rng.random() < read_p:
                o = ("read", None)
            else:
                frm, to = rng.sample(range(n_accounts), 2)
                o = ("transfer", {"from": frm, "to": to,
                                  "amount": rng.randint(1, max_transfer)})
            events.append(("invoke", p, o[0], o[1]))
            open_t[p] = o
            t_count += 1
        else:
            p = rng.choice(list(open_t))
            f, v = open_t.pop(p)
            if f == "transfer":
                amt = v["amount"]
                if balances[v["from"]] >= amt:
                    balances[v["from"]] -= amt
                    balances[v["to"]] += amt
                    events.append(("ok", p, f, v))
                else:
                    events.append(("fail", p, f, v))
            else:
                events.append(("ok", p, f, dict(balances)))
    ops = [op(index=i, time=i, type=t, process=p, f=f, value=v)
           for i, (t, p, f, v) in enumerate(events)]
    return History(ops, assign_indices=False)


def rw_register_history(n_txns: int, n_procs: int = 5,
                        n_keys: int = 32, max_len: int = 4,
                        seed: int = 0) -> History:
    """A valid concurrent rw-register txn history: writes apply to true
    registers at completion, reads snapshot them, every written value
    unique (elle's rw-register generator guarantee). BASELINE config 3
    fodder alongside list_append_history."""
    rng = random.Random(seed)
    regs: dict = {}
    events: list = []
    open_t: dict[int, list] = {}
    nv = 1
    t_count = 0
    while t_count < n_txns or open_t:
        idle = n_procs - len(open_t)
        if t_count < n_txns and idle and (rng.random() < 0.6
                                          or not open_t):
            p = rng.choice([q for q in range(n_procs)
                            if q not in open_t])
            txn = []
            for _ in range(rng.randint(1, max_len)):
                k = f"k{rng.randrange(n_keys)}"
                if rng.random() < 0.5:
                    txn.append(["w", k, nv])
                    nv += 1
                else:
                    txn.append(["r", k, None])
            events.append(("invoke", p, txn))
            open_t[p] = txn
            t_count += 1
        else:
            p = rng.choice(list(open_t))
            txn = open_t.pop(p)
            res = []
            for f, k, v in txn:
                if f == "w":
                    regs[k] = v
                    res.append(["w", k, v])
                else:
                    res.append(["r", k, regs.get(k)])
            events.append(("ok", p, res))
    ops = [op(index=i, time=i, type=t, process=p, f="txn", value=m)
           for i, (t, p, m) in enumerate(events)]
    return History(ops, assign_indices=False)
