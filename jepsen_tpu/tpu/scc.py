"""Strongly-connected components as a batched device kernel.

Capability reference: elle 0.2.1 runs Tarjan's SCC on the JVM over the
inferred dependency graph (consumed via jepsen/src/jepsen/tests/cycle/
append.clj:6-27); SURVEY §2.2 plans its replacement as "vectorized edge
inference + iterative/batched SCC (forward-backward reachability) on
int32 adjacency tensors".

Tarjan is inherently sequential, so the device formulation is Orzan's
coloring algorithm, whose primitives are pure data-parallel segment
ops that XLA maps well:

  repeat until no active nodes:
    1. forward pass — propagate the max node id ("color") along active
       edges to a fixpoint: c[v] = max(c[v], max_{u->v} c[u]). Each
       sweep is one scatter-max over the edge list; the fixpoint runs
       in a lax.while_loop on device.
    2. backward pass — for every color root r (c[r]==r), mark the
       nodes that reach r inside r's color class; again a scatter-max
       fixpoint, all roots in parallel. Marked nodes = the exact SCC
       of each root (they reach r and r reaches them).
    3. retire every marked SCC; survivors recolor next round.

Node ids follow history order, so dependency edges point mostly
forward (u < v) and a forward sweep changes nothing for them: the
fixpoint converges in a handful of sweeps rather than O(diameter).
Both loops carry iteration caps; on non-convergence (adversarial
graphs) the caller falls back to the host path (scipy's compiled
Tarjan-equivalent), so results are always exact.

Edge subsets (elle checks cycles over WW, WW+WR, ... cumulative edge
classes) are expressed as boolean edge masks over ONE shared edge
array, so every subset reuses the same compiled kernel executable
instead of recompiling per subset shape.
"""

from __future__ import annotations

from functools import lru_cache
from time import monotonic_ns

import numpy as np

from .. import telemetry
from . import profiler

# Caps: sweeps per fixpoint and outer peeling rounds. Each fixpoint
# sweep is O(E) on device, so generous caps cost little; they exist to
# bound adversarial graphs, which then take the host fallback.
SWEEP_CAP = 512
ROUND_CAP = 64

# Below this edge count the host path wins on dispatch overhead alone.
DEVICE_MIN_EDGES = 20_000


def _next_pow2(x: int) -> int:
    n = 1
    while n < x:
        n <<= 1
    return n


def _scc_program(n_pad: int, sweep_cap: int, round_cap: int,
                 combine=None):
    """The ENTIRE Orzan peeling loop as one traceable program: rounds
    and fixpoints run in nested lax.while_loops, one download of
    (labels, ok). On a tunneled TPU the per-transfer latency dominates
    sweep compute by orders of magnitude, so round-trips — not FLOPs
    — are the budget.

    `combine` is the SPMD hook: the sharded path passes a pmax over
    the mesh axis, turning each sweep's scatter-max into local
    scatter over the shard's edge block + one small all-reduce of the
    color array — the edge list (the big operand) never replicates."""
    import jax
    import jax.numpy as jnp

    def fixpoint(carry_c, src, dst, live_e, neutral):
        """Scatter-max propagation to fixpoint; returns (c, converged)."""

        def cond(state):
            c, changed, it = state
            return jnp.logical_and(changed, it < sweep_cap)

        def body(state):
            c, _, it = state
            vals = jnp.where(live_e, c[src], neutral)
            prop = jnp.full((n_pad,), neutral, dtype=jnp.int32
                            ).at[dst].max(vals)
            if combine is not None:
                prop = combine(prop)
            nc = jnp.maximum(c, prop)
            # c and (combined) prop are replicated on the sharded
            # path, so `changed` agrees across shards and the while
            # loops stay in lockstep
            return nc, jnp.any(nc != c), it + 1

        c, changed, _ = jax.lax.while_loop(
            cond, body, (carry_c, jnp.bool_(True), jnp.int32(0)))
        return c, jnp.logical_not(changed)

    def one_round(active, src, dst, edge_on):
        """One coloring round. Returns (labels for nodes retired this
        round, new active mask, converged)."""
        node_ids = jnp.arange(n_pad, dtype=jnp.int32)
        live_e = jnp.logical_and(
            edge_on, jnp.logical_and(active[src], active[dst]))
        # 1. forward colors
        c0 = jnp.where(active, node_ids, jnp.int32(-1))
        c, ok_f = fixpoint(c0, src, dst, live_e, jnp.int32(-1))
        # 2. backward membership within color classes, all roots at
        # once: m[v]=1 iff v reaches its color root inside the class.
        same_color = jnp.logical_and(live_e, c[src] == c[dst])
        m0 = jnp.where(jnp.logical_and(active, c == node_ids),
                       jnp.int32(1), jnp.int32(0))
        # propagate backward: m[u] |= m[v] for edge u->v in-class
        m, ok_b = fixpoint(m0, dst, src, same_color, jnp.int32(0))
        member = jnp.logical_and(active, m > 0)
        labels = jnp.where(member, c, jnp.int32(-1))
        return labels, jnp.logical_and(active, ~member), \
            jnp.logical_and(ok_f, ok_b)

    def full(active0, src, dst, edge_on):
        def cond(state):
            active, _out, ok, rounds = state
            return ok & jnp.any(active) & (rounds < round_cap)

        def body(state):
            active, out, ok, rounds = state
            labels, new_active, converged = one_round(active, src, dst,
                                                      edge_on)
            return (new_active, jnp.where(labels >= 0, labels, out),
                    ok & converged, rounds + 1)

        out0 = jnp.full((n_pad,), -1, dtype=jnp.int32)
        active, out, ok, _ = jax.lax.while_loop(
            cond, body, (active0, out0, jnp.bool_(True), jnp.int32(0)))
        done = ok & jnp.logical_not(jnp.any(active))
        # ok flag rides IN the labels array (slot n_pad-1 is sentinel
        # territory): one device->host transfer instead of two — each
        # transfer pays full link latency on a tunneled TPU.
        return out.at[-1].set(done.astype(jnp.int32))

    return full


# The edge arrays (src/dst/edge_on — the big per-launch payload) are
# donated: scc_device builds fresh device arrays per call, so XLA may
# reuse them as scratch (graftlint R3). `active` stays live (tiny).
DONATE_ARGNUMS = (1, 2, 3)
SCC_ARGS = ("active", "src", "dst", "edge_on")


@lru_cache(maxsize=None)
def _jitted_scc(n_pad: int, e_pad: int, sweep_cap: int,
                round_cap: int):
    """Single-device compile of the peeling loop, one executable per
    (node, edge) shape bucket."""
    import jax

    from . import spmd
    from .wgl import quiet_unusable_donation

    spmd.enable_compile_cache()
    quiet_unusable_donation()
    return jax.jit(_scc_program(n_pad, sweep_cap, round_cap),
                   donate_argnums=DONATE_ARGNUMS)


@lru_cache(maxsize=None)
def _jitted_scc_sharded(mesh, n_pad: int, e_pad: int, sweep_cap: int,
                        round_cap: int):
    """SPMD compile: the edge list shards over the mesh's 'b' axis
    (in key blocks — see scc_device), the color array stays
    replicated, and each sweep's fixpoint combines per-shard
    scatter-max results with ONE pmax of n_pad ints. Per-sweep
    compute and H2D both scale ~1/N in edges; the collective moves
    node-count bytes, not edge-count."""
    import jax
    from jax.experimental.shard_map import shard_map
    from jax.sharding import NamedSharding, PartitionSpec as P

    from . import spmd
    from .wgl import quiet_unusable_donation

    spmd.enable_compile_cache()
    quiet_unusable_donation()
    full = _scc_program(
        n_pad, sweep_cap, round_cap,
        combine=lambda prop: jax.lax.pmax(prop, spmd.AXIS))
    specs = spmd.match_partition_rules(spmd.SCC_RULES, SCC_ARGS)
    mapped = shard_map(full, mesh=mesh, in_specs=specs, out_specs=P(),
                       check_rep=False)
    shardings = tuple(NamedSharding(mesh, s) for s in specs)
    return jax.jit(mapped, in_shardings=shardings,
                   donate_argnums=DONATE_ARGNUMS)


def _edge_pad(e: int) -> int:
    """Edge shape buckets: multiples of 128Ki (capped pow2 below that)
    rather than next-pow2 — the padding is uploaded over the (slow)
    host->device link, so a 600k-edge graph shouldn't ship 1M slots."""
    if e <= (1 << 17):
        return _next_pow2(max(e, 1))
    step = 1 << 17
    return ((e + step - 1) // step) * step


def scc_device(n: int, src, dst, emask=None,
               ekey=None) -> np.ndarray | None:
    """SCC labels per node (label = the component's max node id), or
    None when iteration caps were hit (caller must take the host
    path). Singleton components get their own id, so callers test
    non-triviality by label multiplicity.

    On a multi-device process the edge list shards over the mesh
    (_jitted_scc_sharded). ekey — the per-edge key id from the elle
    edge-inference passes — orders the edge array into key blocks
    first, so each device's contiguous shard covers whole keys:
    same-key dependency edges (the bulk of ww/wr/rw) propagate inside
    one shard and only cross-key session/realtime edges ride the
    pmax. Labels are order-independent, so the layout cannot change
    the verdict."""
    import jax.numpy as jnp

    from . import spmd

    src = np.asarray(src, dtype=np.int32)
    dst = np.asarray(dst, dtype=np.int32)
    if n == 0:
        return np.empty(0, dtype=np.int32)
    n_dev = spmd.spmd_devices()
    if n_dev > 1:
        # pow2 mesh sizes only, like ensemble.sharded_launch: each
        # mesh size is its own compile family, and compile latency —
        # not FLOPs — is this kernel's budget
        n_dev = 1 << (n_dev.bit_length() - 1)
    shard = n_dev > 1 and len(src) >= DEVICE_MIN_EDGES
    if shard and ekey is not None and len(ekey) == len(src):
        ekey = np.asarray(ekey)
        if np.any(ekey[:-1] > ekey[1:]):
            # callers that launch several graded subsets over one edge
            # array (cycle_anomalies_arrays) pre-sort once; this sort
            # only runs for one-shot callers
            order = np.argsort(ekey, kind="stable")
            src, dst = src[order], dst[order]
            if emask is not None:
                emask = np.asarray(emask)[order]
        telemetry.count("scc.keyblock-layouts")
    n_pad = _next_pow2(n + 1)
    e_pad = _edge_pad(len(src))
    if shard and e_pad % n_dev:
        e_pad += n_dev - e_pad % n_dev
    # pad edges as self-loops on the sentinel (inactive) node n
    psrc = np.full(e_pad, n, dtype=np.int32)
    pdst = np.full(e_pad, n, dtype=np.int32)
    psrc[:len(src)] = src
    pdst[:len(dst)] = dst
    pmask = np.zeros(e_pad, dtype=bool)
    pmask[:len(src)] = True if emask is None else np.asarray(emask)
    active = np.zeros(n_pad, dtype=bool)
    active[:n] = True
    prof = profiler.get()
    if shard:
        mesh = spmd.mesh_for(n_dev)
        fn = _jitted_scc_sharded(mesh, n_pad, e_pad, SWEEP_CAP,
                                 ROUND_CAP)
        bucket = ("scc-sharded", n_dev, n_pad, e_pad)
        telemetry.gauge_max("scc.spmd.devices", n_dev)
    else:
        fn = _jitted_scc(n_pad, e_pad, SWEEP_CAP, ROUND_CAP)
        bucket = ("scc", n_pad, e_pad)
    rec = prof.begin("scc", bucket=bucket, nodes=n, edges=len(src),
                     devices=n_dev if shard else None)
    fresh = prof.bucket_fresh("scc", bucket)
    t0 = monotonic_ns()
    if shard:
        import jax
        from jax.sharding import NamedSharding

        specs = spmd.match_partition_rules(spmd.SCC_RULES, SCC_ARGS)
        args = tuple(
            jax.device_put(a, NamedSharding(mesh, s))
            for a, s in zip((active, psrc, pdst, pmask), specs))
    else:
        args = (jnp.asarray(active), jnp.asarray(psrc),
                jnp.asarray(pdst), jnp.asarray(pmask))
    rec["h2d_ns"] = monotonic_ns() - t0
    try:
        t0 = monotonic_ns()
        dev = fn(*args)
        rec["dispatch_ns"] = monotonic_ns() - t0
        if fresh:
            rec["compile_ns"] = rec["dispatch_ns"]
        rec.update(prof.bucket_cost(bucket, lambda: fn.lower(*args),
                                    fresh))
        t0 = monotonic_ns()
        labels = np.asarray(dev)
        rec["compute_ns"] = monotonic_ns() - t0
    except BaseException:
        if fresh:
            # failed first launch: release the claim so the retry's
            # real recompile records a miss, not a phantom hit
            prof.bucket_unclaim("scc", bucket)
        raise
    finally:
        prof.finish(rec)
    if not labels[-1]:  # convergence flag (see _jitted_scc)
        return None
    return labels[:n]


def _scc_host(n: int, src, dst) -> np.ndarray:
    """Exact host SCC via scipy (compiled Tarjan-equivalent), with
    labels normalized to the component's max node id so device and
    host paths are interchangeable."""
    from scipy.sparse import coo_matrix
    from scipy.sparse.csgraph import connected_components

    g = coo_matrix((np.ones(len(src), dtype=np.int8),
                    (np.asarray(src), np.asarray(dst))), shape=(n, n))
    _, comp = connected_components(g, directed=True, connection="strong")
    # int32 throughout: node ids are < 2^31 by construction, and the
    # int64 intermediates here doubled the representative-id pass's
    # memory traffic on million-node graphs (graftlint R2)
    ids = np.arange(n, dtype=np.int32)
    rep = np.full(int(comp.max()) + 1 if n else 0, -1, dtype=np.int32)
    np.maximum.at(rep, comp, ids)
    return rep[comp]


def scc(n: int, src, dst, emask=None, device: bool = True,
        ekey=None) -> np.ndarray:
    """SCC labels (component max-id per node); device kernel with host
    fallback on non-convergence, host path outright for small graphs
    (dispatch overhead dominates under DEVICE_MIN_EDGES edges).
    ekey: optional per-edge key ids for the sharded path's key-block
    layout (see scc_device)."""
    src = np.asarray(src)
    dst = np.asarray(dst)
    if emask is not None:
        emask = np.asarray(emask, dtype=bool)
    n_live = len(src) if emask is None else int(emask.sum())
    if n == 0 or n_live == 0:
        return np.arange(n, dtype=np.int32)
    telemetry.count("scc.nodes", n)
    telemetry.count("scc.edges", n_live)
    if device and n_live >= DEVICE_MIN_EDGES:
        try:
            labels = scc_device(n, src, dst, emask, ekey=ekey)
        except Exception:
            labels = None
        if labels is not None:
            telemetry.count("scc.path.device")
            return labels
        telemetry.count("scc.device-nonconverged")
    if emask is not None:
        src, dst = src[emask], dst[emask]
    telemetry.count("scc.path.host")
    return _scc_host(n, src, dst)


def nontrivial_from_labels(labels: np.ndarray) -> list[np.ndarray]:
    """Member arrays of every component with >= 2 nodes (self-loops are
    not cycles in dependency graphs: a txn never depends on itself)."""
    uniq, inverse, counts = np.unique(labels, return_inverse=True,
                                      return_counts=True)
    big = counts > 1
    if not big.any():
        return []
    order = np.argsort(inverse, kind="stable")
    sorted_inv = inverse[order]
    bounds = np.concatenate([[0], np.cumsum(counts)])
    groups = [order[bounds[i]:bounds[i + 1]]
              for i in np.flatnonzero(big)]
    telemetry.count("scc.nontrivial-components", len(groups))
    telemetry.gauge_max("scc.largest-component",
                        int(max(len(g) for g in groups)))
    return groups


def nontrivial_sccs(n: int, src, dst, emask=None, device: bool = True,
                    ekey=None) -> list[np.ndarray]:
    if n == 0:
        return []
    return nontrivial_from_labels(scc(n, src, dst, emask,
                                      device=device, ekey=ekey))
