"""graftlint: static analysis of the compiled device kernels.

PR 9's certificates make a *wrong* answer fail by proof; this module
makes a *slow* answer fail by lint. The SPMD rebuild of the wgl /
ensemble / elle kernels (ROADMAP items 1-2) is exactly the kind of
aggressive refactor that silently reintroduces host syncs, full
replication, dtype widening, donation misses and recompile storms —
the failure class AccelSync (PAPERS.md, arXiv:2605.07881) argues must
be caught by *static* verification of accelerator pipeline programs
rather than by benchmarking luck.

The unit of analysis is a KernelTrace: one compiled entry point traced
abstractly (jax.make_jaxpr / Lowered over ShapeDtypeStructs — no
execution, no devices needed) at one of the profiler's real shape
buckets. The rule suite runs over the jaxpr, the lowered HLO text, the
Lowered's argument/donation info, and declared partition metadata:

  R1 host-sync        callback/infeed primitives inside a hot kernel
                      (each one serializes the device on the host)
  R2 dtype-widening   64-bit avals or widening converts in the jaxpr,
                      plus explicit np.int64/float64 in the host
                      feeder modules (the direct input to the
                      int8/int16 state-packing item)
  R3 donation-miss    large non-donated args, measured in bytes
  R4 sharding         large operands replicated across the mesh;
                      embarrassingly-parallel batch axes with no
                      partition rule; collectives in lowered HLO
  R5 recompile-risk   python scalars/large arrays closed over as
                      jaxpr consts; unquantized shape-bucket policies;
                      runtime bucket-cardinality blowups
  R6 carry-bloat      while-loop carries past the byte budget (every
                      byte of carry is serialized through each BFS
                      level)

Findings carry file:line provenance (jaxpr source info where
available), an estimated cost in bytes, and a fix hint. A committed
baseline (lint-baseline.json) pins today's findings so tier-1 fails
only on NEW ones — the ratchet that guards the SPMD refactor. The
kernel registry and driver live in jepsen_tpu.analysis; the threaded
harness modules get their own AST concurrency lint there too.
"""

from __future__ import annotations

import ast
import inspect
import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Any, Iterator

# Rule identifiers, in catalog order (doc/static-analysis.md).
RULES = ("R1", "R2", "R3", "R4", "R5", "R6", "C1", "C2", "C3")

# R1: primitives that bounce through the host mid-program. Any one of
# these inside a hot kernel turns an async device dispatch into a
# synchronous host round trip per call.
HOST_SYNC_PRIMITIVES = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
    "outside_call", "infeed", "outfeed", "host_callback_call",
})

# R2: dtypes that double the memory traffic of the int32 house style.
WIDE_DTYPES = frozenset({"int64", "uint64", "float64"})

# R3: args below this size aren't worth a donation finding (the
# scheduler copies small buffers anyway).
DONATION_MIN_BYTES = 256 * 1024

# R4: a replicated operand below this costs less than the finding.
REPLICATED_MIN_BYTES = 256 * 1024

# R4: collective ops in lowered HLO that force cross-device traffic.
HLO_COLLECTIVES = ("all-gather", "all-to-all", "collective-permute")

# R5: consts bigger than this bloat every compiled executable of the
# bucket (and re-trace per value if the capture isn't stable).
LARGE_CONST_BYTES = 64 * 1024

# R5: runtime cross-check — more compiled buckets than this per kernel
# means the bucketing policy is leaking shapes (one ~seconds compile
# per bucket).
MAX_RUNTIME_BUCKETS = 32

# R6: while-loop carry budget. Every carry byte rides through every
# BFS level; past this the carry itself becomes the bandwidth bill.
CARRY_WARN_BYTES = 128 * 1024


@dataclass
class Finding:
    """One lint finding. `key` (rule:kernel:site) is the stable
    identity the baseline ratchet matches on — deliberately free of
    line numbers, which churn under unrelated edits; file:line ride
    along as provenance only."""

    rule: str
    kernel: str
    site: str
    message: str
    file: str | None = None
    line: int | None = None
    hint: str | None = None
    severity: str = "warn"        # "warn" | "info"
    cost_bytes: int | None = None

    @property
    def key(self) -> str:
        return f"{self.rule}:{self.kernel}:{self.site}"

    def to_dict(self) -> dict:
        d = {"key": self.key, "rule": self.rule, "kernel": self.kernel,
             "site": self.site, "message": self.message,
             "severity": self.severity}
        if self.file:
            d["file"] = self.file
            d["line"] = self.line
        if self.hint:
            d["hint"] = self.hint
        if self.cost_bytes is not None:
            d["cost_bytes"] = int(self.cost_bytes)
        return d


@dataclass
class ArgSpec:
    """One kernel argument as the Lowered saw it."""

    name: str
    shape: tuple
    dtype: str
    nbytes: int
    donated: bool = False


@dataclass
class KernelTrace:
    """One compiled entry point abstractly traced at one shape bucket.

    The registry (jepsen_tpu.analysis.registry) builds these from the
    real jit factories so donation flags, static config and partition
    layout are read off the actual compiled artifacts, not off a
    parallel description that can drift."""

    name: str                     # kernel/registry-entry name
    bucket: str                   # stable bucket label, e.g. B64xM512
    jaxpr: Any = None             # ClosedJaxpr | None
    args: list[ArgSpec] = field(default_factory=list)
    hlo_text: str | None = None
    cost: dict = field(default_factory=dict)   # flops/bytes_accessed
    # {"axis": name, "sharded": [argnames], "replicated": [argnames]}
    # mirroring the launch site's in_shardings; None = no mesh at all
    partition: dict | None = None
    # [(argname, axis_index, why-it-is-embarrassingly-parallel)]
    batch_axes: list = field(default_factory=list)
    bucket_policy: str | None = None   # "pow2" | "quantized" | "linear"
    file: str | None = None
    line: int | None = None


# ---------------------------------------------------------------------------
# jaxpr plumbing
# ---------------------------------------------------------------------------

def iter_eqns(jaxpr) -> Iterator:
    """Every eqn in a (Closed)Jaxpr, recursing through call/control-
    flow sub-jaxprs (while bodies, cond branches, scans, pjit)."""
    inner = getattr(jaxpr, "jaxpr", jaxpr)
    for eqn in getattr(inner, "eqns", ()):
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def _sub_jaxprs(eqn) -> Iterator:
    for v in eqn.params.values():
        for j in _jaxprs_in(v):
            yield j


def _jaxprs_in(v) -> Iterator:
    if hasattr(v, "eqns") or hasattr(v, "jaxpr"):
        yield v
    elif isinstance(v, (tuple, list)):
        for x in v:
            yield from _jaxprs_in(x)


def eqn_provenance(eqn) -> tuple[str | None, int | None]:
    """file:line for an eqn via jaxpr source info (private jax API,
    best-effort: a jax upgrade degrades provenance, never the rule)."""
    try:
        from jax._src import source_info_util

        s = source_info_util.summarize(eqn.source_info)
        # "path/to/file.py:123 (fn_name)"
        loc = s.split(" ")[0]
        f, _, ln = loc.rpartition(":")
        return f or None, int(ln) if ln.isdigit() else None
    except Exception:  # noqa: BLE001 — provenance is best-effort
        return None, None


def _aval_bytes(aval) -> int:
    try:
        import numpy as np

        n = 1
        for d in aval.shape:
            n *= int(d)
        return n * np.dtype(aval.dtype).itemsize
    except Exception:  # noqa: BLE001 — abstract avals only
        return 0


# ---------------------------------------------------------------------------
# R1 — host-sync
# ---------------------------------------------------------------------------

def rule_host_sync(trace: KernelTrace) -> list[Finding]:
    out: list[Finding] = []
    if trace.jaxpr is None:
        return out
    counts: dict[str, int] = {}
    for eqn in iter_eqns(trace.jaxpr):
        prim = eqn.primitive.name
        if prim not in HOST_SYNC_PRIMITIVES:
            continue
        n = counts.get(prim, 0)
        counts[prim] = n + 1
        f, ln = eqn_provenance(eqn)
        out.append(Finding(
            rule="R1", kernel=trace.name, site=f"{prim}:{n}",
            message=f"host-sync primitive `{prim}` inside the compiled "
                    f"kernel (bucket {trace.bucket}): every call is a "
                    "synchronous device->host->device round trip",
            file=f or trace.file, line=ln or trace.line,
            hint="compute it on device, or hoist the callback out of "
                 "the jitted program (pre/post-process on host)"))
    return out


# ---------------------------------------------------------------------------
# R2 — dtype widening (jaxpr side; host-feeder AST scan below)
# ---------------------------------------------------------------------------

def rule_dtype_widening(trace: KernelTrace) -> list[Finding]:
    out: list[Finding] = []
    if trace.jaxpr is None:
        return out
    seen: set[tuple[str, str]] = set()
    for eqn in iter_eqns(trace.jaxpr):
        prim = eqn.primitive.name
        for v in eqn.outvars:
            aval = getattr(v, "aval", None)
            dt = str(getattr(aval, "dtype", ""))
            if dt in WIDE_DTYPES and (prim, dt) not in seen:
                seen.add((prim, dt))
                f, ln = eqn_provenance(eqn)
                out.append(Finding(
                    rule="R2", kernel=trace.name,
                    site=f"{dt}:{prim}",
                    message=f"64-bit intermediate ({dt} out of "
                            f"`{prim}`) in the kernel jaxpr: doubles "
                            "memory traffic vs the int32 house style",
                    file=f or trace.file, line=ln or trace.line,
                    cost_bytes=_aval_bytes(aval) // 2,
                    hint="keep device math in int32/float32 (or "
                         "narrower); check for x64 leaks and python "
                         "int promotion"))
        if prim == "convert_element_type":
            try:
                import numpy as np

                new = np.dtype(eqn.params.get("new_dtype"))
                old = np.dtype(eqn.invars[0].aval.dtype)
                if new.itemsize >= 8 and new.itemsize > old.itemsize \
                        and ("widen", str(new)) not in seen:
                    seen.add(("widen", str(new)))
                    f, ln = eqn_provenance(eqn)
                    out.append(Finding(
                        rule="R2", kernel=trace.name,
                        site=f"widen:{old}->{new}",
                        message=f"widening convert {old}->{new} "
                                "inside the kernel",
                        file=f or trace.file, line=ln or trace.line,
                        hint="narrow the target dtype"))
            except Exception:  # noqa: BLE001 — param shape drift
                pass
    return out


# host-feeder side: explicit 64-bit numpy dtypes in the modules that
# build kernel inputs. One finding per (function, dtype) so line churn
# inside a function doesn't move the baseline key.

_DTYPE_ATTRS = {"int64", "uint64", "float64"}


def scan_module_dtypes(module) -> list[Finding]:
    """AST scan of one host-feeder module for explicit 64-bit numpy
    dtypes (np.int64 / jnp.float64 / dtype="int64") inside function
    bodies — each one is host-side widening feeding the device."""
    try:
        src = inspect.getsource(module)
        fname = inspect.getsourcefile(module)
    except (OSError, TypeError):
        return []
    modname = module.__name__.rsplit(".", 1)[-1]
    return scan_source_dtypes(src, fname, modname)


def scan_source_dtypes(src: str, fname: str | None,
                       modname: str) -> list[Finding]:
    try:
        tree = ast.parse(src)
    except SyntaxError:
        return []
    out: list[Finding] = []
    seen: set[tuple[str, str]] = set()

    def visit(node, func: str | None):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            func = node.name if func is None else f"{func}.{node.name}"
        for child in ast.iter_child_nodes(node):
            dt = _wide_dtype_of(child)
            if dt and func and (func, dt) not in seen:
                seen.add((func, dt))
                out.append(Finding(
                    rule="R2", kernel=f"module:{modname}",
                    site=f"{func}:{dt}",
                    message=f"explicit {dt} in host feeder "
                            f"{modname}.{func} (8-byte elements where "
                            "the kernels speak int32)",
                    file=fname, line=child.lineno,
                    hint="use int32/float32 unless the value range "
                         "genuinely needs 64 bits"))
            visit(child, func)

    visit(tree, None)
    return out


def _wide_dtype_of(node) -> str | None:
    if isinstance(node, ast.Attribute) and node.attr in _DTYPE_ATTRS \
            and isinstance(node.value, ast.Name) \
            and node.value.id in ("np", "jnp", "numpy"):
        return node.attr
    if isinstance(node, ast.keyword) and node.arg == "dtype" \
            and isinstance(node.value, ast.Constant) \
            and node.value.value in _DTYPE_ATTRS:
        return str(node.value.value)
    return None


# ---------------------------------------------------------------------------
# R3 — donation misses
# ---------------------------------------------------------------------------

def rule_donation(trace: KernelTrace) -> list[Finding]:
    out: list[Finding] = []
    for a in trace.args:
        if a.donated or a.nbytes < DONATION_MIN_BYTES:
            continue
        out.append(Finding(
            rule="R3", kernel=trace.name, site=a.name,
            message=f"arg `{a.name}` ({a.dtype}{list(a.shape)}, "
                    f"{a.nbytes / 1024:.0f} KiB) is not donated "
                    f"(bucket {trace.bucket}): the buffer stays live "
                    "across the launch instead of being reusable as "
                    "scratch/output",
            file=trace.file, line=trace.line,
            cost_bytes=a.nbytes,
            hint="add it to donate_argnums at the jit site (launch "
                 "sites re-create device arrays per call, so donation "
                 "is safe)"))
    return out


# ---------------------------------------------------------------------------
# R4 — sharding readiness
# ---------------------------------------------------------------------------

def rule_sharding(trace: KernelTrace) -> list[Finding]:
    out: list[Finding] = []
    part = trace.partition
    args = {a.name: a for a in trace.args}
    if part:
        for name in part.get("replicated", ()):
            a = args.get(name)
            if a is None or a.nbytes < REPLICATED_MIN_BYTES:
                continue
            out.append(Finding(
                rule="R4", kernel=trace.name,
                site=f"replicated:{name}",
                message=f"arg `{name}` ({a.nbytes / 1024:.0f} KiB) is "
                        "fully replicated across the "
                        f"'{part.get('axis')}' mesh axis (bucket "
                        f"{trace.bucket}): H2D cost and HBM footprint "
                        "scale with device count, work does not",
                file=trace.file, line=trace.line,
                cost_bytes=a.nbytes,
                hint="give it a partition rule (shard the segment/"
                     "row dimension) or slice it per shard"))
    for name, axis, why in trace.batch_axes:
        if part and name in (part.get("sharded") or ()):
            continue
        a = args.get(name)
        out.append(Finding(
            rule="R4", kernel=trace.name,
            site=f"unsharded-axis:{name}.{axis}",
            message=f"batch axis {axis} of `{name}` is embarrassingly "
                    f"parallel ({why}) but has no partition rule "
                    f"(bucket {trace.bucket}): the mesh adds devices "
                    "without adding throughput",
            file=trace.file, line=trace.line,
            cost_bytes=a.nbytes if a else None,
            hint="lay this axis out over the mesh (shard_map/pjit "
                 "with a PartitionSpec on it; SNIPPETS.md [1]-[3])"))
    if trace.hlo_text:
        low = trace.hlo_text.lower()
        for coll in HLO_COLLECTIVES:
            if coll in low:
                out.append(Finding(
                    rule="R4", kernel=trace.name,
                    site=f"collective:{coll}",
                    message=f"lowered HLO contains `{coll}` (bucket "
                            f"{trace.bucket}): an op inside the "
                            "partitioned program forces cross-device "
                            "gathering",
                    file=trace.file, line=trace.line,
                    hint="check the op's partition spec; reformulate "
                         "to keep the batch axis local"))
    return out


# ---------------------------------------------------------------------------
# R5 — recompile risk
# ---------------------------------------------------------------------------

def rule_recompile(trace: KernelTrace) -> list[Finding]:
    out: list[Finding] = []
    consts = [c for c in (getattr(trace.jaxpr, "consts", ()) or ())
              if getattr(c, "nbytes", None) is not None]
    big = [c for c in consts if c.nbytes >= LARGE_CONST_BYTES]
    small = [c for c in consts if c.nbytes < LARGE_CONST_BYTES]
    if small:
        # every captured value bakes into the traced program: a
        # varying capture (config, rng state, a python-built table)
        # is one retrace+recompile per distinct value
        out.append(Finding(
            rule="R5", kernel=trace.name, site="captured-consts",
            message=f"{len(small)} closure-captured array const(s) "
                    "in the jaxpr: each distinct captured value is "
                    "its own traced program (one recompile per "
                    "value if the capture varies)",
            file=trace.file, line=trace.line, severity="info",
            hint="pass varying values as arguments; keep closure "
                 "captures to true constants"))
    if big:
        total = sum(int(c.nbytes) for c in big)
        out.append(Finding(
            rule="R5", kernel=trace.name, site="large-consts",
            message=f"{len(big)} closure-captured array const(s) "
                    f"totalling {total / 1024:.0f} KiB bloat every "
                    "compiled executable of this bucket",
            file=trace.file, line=trace.line, cost_bytes=total,
            hint="pass large tables as arguments so the executable "
                 "is shape-generic"))
    if trace.bucket_policy == "linear":
        out.append(Finding(
            rule="R5", kernel=trace.name, site="bucket-policy",
            message="shape buckets grow linearly (not pow2/"
                    "quantized): bucket cardinality — and compile "
                    "count — is unbounded in input size",
            file=trace.file, line=trace.line,
            hint="quantize the padded shape (next_pow2 or coarse "
                 "fixed steps) so the compile cache saturates"))
    return out


def runtime_bucket_findings(buckets: dict[str, set],
                            max_buckets: int = MAX_RUNTIME_BUCKETS
                            ) -> list[Finding]:
    """R5's runtime cross-check over profiler.shape_buckets(): a
    kernel that compiled more than max_buckets distinct shapes this
    process is leaking shapes through its bucketing policy."""
    out = []
    for kernel, bs in sorted(buckets.items()):
        if len(bs) > max_buckets:
            out.append(Finding(
                rule="R5", kernel=kernel, site="bucket-cardinality",
                message=f"{len(bs)} distinct compiled shape buckets "
                        f"this process (> {max_buckets}): each one "
                        "paid a full XLA compile",
                hint="coarsen the bucket quantization "
                     "(profiler.<k>.bucket_cardinality tracks this "
                     "per run)"))
    return out


# ---------------------------------------------------------------------------
# R6 — while-loop carry bloat
# ---------------------------------------------------------------------------

def rule_carry(trace: KernelTrace) -> list[Finding]:
    out: list[Finding] = []
    if trace.jaxpr is None:
        return out
    n = 0
    for eqn in iter_eqns(trace.jaxpr):
        if eqn.primitive.name != "while":
            continue
        body = eqn.params.get("body_jaxpr")
        avals = list(getattr(body, "out_avals", ()) or ())
        sizes = sorted(((_aval_bytes(a), a) for a in avals),
                       key=lambda t: -t[0])
        total = sum(s for s, _ in sizes)
        site = f"while:{n}"
        n += 1
        if total < CARRY_WARN_BYTES:
            continue
        top = ", ".join(
            f"{str(getattr(a, 'dtype', '?'))}{list(a.shape)}"
            f"={s // 1024}KiB" for s, a in sizes[:3])
        f, ln = eqn_provenance(eqn)
        out.append(Finding(
            rule="R6", kernel=trace.name, site=site,
            message=f"while-loop carry is {total / 1024:.0f} KiB "
                    f"(bucket {trace.bucket}; largest: {top}): every "
                    "carry byte is serialized through every BFS "
                    "level",
            file=f or trace.file, line=ln or trace.line,
            cost_bytes=total,
            hint="move per-level accumulators (telemetry series, "
                 "debug state) out of the carry, or narrow/pack the "
                 "frontier encoding (int8/int16 state packing)"))
    return out


# ---------------------------------------------------------------------------
# Rule suite + baseline ratchet
# ---------------------------------------------------------------------------

TRACE_RULES = (rule_host_sync, rule_dtype_widening, rule_donation,
               rule_sharding, rule_recompile, rule_carry)


def run_rules(trace: KernelTrace) -> list[Finding]:
    """The full R1-R6 suite over one kernel trace."""
    out: list[Finding] = []
    for rule in TRACE_RULES:
        out.extend(rule(trace))
    return out


BASELINE_VERSION = 1


def load_baseline(path) -> dict:
    """The committed baseline document ({"version", "findings"});
    an empty skeleton when the file doesn't exist."""
    p = Path(path)
    if not p.exists():
        return {"version": BASELINE_VERSION, "findings": []}
    with open(p) as f:
        doc = json.load(f)
    if not isinstance(doc, dict) or \
            not isinstance(doc.get("findings"), list):
        raise ValueError(f"{path}: not a lint baseline document")
    return doc


def baseline_doc(findings: list[Finding]) -> dict:
    """A baseline document pinning `findings` (sorted by key so the
    committed file diffs cleanly)."""
    return {
        "version": BASELINE_VERSION,
        "findings": sorted(
            ({"key": f.key, "rule": f.rule, "kernel": f.kernel,
              "site": f.site, "message": f.message}
             for f in findings), key=lambda d: d["key"]),
    }


def write_baseline(path, findings: list[Finding]) -> dict:
    doc = baseline_doc(findings)
    with open(path, "w") as f:
        json.dump(doc, f, indent=1)
        f.write("\n")
    return doc


def ratchet(findings: list[Finding], baseline: dict) -> dict:
    """The baseline comparison: {'new': [Finding], 'baselined':
    [Finding], 'stale': [keys]}. NEW findings fail the gate; STALE
    baseline entries (fixed findings) are prune candidates —
    `--update` rewrites the file without them, so the ratchet only
    ever tightens."""
    known = {e["key"] for e in baseline.get("findings", ())}
    have = {f.key for f in findings}
    return {
        "new": [f for f in findings if f.key not in known],
        "baselined": [f for f in findings if f.key in known],
        "stale": sorted(known - have),
    }
