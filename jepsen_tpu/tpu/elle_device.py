"""Device-path list-append analysis: interned int arrays + vectorized
edge inference + batched SCC.

Capability reference: elle 0.2.1 behind
jepsen/src/jepsen/tests/cycle/append.clj:6-27 — infer ww/wr/rw
dependency edges from txn external reads/writes, search for cycles,
classify anomalies. The host engine (jepsen_tpu.tpu.elle) is the
correctness reference; this module re-derives the same anomalies with:

  1. one flattening pass turning txn micro-ops into dense int arrays
     (txn ids, interned keys, (key, value) pair ids);
  2. numpy segment ops for writer resolution, version orders (spines),
     read anomalies (G1a/G1b/internal/unobservable/incompatible), and
     ww/wr/rw edge inference — no per-element Python;
  3. cycle detection through the batched label-propagation SCC kernel
     (jepsen_tpu.tpu.scc) on device, host scipy on fallback;
  4. host-side cycle witness extraction and classification (shared
     with the host engine).

Histories whose append values aren't machine ints (or whose key/value
ranges overflow the pair packing) raise Unvectorizable and the caller
drops to the host engine, so the fast path never changes results.
"""

from __future__ import annotations

from collections import defaultdict

import numpy as np

from .. import history as h
from .. import telemetry
from ..history import History
from . import profiler
from . import scc as scc_mod
from .elle import (EDGE_NAMES, PROC, RT, RW, WR, WW, Txn, _classify,
                   _find_cycle, collect, order_edges_from_arrays)

_TYPE_OK, _TYPE_INFO, _TYPE_FAIL = 0, 1, 2
_T_CODE = {h.OK: _TYPE_OK, h.INFO: _TYPE_INFO, h.FAIL: _TYPE_FAIL}

_KEY_BITS = 23
_VAL_BITS = 40


class Unvectorizable(Exception):
    """History can't take the int-array fast path."""


def _dense_first_seen(xs: np.ndarray) -> np.ndarray:
    """Raw ids -> dense codes in FIRST-SEEN order, matching the
    Python flattener's process interning dict."""
    if not len(xs):
        return xs
    _u, first, inv = np.unique(xs, return_index=True,
                               return_inverse=True)
    order = np.argsort(first, kind="stable")
    rank = np.empty(len(order), dtype=np.int64)
    rank[order] = np.arange(len(order))
    return rank[inv]


def _txn_mops(ops: list, arrs: dict, ti: int):
    """A txn's effective micro-ops, mirroring collect(): the completion
    value for committed txns (unless None), else the invocation's."""
    op = ops[int(arrs["t_opidx"][ti])]
    if int(arrs["t_type"][ti]) == _TYPE_OK and op.value is not None:
        return op.value
    return ops[int(arrs["t_inv"][ti])].value or []


def _internal_from_flags(ops: list, arrs: dict) -> list[tuple]:
    """Replays the own-append suffix rule for the (rare) reads the C
    flattener flagged: a committed read of a key the same txn appended
    to earlier must end with the txn's own appends, in order."""
    out: list[tuple] = []
    flags = arrs["flag_rd"]
    if not len(flags):
        return out
    for ti in np.unique(arrs["rd_txn"][flags]):
        op = ops[int(arrs["t_opidx"][ti])]
        own: dict = {}
        for mop in _txn_mops(ops, arrs, int(ti)):
            f, k, v = mop[0], mop[1], mop[2]
            if f == "append":
                own.setdefault(k, []).append(v)
            elif f == "r" and v is not None:
                vs = list(v)
                pre = own.get(k)
                if pre and vs[-len(pre):] != pre:
                    out.append((int(ti), k, {
                        "key": k, "expected-suffix": list(pre),
                        "read": vs, "op": op}))
    return out


class Flat:
    """Dense-array view of a list-append history. Two constructors:
    the Python loop over collected Txn objects (reference semantics),
    and from_native() over the C flattener's arrays (native/elleflat.c,
    one C pass over the raw op list — the fast path; differential
    tests pin the two to identical arrays)."""

    @classmethod
    def from_native(cls, ops: list, arrs: dict, keys: list) -> "Flat":
        self = cls.__new__(cls)
        self.n = len(arrs["t_type"])
        self.t_type = arrs["t_type"].astype(np.int8)
        self.t_inv = arrs["t_inv"]
        self.t_comp = arrs["t_comp"]
        self.t_proc = _dense_first_seen(arrs["t_proc"])
        self.t_opidx = arrs["t_opidx"]
        self.key_names = keys
        for f in ("ap_txn", "ap_key", "ap_val", "rd_txn", "rd_key",
                  "rd_len", "re_vals"):
            setattr(self, f, arrs[f])
        self.rd_off = np.concatenate(
            [[0], np.cumsum(self.rd_len)])[:-1].astype(np.int64)
        self.re_read = np.repeat(np.arange(len(self.rd_txn)),
                                 self.rd_len)
        # The C pass flags reads whose txn appended the same key
        # earlier; only those few txns replay the own-suffix rule here.
        self.internal_bad = _internal_from_flags(ops, arrs)
        return self

    def __init__(self, txns: list[Txn]):
        self.txns = txns
        n = len(txns)
        self.n = n
        self.t_type = np.fromiter((_T_CODE[t.type] for t in txns),
                                  dtype=np.int8, count=n)
        self.t_inv = np.fromiter((t.invoke_pos for t in txns),
                                 dtype=np.int64, count=n)
        self.t_comp = np.fromiter((t.complete_pos for t in txns),
                                  dtype=np.int64, count=n)
        proc_ids: dict = {}
        self.t_proc = np.fromiter(
            (proc_ids.setdefault(t.process, len(proc_ids))
             for t in txns), dtype=np.int64, count=n)

        key_ids: dict = {}
        ap_txn: list[int] = []
        ap_key: list[int] = []
        ap_val: list[int] = []
        rd_txn: list[int] = []
        rd_key: list[int] = []
        rd_len: list[int] = []
        re_vals: list[int] = []
        internal_bad: list[tuple] = []  # (txn_i, key_id, record)

        for t in txns:
            own: dict = {}
            consider_reads = t.type == h.OK
            for mop in t.mops:
                f, k, v = mop[0], mop[1], mop[2]
                kid = key_ids.get(k)
                if kid is None:
                    kid = key_ids[k] = len(key_ids)
                if f == "append":
                    if type(v) is not int or not (0 <= v < (1 << _VAL_BITS)):
                        raise Unvectorizable(f"append value {v!r}")
                    ap_txn.append(t.i)
                    ap_key.append(kid)
                    ap_val.append(v)
                    own.setdefault(kid, []).append(v)
                elif f == "r":
                    if v is None or not consider_reads:
                        continue
                    vs = list(v)
                    for x in vs:
                        if type(x) is not int or not (
                                0 <= x < (1 << _VAL_BITS)):
                            raise Unvectorizable(f"read value {x!r}")
                    rd_txn.append(t.i)
                    rd_key.append(kid)
                    rd_len.append(len(vs))
                    re_vals.extend(vs)
                    pre = own.get(kid)
                    if pre and vs[-len(pre):] != pre:
                        internal_bad.append((t.i, kid, {
                            "key": k, "expected-suffix": list(pre),
                            "read": vs, "op": t.op}))
        if len(key_ids) >= (1 << _KEY_BITS):
            raise Unvectorizable("too many keys for pair packing")

        self.key_names = list(key_ids)
        self.ap_txn = np.asarray(ap_txn, dtype=np.int64)
        self.ap_key = np.asarray(ap_key, dtype=np.int64)
        self.ap_val = np.asarray(ap_val, dtype=np.int64)
        self.rd_txn = np.asarray(rd_txn, dtype=np.int64)
        self.rd_key = np.asarray(rd_key, dtype=np.int64)
        self.rd_len = np.asarray(rd_len, dtype=np.int64)
        self.re_vals = np.asarray(re_vals, dtype=np.int64)
        self.rd_off = np.concatenate(
            [[0], np.cumsum(self.rd_len)])[:-1]
        self.re_read = np.repeat(np.arange(len(rd_txn)), self.rd_len)
        self.internal_bad = internal_bad


def _pack(keys: np.ndarray, vals: np.ndarray) -> np.ndarray:
    return (keys << _VAL_BITS) | vals


class DeviceAppendAnalysis:
    """Mirrors elle.AppendAnalysis over Flat arrays. Flattening runs
    through the C pass (native.elle_flatten) when available; txn/op
    objects materialize lazily, only for anomaly witnesses."""

    _KIND = 0
    _FLAT_CLS = Flat

    def __init__(self, hist: History, device: bool = True):
        self.device = device
        self._ops = list(hist)
        self.txns: list[Txn] | None = None
        self.flat = self._flatten(hist)
        self.anomalies: dict[str, list] = defaultdict(list)
        self._resolve_writers()
        self._spines()
        self._read_anomalies()
        (self.edge_src, self.edge_dst, self.edge_ty,
         self.edge_key) = self._edges()

    def _flatten(self, hist: History):
        from .. import native

        try:
            arrs, keys = native.elle_flatten(self._ops, self._KIND)
            telemetry.count("elle.flatten.native")
            return self._FLAT_CLS.from_native(self._ops, arrs, keys)
        except native.NotVectorizable as e:
            raise Unvectorizable(str(e)) from e
        except RuntimeError:
            telemetry.count("elle.flatten.python")
            self.txns = collect(hist)
            return self._FLAT_CLS(self.txns)

    def _op(self, ti: int):
        """The witness op for txn row ti (lazy: no Txn objects on the
        native path)."""
        if self.txns is not None:
            return self.txns[int(ti)].op
        return self._ops[int(self.flat.t_opidx[int(ti)])]

    @property
    def n(self) -> int:
        return self.flat.n

    # -- writers -----------------------------------------------------------

    def _resolve_writers(self):
        f = self.flat
        A = len(f.ap_txn)
        ap_code = _pack(f.ap_key, f.ap_val)
        re_code = (_pack(f.rd_key[f.re_read], f.re_vals)
                   if len(f.re_vals) else np.empty(0, dtype=np.int64))
        # dense pair ids over appends AND read elements, so value-based
        # lookups (spine successors) work even for values no append
        # wrote (the host engine keys its nxt dict by raw value)
        codes = np.unique(np.concatenate([ap_code, re_code]))
        self.pair_codes = codes            # sorted unique codes [P]
        P = len(codes)
        inv = np.searchsorted(codes, ap_code)
        self.ap_pid = inv                  # pid per append
        order = np.arange(A)
        nonfail = f.t_type[f.ap_txn] != _TYPE_FAIL
        # writer append-row per pid: last non-fail, else first append;
        # pids nothing appended keep w_txn == -1
        last_nf = np.full(P, -1, dtype=np.int64)
        if A:
            np.maximum.at(last_nf, inv[nonfail], order[nonfail])
        first_any = np.full(P, -1, dtype=np.int64)
        if A:
            has = np.zeros(P, dtype=bool)
            has[inv] = True
            first_of = np.full(P, A, dtype=np.int64)
            np.minimum.at(first_of, inv, order)
            first_any[has] = first_of[has]
        w_row = np.where(last_nf >= 0, last_nf, first_any)
        self.w_txn = np.where(w_row >= 0, f.ap_txn[np.clip(w_row, 0, None)]
                              if A else -1, -1)            # [P]
        self.w_fail = np.where(
            self.w_txn >= 0,
            f.t_type[np.clip(self.w_txn, 0, None)] == _TYPE_FAIL,
            False)                                         # [P]
        # j (index among txn's appends to key) and tot, per append row
        grp = np.lexsort((order, f.ap_key, f.ap_txn))
        gk = np.stack([f.ap_txn[grp], f.ap_key[grp]], axis=1)
        new_grp = np.ones(A, dtype=bool)
        if A > 1:
            new_grp[1:] = (gk[1:] != gk[:-1]).any(axis=1)
        grp_id = np.cumsum(new_grp) - 1
        starts = np.flatnonzero(new_grp)
        j_sorted = np.arange(A) - starts[grp_id]
        counts = np.bincount(grp_id, minlength=starts.size)
        tot_sorted = counts[grp_id]
        j = np.empty(A, dtype=np.int64)
        tot = np.empty(A, dtype=np.int64)
        j[grp] = j_sorted
        tot[grp] = tot_sorted
        self.w_j = np.where(w_row >= 0,
                            j[np.clip(w_row, 0, None)] if A else -1, -1)
        self.w_tot = np.where(w_row >= 0,
                              tot[np.clip(w_row, 0, None)] if A else -1,
                              -1)
        # duplicate-appends: non-fail appends beyond the first non-fail
        # of their pid (mirrors the host writer-overwrite rule)
        if A:
            sub = np.flatnonzero(nonfail)
            if sub.size:
                srt = sub[np.argsort(inv[sub], kind="stable")]
                pid_s = inv[srt]
                first_of = np.ones(srt.size, dtype=bool)
                first_of[1:] = pid_s[1:] != pid_s[:-1]
                for row in srt[~first_of]:
                    self.anomalies["duplicate-appends"].append({
                        "key": f.key_names[f.ap_key[row]],
                        "value": int(f.ap_val[row]),
                        "op": self._op(f.ap_txn[row])})
        # possibly-committed writer txns per key (for empty-read rw)
        nf_k = f.ap_key[nonfail]
        nf_t = f.ap_txn[nonfail]
        kt = np.unique(np.stack([nf_k, nf_t], axis=1), axis=0) \
            if nf_k.size else np.empty((0, 2), dtype=np.int64)
        self.wk_key, self.wk_txn = kt[:, 0], kt[:, 1]

    def _pid_of(self, keys, vals) -> np.ndarray:
        """pid per (key, val); -1 only for pairs seen neither in an
        append nor in any read (writerless pairs have a pid with
        w_txn[pid] == -1)."""
        codes = _pack(np.asarray(keys, dtype=np.int64),
                      np.asarray(vals, dtype=np.int64))
        if len(self.pair_codes) == 0:
            return np.full(len(codes), -1, dtype=np.int64)
        pos = np.searchsorted(self.pair_codes, codes)
        pos = np.clip(pos, 0, len(self.pair_codes) - 1)
        return np.where(self.pair_codes[pos] == codes, pos, -1)

    # -- version orders ----------------------------------------------------

    def _spines(self):
        f = self.flat
        R = len(f.rd_txn)
        K = len(f.key_names)
        # spine read per key: longest, earliest on ties (host tie-break)
        self.spine_read = np.full(K, -1, dtype=np.int64)
        self.spine_len = np.zeros(K, dtype=np.int64)
        if R:
            order = np.lexsort((np.arange(R), -f.rd_len, f.rd_key))
            first = np.ones(R, dtype=bool)
            kk = f.rd_key[order]
            first[1:] = kk[1:] != kk[:-1]
            sel = order[first]
            keep = f.rd_len[sel] > 0
            self.spine_read[kk[first][keep]] = sel[keep]
            self.spine_len[kk[first][keep]] = f.rd_len[sel][keep]
        # flat spine arrays
        srd = self.spine_read[self.spine_read >= 0]
        skey = np.flatnonzero(self.spine_read >= 0)
        self.sp_key_of = skey
        lens = f.rd_len[srd] if srd.size else np.empty(0, dtype=np.int64)
        self.sp_off = np.zeros(K, dtype=np.int64)
        off = np.concatenate([[0], np.cumsum(lens)])[:-1] \
            if srd.size else np.empty(0, dtype=np.int64)
        self.sp_off[skey] = off
        # gather spine element values
        idx = []
        for r in srd:
            idx.append(np.arange(f.rd_off[r], f.rd_off[r] + f.rd_len[r]))
        self.sp_vals = (f.re_vals[np.concatenate(idx)] if idx
                        else np.empty(0, dtype=np.int64))
        self.sp_keys = np.repeat(skey, lens) if srd.size else \
            np.empty(0, dtype=np.int64)
        self.sp_pid = self._pid_of(self.sp_keys, self.sp_vals)
        # successor pid along each spine
        P = len(self.pair_codes)
        self.pair_nxt = np.full(P, -1, dtype=np.int64)
        if len(self.sp_pid) > 1:
            same = self.sp_keys[1:] == self.sp_keys[:-1]
            a = self.sp_pid[:-1][same]
            b = self.sp_pid[1:][same]
            good = a >= 0
            self.pair_nxt[a[good]] = b[good]
        # incompatible-order: each read must be a prefix of its spine
        if R:
            too_long = f.rd_len > self.spine_len[f.rd_key]
            elem_pos = np.arange(len(f.re_vals)) - f.rd_off[f.re_read]
            sp_at = self.sp_off[f.rd_key[f.re_read]] + elem_pos
            in_range = elem_pos < self.spine_len[f.rd_key[f.re_read]]
            if len(self.sp_vals):
                sp_val = np.where(in_range, self.sp_vals[
                    np.clip(sp_at, 0, len(self.sp_vals) - 1)], -1)
            else:
                sp_val = np.full(len(f.re_vals), -1, dtype=np.int64)
            mismatch = np.where(in_range, sp_val != f.re_vals, True)
            bad = too_long.copy()
            np.logical_or.at(bad, f.re_read, mismatch)
            for r in np.flatnonzero(bad):
                o, n_ = int(f.rd_off[r]), int(f.rd_len[r])
                k = int(f.rd_key[r])
                so, sl = int(self.sp_off[k]), int(self.spine_len[k])
                self.anomalies["incompatible-order"].append({
                    "key": f.key_names[k],
                    "read": f.re_vals[o:o + n_].tolist(),
                    "spine": self.sp_vals[so:so + sl].tolist(),
                    "op": self._op(f.rd_txn[r])})

    # -- read anomalies ----------------------------------------------------

    def _read_anomalies(self):
        f = self.flat
        re_pid = self._pid_of(f.rd_key[f.re_read], f.re_vals)
        self.re_pid = re_pid
        # every read element has a pid now; writerless pairs carry -1
        re_w = np.where(re_pid >= 0,
                        self.w_txn[np.clip(re_pid, 0, None)]
                        if len(self.w_txn) else -1, -1)
        unobs = re_w < 0
        for i in np.flatnonzero(unobs):
            r = f.re_read[i]
            self.anomalies["unobservable-read"].append({
                "key": f.key_names[f.rd_key[r]],
                "value": int(f.re_vals[i]), "op": self._op(f.rd_txn[r])})
        aborted = np.zeros(len(re_pid), dtype=bool)
        if len(self.w_txn):
            aborted[~unobs] = self.w_fail[re_pid[~unobs]]
        for i in np.flatnonzero(aborted):
            r = f.re_read[i]
            self.anomalies["G1a"].append({
                "key": f.key_names[f.rd_key[r]],
                "value": int(f.re_vals[i]), "op": self._op(f.rd_txn[r]),
                "writer": self._op(self.w_txn[re_pid[i]])})
        # G1b: last element is an intermediate version of another txn
        nz = np.flatnonzero(f.rd_len > 0)
        last_idx = f.rd_off[nz] + f.rd_len[nz] - 1
        last_pid = re_pid[last_idx]
        self.nz_reads = nz
        self.last_pid = last_pid
        if not len(self.w_txn):
            for _ti, _kid, rec in f.internal_bad:
                self.anomalies["internal"].append(rec)
            return
        wi = np.clip(last_pid, 0, None)
        has_w = (last_pid >= 0) & (self.w_txn[wi] >= 0)
        g1b = has_w & (self.w_j[wi] != self.w_tot[wi] - 1) & \
            (self.w_txn[wi] != f.rd_txn[nz])
        for i in np.flatnonzero(g1b):
            r = nz[i]
            o = int(f.rd_off[r] + f.rd_len[r] - 1)
            self.anomalies["G1b"].append({
                "key": f.key_names[f.rd_key[r]],
                "value": int(f.re_vals[o]), "op": self._op(f.rd_txn[r]),
                "writer": self._op(self.w_txn[last_pid[i]])})
        for _ti, _kid, rec in f.internal_bad:
            self.anomalies["internal"].append(rec)

    # -- edges -------------------------------------------------------------

    def _edges(self):
        f = self.flat
        srcs: list[np.ndarray] = []
        dsts: list[np.ndarray] = []
        tys: list[np.ndarray] = []
        kks: list[np.ndarray] = []

        def emit(s, d, ty, kk=None):
            # kk: the key each dependency edge belongs to (-1 for
            # cross-key session/realtime order) — the sharded SCC
            # kernel's key-block layout hint (tpu/scc.py)
            s = np.asarray(s, dtype=np.int64)
            if s.size:
                srcs.append(s)
                dsts.append(np.asarray(d, dtype=np.int64))
                tys.append(np.full(s.size, ty, dtype=np.int64))
                kks.append(np.full(s.size, -1, dtype=np.int64)
                           if kk is None
                           else np.asarray(kk, dtype=np.int64))

        # ww: consecutive distinct valid writers along each spine
        if len(self.w_txn):
            spw = np.where(self.sp_pid >= 0,
                           self.w_txn[np.clip(self.sp_pid, 0, None)], -1)
            valid = (spw >= 0) & ~self.w_fail[
                np.clip(self.sp_pid, 0, None)]
        else:
            spw = np.empty(0, dtype=np.int64)
            valid = np.zeros(len(self.sp_pid), dtype=bool)
        vk = self.sp_keys[valid]
        vt = spw[valid]
        if vt.size > 1:
            same = vk[1:] == vk[:-1]
            diff = vt[1:] != vt[:-1]
            emit(vt[:-1][same & diff], vt[1:][same & diff], WW,
                 vk[1:][same & diff])
        # wr and rw from each non-empty read's last element
        nz, last_pid = self.nz_reads, self.last_pid
        reader = f.rd_txn[nz]
        rkey = f.rd_key[nz]
        if len(self.w_txn):
            wi = np.clip(last_pid, 0, None)
            has_w = (last_pid >= 0) & (self.w_txn[wi] >= 0)
            wr_ok = has_w & (self.w_txn[wi] != reader) & ~self.w_fail[wi]
            emit(self.w_txn[wi[wr_ok]], reader[wr_ok], WR, rkey[wr_ok])
            # nxt is value-based (host keys its dict by raw value), so
            # the anti-dependency fires even when the read's last
            # element itself has no writer (unobservable value)
            nxt = np.where(last_pid >= 0, self.pair_nxt[wi], -1)
            has_n = nxt >= 0
            ni = np.where(has_n, nxt, 0)
            rw_ok = has_n & (self.w_txn[ni] >= 0) & \
                (self.w_txn[ni] != reader) & ~self.w_fail[ni]
            emit(reader[rw_ok], self.w_txn[ni[rw_ok]], RW, rkey[rw_ok])
        # empty reads: rw to first spine writer + off-spine writers
        ez = np.flatnonzero(f.rd_len == 0)
        if ez.size:
            K = len(f.key_names)
            # first valid spine writer per key
            first_w = np.full(K, -1, dtype=np.int64)
            if vt.size:
                rev_k = vk[::-1]
                rev_t = vt[::-1]
                first_w[rev_k] = rev_t  # earliest wins (reverse order)
            # spine writer txn set per (key, txn)
            if vt.size:
                sp_kt = np.unique(np.stack([vk, vt], axis=1), axis=0)
                sp_kt_code = sp_kt[:, 0] * (self.flat.n + 1) + sp_kt[:, 1]
            else:
                sp_kt_code = np.empty(0, dtype=np.int64)
            wk_code = self.wk_key * (self.flat.n + 1) + self.wk_txn
            off_spine = ~np.isin(wk_code, sp_kt_code)
            tk_key = np.concatenate([
                self.wk_key[off_spine],
                np.flatnonzero(first_w >= 0)])
            tk_txn = np.concatenate([
                self.wk_txn[off_spine], first_w[first_w >= 0]])
            t_order = np.argsort(tk_key, kind="stable")
            tk_key, tk_txn = tk_key[t_order], tk_txn[t_order]
            cnt = np.bincount(tk_key, minlength=K)
            off = np.concatenate([[0], np.cumsum(cnt)])[:-1]
            ek = f.rd_key[ez]
            reps = cnt[ek]
            er_src = np.repeat(f.rd_txn[ez], reps)
            base = np.repeat(off[ek], reps)
            step = np.arange(reps.sum()) - np.repeat(
                np.concatenate([[0], np.cumsum(reps)])[:-1], reps)
            er_dst = tk_txn[base + step]
            keep = er_src != er_dst
            emit(er_src[keep], er_dst[keep], RW,
                 np.repeat(ek, reps)[keep])
        # session order + realtime: the host engine's sweep, shared
        comm = np.flatnonzero(self.flat.t_type == _TYPE_OK)
        if comm.size:
            fl = self.flat
            o_src, o_dst, o_ty = order_edges_from_arrays(
                comm, fl.t_inv[comm], fl.t_comp[comm], fl.t_proc[comm])
            if o_src.size:
                srcs.append(o_src)
                dsts.append(o_dst)
                tys.append(o_ty)
                kks.append(np.full(o_src.size, -1, dtype=np.int64))
        if not srcs:
            e = np.empty(0, dtype=np.int64)
            return e, e, e, e
        src = np.concatenate(srcs)
        dst = np.concatenate(dsts)
        ty = np.concatenate(tys)
        kk = np.concatenate(kks)
        code = (src * (self.flat.n + 1) + dst) * 8 + ty
        _, keep = np.unique(code, return_index=True)
        keep.sort()
        return src[keep], dst[keep], ty[keep], kk[keep]


_SUBSETS = ((WW,), (WW, WR), (WW, WR, RW), (WW, WR, RW, PROC),
            (WW, WR, RW, PROC, RT))


def cycle_anomalies_arrays(n: int, src, dst, ty, txns,
                           device: bool = True,
                           ekey=None) -> dict[str, list]:
    """elle.cycle_anomalies over edge arrays: SCCs per cumulative edge
    subset via the device kernel, witnesses extracted host-side. txns
    is either a Txn list or a callable ti -> witness op (the lazy
    accessor of the native flattening path). ekey: per-edge key ids
    for the sharded SCC kernel's key-block edge layout."""
    op_of = txns if callable(txns) else (lambda i: txns[i].op)
    out: dict[str, list] = defaultdict(list)
    if not len(src):
        return out
    from . import spmd

    if (ekey is not None and len(ekey) == len(src) and device
            and spmd.spmd_devices() > 1
            and len(src) >= scc_mod.DEVICE_MIN_EDGES):
        # order edges into key blocks ONCE — up to six SCC launches
        # below share the same edge array (only the subset mask
        # differs), and scc_device skips its own argsort when the
        # array is already key-sorted. Gated on the same conditions as
        # scc_device's sharded path: anywhere else the layout is never
        # consumed and the sort+copies would be pure overhead.
        order = np.argsort(np.asarray(ekey), kind="stable")
        src = np.asarray(src)[order]
        dst = np.asarray(dst)[order]
        ty = np.asarray(ty)[order]
        ekey = np.asarray(ekey)[order]
    # Early exit: subset edges are subsets of the full graph, so a
    # clean full graph proves every graded subset clean too — valid
    # histories cost ONE device SCC instead of five.
    full = scc_mod.scc(n, src, dst, device=device, ekey=ekey)
    if not scc_mod.nontrivial_from_labels(full):
        return out
    seen: set = set()
    for sub in _SUBSETS:
        # boolean mask over ONE shared edge array: every subset reuses
        # the same compiled kernel shape bucket. The final subset is
        # the full graph, already solved above.
        mask = np.isin(ty, sub)
        if not mask.any():
            continue
        if sub == _SUBSETS[-1]:
            groups = scc_mod.nontrivial_from_labels(full)
        else:
            groups = scc_mod.nontrivial_sccs(n, src, dst, emask=mask,
                                             device=device, ekey=ekey)
        for members in groups:
            key = frozenset(int(x) for x in members)
            if key in seen:
                continue
            seen.add(key)
            em = mask & np.isin(src, members) & np.isin(dst, members)
            edges = [(int(a), int(b), int(c))
                     for a, b, c in zip(src[em], dst[em], ty[em])]
            cycle = _find_cycle(sorted(int(x) for x in members), edges)
            if not cycle:
                continue
            name = _classify(cycle)
            out[name].append({
                "cycle": [op_of(a) for a, _b, _c in cycle],
                "steps": [{"from": a, "to": b, "type": EDGE_NAMES[c]}
                          for a, b, c in cycle]})
    return out


def check_list_append_device(hist, device: bool = True) -> dict:
    """Drop-in device-path analog of elle.check_list_append. Raises
    Unvectorizable when the history can't be interned."""
    if not isinstance(hist, History):
        hist = History(hist)
    prof = profiler.get()
    rec = prof.begin("elle-append")
    with telemetry.span("elle:list-append") as sp:
        with prof.phase(rec, "encode_ns"):
            # host side: flatten + edge inference (the SCC launches
            # inside cycle_anomalies_arrays profile themselves)
            a = DeviceAppendAnalysis(hist, device=device)
        if sp is not None:
            sp["attrs"] = {"txns": a.flat.n,
                           "edges": int(len(a.edge_src))}
    telemetry.count("elle.txns", a.flat.n)
    telemetry.count("elle.edges", int(len(a.edge_src)))
    rec.update(txns=a.flat.n, edges=int(len(a.edge_src)))
    anomalies = dict(a.anomalies)
    with prof.phase(rec, "compute_ns"):
        for name, ws in cycle_anomalies_arrays(
                a.flat.n, a.edge_src, a.edge_dst, a.edge_ty, a._op,
                device=device, ekey=a.edge_key).items():
            anomalies[name] = ws
    prof.finish(rec)
    return {
        "valid?": not anomalies,
        "anomaly-types": sorted(anomalies.keys()),
        "anomalies": {k: v[:8] for k, v in anomalies.items()},
        "edge-count": int(len(a.edge_src)),
        "txn-count": a.flat.n,
    }


# ---------------------------------------------------------------------------
# rw-register device path
# ---------------------------------------------------------------------------

class RwFlat:
    """Dense-array view of a write/read-register history (the
    rw-register analog of Flat). One Python pass collects writes
    (all txn types — they all claim writer slots), committed reads,
    write-follows-read pairs, external reads, and the per-txn internal
    anomalies; everything downstream is numpy over packed (key, value)
    codes."""

    @classmethod
    def from_native(cls, ops: list, arrs: dict, keys: list) -> "RwFlat":
        self = cls.__new__(cls)
        self.n = len(arrs["t_type"])
        self.t_type = arrs["t_type"].astype(np.int8)
        self.t_inv = arrs["t_inv"]
        self.t_comp = arrs["t_comp"]
        self.t_proc = _dense_first_seen(arrs["t_proc"])
        self.t_opidx = arrs["t_opidx"]
        self.key_names = keys
        for f in ("wr_txn", "wr_key", "wr_val", "wr_nonfinal",
                  "rd_txn", "rd_key", "rd_val",
                  "fr_txn", "fr_key", "fr_prev", "fr_new",
                  "er_txn", "er_key", "er_val"):
            setattr(self, f, arrs[f])
        # internal anomalies: the C pass records (read row, expected)
        self.internal_bad = [
            {"key": keys[int(self.rd_key[r])],
             "expected": int(e), "read": int(self.rd_val[r]),
             "op": ops[int(arrs["t_opidx"][self.rd_txn[r]])]}
            for r, e in zip(arrs["int_row"], arrs["int_expected"])]
        return self

    def __init__(self, txns: list[Txn]):
        self.txns = txns
        n = len(txns)
        self.t_type = np.fromiter((_T_CODE[t.type] for t in txns),
                                  dtype=np.int8, count=n)
        self.t_inv = np.fromiter((t.invoke_pos for t in txns),
                                 dtype=np.int64, count=n)
        self.t_comp = np.fromiter((t.complete_pos for t in txns),
                                  dtype=np.int64, count=n)
        proc_ids: dict = {}
        self.t_proc = np.fromiter(
            (proc_ids.setdefault(t.process, len(proc_ids))
             for t in txns), dtype=np.int64, count=n)
        key_ids: dict = {}
        wr_txn: list[int] = []
        wr_key: list[int] = []
        wr_val: list[int] = []
        wr_nonfinal: list[int] = []  # row indices of non-final writes
        rd_txn: list[int] = []
        rd_key: list[int] = []
        rd_val: list[int] = []
        fr_txn: list[int] = []       # write-follows-read rows
        fr_key: list[int] = []
        fr_prev: list[int] = []
        fr_new: list[int] = []
        er_txn: list[int] = []       # external reads
        er_key: list[int] = []
        er_val: list[int] = []
        internal_bad: list[dict] = []

        def check_val(v):
            if type(v) is not int or not (0 <= v < (1 << _VAL_BITS)):
                raise Unvectorizable(f"register value {v!r}")

        for t in txns:
            ok = t.type == h.OK
            nonfail = t.type != h.FAIL
            expected: dict = {}
            last_read: dict = {}
            written: set = set()
            er_seen: set = set()
            per_key_rows: dict = {}
            for mop in t.mops:
                f, k, v = mop[0], mop[1], mop[2]
                kid = key_ids.get(k)
                if kid is None:
                    kid = key_ids[k] = len(key_ids)
                if f == "w":
                    check_val(v)
                    row = len(wr_txn)
                    wr_txn.append(t.i)
                    wr_key.append(kid)
                    wr_val.append(v)
                    if nonfail:
                        per_key_rows.setdefault(kid, []).append(row)
                    if ok:
                        pv = last_read.pop(kid, None)
                        if pv is not None:
                            fr_txn.append(t.i)
                            fr_key.append(kid)
                            fr_prev.append(pv)
                            fr_new.append(v)
                        expected[kid] = v
                    written.add(kid)
                elif f == "r" and ok:
                    if v is None:
                        # A None first read IS the key's external read
                        # (txnlib.ext_reads records it; the host rw
                        # pass then skips the key) — a later valued
                        # read must NOT be promoted to external
                        if kid not in written:
                            er_seen.add(kid)
                        continue
                    check_val(v)
                    rd_txn.append(t.i)
                    rd_key.append(kid)
                    rd_val.append(v)
                    if kid in expected and expected[kid] != v:
                        internal_bad.append(
                            {"key": k, "expected": expected[kid],
                             "read": v, "op": t.op})
                    expected[kid] = v
                    last_read[kid] = v
                    if kid not in written and kid not in er_seen:
                        er_seen.add(kid)
                        er_txn.append(t.i)
                        er_key.append(kid)
                        er_val.append(v)
            # non-final writes per key (txn.clj: intermediates)
            for rows in per_key_rows.values():
                wr_nonfinal.extend(rows[:-1])
        if len(key_ids) >= (1 << _KEY_BITS):
            raise Unvectorizable("too many keys for pair packing")
        self.key_names = list(key_ids)
        self.wr_txn = np.asarray(wr_txn, dtype=np.int64)
        self.wr_key = np.asarray(wr_key, dtype=np.int64)
        self.wr_val = np.asarray(wr_val, dtype=np.int64)
        self.wr_nonfinal = np.asarray(wr_nonfinal, dtype=np.int64)
        self.rd_txn = np.asarray(rd_txn, dtype=np.int64)
        self.rd_key = np.asarray(rd_key, dtype=np.int64)
        self.rd_val = np.asarray(rd_val, dtype=np.int64)
        self.fr_txn = np.asarray(fr_txn, dtype=np.int64)
        self.fr_key = np.asarray(fr_key, dtype=np.int64)
        self.fr_prev = np.asarray(fr_prev, dtype=np.int64)
        self.fr_new = np.asarray(fr_new, dtype=np.int64)
        self.er_txn = np.asarray(er_txn, dtype=np.int64)
        self.er_key = np.asarray(er_key, dtype=np.int64)
        self.er_val = np.asarray(er_val, dtype=np.int64)
        self.internal_bad = internal_bad
        self.n = n


class DeviceRwAnalysis:
    """Vectorized analog of elle.check_rw_register's per-txn dict
    passes: writer resolution, duplicate/aborted/intermediate read
    anomalies, and wr/ww/rw edge inference as packed-array lookups.
    Witness payloads for flagged rows are extracted host-side, capped
    at the same 8 the result slice keeps."""

    CAP = 8

    _KIND = 1
    _FLAT_CLS = RwFlat

    def __init__(self, hist: History, device: bool = True):
        self.device = device
        self._ops = list(hist)
        self.txns: list[Txn] | None = None
        f = self.flat = self._flatten(hist)
        self.anomalies: dict[str, list] = defaultdict(list)
        for rec in f.internal_bad:
            self.anomalies["internal"].append(rec)
        self._resolve_writers()
        self._read_anomalies_and_edges()

    _flatten = DeviceAppendAnalysis._flatten
    _op = DeviceAppendAnalysis._op

    def _resolve_writers(self):
        f = self.flat
        W = len(f.wr_txn)
        codes = np.unique(_pack(f.wr_key, f.wr_val)) if W else \
            np.empty(0, dtype=np.int64)
        self.pair_codes = codes
        P = len(codes)
        inv = (np.searchsorted(codes, _pack(f.wr_key, f.wr_val))
               if W else np.empty(0, dtype=np.int64))
        order = np.arange(W)
        nonfail = f.t_type[f.wr_txn] != _TYPE_FAIL if W else \
            np.empty(0, dtype=bool)
        # writer row per pair: last non-fail write, else first write
        # (the host's writer-dict overwrite rule)
        last_nf = np.full(P, -1, dtype=np.int64)
        first_any = np.full(P, W, dtype=np.int64)
        if W:
            np.maximum.at(last_nf, inv[nonfail], order[nonfail])
            np.minimum.at(first_any, inv, order)
        w_row = np.where(last_nf >= 0, last_nf, first_any)
        self.w_txn = (f.wr_txn[np.clip(w_row, 0, max(W - 1, 0))]
                      if W else np.empty(0, dtype=np.int64))
        self.w_fail = (f.t_type[self.w_txn] == _TYPE_FAIL
                       if W else np.empty(0, dtype=bool))
        # duplicate-writes: non-fail writes beyond their pair's first
        # non-fail (host flags when the standing writer is non-fail)
        if W:
            sub = np.flatnonzero(nonfail)
            if sub.size:
                srt = sub[np.argsort(inv[sub], kind="stable")]
                pid_s = inv[srt]
                first = np.ones(srt.size, dtype=bool)
                first[1:] = pid_s[1:] != pid_s[:-1]
                for row in srt[~first][:self.CAP]:
                    self.anomalies["duplicate-writes"].append({
                        "key": f.key_names[f.wr_key[row]],
                        "value": int(f.wr_val[row]),
                        "op": self._op(f.wr_txn[row])})
        # intermediate (non-final) writer per pair: last row in txn
        # order wins, like the host's dict overwrite
        self.inter_txn = np.full(P, -1, dtype=np.int64)
        if len(f.wr_nonfinal):
            rows = f.wr_nonfinal
            pids = inv[rows]
            np.maximum.at(self.inter_txn, pids, rows)
            got = self.inter_txn >= 0
            self.inter_txn[got] = f.wr_txn[self.inter_txn[got]]

    def _pid_of(self, keys, vals) -> np.ndarray:
        codes = _pack(np.asarray(keys, dtype=np.int64),
                      np.asarray(vals, dtype=np.int64))
        if len(self.pair_codes) == 0:
            return np.full(len(codes), -1, dtype=np.int64)
        pos = np.searchsorted(self.pair_codes, codes)
        pos = np.clip(pos, 0, len(self.pair_codes) - 1)
        return np.where(self.pair_codes[pos] == codes, pos, -1)

    def _read_anomalies_and_edges(self):
        f = self.flat
        src: list = []
        dst: list = []
        ty: list = []
        kks: list = []

        def emit(s, d, t, kk=None):
            src.append(np.asarray(s, dtype=np.int64))
            dst.append(np.asarray(d, dtype=np.int64))
            ty.append(np.full(len(s), t, dtype=np.int64))
            kks.append(np.full(len(s), -1, dtype=np.int64)
                       if kk is None
                       else np.asarray(kk, dtype=np.int64))

        # -- reads: unobservable / G1a / G1b + wr edges
        if len(f.rd_txn):
            pid = self._pid_of(f.rd_key, f.rd_val)
            missing = pid == -1
            for i in np.flatnonzero(missing)[:self.CAP]:
                self.anomalies["unobservable-read"].append({
                    "key": f.key_names[f.rd_key[i]],
                    "value": int(f.rd_val[i]),
                    "op": self._op(f.rd_txn[i])})
            found = ~missing
            if len(self.pair_codes):
                wt = np.where(found,
                              self.w_txn[np.clip(pid, 0, None)], -1)
                wfail = np.where(
                    found, self.w_fail[np.clip(pid, 0, None)], False)
            else:  # reads but not a single write anywhere
                wt = np.full(len(f.rd_txn), -1, dtype=np.int64)
                wfail = np.zeros(len(f.rd_txn), dtype=bool)
            g1a = found & wfail
            for i in np.flatnonzero(g1a)[:self.CAP]:
                self.anomalies["G1a"].append({
                    "key": f.key_names[f.rd_key[i]],
                    "value": int(f.rd_val[i]),
                    "op": self._op(f.rd_txn[i]),
                    "writer": self._op(wt[i])})
            ext = found & ~wfail & (wt != f.rd_txn)
            inter = np.where(found,
                             self.inter_txn[np.clip(pid, 0, None)], -1)
            g1b = ext & (inter >= 0) & (inter != f.rd_txn)
            for i in np.flatnonzero(g1b)[:self.CAP]:
                self.anomalies["G1b"].append({
                    "key": f.key_names[f.rd_key[i]],
                    "value": int(f.rd_val[i]),
                    "op": self._op(f.rd_txn[i]),
                    "writer": self._op(inter[i])})
            emit(wt[ext], f.rd_txn[ext], WR, f.rd_key[ext])

        # -- write-follows-read: ww edges + version succession
        if len(f.fr_txn):
            pw_pid = self._pid_of(f.fr_key, f.fr_prev)
            ok = pw_pid >= 0
            pw = np.where(ok, self.w_txn[np.clip(pw_pid, 0, None)], -1)
            m = ok & (pw >= 0) & (pw != f.fr_txn)
            emit(pw[m], f.fr_txn[m], WW, f.fr_key[m])
            # succ[(k, prev)] = new, last in txn order wins
            fp = _pack(f.fr_key, f.fr_prev)
            order = np.argsort(fp, kind="stable")
            fp_s = fp[order]
            last = np.ones(len(fp_s), dtype=bool)
            last[:-1] = fp_s[1:] != fp_s[:-1]
            self.succ_codes = fp_s[last]
            self.succ_vals = f.fr_new[order][last]
        else:
            self.succ_codes = np.empty(0, dtype=np.int64)
            self.succ_vals = np.empty(0, dtype=np.int64)

        # -- external reads -> rw edges against the proven successor
        if len(f.er_txn) and len(self.succ_codes):
            ec = _pack(f.er_key, f.er_val)
            pos = np.searchsorted(self.succ_codes, ec)
            pos = np.clip(pos, 0, len(self.succ_codes) - 1)
            has = self.succ_codes[pos] == ec
            nv = np.where(has, self.succ_vals[pos], 0)
            w2_pid = self._pid_of(f.er_key, nv)
            w2_ok = has & (w2_pid >= 0)
            w2 = np.where(w2_ok,
                          self.w_txn[np.clip(w2_pid, 0, None)], -1)
            m = (w2_ok & (w2 >= 0) & (w2 != f.er_txn)
                 & (f.t_type[np.clip(w2, 0, None)] == _TYPE_OK))
            emit(f.er_txn[m], w2[m], RW, f.er_key[m])

        fl = self.flat
        comm = np.flatnonzero(fl.t_type == _TYPE_OK)
        o_src, o_dst, o_ty = order_edges_from_arrays(
            comm, fl.t_inv[comm], fl.t_comp[comm], fl.t_proc[comm])
        src.append(o_src)
        dst.append(o_dst)
        ty.append(o_ty)
        kks.append(np.full(o_src.size, -1, dtype=np.int64))
        self.edge_src = np.concatenate(src) if src else \
            np.empty(0, dtype=np.int64)
        self.edge_dst = np.concatenate(dst) if dst else \
            np.empty(0, dtype=np.int64)
        self.edge_ty = np.concatenate(ty) if ty else \
            np.empty(0, dtype=np.int64)
        self.edge_key = np.concatenate(kks) if kks else \
            np.empty(0, dtype=np.int64)



def check_rw_register_device(hist, device: bool = True) -> dict:
    """Drop-in device-path analog of elle.check_rw_register. Raises
    Unvectorizable when the history can't be interned."""
    if not isinstance(hist, History):
        hist = History(hist)
    prof = profiler.get()
    rec = prof.begin("elle-rw")
    with telemetry.span("elle:rw-register") as sp:
        with prof.phase(rec, "encode_ns"):
            a = DeviceRwAnalysis(hist, device=device)
        if sp is not None:
            sp["attrs"] = {"txns": a.flat.n,
                           "edges": int(len(a.edge_src))}
    telemetry.count("elle.txns", a.flat.n)
    telemetry.count("elle.edges", int(len(a.edge_src)))
    rec.update(txns=a.flat.n, edges=int(len(a.edge_src)))
    anomalies = dict(a.anomalies)
    with prof.phase(rec, "compute_ns"):
        for name, ws in cycle_anomalies_arrays(
                a.flat.n, a.edge_src, a.edge_dst, a.edge_ty, a._op,
                device=device, ekey=a.edge_key).items():
            anomalies[name] = ws
    prof.finish(rec)
    return {
        "valid?": not anomalies,
        "anomaly-types": sorted(anomalies.keys()),
        "anomalies": {k: v[:8] for k, v in anomalies.items()},
        "edge-count": int(len(a.edge_src)),
        "txn-count": a.flat.n,
    }
