"""TPU-native history analysis kernels.

This package is the framework's differentiator: the search-heavy checkers
the reference delegates to external JVM libraries (knossos for
linearizability, elle for transactional cycle anomalies — see
jepsen/src/jepsen/checker.clj:202-233 and
jepsen/src/jepsen/tests/cycle/append.clj:17-27) are reimplemented as
batched JAX programs over packed int32 tensors:

- encode:   history -> entry tensors; model -> dense transition tables
- wgl:      Wing&Gong/Lowe linearizability as a fixed-width batched
            frontier search (host reference + device kernel)
- elle:     dependency-edge inference + SCC cycle detection
- ensemble: vmap/shard_map over batches of histories on a device mesh
"""

from . import encode  # noqa: F401
from . import wgl  # noqa: F401
