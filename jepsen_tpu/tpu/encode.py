"""History -> tensor encoding for the TPU checkers.

Turns a single-key client history into flat int32 entry arrays (one entry
per surviving invocation, sorted by invocation order) and compiles a
sequential model (jepsen_tpu.checker.models) into a dense transition table
by closing over its reachable state space.

Capability reference: knossos preprocesses histories the same way before
search (pairing invocations with completions, dropping :fail ops because
they never took effect, treating :info ops as possibly-effective forever —
behavior observed through jepsen/src/jepsen/checker.clj:202-233 and the
model-protocol mirror at jepsen/src/jepsen/tests/causal.clj:10-29). Where
knossos steps model *objects* during the search, we pre-tabulate
`trans[entry, state] -> state'` so the search itself is pure integer
gathers that run on device.
"""

from __future__ import annotations

from typing import Any

import numpy as np

from .. import history as h
from ..checker import models as model_mod
from ..history import History, Op

# Sentinel "time" for completions that never happen (crashed ops) and for
# padding entries. Far above any real history position, still well inside
# int32.
INF = np.int32(1 << 30)


class EncodingError(Exception):
    """The history/model can't be compiled to dense tables (e.g. the
    reachable state space exceeds max_states). Callers fall back to the
    object-model host search."""


def _freeze(v: Any):
    """Hashable view of an op value (lists/dicts appear in txn values)."""
    if isinstance(v, list):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, tuple):
        return tuple(_freeze(x) for x in v)
    if isinstance(v, dict):
        return tuple(sorted((k, _freeze(x)) for k, x in v.items()))
    if isinstance(v, set):
        return frozenset(_freeze(x) for x in v)
    return v


class Encoded:
    """A single history compiled for the WGL kernel.

    Arrays (length m, entries sorted by invocation position):
      inv_t   int32  invocation position in the source history
      ret_t   int32  completion position (INF when crashed)
      crashed bool   completion was :info / missing (op may or may not
                     have taken effect, at any later time)
      trans   int32 [m, n_states]  next-state code, -1 = inconsistent

    State 0 is the initial model state. entry_ops[e] is the merged Op for
    witness reporting.
    """

    __slots__ = ("inv_t", "ret_t", "crashed", "trans", "m", "n_states",
                 "states", "entry_ops", "init_state")

    def __init__(self, inv_t, ret_t, crashed, trans, states, entry_ops,
                 init_state: int = 0):
        self.inv_t = inv_t
        self.ret_t = ret_t
        self.crashed = crashed
        self.trans = trans
        self.m = len(inv_t)
        self.n_states = trans.shape[1] if trans.size else 1
        self.states = states
        self.entry_ops = entry_ops
        self.init_state = init_state

    def segment(self, lo: int, hi: int, init_state: int = 0) -> "Encoded":
        """Sub-history over entries [lo, hi) starting from init_state.
        Entry positions are re-based so the window math stays in-range."""
        base = self.inv_t[lo] if hi > lo else 0
        ret = self.ret_t[lo:hi].copy()
        ret[ret < INF] -= base
        return Encoded(self.inv_t[lo:hi] - base, ret,
                       self.crashed[lo:hi], self.trans[lo:hi],
                       self.states, self.entry_ops[lo:hi], init_state)

    def suffix_min_ret(self) -> np.ndarray:
        """suffix_min_ret[i] = min(ret_t[i:]), length m+1, [m] = INF."""
        out = np.full(self.m + 1, INF, dtype=np.int32)
        if self.m:
            out[:-1] = np.minimum.accumulate(self.ret_t[::-1])[::-1]
        return out

    def with_init(self, init_state: int) -> "Encoded":
        """A view of this history starting from a different model state
        (shares all arrays)."""
        return Encoded(self.inv_t, self.ret_t, self.crashed, self.trans,
                       self.states, self.entry_ops, init_state)

    def __repr__(self):
        return f"Encoded<m={self.m} states={self.n_states}>"


def balanced_groups(weights, n_groups: int) -> list[list[int]]:
    """Shard-aligned packing: partition item indices into n_groups
    groups balanced by weight (longest-processing-time greedy), so
    the packed segment tensors slice cleanly along the mesh axis with
    near-even per-device search work. Groups keep ascending index
    order internally (stable layouts keep compile buckets stable);
    every group exists even when items < groups (empty groups map to
    sentinel-only shards)."""
    n_groups = max(int(n_groups), 1)
    weights = list(weights)
    order = sorted(range(len(weights)), key=lambda i: (-weights[i], i))
    loads = [0.0] * n_groups
    groups: list[list[int]] = [[] for _ in range(n_groups)]
    for i in order:
        g = loads.index(min(loads))
        groups[g].append(i)
        loads[g] += max(float(weights[i]), 1.0)
    for g in groups:
        g.sort()
    return groups


def _with_value(inv: Op, value) -> Op:
    """inv with a substituted value. A slot-direct constructor: this
    runs once per completed read in a million-op encode, where
    Op.copy's dict round trip is ~4x the cost."""
    op = Op.__new__(Op)
    op.index = inv.index
    op.time = inv.time
    op.type = inv.type
    op.process = inv.process
    op.f = inv.f
    op.value = value
    op.ext = inv.ext
    return op


def _merged_entry(inv: Op, comp: Op | None) -> tuple[Op, bool]:
    """The op a model should step, plus crashed?. For :ok completions the
    completion's value wins (reads invoke with value nil and complete with
    the observed value); crashed ops keep the invocation's value."""
    if comp is not None and comp.type == h.OK:
        op = inv if comp.value is None else _with_value(inv, comp.value)
        return op, False
    return inv, True


def entries(hist: History) -> list[tuple[int, int, bool, Op]]:
    """[(inv_pos, ret_pos, crashed, op)] for each effective invocation.
    :fail completions are dropped (the op never happened); crashed reads
    and other provably effect-free crashed ops are dropped by encode()
    once the transition table shows they're identity."""
    out = []
    open_inv: dict[Any, tuple[int, Op]] = {}
    ops = list(hist)
    for pos, op in enumerate(ops):
        if not h.is_client_op(op):
            continue
        if op.type == h.INVOKE:
            open_inv[op.process] = (pos, op)
        elif op.type in (h.OK, h.FAIL, h.INFO):
            pair = open_inv.pop(op.process, None)
            if pair is None:
                continue
            inv_pos, inv = pair
            if op.type == h.FAIL:
                continue
            merged, crashed = _merged_entry(inv, op if op.type == h.OK
                                            else None)
            out.append((inv_pos, pos if not crashed else int(INF), crashed,
                        merged))
    # invocations that never completed at all == crashed
    for inv_pos, inv in open_inv.values():
        merged, _ = _merged_entry(inv, None)
        out.append((inv_pos, int(INF), True, merged))
    out.sort(key=lambda e: e[0])
    return out


def encode(model, hist: History, max_states: int = 4096) -> Encoded:
    """Compiles (model, history) into an Encoded. Raises EncodingError if
    the reachable state space exceeds max_states or the model declares
    itself non-tabulable (step() depends on more than op.f/op.value).

    Host-encode time is the first phase of every kernel launch
    pipeline, so it's accounted to the device profiler (aggregate
    `profiler.encode.*` counters — ensembles encode thousands of
    histories, so no per-call records)."""
    from time import monotonic_ns

    from . import profiler

    t0 = monotonic_ns()
    enc = _encode(model, hist, max_states)
    profiler.get().record_host("encode", monotonic_ns() - t0,
                               entries=enc.m)
    return enc


def _encode(model, hist: History, max_states: int) -> Encoded:
    if not getattr(model, "tabulable", True):
        raise EncodingError(f"{type(model).__name__} is not tabulable")
    ents = entries(hist)

    # Distinct ops (by f, frozen value) index the transition-table rows.
    distinct: dict[Any, int] = {}
    ent_op_idx = []
    d_ops: list[Op] = []
    for _, _, _, op in ents:
        key = (op.f, _freeze(op.value))
        if key not in distinct:
            distinct[key] = len(d_ops)
            d_ops.append(op)
        ent_op_idx.append(distinct[key])

    # Close the state space under all distinct ops.
    states: dict[Any, int] = {model: 0}
    state_list = [model]
    d_trans: list[list[int]] = []  # [n_states][n_distinct]
    frontier = [model]
    while frontier:
        nxt = []
        for st in frontier:
            si = states[st]
            while len(d_trans) <= si:
                d_trans.append([-1] * len(d_ops))
            for di, dop in enumerate(d_ops):
                st2 = st.step(dop)
                if model_mod.is_inconsistent(st2):
                    d_trans[si][di] = -1
                    continue
                if st2 not in states:
                    if len(states) >= max_states:
                        raise EncodingError(
                            f"state space exceeds {max_states} states")
                    states[st2] = len(state_list)
                    state_list.append(st2)
                    nxt.append(st2)
                d_trans[si][di] = states[st2]
        frontier = nxt

    n_states = len(state_list)
    d_trans_arr = np.array(d_trans, dtype=np.int32)  # [S, D]

    # Drop crashed entries that are identity on every state (e.g. crashed
    # reads with unknown result): linearizing them never matters.
    # Identity-ness is a property of the DISTINCT op, computed once per
    # table column instead of once per entry.
    identity = np.arange(n_states, dtype=np.int32)
    id_cols = (d_trans_arr == identity[:, None]).all(axis=0)  # [D]
    op_idx = np.asarray(ent_op_idx, dtype=np.int32)
    crashed_all = np.fromiter((e[2] for e in ents), dtype=bool,
                              count=len(ents))
    keep = np.flatnonzero(~(crashed_all & id_cols[op_idx]))

    inv_t = np.fromiter((e[0] for e in ents), dtype=np.int32,
                        count=len(ents))[keep]
    ret_t = np.fromiter((e[1] for e in ents), dtype=np.int32,
                        count=len(ents))[keep]
    crashed_a = crashed_all[keep]
    # one gather instead of an m-iteration python fill
    trans = d_trans_arr[:, op_idx[keep]].T.copy()
    entry_ops = [ents[i][3] for i in keep]
    return Encoded(inv_t, ret_t, crashed_a, trans, state_list, entry_ops)
