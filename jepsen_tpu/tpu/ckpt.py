"""Crash-consistent checkpoint store for incremental re-checking.

ROADMAP item 3's checkpoint-and-extend layer (JASS-style crash-
consistent checkpoints, arXiv:2301.11511): a checker that already
certified a prefix of a history should never pay for that prefix
again — not after a SIGKILL, not when a run-dir grows, not when a
fleet stream resumes. This module is the durable store those resumes
trust:

  record    one schema-validated JSON dict per checkpoint file, keyed
            by a history-prefix digest. Three kinds:
              stream-wgl   a streaming run's frontier: entries
                           certified, reachable-state mask, raw-op
                           prefix digest (fleet.scheduler.StreamingRun)
              wgl-extend   the segmented extend-check's frontier: the
                           stride-stable cut layout, per-cut entry
                           digests, and every resolved
                           (segment, state) -> reach mask
                           (wgl.analysis_extend)
              elle         a committed-txn graph summary: per-key
                           version orders + the SCC condensation
                           frontier (elle.StreamingElle)
  framing   CKPT_MAGIC + <len, crc32> + payload — the jlog discipline.
            Writes go to a tmp file (fsync'd) then os.replace, so a
            reader sees old-or-new, never torn. A torn/truncated/
            stale file (chaos can seed all three) is DETECTED AND
            DISCARDED, never trusted: bad magic / short frame / CRC
            mismatch / schema violation all read as None with a
            counted telemetry event, and the caller falls back to a
            full re-check.
  digests   sha256 over the canonical store codec bytes of the
            history prefix (ops_digest) or over the encoded entry
            prefix (entry_digest_chain). A digest mismatch means the
            checkpointed prefix is NOT a prefix of the history at
            hand — `ckpt.stale` is counted and the checkpoint is
            ignored. Never a wrong verdict, only a slower one.

Durability faults (ENOSPC/EIO — chaos injects them via
set_fault_hook) surface as OSError from write(); try_write() absorbs
them into a False + `ckpt.write-error` count so serving paths shed
instead of crashing. See doc/robustness.md, "Checkpoint-and-extend".
"""

from __future__ import annotations

import hashlib
import json
import os
import struct
import threading
import zlib
from pathlib import Path

from .. import telemetry

CKPT_MAGIC = b"JTPUCKP1"
_HDR = struct.Struct("<II")
VERSION = 1

KINDS = ("stream-wgl", "wgl-extend", "elle")

# chaos hook: called as hook(path, data) before the tmp write; may
# raise OSError (ENOSPC/EIO) or return mutated bytes (torn/stale
# seeding). Installed/cleared under _hook_lock (chaos.DurabilityChaos).
_fault_hook = None
_hook_lock = threading.Lock()


def set_fault_hook(hook) -> None:
    """Installs (or, with None, clears) the write-path fault hook —
    the chaos rig's injection point for ENOSPC/EIO and seeded
    torn/stale checkpoint bytes."""
    global _fault_hook
    with _hook_lock:
        _fault_hook = hook


# ---------------------------------------------------------------------------
# Digests
# ---------------------------------------------------------------------------

def ops_digest(ops, n: int | None = None) -> str:
    """sha256 hex over the canonical store-codec bytes of ops[:n] —
    the history-prefix key. Stable across live streaming, WAL replay,
    and history.jlog recovery: all three hand the same Op objects to
    the same codec."""
    from ..store import format as fmt

    h = hashlib.sha256()
    take = len(ops) if n is None else min(n, len(ops))
    for i in range(take):
        h.update(fmt.encode_op(ops[i]))
        h.update(b"\n")
    return h.hexdigest()


def entry_digest_chain(enc, cuts) -> list[str]:
    """One sha256 hex per cut: digest i covers the ENCODED entries
    [0, cuts[i]). Entries before a valid cut are fully determined by
    the history prefix (every one completed before later ops invoked),
    so the chain is prefix-stable under history growth — the property
    wgl-extend records key on."""
    h = hashlib.sha256()
    out: list[str] = []
    pos = 0
    for c in cuts:
        while pos < c:
            op = enc.entry_ops[pos]
            line = json.dumps(
                [int(getattr(op, "index", -1)), str(op.process),
                 str(op.f), _jsonable(op.value),
                 bool(enc.crashed[pos])],
                separators=(",", ":"), sort_keys=True)
            h.update(line.encode())
            h.update(b"\n")
            pos += 1
        out.append(h.hexdigest())
    return out


def _jsonable(v):
    from ..store import format as fmt

    return fmt.jsonable(v)


# ---------------------------------------------------------------------------
# Schema
# ---------------------------------------------------------------------------

def _bad(msg: str) -> None:
    raise ValueError(f"checkpoint record: {msg}")


def validate_record(rec) -> None:
    """Raises ValueError unless `rec` is a schema-valid checkpoint
    record. A record that fails here is never trusted — the reader
    treats it exactly like a torn file."""
    if not isinstance(rec, dict):
        _bad(f"not a dict: {type(rec).__name__}")
    if rec.get("v") != VERSION:
        _bad(f"bad version {rec.get('v')!r}")
    kind = rec.get("kind")
    if kind not in KINDS:
        _bad(f"unknown kind {kind!r}")
    dig = rec.get("digest")
    if not (isinstance(dig, str) and len(dig) == 64):
        _bad(f"bad digest {dig!r}")
    n_ops = rec.get("n_ops")
    if not isinstance(n_ops, int) or isinstance(n_ops, bool) \
            or n_ops < 0:
        _bad(f"bad n_ops {n_ops!r}")
    if kind == "stream-wgl":
        for k in ("checked", "mask"):
            v = rec.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                _bad(f"bad {k} {v!r}")
        if not isinstance(rec.get("model"), str):
            _bad("bad model")
    elif kind == "wgl-extend":
        for k in ("stride", "model_fp"):
            v = rec.get(k)
            if not isinstance(v, int) or isinstance(v, bool) or v < 0:
                _bad(f"bad {k} {v!r}")
        cuts = rec.get("cuts")
        if not (isinstance(cuts, list) and len(cuts) >= 2
                and all(isinstance(c, int) and not isinstance(c, bool)
                        and c >= 0 for c in cuts)
                and all(a <= b for a, b in zip(cuts, cuts[1:]))):
            _bad(f"bad cuts {cuts!r}")
        digs = rec.get("digests")
        if not (isinstance(digs, list) and len(digs) == len(cuts)
                and all(isinstance(d, str) and len(d) == 64
                        for d in digs)):
            _bad("bad digests")
        states = rec.get("states")
        if not (isinstance(states, list) and 0 < len(states) <= 32
                and all(isinstance(s, str) for s in states)):
            _bad("bad states")
        masks = rec.get("masks")
        if not isinstance(masks, dict):
            _bad("bad masks")
        for key, m in masks.items():
            parts = str(key).split(":")
            if len(parts) != 2 or not all(p.isdigit() for p in parts):
                _bad(f"bad mask key {key!r}")
            if not isinstance(m, int) or isinstance(m, bool) or m < 0:
                _bad(f"bad mask {m!r}")
    elif kind == "elle":
        if not isinstance(rec.get("family"), str):
            _bad("bad family")
        n = rec.get("n_closed")
        if not isinstance(n, int) or isinstance(n, bool) or n < 0:
            _bad(f"bad n_closed {n!r}")
        versions = rec.get("versions")
        if not isinstance(versions, dict) or not all(
                isinstance(vs, list) for vs in versions.values()):
            _bad("bad versions")
        frontier = rec.get("frontier")
        if not isinstance(frontier, dict):
            _bad("bad frontier")


# ---------------------------------------------------------------------------
# Paths
# ---------------------------------------------------------------------------

def fleet_path(base, tenant: str, run: str) -> Path:
    """The fleet's per-(tenant, run) stream checkpoint file."""
    from ..fleet import wal as fwal

    assert fwal.safe_name(tenant) and fwal.safe_name(run), (tenant,
                                                            run)
    return Path(base) / "ckpt" / tenant / f"{run}.ckpt"


def run_dir_path(d, name: str) -> Path:
    """A stored run-dir's checkpoint file (analyze --resume reuse)."""
    return Path(d) / "ckpt" / f"{name}.ckpt"


# ---------------------------------------------------------------------------
# Atomic write / validated read
# ---------------------------------------------------------------------------

def write(path, rec: dict) -> Path:
    """Schema-validates and atomically writes one checkpoint record:
    CRC-framed payload to a tmp file, fsync, os.replace. Raises
    OSError on durability faults (ENOSPC/EIO, injected or real) after
    counting `ckpt.write-error` — callers on serving paths use
    try_write() and shed instead."""
    validate_record(rec)
    p = Path(path)
    payload = json.dumps(rec, separators=(",", ":"),
                         sort_keys=True).encode()
    data = (CKPT_MAGIC
            + _HDR.pack(len(payload), zlib.crc32(payload)) + payload)
    with _hook_lock:
        hook = _fault_hook
    try:
        if hook is not None:
            data = hook(p, data)
        p.parent.mkdir(parents=True, exist_ok=True)
        tmp = p.with_suffix(".tmp")
        fd = os.open(tmp, os.O_CREAT | os.O_TRUNC | os.O_WRONLY)
        try:
            from ..ledger import write_all

            write_all(fd, data)
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, p)
    except OSError:
        telemetry.count("ckpt.write-error")
        raise
    telemetry.count("ckpt.saved")
    return p


def try_write(path, rec: dict) -> bool:
    """write(), with durability faults absorbed: False means the
    checkpoint did NOT land (the stream keeps running from its
    previous one — degraded, never wrong)."""
    try:
        write(path, rec)
        return True
    except OSError:
        return False


def read(path) -> dict | None:
    """The record, or None for missing/torn/truncated/corrupt/
    schema-invalid files — each counted, none trusted."""
    p = Path(path)
    try:
        buf = p.read_bytes()
    except OSError:
        return None
    if buf[:len(CKPT_MAGIC)] != CKPT_MAGIC:
        telemetry.count("ckpt.torn")
        return None
    pos = len(CKPT_MAGIC)
    if len(buf) < pos + _HDR.size:
        telemetry.count("ckpt.torn")
        return None
    n, crc = _HDR.unpack(buf[pos:pos + _HDR.size])
    payload = buf[pos + _HDR.size:pos + _HDR.size + n]
    if len(payload) < n or zlib.crc32(payload) != crc:
        telemetry.count("ckpt.torn")
        return None
    try:
        rec = json.loads(payload)
        validate_record(rec)
    except ValueError:
        telemetry.count("ckpt.invalid")
        return None
    return rec


def load(path, kind: str, digest: str | None = None,
         n_ops: int | None = None) -> dict | None:
    """read() + kind/digest screening. A digest (or op-count) mismatch
    means the checkpoint describes a DIFFERENT history prefix: count
    `ckpt.stale` and fall back to the full check — stale checkpoints
    cost time, never correctness."""
    rec = read(path)
    if rec is None or rec.get("kind") != kind:
        return None
    if n_ops is not None and rec.get("n_ops", 0) > n_ops:
        telemetry.count("ckpt.stale")
        return None
    if digest is not None and rec.get("digest") != digest:
        telemetry.count("ckpt.stale")
        return None
    return rec
