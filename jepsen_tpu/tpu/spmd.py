"""SPMD plumbing shared by the sharded checker kernels.

One place answers three questions every sharded launch site asks:

  - *May I shard, and over how many devices?* `spmd_devices()` reads
    the process's device count through the JEPSEN_TPU_SPMD /
    JEPSEN_TPU_SPMD_DEVICES knobs (0/1 = single-device path). The
    gate is re-read per call so tests and benches can flip it without
    re-importing anything.
  - *How do the kernel's arguments lay out over the mesh?* The
    regex partition-rule table (the fmengine/EasyLM idiom, SNIPPETS.md
    [1]): arg names match rules, rules name PartitionSpecs. The
    lint registry reads the same table, so graftlint R4 prices the
    layout the launch sites actually use — not a parallel description
    that can drift.
  - *Is the XLA compilation cache on?* `enable_compile_cache()` wires
    jax's persistent compilation cache behind a CLI/env knob
    (JEPSEN_TPU_COMPILE_CACHE; default under store/), called lazily by
    every jit factory — a warm cache makes first-check compile ~0 and
    un-gates the profiler's memory_analysis path (which needs a second
    compile per bucket to be cheap).

The mesh itself is 1-D over the batch axis ("b"): every kernel family
here is embarrassingly parallel in exactly one axis (histories in the
ensemble, segments x start-states in WGL, edges-by-key-block in SCC —
P-compositionality, PAPERS.md arXiv:1504.00204), so one axis name
serves all of them and `mesh_for(n)` memoizes one Mesh per size.
"""

from __future__ import annotations

import functools
import logging
import os
import re

logger = logging.getLogger(__name__)

# The mesh's single batch axis name, shared by every sharded kernel.
AXIS = "b"

# Below this many independent rows a sharded launch is pure overhead.
MIN_ROWS = 2


def spmd_enabled() -> bool:
    """SPMD launches may be disabled outright (JEPSEN_TPU_SPMD=0):
    the single-device kernels are the fallback and the differential
    reference."""
    return os.environ.get("JEPSEN_TPU_SPMD", "1") != "0"


def spmd_devices() -> int:
    """How many devices a sharded launch may span right now: the
    process's device count, capped by JEPSEN_TPU_SPMD_DEVICES (tests
    parametrize mesh sizes through it), 0 when sharding is disabled
    or jax is unavailable. Values <= 1 mean 'take the single-device
    path'."""
    if not spmd_enabled():
        return 0
    try:
        import jax

        n = len(jax.devices())
    except Exception:  # noqa: BLE001 — no backend, no mesh
        return 0
    cap = os.environ.get("JEPSEN_TPU_SPMD_DEVICES")
    if cap:
        try:
            n = min(n, max(int(cap), 0))
        except ValueError:
            pass
    return n


@functools.lru_cache(maxsize=None)
def mesh_for(n_devices: int):
    """The memoized 1-D ('b',) mesh over the first n devices. One Mesh
    object per size keeps the jit factories' lru_cache keys stable
    (jax Meshes hash by devices + axis names, but identity-stable
    objects avoid rebuilding device arrays per launch)."""
    import jax
    import numpy as np

    from . import dist

    dist.ensure_initialized()
    devs = jax.devices()[:n_devices]
    return jax.sharding.Mesh(np.array(devs), (AXIS,))


# ---------------------------------------------------------------------------
# Regex partition rules (SNIPPETS.md [1]: match_partition_rules)
# ---------------------------------------------------------------------------

# WGL kernel family: the packed segment tensors are laid out in
# per-device blocks (ensemble.shard_layout), so their leading axis
# shards with the search rows — nothing big is replicated. Only the
# tiny result-ordering permutation stays replicated.
WGL_RULES = (
    (r"^(inv_t|ret_t|trans|mseg|sufmin)$", (AXIS,)),
    (r"^(row_seg|st0)$", (AXIS,)),
    (r"^inv_perm$", ()),
)

# SCC coloring kernel: the edge list (the big operand — the color
# array is n_pad ints) shards over the mesh; colors stay replicated
# and are pmax-combined per sweep.
SCC_RULES = (
    (r"^(src|dst|edge_on)$", (AXIS,)),
    (r"^active$", ()),
)


def match_partition_rules(rules, names):
    """PartitionSpec per arg name via the first matching regex rule
    (re.search, like the reference snippet). Raises on an unmatched
    name — a silently-replicated new argument is exactly the bug the
    table exists to prevent."""
    from jax.sharding import PartitionSpec as P

    out = []
    for name in names:
        for rule, axes in rules:
            if re.search(rule, name):
                out.append(P(*axes))
                break
        else:
            raise ValueError(f"no partition rule for arg {name!r}")
    return tuple(out)


def describe_partition(rules, names) -> dict:
    """The lint-facing view of a rule table: which args shard over
    the mesh axis and which stay replicated (graftlint R4's input —
    jepsen_tpu.analysis.registry reads the table the launch sites
    use)."""
    sharded, replicated = [], []
    for name in names:
        for rule, axes in rules:
            if re.search(rule, name):
                (sharded if axes else replicated).append(name)
                break
        else:
            # same contract as match_partition_rules: an arg the lint
            # view can't place would silently escape R4 pricing
            raise ValueError(f"no partition rule for arg {name!r}")
    return {"axis": AXIS, "sharded": sharded, "replicated": replicated}


# ---------------------------------------------------------------------------
# Persistent XLA compilation cache
# ---------------------------------------------------------------------------

_cache_done = False


def compile_cache_dir() -> str | None:
    """The configured cache directory: JEPSEN_TPU_COMPILE_CACHE (a
    path, or '0'/'' to disable), defaulting under the store directory
    (store/.xla-cache) so a repo checkout warms up across runs."""
    env = os.environ.get("JEPSEN_TPU_COMPILE_CACHE")
    if env is not None:
        if env in ("0", ""):
            return None
        return env
    from .. import store

    return str(store.BASE / ".xla-cache")


def enable_compile_cache() -> str | None:
    """Idempotently points jax's persistent compilation cache at
    compile_cache_dir(). Called by every kernel jit factory (lazily —
    before the first compile, never at import). A dir already set
    through jax.config (bench, tests/conftest.py) wins; returns the
    active dir or None. First-check compile on a warm cache is ~0
    (bench_warm_start measures it) and a configured cache un-gates
    profiler memory_analysis."""
    global _cache_done
    try:
        import jax

        if jax.config.jax_compilation_cache_dir:
            return jax.config.jax_compilation_cache_dir
        if _cache_done:
            return None
        _cache_done = True
        d = compile_cache_dir()
        if d is None:
            return None
        os.makedirs(d, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update(
            "jax_persistent_cache_min_compile_time_secs", 0.5)
        return d
    except Exception as e:  # noqa: BLE001 — cache is best-effort
        logger.debug("compilation cache unavailable: %r", e)
        return None
