"""Cross-run performance ledger + slow-bleed detection.

The per-round regression gate (bench.py, PR 4) compares one headline
against the immediately previous round, so two consecutive ~15% drops
sail through — exactly how the 1M-event headline bled 77.5k -> 65.2k
ops/s without a single gate trip. This module closes that hole:

  bench_ledger.jsonl   one JSON line per bench round, appended by
                       bench.py next to the BENCH_r*.json artifacts:
                       round id, timestamp, the headline line, and a
                       per-kernel breakdown ({name: {value, unit,
                       higher_is_better}}) so a regression is
                       attributed to wgl-vs-elle-vs-encode rather than
                       just the blended headline.

  slow_bleed()         an EWMA vs best-of-N detector: the recency-
                       weighted average of a kernel's series is
                       compared against the best value in the recent
                       window; a drift that never trips the per-round
                       gate still accumulates in the EWMA and fires
                       here (3 x 10% drops -> ~20% below best ->
                       flagged; round-to-round noise stays silent).

  validate_entries()   the tracing.validate_records analog for the
                       ledger (required keys, strictly-monotonic round
                       ids), run in tier-1.

Reading tolerates a torn trailing line (the writer died mid-append) —
the shared crash-tolerance contract of the repo's jsonl artifacts.
"""

from __future__ import annotations

import json
import os
import time
from pathlib import Path

LEDGER_FILE = "bench_ledger.jsonl"

REQUIRED = ("round", "ts", "headline", "kernels")

# slow-bleed policy (doc/observability.md): EWMA weight on the newest
# point, how many recent rounds define "best", and the drop fraction
# below best that fires. 0.15 sits under the per-round gate's 0.20 on
# purpose: the gate catches cliffs, this catches drifts.
EWMA_ALPHA = 0.5
BEST_WINDOW = 5
BLEED_THRESHOLD = 0.15
MIN_ROUNDS = 3


def read_entries(path) -> list[dict]:
    """Ledger entries in append order; a torn/corrupt trailing line is
    dropped rather than raised."""
    p = Path(path)
    if not p.exists():
        return []
    out: list[dict] = []
    with open(p) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                out.append(json.loads(line))
            except ValueError:
                break
    return out


def append_entry(path, entry: dict) -> dict:
    """Appends one round's entry (ts stamped if absent); returns it.
    The append is ONE os.write on an O_APPEND fd — the jlog
    concurrent-append discipline: two writers (a bench round racing a
    fleet server's bookkeeping, or two bench invocations) can
    interleave LINES but never bytes, so readers at worst drop a torn
    trailing line, never mis-parse a spliced one."""
    entry = dict(entry)
    entry.setdefault("ts", round(time.time(), 3))
    atomic_append_line(path, json.dumps(entry))
    return entry


def write_all(fd: int, buf: bytes) -> None:
    """os.write until every byte lands, raising on a zero-progress
    write — the one short-write loop shared by every crash-safe
    append in the repo (this module, the coverage atlas, the fleet
    WAL). A silently-torn record behind a durability promise is the
    failure mode this exists to kill."""
    view = memoryview(buf)
    while view:
        n = os.write(fd, view)
        if n <= 0:
            raise OSError("short write")
        view = view[n:]


def atomic_append_line(path, line: str) -> None:
    """One whole line, one os.write, O_APPEND: the shared-ledger
    append primitive (used by this module and the coverage atlas).
    Short writes (ENOSPC, signals) are continued rather than silently
    torn — the continuation can interleave with another writer only
    in the already-degraded disk-full case, where the torn-tail read
    rule still drops the damage."""
    fd = os.open(path, os.O_APPEND | os.O_CREAT | os.O_WRONLY, 0o644)
    try:
        write_all(fd, (line + "\n").encode())
    finally:
        os.close(fd)


def next_round(entries: list[dict], floor: int = 0) -> int:
    """The next round id: one past the ledger's max (and past `floor`,
    the newest BENCH_r<NN> artifact's round, so ledger rounds stay
    aligned with the driver's files even if the ledger starts late)."""
    last = max((int(e.get("round", 0)) for e in entries), default=0)
    return max(last, floor) + 1


def validate_entries(entries) -> int:
    """Schema check for a ledger stream: required keys, numeric
    headline value, kernels a dict of {value, ...} maps, and STRICTLY
    monotonic round ids. Returns the entry count; raises ValueError on
    the first violation. Run in tier-1 like tracing.validate_records."""
    prev_round = 0
    n = 0
    for i, e in enumerate(entries):
        if not isinstance(e, dict):
            raise ValueError(f"entry {i}: not a dict")
        for key in REQUIRED:
            if key not in e:
                raise ValueError(f"entry {i} missing {key!r}: {e}")
        rnd = e["round"]
        if not isinstance(rnd, int) or rnd <= prev_round:
            raise ValueError(
                f"entry {i}: round {rnd!r} not monotonic "
                f"(previous {prev_round})")
        prev_round = rnd
        if not isinstance(e["ts"], (int, float)) or e["ts"] < 0:
            raise ValueError(f"entry {i}: bad ts {e['ts']!r}")
        hl = e["headline"]
        if not isinstance(hl, dict) or not isinstance(
                hl.get("value"), (int, float)):
            raise ValueError(f"entry {i}: bad headline {hl!r}")
        if not isinstance(e["kernels"], dict):
            raise ValueError(f"entry {i}: kernels must be a dict")
        for name, k in e["kernels"].items():
            if not isinstance(k, dict) or not isinstance(
                    k.get("value"), (int, float)):
                raise ValueError(
                    f"entry {i}: kernel {name!r} bad value: {k!r}")
        # optional search-shape fields (witness position, frontier
        # peak, states explored — jepsen_tpu.tpu.wgl's explorer): the
        # cross-run view of how the search's shape drifts
        s = e.get("search")
        if s is not None:
            if not isinstance(s, dict) or not all(
                    isinstance(v, (int, float)) and not isinstance(
                        v, bool)
                    for v in s.values()):
                raise ValueError(f"entry {i}: bad search stats {s!r}")
        # optional graftlint aggregates (jepsen_tpu.analysis): the
        # R3/R4 numbers the SPMD rebuild drives to zero — non-donated
        # bytes, replicated bytes, unsharded batch-axis count, plus a
        # per-rule findings breakdown
        li = e.get("lint")
        if li is not None:
            if not isinstance(li, dict):
                raise ValueError(f"entry {i}: bad lint stats {li!r}")
            for k, v in li.items():
                if k == "findings":
                    if not isinstance(v, dict) or not all(
                            isinstance(x, int) for x in v.values()):
                        raise ValueError(
                            f"entry {i}: bad lint findings {v!r}")
                elif not isinstance(v, int) or isinstance(v, bool):
                    raise ValueError(
                        f"entry {i}: bad lint field {k!r}: {v!r}")
        # optional fleet flight-recorder summary (jepsen_tpu.fleet.
        # flightrec): verdict/ack latency quantiles, per-class batch
        # occupancy, and the scheduler decision-log counts
        fl = e.get("fleet")
        if fl is not None:
            if not isinstance(fl, dict):
                raise ValueError(f"entry {i}: bad fleet stats {fl!r}")
            for k, v in fl.items():
                if k == "occupancy":
                    if not isinstance(v, dict) or not all(
                            x is None or (
                                isinstance(x, (int, float))
                                and not isinstance(x, bool)
                                and 0 <= x <= 1)
                            for x in v.values()):
                        raise ValueError(
                            f"entry {i}: bad fleet occupancy {v!r}")
                elif k == "decisions":
                    if not isinstance(v, dict) or not all(
                            isinstance(x, int)
                            and not isinstance(x, bool)
                            for x in v.values()):
                        raise ValueError(
                            f"entry {i}: bad fleet decisions {v!r}")
                elif v is not None and (
                        isinstance(v, bool)
                        or not isinstance(v, (int, float))):
                    raise ValueError(
                        f"entry {i}: bad fleet field {k!r}: {v!r}")
        n += 1
    return n


# ---------------------------------------------------------------------------
# Slow-bleed detection
# ---------------------------------------------------------------------------

def ewma(values, alpha: float = EWMA_ALPHA) -> float:
    it = iter(values)
    acc = float(next(it))
    for v in it:
        acc = alpha * float(v) + (1 - alpha) * acc
    return acc


def slow_bleed(values, window: int = BEST_WINDOW,
               threshold: float = BLEED_THRESHOLD,
               alpha: float = EWMA_ALPHA,
               higher_is_better: bool = True) -> dict:
    """Detects gradual regression in a chronological series of round
    values. Returns {'bleeding': bool, 'ewma', 'best', 'drop', 'n'}
    where `drop` is how far the recency-weighted average sits below
    the best of the last `window` rounds (in the higher-is-better
    frame; lower-is-better series — seconds — are inverted first).
    Under MIN_ROUNDS points nothing fires: one round is a gate's job,
    a bleed needs history."""
    vals = [float(v) for v in values if v is not None]
    out = {"bleeding": False, "ewma": None, "best": None,
           "drop": None, "n": len(vals)}
    if len(vals) < MIN_ROUNDS or any(v <= 0 for v in vals):
        return out
    series = vals if higher_is_better else [1.0 / v for v in vals]
    avg = ewma(series, alpha)
    best = max(series[-window:])
    drop = 1.0 - avg / best
    out.update(ewma=round(avg, 6), best=round(best, 6),
               drop=round(drop, 4), bleeding=drop > threshold)
    return out


def kernel_series(entries: list[dict], name: str) -> list[float]:
    """One kernel's chronological value series across ledger entries
    (rounds missing the kernel are skipped, keeping ratios honest)."""
    out = []
    for e in entries:
        k = (e.get("kernels") or {}).get(name)
        if isinstance(k, dict) and isinstance(k.get("value"),
                                              (int, float)):
            out.append(float(k["value"]))
    return out


def detect(entries: list[dict], window: int = BEST_WINDOW,
           threshold: float = BLEED_THRESHOLD) -> dict[str, dict]:
    """Per-kernel slow-bleed verdicts over a ledger (the newest entry
    is the round under test). Keys: every kernel named by the newest
    entry, plus 'headline'. Each verdict is slow_bleed()'s dict."""
    if not entries:
        return {}
    newest = entries[-1]
    out: dict[str, dict] = {}
    hl = [e["headline"]["value"] for e in entries
          if isinstance(e.get("headline"), dict)
          and isinstance(e["headline"].get("value"), (int, float))]
    out["headline"] = slow_bleed(hl, window, threshold)
    for name, k in (newest.get("kernels") or {}).items():
        out[name] = slow_bleed(
            kernel_series(entries, name), window, threshold,
            higher_is_better=bool(k.get("higher_is_better", True))
            if isinstance(k, dict) else True)
    return out
