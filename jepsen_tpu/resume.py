"""Resumable analysis: recover a crashed run's history and re-check it.

A control-process crash (OOM-killed, SIGKILL, power loss) mid-run used
to forfeit the whole test. The store already journals everything needed
to finish the job — the CRC-framed op log survives with at most a torn
tail (store/format.py), wgl segment checkpoints persist per-(segment,
state) search results, and the partial-results log holds every checker
that completed before the crash. This module stitches those together:

  python -m jepsen_tpu analyze <run-dir> [--resume]

  1. recovers the valid history prefix from history.jlog (torn tail
     dropped — the same recovery rule the writer uses on reopen);
  2. rebuilds the checker stack from the run's spec.json (a
     reconstructible test spec serialized at run start — store.save_spec);
  3. re-runs analysis; with --resume, checkers that already landed in
     results.partial.jlog are reused verbatim and the wgl segmented
     search reloads its frontier checkpoints (test["checkpoint?"]);
  4. writes results.json exactly as an uninterrupted run would.

So a kill -9 mid-run loses seconds of work, not the run. See
doc/robustness.md.
"""

from __future__ import annotations

import json
import logging
from pathlib import Path

from . import telemetry, util

logger = logging.getLogger(__name__)

# live/lifecycle objects a rebuilt spec test may carry that offline
# analysis must not touch (no cluster exists anymore)
_LIFECYCLE_KEYS = ("client", "generator", "final_generator", "nemesis",
                   "db", "os", "remote", "sessions", "barrier",
                   "history_writer", "monitor", "watchdog", "net",
                   "nodeprobe", "_fleet_streamer", "fleet")


def recover_history(d):
    """The valid history prefix from <d>/history.jlog: intact CRC
    records only, torn/corrupt tail dropped (store.format crash
    recovery). Returns (History, ops_recovered)."""
    from .store import format as fmt

    p = Path(d) / "history.jlog"
    hist = fmt.read_history(p)
    return hist, len(hist)


def _fallback_checker():
    """When a run predates spec.json: generic checkers that apply to
    any history. The verdict degrades honestly — stats/exceptions say
    what happened, nothing claims workload-level validity."""
    from . import checker as chk

    return chk.compose({"stats": chk.stats(),
                        "exceptions": chk.unhandled_exceptions()})


def rebuild_test(d, test_fn=None) -> dict:
    """A test map for offline analysis of stored run `d`: the stored
    scalars (test.json) plus a checker stack rebuilt from spec.json via
    test_fn (default: the bundled-workload builder in __main__)."""
    from . import store as jstore

    d = Path(d)
    with open(d / "test.json") as f:
        stored = json.load(f)
    spec = jstore.load_spec(d)
    test: dict = {}
    if spec and isinstance(spec.get("opts"), dict):
        try:
            if test_fn is None:
                from .__main__ import make_test as test_fn  # noqa: PLC0415
            opts = dict(spec["opts"])
            if spec.get("workload"):
                opts.setdefault("workload", spec["workload"])
            test = dict(test_fn(opts))
            for k in _LIFECYCLE_KEYS:
                test.pop(k, None)
        except (Exception, SystemExit) as e:  # noqa: BLE001 —
            # SystemExit included: CLI test builders sys.exit on an
            # unknown workload. A spec this analyzer can't rebuild
            # (suite-only workload, schema drift) must still yield
            # results, just generic ones.
            logger.warning(
                "couldn't rebuild the checker stack from %s's spec.json "
                "(%s); falling back to generic stats/exception checkers",
                d, e)
            test = {"checker": _fallback_checker(),
                    "rebuilt-from": "fallback"}
    else:
        logger.warning(
            "%s has no spec.json (run predates resumable analysis?); "
            "falling back to generic stats/exception checkers", d)
        test["checker"] = _fallback_checker()
        test["rebuilt-from"] = "fallback"
    # stored scalars win: name/start_time must address THIS run dir
    for k, v in stored.items():
        if k not in ("results", "history") and k not in _LIFECYCLE_KEYS:
            test[k] = v
    test["store_dir"] = str(d)
    return test


def analyze_run(d, resume: bool = False, test_fn=None,
                checker_timeout_s: float | None = None) -> dict:
    """Recovers `d`'s history and (re)runs analysis over it, writing
    results.json. With resume=True, completed checkers are reused from
    the crash-surviving partial-results log and the wgl segmented
    search reloads its per-segment checkpoints."""
    from . import core
    from . import store as jstore
    from .store import format as fmt

    d = Path(d)
    test = rebuild_test(d, test_fn=test_fn)
    hist, n_ops = recover_history(d)
    test["history"] = hist
    if checker_timeout_s:
        test["checker_timeout_s"] = checker_timeout_s

    extra_opts: dict = {}
    resumed_names: list = []
    if resume:
        # reuse the crashed analysis's completed checkers — read the
        # partial log BEFORE core.analyze truncates it for this pass
        partial_p = d / "results.partial.jlog"
        if partial_p.exists():
            got = fmt.read_partial_results(partial_p)
            # a checker that degraded to 'unknown' (timeout, hung, or
            # crashed) is re-run, not reused: resuming with a larger
            # --checker-timeout must be able to improve on it
            got = {k: v for k, v in got.items()
                   if not (isinstance(v, dict)
                           and v.get("valid?") == "unknown")}
            if got:
                extra_opts["resume_results"] = got
                resumed_names = sorted(got)
        # the segmented wgl search reloads its frontier checkpoints
        # (checker-frontier/*.jlog, keyed by history fingerprint)
        test["checkpoint?"] = True
        # checkpoint-and-extend (doc/robustness.md): linearizable
        # checkers reuse the run-dir's ckpt/ store, so re-checking a
        # GROWN run costs O(suffix) — a stale record (the history
        # changed under the digest) falls back to the full check
        test["extend?"] = True

    # degraded/watchdog sections can't be recomputed offline (no live
    # health registry or watchdog survives the crash) — carry them over
    # from the original results.json before this pass overwrites it
    prev_results: dict = {}
    try:
        with open(d / "results.json") as f:
            prev_results = json.load(f)
    except (OSError, ValueError):
        pass  # crashed before analysis, or torn write: nothing to keep

    telemetry.reset()
    with util.with_relative_time():
        with telemetry.span("analyze-offline", run=str(d)):
            test = core.analyze(test, store_ctx=jstore,
                                extra_opts=extra_opts)
    if isinstance(test.get("results"), dict):
        res = test["results"]
        # a completed checker whose name the rebuilt stack doesn't
        # carry (fallback path, renamed checker) is still the verdict
        # --resume exists to preserve: merge it in rather than
        # silently dropping it while claiming it was reused
        orphans = {k: v
                   for k, v in extra_opts.get("resume_results",
                                              {}).items()
                   if k not in res}
        if orphans:
            from . import checker as chk

            res.update(orphans)
            res["valid?"] = chk.merge_valid(
                [res.get("valid?")]
                + [(v or {}).get("valid?") for v in orphans.values()
                   if isinstance(v, dict)])
            # orphans were merged AFTER core.analyze's certificate
            # stamping pass — validate their proofs too, or a resumed
            # run's reused verdict would ride unvalidated
            try:
                from .tpu import certify as jcertify

                jcertify.stamp_results(
                    {k: v for k, v in orphans.items()
                     if isinstance(v, dict)}, hist)
            except Exception:  # noqa: BLE001 — best-effort
                logger.exception("stamping orphaned certificates "
                                 "failed")
        if isinstance(prev_results, dict):
            for k in ("degraded", "watchdog"):
                if k in prev_results and k not in res:
                    res[k] = prev_results[k]
        test["results"]["analysis"] = {
            "offline?": True,
            "resumed?": bool(resume),
            "recovered-ops": n_ops,
            "resumed-checkers": resumed_names,
        }
        # verdict-certificate outcomes (stamped inside core.analyze
        # against the recovered history) summarized for the offline
        # reader: a crashed run whose re-analysis carries validated
        # proofs is as trustworthy as an uninterrupted one
        try:
            from .tpu import certify as jcertify

            counts = {"certified": 0, "errors": 0, "absent": 0}
            for _path, r in jcertify.iter_certificates(res):
                if "absent" in (r.get("certificate") or {}):
                    counts["absent"] += 1
                elif r.get("certificate-error"):
                    counts["errors"] += 1
                elif r.get("certified"):
                    counts["certified"] += 1
            if any(counts.values()):
                test["results"]["analysis"]["certificates"] = counts
        except Exception:  # noqa: BLE001 — summary is best-effort
            logger.exception("summarizing certificates failed")
    # results.json only: save_results would retire the store-wide
    # `current` symlink (owned by whichever run is live right now) and
    # clobber the run's original test.json with the rebuilt map
    jstore.save_results_only(test)
    _refresh_coverage(d, test)
    core.log_results(test)
    return test


def _refresh_coverage(d: Path, test: dict) -> None:
    """Regenerates the run's coverage.json after offline re-analysis
    and re-appends its atlas entry. The live run's fault activations
    (recorded by the nemesis Validate wrapper with nemesis-declared
    kinds) are carried over when present — the offline fallback only
    knows the generic f→kind registry — so an unchanged run re-appends
    an identical digest and the atlas merge is a no-op: cell counts
    cannot double under --resume."""
    from . import coverage as jcoverage

    try:
        prev = jcoverage.load_record(d)
        # a fresh recorder: offline analysis has no live nemesis, so
        # faults derive from the history (or carry over from `prev`) —
        # never from whatever run the process-global recorder last saw
        rec = jcoverage.build_record(test,
                                     recorder=jcoverage.Recorder())
        if prev and prev.get("faults"):
            rec["faults"] = prev["faults"]
        jcoverage.validate_record(rec)
        with open(d / jcoverage.RECORD_FILE, "w") as f:
            json.dump(rec, f, indent=1)
        jcoverage.append_run(d.parent.parent, rec)
    except Exception:  # noqa: BLE001 — coverage must not sink analyze
        logger.exception("refreshing coverage record failed")
